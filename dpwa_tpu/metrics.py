"""Structured per-step metrics (JSONL).

The reference logs free-text lines via the ``logging`` module (peer chosen,
α, clocks — SURVEY.md §5 "Metrics/logging").  The rebuild emits structured
records instead: one JSON object per step with loss, exchange partner, α,
participation, bytes moved, and wall-clock timings, to stdout and/or a
JSONL file — greppable and plottable without parsing prose."""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from typing import IO, Any, Mapping, Optional

import numpy as np


def _jsonable(v: Any) -> Any:
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    if hasattr(v, "tolist"):  # jax arrays
        return np.asarray(v).tolist()
    return v


class MetricsLogger:
    """Writes one JSON object per record; stdlib-only, no deps.

    CONTRACT: :meth:`log_exchange` is *deferred* — it holds each record
    until the next logging point, so the final record of a run is only
    written by :meth:`flush` / :meth:`close`.  Call :meth:`close` when
    done, or use the logger as a context manager.  As a safety net an
    ``atexit`` flush is registered, so a forgotten close loses nothing on
    a clean interpreter exit — but records written that late appear after
    anything else the process printed.  Output order is guaranteed:
    every logging point (:meth:`log` or :meth:`log_exchange`) first
    writes any pending deferred record, so records always land in the
    order they were produced — just one logging interval late, with
    their original ``step``/``t`` stamps."""

    def __init__(
        self,
        path: Optional[str] = None,
        stream: Optional[IO[str]] = None,
        every: int = 1,
        max_bytes: int = 0,
        keep: int = 1,
    ):
        self._path = path
        self._file = open(path, "a", encoding="utf-8") if path else None
        self._stream = stream
        self.every = max(1, every)
        # Size cap for the JSONL file: when the next record would push it
        # past ``max_bytes`` the current file rolls into a ``<path>.1`` …
        # ``<path>.keep`` cascade (``.i`` shifts to ``.i+1``, the oldest
        # roll is replaced) and a fresh file starts — a soak run keeps at
        # most ~(keep+1)x max_bytes on disk instead of growing
        # unboundedly, and ``keep`` large enough covers the incident
        # window a post-mortem needs.  0 = unbounded (the historical
        # behaviour); keep=1 = the historical single-roll behaviour.
        self.max_bytes = max(0, int(max_bytes))
        self.keep = max(1, int(keep))
        self._t0 = time.perf_counter()
        self._pending = None
        # Guards the _pending handoff: log_exchange (training thread)
        # parks the deferred record, while ANY logging point — including
        # log_event from an Rx/healthz thread — may pop it.  Without the
        # lock two concurrent poppers could both pass the None check and
        # write the record twice.  Separate from _write_lock because
        # flush() re-enters log() → _write() and the locks are
        # non-reentrant.
        self._pending_lock = threading.Lock()
        # Serializes writers: the training thread and any Rx/healthz
        # thread logging events through the same logger must not
        # interleave mid-rotation (torn lines, double-rolls).
        self._write_lock = threading.Lock()
        self._atexit = atexit.register(self.flush)

    # dpwalint: guarded_by(_write_lock)
    def _rotate(self) -> None:
        """Roll ``<path>`` into the ``.1`` … ``.keep`` cascade.

        Only ever called from ``_write`` with ``_write_lock`` held."""
        try:
            self._file.close()
            for i in range(self.keep - 1, 0, -1):
                older = f"{self._path}.{i}"
                if os.path.exists(older):
                    os.replace(older, f"{self._path}.{i + 1}")
            os.replace(self._path, self._path + ".1")
        except OSError:
            pass
        self._file = open(self._path, "a", encoding="utf-8")

    def _write(self, line: str) -> None:
        with self._write_lock:
            if self._file is not None:
                if self.max_bytes and self._path:
                    try:
                        pos = self._file.tell()
                    except OSError:
                        pos = 0
                    if pos and pos + len(line) + 1 > self.max_bytes:
                        self._rotate()
                self._file.write(line + "\n")
                self._file.flush()
            if self._stream is not None:
                print(line, file=self._stream, flush=True)

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def log(self, step: int, _t: Optional[float] = None, **fields: Any) -> None:
        if step % self.every != 0:
            return
        # Keep file order == production order: a deferred exchange record
        # from an earlier step must land before this one.  (flush() pops
        # _pending before re-entering log(), so this never recurses.)
        self.flush()
        rec: dict[str, Any] = {
            "step": int(step),
            "t": round(
                (time.perf_counter() - self._t0) if _t is None else _t, 4
            ),
        }
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        self._write(json.dumps(rec))

    def elapsed(self) -> float:
        """Seconds since this logger was created (the ``t`` clock)."""
        return time.perf_counter() - self._t0

    def log_exchange(
        self,
        step: int,
        losses,
        info,
        payload_bytes: int,
        t: Optional[float] = None,
        **extra: Any,
    ) -> None:
        """Convenience: the standard gossip-round record — **deferred**.

        Materializing a device value mid-stream blocks on the whole
        in-flight dispatch pipeline, and that sync can dominate the loop
        when device↔host latency is high (observed: seconds per sync
        through a tunneled chip vs a sub-ms train step).  So this method
        never blocks: on non-logging steps it returns without touching
        ``losses``/``info`` at all; on logging steps it starts async
        device→host copies and WRITES THE RECORD AT THE NEXT LOGGING
        POINT (or :meth:`close`), by which time the data has long
        arrived.  Records therefore appear one logging interval late,
        with their original ``step``/``t`` stamps.

        ``t`` overrides the record's time stamp (seconds on the
        :meth:`elapsed` clock) — for callers that buffer records
        themselves and replay them after a timed region."""
        if step % self.every != 0:
            return
        for arr in (losses, info.partner, info.alpha, info.participated):
            if hasattr(arr, "copy_to_host_async"):
                arr.copy_to_host_async()
        self.flush()
        with self._pending_lock:
            self._pending = (
                step,
                self.elapsed() if t is None else t,
                losses,
                info,
                payload_bytes,
                extra,
            )

    def log_health(
        self, step: int, snapshot: Mapping[str, Any], **extra: Any
    ) -> None:
        """One health record from a scoreboard snapshot
        (:meth:`dpwa_tpu.parallel.tcp.TcpTransport.health_snapshot`).

        Flattens the per-peer dict into parallel lists keyed by ``peer``
        so downstream tooling (tools/health_report.py, jq one-liners)
        can read columns without walking nested objects:

        - ``peer_state`` — scoreboard state per remote peer;
        - ``suspicion`` — detector suspicion score per remote peer;
        - ``quarantined_rounds`` — lifetime rounds spent quarantined;
        - ``trust`` / ``trust_damped`` / ``trust_rejected`` — the trust
          plane's per-peer EWMA and verdict counters (present only when
          the content-trust plane contributed to the snapshot);
        - ``deadline_ms`` / ``hedges`` / ``hedge_wins`` / ``busy`` /
          ``slow`` — the flowctl plane's per-peer adaptive deadline and
          hedge/soft-outcome counters, plus top-level ``hedge_rate`` and
          ``shed_total`` (present only when flowctl contributed);
        - ``wire_codec`` / ``wire_bytes`` / ``compression_ratio`` and
          ``overlap_occupancy`` / ``overlap_hidden_frac`` /
          ``overlap_prefetched`` / ``overlap_straddled`` — the wire
          plane's codec accounting and prefetch-overlap view (present
          only when the topk codec or the prefetch pipeline is on);
        - ``copies_per_frame`` / ``ring_occupancy`` — the zero-copy
          frame path's decode-copy tally and receive-ring occupancy
          (ride the wire group when the snapshot carries them);
        - ``device_rounds`` / ``jit_cache_hits`` / ``jit_cache_misses``
          / ``device_dispatches_per_round`` / ``h2d_zero_copy_frac`` /
          ``fold_frames`` — the device merge engine's jit-cache and
          dispatch accounting (present only once a device-resident
          exchange has served a round, docs/device.md);
        - ``view_active`` / ``view_passive`` / ``view_tracked`` /
          ``view_capped`` / ``view_digest_entries`` /
          ``view_digest_bytes`` / ``view_evicted_dead`` /
          ``view_evicted_cap`` / ``view_promotions`` /
          ``view_shuffles`` — the bounded partial-view plane's sizes,
          residency, per-frame digest footprint, and evictions by cause
          (present only under ``membership.view``, docs/membership.md);
        - ``disagreement_rms`` / ``disagreement_rel`` / ``sketch_peers``
          — the obs plane's sketch-based ring-disagreement estimate
          (present only when ``obs.sketch`` is on);
        - ``reactor_loop_lag_ms`` / ``reactor_ready_depth`` /
          ``reactor_open`` / ``reactor_evicted`` /
          ``reactor_busy_shed`` — the reactor Rx scheduler's loop and
          connection accounting (present only under
          ``protocol.rx_server: reactor``);
        - ``async_rounds`` / ``async_merges`` / ``async_stale_drops``
          / ``async_dup_drops`` / ``async_shed`` /
          ``async_fold_frames`` / ``async_staleness_hist`` and the
          per-peer ``async_peer_merges`` / ``async_peer_stale`` /
          ``async_peer_pending`` / ``async_peer_lag`` — the barrier-
          free async round loop's merge/drop/queue accounting (present
          only under ``protocol.async_rounds``, docs/async.md);

        plus attempt/success/quarantine counters.  Obeys ``every`` like
        every other record; written immediately (health snapshots are
        plain host dicts — nothing to defer)."""
        if step % self.every != 0:
            return
        peers = snapshot.get("peers", {})
        order = sorted(peers)
        cols = lambda key: [peers[p].get(key) for p in order]  # noqa: E731
        membership = snapshot.get("membership")
        if membership is not None:
            # Membership view rides the same record: the merged-view
            # incarnation column plus the node's own component/quorum
            # state (scoreboards without an attached MembershipManager
            # produce records byte-identical to the pre-membership ones).
            extra = dict(
                extra,
                incarnation=cols("incarnation"),
                own_incarnation=membership.get("incarnation"),
                component=membership.get("component"),
                component_id=membership.get("component_id"),
                partition_state=membership.get("partition_state"),
            )
        if order and "trust" in peers[order[0]]:
            # Trust columns ride the same record (absent without the
            # trust plane, keeping pre-trust records byte-identical).
            extra = dict(
                extra,
                trust=cols("trust"),
                trust_verdict=cols("trust_verdict"),
                trust_damped=cols("trust_damped"),
                trust_rejected=cols("trust_rejected"),
            )
        flowctl = snapshot.get("flowctl")
        if flowctl is not None and order:
            # Flowctl columns ride the same record (absent without the
            # flow-control plane, keeping earlier records byte-identical).
            hedges = flowctl.get("hedges", 0)
            admission = flowctl.get("admission") or {}
            extra = dict(
                extra,
                deadline_ms=cols("deadline_ms"),
                hedges=cols("hedges"),
                hedge_wins=cols("hedge_wins"),
                busy=cols("busy"),
                slow=cols("slow"),
                hedge_rate=(
                    round(flowctl.get("hedge_wins", 0) / hedges, 4)
                    if hedges
                    else 0.0
                ),
                shed_total=admission.get("shed_total", 0),
            )
        wire = snapshot.get("wire")
        if wire is not None:
            # Wire-plane columns (absent without the topk codec or the
            # prefetch pipeline, keeping dense sequential records
            # byte-identical): which codec published, the honest
            # wire-vs-dense byte ratio, and — under prefetch — how much
            # of the fetch wall-time the pipeline hid under compute.
            extra = dict(
                extra,
                wire_codec=wire.get("codec"),
                wire_bytes=wire.get("wire_bytes"),
                compression_ratio=wire.get("compression_ratio"),
            )
            if wire.get("copies_per_frame") is not None:
                # Zero-copy columns (docs/transport.md): mean payload-
                # sized copies per decoded frame (0.0 = views straight
                # out of the receive ring) and the fraction of ring
                # bytes currently leased out.
                extra = dict(
                    extra,
                    copies_per_frame=wire.get("copies_per_frame"),
                    ring_occupancy=wire.get("ring_occupancy"),
                )
            overlap = wire.get("overlap")
            if overlap is not None:
                extra = dict(
                    extra,
                    overlap_occupancy=overlap.get("occupancy"),
                    overlap_hidden_frac=overlap.get("hidden_frac"),
                    overlap_prefetched=overlap.get("prefetched"),
                    overlap_straddled=overlap.get("straddled"),
                )
            device = wire.get("device")
            if device is not None and device.get("device_rounds"):
                # Device merge engine columns (docs/device.md; absent
                # until a device-resident exchange has served a round,
                # keeping host-only records byte-identical): jit-cache
                # health, fused dispatches per round, and the fraction
                # of host→device crossings that were pointer adoptions.
                extra = dict(
                    extra,
                    device_rounds=device.get("device_rounds"),
                    jit_cache_hits=device.get("jit_cache_hits"),
                    jit_cache_misses=device.get("jit_cache_misses"),
                    device_dispatches_per_round=device.get(
                        "device_dispatches_per_round"
                    ),
                    h2d_zero_copy_frac=device.get("h2d_zero_copy_frac"),
                    fold_frames=device.get("fold_frames"),
                )
            view = wire.get("view")
            if view is not None:
                # Partial-view columns (docs/membership.md; absent
                # without membership.view, keeping global-view records
                # byte-identical): view sizes, tracked residency vs the
                # state cap, digest entries/bytes per frame, and the
                # eviction tally split by cause (dead vs LRU cap).
                extra = dict(
                    extra,
                    view_active=view.get("view_active"),
                    view_passive=view.get("view_passive"),
                    view_tracked=view.get("view_tracked"),
                    view_capped=view.get("view_capped"),
                    view_digest_entries=view.get("view_digest_entries"),
                    view_digest_bytes=view.get("view_digest_bytes"),
                    view_evicted_dead=view.get("view_evicted_dead"),
                    view_evicted_cap=view.get("view_evicted_cap"),
                    view_promotions=view.get("view_promotions"),
                    view_shuffles=view.get("view_shuffles"),
                )
            shard = wire.get("shard")
            if shard is not None:
                # Sharded-wire columns (absent at shard.k == 1, keeping
                # unsharded records byte-identical): the shard count and
                # the round-robin coverage (distinct shards served / k,
                # 1.0 once every shard has crossed the wire).
                extra = dict(
                    extra,
                    shard_k=shard.get("k"),
                    shard_coverage=shard.get("coverage"),
                )
        reactor = snapshot.get("reactor")
        if reactor is not None:
            # Reactor scheduler columns (absent under the threaded Rx
            # server, keeping those records byte-identical): the event
            # loop's saturation signal plus its connection accounting.
            extra = dict(
                extra,
                reactor_loop_lag_ms=reactor.get("loop_lag_ms"),
                reactor_ready_depth=reactor.get("ready_depth"),
                reactor_open=reactor.get("open"),
                reactor_evicted=reactor.get("evicted"),
                reactor_busy_shed=reactor.get("busy_shed"),
            )
        obs = snapshot.get("obs")
        if obs is not None:
            # Observability columns (absent without the obs plane,
            # keeping earlier records byte-identical): the sketch-based
            # ring-disagreement estimate described in docs/observability.md.
            conv = obs.get("convergence")
            if conv is not None:
                extra = dict(
                    extra,
                    disagreement_rms=conv.get("rms"),
                    disagreement_rel=conv.get("rel_rms"),
                    sketch_peers=conv.get("peers_seen"),
                )
        tune = snapshot.get("tune")
        if tune is not None and order:
            # Self-tuning wire columns (absent without tune.enabled,
            # keeping static-wire records byte-identical): the EFFECTIVE
            # ladder rung/codec each tracked link publishes at (None for
            # peers the controller has not yet observed), the DEGRADED
            # shed flags, and the ladder's lifetime traffic counters —
            # dwell_violations is the hysteresis invariant (always 0).
            links = tune.get("links") or {}
            tcol = lambda key: [  # noqa: E731
                links.get(p, {}).get(key) for p in order
            ]
            extra = dict(
                extra,
                tune_rung=tcol("effective_rung"),
                tune_codec=tcol("codec"),
                tune_shed=tcol("shed_active"),
                tune_escalations=tune.get("escalations"),
                tune_backoffs=tune.get("backoffs"),
                tune_sheds=tune.get("sheds"),
                tune_dwell_violations=tune.get("dwell_violations"),
            )
        async_snap = snapshot.get("async")
        if async_snap is not None and order:
            # Async round-loop columns (absent under lock-step rounds,
            # keeping those records byte-identical): cumulative merge/
            # drop/queue tallies, the staleness histogram (buckets
            # 0..max_staleness plus overflow = drops), and the per-peer
            # view aligned to the record's ``peer`` column.
            apeers = async_snap.get("peers") or {}
            acol = lambda key, d: [  # noqa: E731
                apeers.get(p, {}).get(key, d) for p in order
            ]
            extra = dict(
                extra,
                async_rounds=async_snap.get("rounds"),
                async_merges=async_snap.get("merges"),
                async_stale_drops=async_snap.get("stale_drops"),
                async_dup_drops=async_snap.get("dup_drops"),
                async_shed=async_snap.get("shed"),
                async_fold_frames=async_snap.get("fold_frames"),
                async_staleness_hist=async_snap.get("staleness_hist"),
                async_peer_merges=acol("merges", 0),
                async_peer_stale=acol("stale", 0),
                async_peer_pending=acol("pending", 0),
                async_peer_lag=acol("last_lag", None),
            )
        self.log(
            step,
            record="health",
            me=snapshot.get("me"),
            round=snapshot.get("round"),
            peer=[int(p) for p in order],
            peer_state=cols("state"),
            suspicion=cols("suspicion"),
            quarantined_rounds=cols("quarantined_rounds"),
            quarantines=cols("quarantines"),
            attempts=cols("attempts"),
            failures=cols("failures"),
            probe_attempts=cols("probe_attempts"),
            last_outcome=cols("last_outcome"),
            **extra,
        )

    def log_loss(
        self,
        step: int,
        loss: float,
        me: int,
        epoch: Optional[int] = None,
        alpha: Optional[float] = None,
        partner: Optional[int] = None,
        outcome: Optional[str] = None,
        test_loss: Optional[float] = None,
        test_acc: Optional[float] = None,
        _t: Optional[float] = None,
    ) -> None:
        """One ``record: "loss"`` row — the training harness's per-step
        loss stream (docs/training.md).

        The schema is CLOSED (tools/schema_check.py): only the merge
        metadata that the loss/incident join consumes rides along, so
        the record stays diffable across runs and planes.  Obeys
        ``every`` like ordinary records; the harness additionally
        applies ``run.loss_every`` before calling.  ``_t`` overrides the
        time stamp — the harness passes its VirtualClock so seeded
        reruns produce byte-identical rows."""
        fields: dict[str, Any] = {"record": "loss", "me": int(me)}
        fields["loss"] = float(loss)
        if epoch is not None:
            fields["epoch"] = int(epoch)
        if alpha is not None:
            fields["alpha"] = float(alpha)
        if partner is not None:
            fields["partner"] = int(partner)
        if outcome is not None:
            fields["outcome"] = str(outcome)
        if test_loss is not None:
            fields["test_loss"] = float(test_loss)
        if test_acc is not None:
            fields["test_acc"] = float(test_acc)
        self.log(step, _t=_t, **fields)

    def log_run(
        self, step: int, me: int, leg: str, status: str, peers: int,
        seed: int, _t: Optional[float] = None, **fields: Any,
    ) -> None:
        """One ``record: "run"`` envelope row (docs/training.md).

        ``status: "start"`` opens a node's stream with the leg shape;
        exactly one terminal ``"done"``/``"crashed"`` row carries the
        outcome fields ``tools/run_report.py`` and the bench train leg
        consume.  Bypasses ``every``: an envelope row dropped to a
        sampling interval would orphan the whole stream."""
        self.flush()
        rec: dict[str, Any] = {
            "step": int(step),
            "t": round(
                (time.perf_counter() - self._t0) if _t is None else _t, 4
            ),
            "record": "run",
            "me": int(me),
            "leg": str(leg),
            "status": str(status),
            "peers": int(peers),
            "seed": int(seed),
        }
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        self._write(json.dumps(rec))

    # dpwalint: thread_root(rx)
    def log_event(self, step: int, event: str, **fields: Any) -> None:
        """One recovery/control-plane event record, written immediately.

        Events are rare and load-bearing (rollback, bootstrap, resync,
        poisoned rejection) so they bypass ``every`` — dropping one to a
        sampling interval would hide the exact evidence
        ``tools/health_report.py`` summarizes.  The record carries
        ``record: "event"`` and ``event: <kind>`` so downstream tooling
        can fold all kinds with one filter."""
        self.flush()
        rec: dict[str, Any] = {
            "step": int(step),
            "t": round(time.perf_counter() - self._t0, 4),
            "record": "event",
            "event": str(event),
        }
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        self._write(json.dumps(rec))

    def log_tune(self, step: int, decision: Mapping[str, Any]) -> None:
        """One self-tuning-wire ladder decision (``record: "tune"``),
        written immediately.

        Decisions are rare and load-bearing like events (an escalation
        explains every compressed frame after it; a dwell-window replay
        is the determinism test's fixture) so they bypass ``every``.
        The schema is CLOSED (tools/schema_check.py): exactly the
        fields LinkTuner._record emits, so seeded reruns diff to empty
        on the whole decision log."""
        self.flush()
        rec: dict[str, Any] = {
            "step": int(step),
            "t": round(time.perf_counter() - self._t0, 4),
            "record": "tune",
        }
        for k, v in decision.items():
            rec[k] = _jsonable(v)
        self._write(json.dumps(rec))

    def flush(self) -> None:
        """Write the deferred record, if any (blocks only on its arrays)."""
        with self._pending_lock:
            pending, self._pending = self._pending, None
        if pending is None:
            return
        step, t, losses, info, payload_bytes, extra = pending
        alpha = np.asarray(info.alpha)
        part = np.asarray(info.participated)
        self.log(
            step,
            _t=t,
            loss_mean=float(np.asarray(losses).mean()),
            losses=losses,
            partner=info.partner,
            alpha=alpha,
            participated=part,
            exchanged_bytes=int(payload_bytes * int(part.sum())),
            **extra,
        )

    def close(self) -> None:
        self.flush()
        atexit.unregister(self.flush)
        with self._write_lock:
            if self._file is not None:
                self._file.close()
                self._file = None
