"""Structured per-step metrics (JSONL).

The reference logs free-text lines via the ``logging`` module (peer chosen,
α, clocks — SURVEY.md §5 "Metrics/logging").  The rebuild emits structured
records instead: one JSON object per step with loss, exchange partner, α,
participation, bytes moved, and wall-clock timings, to stdout and/or a
JSONL file — greppable and plottable without parsing prose."""

from __future__ import annotations

import json
import sys
import time
from typing import IO, Any, Mapping, Optional

import numpy as np


def _jsonable(v: Any) -> Any:
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    if hasattr(v, "tolist"):  # jax arrays
        return np.asarray(v).tolist()
    return v


class MetricsLogger:
    """Writes one JSON object per record; stdlib-only, no deps."""

    def __init__(
        self,
        path: Optional[str] = None,
        stream: Optional[IO[str]] = None,
        every: int = 1,
    ):
        self._file = open(path, "a", encoding="utf-8") if path else None
        self._stream = stream
        self.every = max(1, every)
        self._t0 = time.perf_counter()

    def log(self, step: int, **fields: Any) -> None:
        if step % self.every != 0:
            return
        rec: dict[str, Any] = {
            "step": int(step),
            "t": round(time.perf_counter() - self._t0, 4),
        }
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        line = json.dumps(rec)
        if self._file is not None:
            self._file.write(line + "\n")
            self._file.flush()
        if self._stream is not None:
            print(line, file=self._stream, flush=True)

    def log_exchange(
        self,
        step: int,
        losses,
        info,
        payload_bytes: int,
        **extra: Any,
    ) -> None:
        """Convenience: the standard gossip-round record."""
        alpha = np.asarray(info.alpha)
        part = np.asarray(info.participated)
        self.log(
            step,
            loss_mean=float(np.asarray(losses).mean()),
            losses=losses,
            partner=info.partner,
            alpha=alpha,
            participated=part,
            exchanged_bytes=int(payload_bytes * int(part.sum())),
            **extra,
        )

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
