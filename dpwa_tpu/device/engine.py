"""The merge engine: codec-aware fused merges over a device replica.

:class:`MergeEngine` owns the numpy↔JAX seam for the gossip merge —
``TcpTransport.exchange_on_device`` and the bench harness are thin
callers.  Every ``merge_*`` method takes the device-resident local
replica plus a decoded frame's RAW parts (dense view, u16 bf16 view,
int8 q+scale views, top-k index/value pair, shard slice), crosses them
through :mod:`~dpwa_tpu.device.handoff` exactly once, and dispatches
one fused kernel from :mod:`~dpwa_tpu.device.kernels` — compiled once
per ``(family, shape, …)`` key in the engine's :class:`JitCache` and
bit-identical to the host reference merge (the acceptance contract;
tests/test_device_engine.py proves it per codec × shard-k × trailer).

``fold()`` is the batched multi-peer form: k pending dense frames merge
in ONE dispatch as k in-graph sequential lerps — same bits as k
separate ``merge_dense`` calls, minus k−1 dispatch+sync round-trips.

Counters (dispatches, rounds, cache hits/misses) feed
``wire_snapshot()``'s device columns; the module-level
:func:`default_engine` is process-wide for the same reason the receive
ring is — transports share one device and the health columns are
per-process.  Nothing here imports jax at module scope.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from dpwa_tpu.device import handoff, kernels
from dpwa_tpu.ops.quantize import TopkPayload, int8_payload_views
from dpwa_tpu.ops.shard import ShardPayload

try:  # bf16 wire views — ml_dtypes ships with jax
    import ml_dtypes
except ImportError:  # pragma: no cover - ml_dtypes is a jax dependency
    ml_dtypes = None


class MergeEngine:
    """Fused decode+lerp merges, one jit cache, one stats plane."""

    def __init__(self, cache_capacity: int = kernels.DEFAULT_CACHE_CAPACITY):
        self.cache = kernels.JitCache(cache_capacity)
        self._lock = threading.Lock()
        self._dispatches = 0
        self._rounds = 0
        self._fold_frames = 0

    # -- dispatch accounting -------------------------------------------
    def _note_dispatch(self, frames: int = 1) -> None:
        with self._lock:
            self._dispatches += 1
            if frames > 1:
                self._fold_frames += frames

    def note_round(self) -> None:
        """One gossip round consumed the engine (merged or skipped) —
        the denominator of ``device_dispatches_per_round``."""
        with self._lock:
            self._rounds += 1

    @staticmethod
    def _t(alpha: float) -> np.float32:
        # f32 at the trace boundary: ``1.0 - t`` must round in f32 or
        # the kernel drifts one ulp off the native axpy reference.
        return np.float32(alpha)

    # -- kernel families -----------------------------------------------
    def merge_dense(self, local_dev, remote: np.ndarray, alpha: float):
        """Full-vector f32 lerp (dense wire, decoded int8 frames)."""
        n = int(remote.size)
        fn = self.cache.get(
            ("dense", n), lambda: kernels.build_dense(n)
        )
        self._note_dispatch()
        return fn(local_dev, handoff.to_device(remote), self._t(alpha))

    def merge_bf16(self, local_dev, remote_bf16: np.ndarray, alpha: float):
        """bf16 wire frame: crosses as its raw u16 view, upcast fused
        in-kernel — the host upcast copy disappears."""
        raw = remote_bf16.view(np.uint16)
        n = int(raw.size)
        fn = self.cache.get(("bf16", n), lambda: kernels.build_bf16(n))
        self._note_dispatch()
        return fn(local_dev, handoff.to_device(raw), self._t(alpha))

    def merge_int8(self, local_dev, payload: np.ndarray, alpha: float):
        """int8-chunked wire body: fused dequant-lerp straight off the
        payload's q/scale views — no dense f32 remote, host or device."""
        n, scales, q = int8_payload_views(payload)
        chunks = int(scales.size)
        fn = self.cache.get(
            ("int8", n, chunks), lambda: kernels.build_int8(n, chunks)
        )
        self._note_dispatch()
        return fn(
            local_dev,
            handoff.to_device(q),
            handoff.to_device(scales),
            self._t(alpha),
        )

    def merge_topk(
        self, local_dev, indices: np.ndarray, values: np.ndarray,
        alpha: float,
    ):
        """Top-k frame: scatter-lerp over the support; the densified
        estimate exists only inside the fused program."""
        n = int(local_dev.shape[0])
        k = int(indices.size)
        fn = self.cache.get(
            ("topk", n, k), lambda: kernels.build_topk(n, k)
        )
        self._note_dispatch()
        return fn(
            local_dev,
            handoff.to_device(np.ascontiguousarray(indices)),
            handoff.to_device(np.ascontiguousarray(values)),
            self._t(alpha),
        )

    def merge_shard(
        self, local_dev, lo: int, est_slice: np.ndarray, alpha: float
    ):
        """Shard frame with a dense (or already-densified) slice
        estimate: dynamic-slice lerp over ``[lo, lo+m)`` — the k−1
        unshipped slices never leave the device, bit-identical."""
        n = int(local_dev.shape[0])
        m = int(est_slice.size)
        fn = self.cache.get(
            ("shard", n, m), lambda: kernels.build_shard(n, m)
        )
        self._note_dispatch()
        return fn(
            local_dev,
            handoff.to_device(np.ascontiguousarray(est_slice)),
            np.int32(lo),
            self._t(alpha),
        )

    def merge_shard_topk(
        self, local_dev, lo: int, m: int, indices: np.ndarray,
        values: np.ndarray, alpha: float,
    ):
        """Top-k within a shard: scatter into the slice in-graph, lerp,
        splice — no densified slice on either side of the seam."""
        n = int(local_dev.shape[0])
        k = int(indices.size)
        fn = self.cache.get(
            ("shard_topk", n, m, k),
            lambda: kernels.build_shard_topk(n, m, k),
        )
        self._note_dispatch()
        return fn(
            local_dev,
            handoff.to_device(np.ascontiguousarray(indices)),
            handoff.to_device(np.ascontiguousarray(values)),
            np.int32(lo),
            self._t(alpha),
        )

    def merge(self, local_dev, remote, alpha: float):
        """Dispatch a decoded frame by its payload type — the thin-
        caller entry :meth:`~dpwa_tpu.parallel.tcp.TcpTransport`-side
        substrates and the bench harness share."""
        if isinstance(remote, TopkPayload):
            return self.merge_topk(
                local_dev, remote.indices, remote.values, alpha
            )
        if isinstance(remote, ShardPayload):
            lo, hi = remote.bounds
            inner = remote.inner
            if isinstance(inner, TopkPayload):
                return self.merge_shard_topk(
                    local_dev, lo, hi - lo, inner.indices, inner.values,
                    alpha,
                )
            return self.merge_shard(local_dev, lo, inner, alpha)
        if (
            ml_dtypes is not None
            and remote.dtype == np.dtype(ml_dtypes.bfloat16)
        ):
            return self.merge_bf16(local_dev, remote, alpha)
        return self.merge_dense(local_dev, remote, alpha)

    def fold(
        self, local_dev, remotes: Sequence[np.ndarray],
        alphas: Sequence[float],
    ):
        """Batched multi-peer fold: ``x ← lerp(…lerp(x, r_0, t_0)…,
        r_{k-1}, t_{k-1})`` in ONE dispatch, bit-identical to the k
        sequential merges it replaces (in-graph unroll keeps the op
        order)."""
        if len(remotes) != len(alphas):
            raise ValueError(
                f"fold got {len(remotes)} frames but {len(alphas)} alphas"
            )
        if not remotes:
            return local_dev
        k = len(remotes)
        n = int(remotes[0].size)
        fn = self.cache.get(
            ("fold", n, k), lambda: kernels.build_fold(n, k)
        )
        ts = np.array([float(a) for a in alphas], dtype=np.float32)
        devs = [handoff.to_device(r) for r in remotes]
        self._note_dispatch(frames=k)
        return fn(local_dev, handoff.to_device(ts), *devs)

    # -- observability -------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-ready device-plane state (``wire_snapshot()``'s device
        columns + docs/device.md's accounting)."""
        cache = self.cache.snapshot()
        with self._lock:
            dispatches = self._dispatches
            rounds = self._rounds
            fold_frames = self._fold_frames
        out = {
            "jit_cache_hits": cache["hits"],
            "jit_cache_misses": cache["misses"],
            "jit_cache_entries": cache["entries"],
            "device_dispatches": dispatches,
            "device_rounds": rounds,
            "device_dispatches_per_round": (
                round(dispatches / rounds, 4) if rounds else 0.0
            ),
            "fold_frames": fold_frames,
        }
        out.update(handoff.handoff_stats())
        return out


# Process-wide engine: transports share one device plane, and the
# device health columns are per-process (the receive-ring precedent).
_DEFAULT_LOCK = threading.Lock()
_DEFAULT_ENGINE: Optional[MergeEngine] = None


def default_engine() -> MergeEngine:
    global _DEFAULT_ENGINE
    with _DEFAULT_LOCK:
        if _DEFAULT_ENGINE is None:
            _DEFAULT_ENGINE = MergeEngine()
        return _DEFAULT_ENGINE


def device_snapshot() -> dict:
    """The default engine's snapshot — zeros before first use, never a
    jax import (``wire_snapshot()`` must stay backend-free)."""
    return default_engine().snapshot()


def reset_device_stats() -> None:
    """Test/bench hook: fresh default engine + zeroed handoff tally."""
    global _DEFAULT_ENGINE
    with _DEFAULT_LOCK:
        _DEFAULT_ENGINE = None
    handoff.reset_handoff_stats()
