"""Host↔device handoff: the ONE place frame bytes cross the seam.

The zero-copy frame path (docs/transport.md) delivers decoded payload
views straight out of the receive ring; this module moves them onto the
accelerator without re-materializing them on the way.  ``to_device``
ingests a host array via dlpack when the view is eligible — C-contiguous
and 64-byte aligned (``ALIGN``), which :class:`~dpwa_tpu.parallel.ingest
.BufferRing` guarantees for lease-offset-0 views — so the crossing is a
pointer adoption on the CPU backend and a single DMA on a real device,
never ``bytes -> ndarray -> device`` twice.  Ineligible views (unaligned
codec offsets, non-contiguous slices) fall back to ``jax.device_put``,
and the split is tallied so ``wire_snapshot()`` can show when frames
stopped crossing clean.

Ownership contract (the dlpack half of the lease rules in
``parallel/ingest.py``): a zero-copy device array ALIASES the host
buffer, so the source must be immutable-by-convention and stay alive
until every consuming dispatch has run.  Decoded frame views satisfy
both — the lease was detached (the views' refcounts keep the buffer
alive, dlpack's capsule holds the view) and nothing writes a received
frame.  Never hand ``to_device`` a buffer you intend to recycle.

``to_host`` is the sanctioned readback: the merge engine keeps the
replica device-resident between rounds, and host floats exist only at
the boundaries that genuinely need them — publish-encode, checkpoint,
trust/guard screening (``docs/device.md`` "Readback boundaries").  Every
other ``np.asarray(device_array)`` in a merge-path module is a lint
error (``device-host-roundtrip``).

Pure-python tallies only at import: jax loads inside the functions, so
the module is importable without a backend (the bench harness contract).
"""

from __future__ import annotations

import threading

import numpy as np

# dlpack-eligible alignment: XLA's CPU client adopts external buffers at
# 64-byte alignment (cacheline); anything less is copied on import.
ALIGN = 64

_LOCK = threading.Lock()
_H2D_ZERO_COPY = 0
_H2D_COPIED = 0
_H2D_BYTES = 0
_D2H_READBACKS = 0
_D2H_BYTES = 0


def dlpack_eligible(arr: np.ndarray) -> bool:
    """True when ``arr`` can cross by pointer adoption: C-contiguous
    with a 64-byte-aligned base.  Codec views at odd intra-frame offsets
    (int8 q-blocks after scale tables, top-k value blocks after index
    lists) legitimately fail this — they cross via ``device_put``."""
    return bool(
        arr.flags.c_contiguous and arr.ctypes.data % ALIGN == 0
    )


def to_device(arr: np.ndarray):
    """Host array -> device array on the default device, crossing
    exactly once.  dlpack (pointer adoption) when eligible, else
    ``jax.device_put`` (one staging copy); either way the caller's view
    is never routed through an intermediate ``bytes``/``ndarray``."""
    global _H2D_ZERO_COPY, _H2D_COPIED, _H2D_BYTES
    import jax
    import jax.numpy as jnp

    zero_copy = False
    if dlpack_eligible(arr):
        try:
            out = jnp.from_dlpack(arr)
            zero_copy = True
        except (TypeError, ValueError, RuntimeError):
            # Backend refuses this dtype/layout over dlpack (bf16 views,
            # non-CPU platforms importing host memory): staging copy.
            out = jax.device_put(arr)
    else:
        out = jax.device_put(arr)
    with _LOCK:
        _H2D_BYTES += int(arr.nbytes)
        if zero_copy:
            _H2D_ZERO_COPY += 1
        else:
            _H2D_COPIED += 1
    return out


def to_host(dev) -> np.ndarray:
    """Device array -> host f32 ndarray: THE sanctioned readback.

    On the CPU backend this is a view adoption; on a real device it is
    the one d2h DMA a publish/checkpoint boundary pays.  Callers hold
    the result immutable — on CPU it aliases the (immutable) device
    buffer."""
    global _D2H_READBACKS, _D2H_BYTES
    # dpwalint: ignore[device-host-roundtrip] -- this IS the readback boundary every other merge-path module must route through
    out = np.asarray(dev)
    with _LOCK:
        _D2H_READBACKS += 1
        _D2H_BYTES += int(out.nbytes)
    return out


def handoff_stats() -> dict:
    """Snapshot for ``device_snapshot()``: crossings by kind + bytes."""
    with _LOCK:
        total = _H2D_ZERO_COPY + _H2D_COPIED
        return {
            "h2d_transfers": total,
            "h2d_zero_copy": _H2D_ZERO_COPY,
            "h2d_zero_copy_frac": (
                (_H2D_ZERO_COPY / total) if total else 0.0
            ),
            "h2d_bytes": _H2D_BYTES,
            "d2h_readbacks": _D2H_READBACKS,
            "d2h_bytes": _D2H_BYTES,
        }


def reset_handoff_stats() -> None:
    """Test/bench hook: zero the process-wide tally."""
    global _H2D_ZERO_COPY, _H2D_COPIED, _H2D_BYTES
    global _D2H_READBACKS, _D2H_BYTES
    with _LOCK:
        _H2D_ZERO_COPY = _H2D_COPIED = _H2D_BYTES = 0
        _D2H_READBACKS = _D2H_BYTES = 0
