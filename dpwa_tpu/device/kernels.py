"""Fused decode+lerp kernels and the keyed jit cache behind them.

One jitted program per codec family, compiled once per
``(family, shape, dtype[, extras])`` key and held in an explicit LRU
(:class:`JitCache`) — the replacement for the single-slot
``_LERP_CACHE`` that used to live in ``parallel/tcp.py`` and silently
served every shape from one compilation.  alpha always arrives as a
TRACED f32 scalar, so one compiled program serves every interpolation
value, and the ``(1-α)`` subtraction rounds in f32 — the exact
discipline that makes every kernel here bit-identical to the host
reference (``native.merge_out``'s single-pass axpy; both contract the
lerp's mul+add the same way, verified by tests/test_device_engine.py).

Families (docs/device.md "Kernel families"):

- ``dense``     — ``(1-t)·a + t·b`` over the full replica (f32 wire).
- ``bf16``      — the wire's u16 view bitcast to bf16 and upcast
  IN-KERNEL, fused into the lerp: the host-side ``astype(np.float32)``
  copy a bf16 frame used to pay disappears.
- ``int8``      — dequant-lerp: per-chunk scale expansion
  (``q.f32 · s[chunk]``, CHUNK=256, zero-padded in-graph) fused into
  the lerp; the dense f32 remote never exists anywhere.
- ``topk``      — scatter-lerp: self-lerp the full vector, overwrite
  the k support coordinates with their gathered lerp.  Off-support
  coordinates get ``(1-α)x + αx`` — deliberately, because that is what
  the reference merge of the DENSIFIED estimate computes (the estimate
  equals the local value there, so the expressions agree elementwise
  and bit-identity holds) — while the scatter shrinks from full-width
  to k elements.
- ``shard``     — dynamic-slice lerp over ``[lo, lo+m)``: only the
  shipped slice is lerped; the other k−1 slices pass through the
  ``dynamic_update_slice`` untouched, preserving the slice-only merge
  invariant structurally (``ops/shard.py`` module docstring).
- ``shard_topk``— top-k-within-shard: scatter into the slice, lerp the
  slice, splice back.  Composes the two sparse families without a
  densified slice on either side of the seam.
- ``fold``      — batched multi-peer fold: k remotes applied as k
  IN-GRAPH sequential lerps in one dispatch.  The unrolled loop keeps
  the op order of k separate dispatches, so a fold is bit-identical to
  the sequential merges it replaces while paying one dispatch + zero
  intermediate readbacks.

Local-operand donation: on non-CPU backends every kernel donates its
first argument, so the device-resident replica updates in place (XLA
reuses the buffer).  The CPU client ignores donation with a warning, so
it is requested only where it works.

jax imports live inside the builders — this module must be importable
without a backend (same contract as ``parallel/tcp.py``).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Tuple

# int8 codec chunk size — must match ops/quantize.py's CHUNK.
_CHUNK = 256

# Compiled programs kept per engine cache.  Gossip touches a handful of
# (shape, codec) keys per run (one replica shape x a few codecs x the
# fold widths seen), so a small LRU holds the working set while a
# reshard or model swap can still retire dead compilations.
DEFAULT_CACHE_CAPACITY = 32


class JitCache:
    """Keyed LRU of compiled kernels with hit/miss accounting.

    ``get(key, build)`` returns the cached callable for ``key`` or
    builds, caches, and returns it — evicting the least-recently-used
    entry past ``capacity``.  Hits/misses feed the
    ``jit_cache_hits``/``jit_cache_misses`` health columns: a miss per
    round means shapes are churning and every round pays a compile."""

    def __init__(self, capacity: int = DEFAULT_CACHE_CAPACITY):
        self._capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, Callable]" = OrderedDict()
        self._hits = 0
        self._misses = 0

    def get(self, key: tuple, build: Callable[[], Callable]) -> Callable:
        with self._lock:
            fn = self._entries.get(key)
            if fn is not None:
                self._entries.move_to_end(key)
                self._hits += 1
                return fn
            self._misses += 1
        # Build OUTSIDE the lock: tracing/compiling can take seconds and
        # must not serialize unrelated shapes.  A racing duplicate build
        # is harmless — last writer wins, both callables are correct.
        fn = build()
        with self._lock:
            self._entries[key] = fn
            self._entries.move_to_end(key)
            while len(self._entries) > self._capacity:
                self._entries.popitem(last=False)
        return fn

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "hits": self._hits,
                "misses": self._misses,
                "entries": len(self._entries),
                "capacity": self._capacity,
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


def _donate_argnums() -> Tuple[int, ...]:
    """Donate the local replica's buffer where the backend honors it;
    the CPU client ignores donation (with a UserWarning per program),
    so request it only off-CPU."""
    import jax

    return (0,) if jax.default_backend() != "cpu" else ()


def build_dense(n: int) -> Callable:
    """``lerp(a, b, t)`` over ``n`` f32 elements."""
    import jax

    del n  # shape is the cache key; the trace specializes on operands

    def _k(a, b, t):
        return (1.0 - t) * a + t * b

    return jax.jit(_k, donate_argnums=_donate_argnums())


def build_bf16(n: int) -> Callable:
    """``lerp(a, upcast(b_u16), t)``: the remote crosses as its raw u16
    wire view; bitcast+upcast runs fused in-graph (exact — bf16→f32 is
    widening), replacing the host-side upcast copy."""
    import jax
    import jax.numpy as jnp

    del n

    def _k(a, b_u16, t):
        b = jax.lax.bitcast_convert_type(b_u16, jnp.bfloat16).astype(
            jnp.float32
        )
        return (1.0 - t) * a + t * b

    return jax.jit(_k, donate_argnums=_donate_argnums())


def build_int8(n: int, chunks: int) -> Callable:
    """Fused dequant-lerp: ``q`` crosses as the wire's int8 block,
    ``s`` as the f32 scale table; the per-chunk expansion
    (``ops/quantize.py`` layout, CHUNK=256, zero-pad in-graph) feeds the
    lerp directly — the dense f32 remote is never materialized, on
    either side of the seam."""
    import jax
    import jax.numpy as jnp

    def _k(a, q, s, t):
        pad = chunks * _CHUNK - n
        qp = jnp.pad(q, (0, pad)) if pad else q
        deq = (
            qp.astype(jnp.float32).reshape(chunks, _CHUNK) * s[:, None]
        ).reshape(-1)[:n]
        return (1.0 - t) * a + t * deq

    return jax.jit(_k, donate_argnums=_donate_argnums())


def build_topk(n: int, k: int) -> Callable:
    """Scatter-lerp matching the reference merge of the densified
    estimate bit-for-bit.  Off the support the estimate equals the
    local value, so ``lerp(a, est)`` there is elementwise the self-lerp
    ``(1-t)·a + t·a`` — computing it that way and scattering only the k
    gathered lerps (indices validated sorted/unique/in-range by the
    codec decoder) gives the same bits as a full-width
    scatter-then-lerp while touching k elements instead of n in the
    scatter (XLA:CPU scatters are scalar loops; see docs/device.md)."""
    import jax

    del n, k

    def _k(a, idx, v, t):
        base = (1.0 - t) * a + t * a
        merged_v = (1.0 - t) * a[idx] + t * v
        return base.at[idx].set(
            merged_v, indices_are_sorted=True, unique_indices=True
        )

    return jax.jit(_k, donate_argnums=_donate_argnums())


def build_shard(n: int, m: int) -> Callable:
    """Dynamic-slice lerp: lerp ONLY ``[lo, lo+m)``, splice back.  The
    k−1 unshipped slices ride through ``dynamic_update_slice``
    bit-identically — the slice-only merge invariant is structural, not
    a host-side copy discipline."""
    import jax

    del n

    def _k(a, r, lo, t):
        seg = jax.lax.dynamic_slice(a, (lo,), (m,))
        merged = (1.0 - t) * seg + t * r
        return jax.lax.dynamic_update_slice(a, merged, (lo,))

    return jax.jit(_k, donate_argnums=_donate_argnums())


def build_shard_topk(n: int, m: int, k: int) -> Callable:
    """Top-k within a shard: scatter the k values into the ``[lo,
    lo+m)`` slice, lerp the slice, splice back — no densified slice on
    the host, no dense intermediate on the device."""
    import jax

    del n, k

    def _k(a, idx, v, lo, t):
        seg = jax.lax.dynamic_slice(a, (lo,), (m,))
        base = (1.0 - t) * seg + t * seg
        merged_v = (1.0 - t) * seg[idx] + t * v
        merged = base.at[idx].set(
            merged_v, indices_are_sorted=True, unique_indices=True
        )
        return jax.lax.dynamic_update_slice(a, merged, (lo,))

    return jax.jit(_k, donate_argnums=_donate_argnums())


def build_fold(n: int, k: int) -> Callable:
    """Batched k-peer fold: ``k`` sequential lerps IN-GRAPH —
    ``x ← (1-t_i)·x + t_i·r_i`` in arrival order — so one dispatch
    reproduces k sequential merges while the replica never surfaces
    between them.  ``lax.scan`` (not a Python unroll): the carry is a
    fusion barrier per step, so each lerp contracts exactly like a
    standalone dispatch would — an unrolled loop lets XLA fuse ACROSS
    steps and drifts a ulp off the sequential reference."""
    import jax
    import jax.numpy as jnp

    del n, k

    def _k(a, ts, *remotes):
        def body(x, rt):
            r, t = rt
            return (1.0 - t) * x + t * r, None

        x, _ = jax.lax.scan(body, a, (jnp.stack(remotes), ts))
        return x

    return jax.jit(_k, donate_argnums=_donate_argnums())
