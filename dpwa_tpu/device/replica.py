"""Device-resident replica handle with a lazy host mirror.

``exchange_on_device`` used to pay ``np.asarray(vec_dev)`` — a full
d2h readback — at the TOP of every round, merged or skipped, because
the publish leg needs host bytes.  :class:`DeviceReplica` makes the
readback lazy and versioned instead: the replica lives on the device,
``host()`` materializes the mirror through
:func:`~dpwa_tpu.device.handoff.to_host` only when the device state has
changed since the last readback, and a skipped round (self-pair,
masked, timeout — the common case on a sparse schedule) republishes
from the cached mirror for free.  ``swap()`` is the single mutation
point: the merge engine's output replaces ``dev`` and invalidates the
mirror, so staleness is impossible by construction — there is no
"refresh" call to forget.

The mirror is held immutable by the same convention as every decoded
frame view: publish encodes FROM it, trust/guard compare AGAINST it,
nobody writes it.  On the CPU backend it aliases the device buffer
(free); on a real device it is the one d2h DMA a publish boundary
costs, paid at most once per merge.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dpwa_tpu.device import handoff


class DeviceReplica:
    """One worker's device-resident replica across gossip rounds."""

    __slots__ = ("dev", "_mirror", "_readbacks", "_mirror_hits")

    def __init__(self, dev):
        self.dev = dev
        self._mirror: Optional[np.ndarray] = None
        self._readbacks = 0
        self._mirror_hits = 0

    def host(self) -> np.ndarray:
        """The host mirror — read back only if a merge landed since the
        last call (the lazy-readback contract; docs/device.md
        "Readback boundaries")."""
        if self._mirror is None:
            self._mirror = handoff.to_host(self.dev)
            self._readbacks += 1
        else:
            self._mirror_hits += 1
        return self._mirror

    def swap(self, new_dev) -> None:
        """Adopt the merge output as the current replica.  The old
        device buffer stays alive as long as escaped mirrors/views
        reference it — dropping the handle here never invalidates a
        host view already handed to publish or trust."""
        self.dev = new_dev
        self._mirror = None

    def stats(self) -> dict:
        return {
            "readbacks": self._readbacks,
            "mirror_hits": self._mirror_hits,
        }
