"""Device merge engine: the numpy↔JAX seam, owned end to end.

The gossip wire ends where the accelerator begins.  Before this package
existed the seam was ad-hoc: a single-slot jitted lerp in
``parallel/tcp.py``, a ``jnp.asarray`` upload per frame, a full
``np.asarray`` readback per round, and every sparse codec densified on
the host before a dense merge.  The engine replaces all of it with
three parts (docs/device.md):

- :mod:`~dpwa_tpu.device.handoff` — zero-copy host→device ingestion of
  decoded frame views (dlpack pointer adoption at 64-byte alignment,
  ``device_put`` fallback) and the ONE sanctioned d2h readback.
- :mod:`~dpwa_tpu.device.kernels` — one fused decode+lerp kernel per
  codec family (dense f32, bf16-upcast, int8 dequant, top-k scatter,
  shard dynamic-slice, batched k-fold), each compiled once per shape
  key in an explicit LRU'd :class:`~dpwa_tpu.device.kernels.JitCache`
  and bit-identical to the host reference merge.
- :mod:`~dpwa_tpu.device.engine` / :mod:`~dpwa_tpu.device.replica` —
  the :class:`~dpwa_tpu.device.engine.MergeEngine` dispatcher plus the
  :class:`~dpwa_tpu.device.replica.DeviceReplica` handle that keeps the
  replica device-resident between rounds with a lazy, versioned host
  mirror (readback only at publish/checkpoint/trust boundaries).

Importable without a JAX backend — jax loads inside the kernel
builders and handoff calls, never at module scope (the bench-harness
contract shared with ``parallel/tcp.py``).
"""

from dpwa_tpu.device.engine import (
    MergeEngine,
    default_engine,
    device_snapshot,
    reset_device_stats,
)
from dpwa_tpu.device.kernels import JitCache
from dpwa_tpu.device.replica import DeviceReplica

__all__ = [
    "MergeEngine",
    "JitCache",
    "DeviceReplica",
    "default_engine",
    "device_snapshot",
    "reset_device_stats",
]
