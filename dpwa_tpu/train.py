"""The SPMD gossip training loop.

The reference's hot loop (SURVEY.md §3.2) is::

    forward / loss.backward() / optimizer.step()   # local, per process
    adapter.update(loss)                           # publish, fetch, merge

Here the entire loop — per-peer forward/backward, optax update, AND the
gossip exchange — is **one jitted ``shard_map`` program** over the ``peers``
mesh axis (SURVEY.md §3.5).  Manual SPMD, deliberately: auto sharding
propagation through vmapped convolutions makes GSPMD introduce all-gathers
of the per-peer replicas, which is both a performance bug (the whole point
of gossip is that nothing is globally gathered) and a deadlock on
thread-starved CPU test meshes.  Inside ``shard_map`` every peer's
forward/backward/optimizer math is provably local; the **only** collective
in the compiled program is the pairing ``ppermute`` of the exchange.

Elasticity note: inside one SPMD program there are no independently
failing peers — a fault injected via ``fault_probability`` (or the chaos
harness on the TCP path) surfaces to this loop as an α = 0 round: the
replica keeps training on its own.  The peer-health control plane
(:mod:`dpwa_tpu.health` — suspicion, quarantine/backoff, probe
re-admission, fallback remap) lives on the multi-process TCP path, where
peers genuinely die and come back; its scoreboard state is observable via
metrics ``health`` records and the optional ``/healthz`` endpoint."""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from dpwa_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from dpwa_tpu.interpolation import PeerMeta
from dpwa_tpu.parallel.ici import (
    ExchangeInfo,
    IciTransport,
    gossip_exchange_local,
)
from dpwa_tpu.parallel.mesh import peer_sharding
from dpwa_tpu.utils.pytree import combine as pytree_combine
from dpwa_tpu.utils.pytree import partition as pytree_partition

PyTree = Any
# loss_fn(single_peer_params, (x, y)) -> scalar loss
LossFn = Callable[[PyTree, Tuple[jnp.ndarray, jnp.ndarray]], jnp.ndarray]


class GossipTrainState(NamedTuple):
    """Peer-stacked training state. Every leaf's leading axis is n_peers.

    ``model_state`` carries non-parameter model variables (e.g. BatchNorm
    ``batch_stats``); it is exchanged alongside params — running statistics
    are part of the replica and must gossip with the same α — but never
    touched by the optimizer.

    ``loss`` is each peer's most recent training loss — the value the
    reference's Rx thread serves alongside the published vector
    (SURVEY.md §3.3).  Overlapped exchanges ship it as the metadata so the
    collective has no dependency on the current step's forward pass."""

    params: PyTree
    opt_state: PyTree
    clock: jnp.ndarray  # float32[n] — steps trained, rides with exchanges
    step: jnp.ndarray  # int32 scalar — global schedule position
    model_state: PyTree = None
    loss: jnp.ndarray = None  # float32[n] — last step's per-peer loss


def init_gossip_state(
    stacked_params: PyTree,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    stacked_model_state: PyTree = None,
) -> GossipTrainState:
    """Build state from peer-stacked params and shard it over the mesh."""
    n = transport.config.n_peers
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stacked_params)}
    if leading != {n}:
        raise ValueError(
            f"stacked params must have leading peer axis {n}, got {leading}"
        )
    opt_state = jax.vmap(optimizer.init)(stacked_params)
    sh = peer_sharding(transport.mesh, transport.axis_name)
    # The train step donates the state, so it must not alias arrays the
    # caller still holds.  device_put of HOST data always materializes
    # fresh buffers; only an existing jax.Array (possibly already in the
    # target sharding, where device_put can alias) needs the extra copy.
    def own(v):
        out = jax.device_put(v, sh)
        return out.copy() if isinstance(v, jax.Array) else out

    put = lambda t: jax.tree.map(own, t)
    return GossipTrainState(
        params=put(stacked_params),
        opt_state=put(opt_state),
        clock=jax.device_put(jnp.zeros(n, jnp.float32), sh),
        step=jnp.int32(0),
        model_state=put(stacked_model_state)
        if stacked_model_state is not None
        else None,
        loss=jax.device_put(jnp.zeros(n, jnp.float32), sh),
    )


def stack_params(params: PyTree, n_peers: int) -> PyTree:
    """Replicate one pytree n times along a new leading peer axis —
    identical warm start on every peer (the reference's default: every
    process builds the same model)."""
    return jax.tree.map(
        lambda v: jnp.broadcast_to(v[None], (n_peers,) + v.shape), params
    )


def init_params_per_peer(
    init_fn: Callable[[jax.Array], PyTree], key: jax.Array, n_peers: int
) -> PyTree:
    """Independent random init per peer (diverged cold start)."""
    return jax.vmap(init_fn)(jax.random.split(key, n_peers))


def _make_step(
    loss_fn,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    exchange_filter: Optional[Callable[[str], bool]],
    with_state: bool,
    overlap: bool = False,
):
    """Shared builder behind both public step factories.

    When ``with_state`` is False, ``model_state`` is threaded through as an
    empty pytree ``()`` — zero leaves, so it adds nothing to the compiled
    program — keeping one body/shard_map/_step implementation for both.

    ``overlap`` selects which params the exchange ships (see
    :func:`make_gossip_train_step`): post-update (default, the lock-step
    emulation) or pre-update ``x_k`` (the collective overlaps fwd/bwd)."""
    grad_fn = jax.value_and_grad(loss_fn, has_aux=with_state)
    schedule, interp = transport.schedule, transport.interp
    axis, mesh = transport.axis_name, transport.mesh
    shard = lambda t: jax.tree.map(lambda v: v[0], t)
    unshard = lambda t: jax.tree.map(lambda v: v[None], t)

    def body(params, opt_state, model_state, clock, prev_loss, step, batch):
        # Local (per-device) values: strip the size-1 peer block axis.
        params, opt_state = shard(params), shard(opt_state)
        old_params, old_model_state = params, model_state
        if with_state:
            model_state = shard(model_state)
            old_model_state = model_state
            (loss, new_model_state), grads = grad_fn(
                params, model_state, shard(batch)
            )
        else:
            loss, grads = grad_fn(params, shard(batch))
            new_model_state = ()
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        clock = clock[0] + 1.0
        if overlap:
            # Exchange the PRE-update replica with the PREVIOUS step's
            # loss (the last value this peer "published", exactly what the
            # reference's Rx thread would serve, SURVEY.md §3.3).  Every
            # collective operand — x_k, clock, stale loss — is ready at
            # step entry, so nothing gates the ppermute on this step's
            # fwd/bwd and XLA can overlap the DMA with compute.  The
            # model_state (fwd-produced) is also shipped stale; its
            # this-step delta is re-applied to the merge below.
            exchange_params, exchange_state = old_params, old_model_state
            meta = PeerMeta(clock, prev_loss[0])
        else:
            exchange_params, exchange_state = params, new_model_state
            meta = PeerMeta(clock, loss.astype(jnp.float32))
        if exchange_filter is not None:
            selected, _ = pytree_partition(exchange_params, exchange_filter)
            (merged_sel, merged_state), (partner, alpha, part) = (
                gossip_exchange_local(
                    (selected, exchange_state), meta, step,
                    schedule=schedule, interp=interp, axis_name=axis,
                )
            )
        else:
            (merged_sel, merged_state), (partner, alpha, part) = (
                gossip_exchange_local(
                    (exchange_params, exchange_state), meta, step,
                    schedule=schedule, interp=interp, axis_name=axis,
                )
            )
        if overlap:
            # x_{k+1} = merge(x_k) + own update: the merge contributed the
            # partner's pre-update replica (exactly what a free-running
            # reference peer would have pulled from a partner that had not
            # finished its step yet), the local gradient is never lost.
            # Model state gets the same treatment: merge(ms_k) + this
            # step's statistics delta.
            if exchange_filter is not None:
                sel_updates, _ = pytree_partition(updates, exchange_filter)
                merged_sel = optax.apply_updates(merged_sel, sel_updates)
            else:
                merged_sel = optax.apply_updates(merged_sel, updates)
            merged_state = jax.tree.map(
                lambda m, new, old: m + (new - old),
                merged_state, new_model_state, old_model_state,
            )
        if exchange_filter is not None:
            _, rest = pytree_partition(params, exchange_filter)
            merged = pytree_combine(merged_sel, rest)
        else:
            merged = merged_sel
        return (
            unshard(merged),
            unshard(opt_state),
            unshard(merged_state),
            clock[None],
            loss[None],
            (partner[None], alpha[None], part[None]),
        )

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(axis), P(axis), P(axis), P(axis), P(axis), P(), P(axis),
        ),
        out_specs=(
            P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
        ),
    )

    # Donated: each call consumes the input state's buffers (the caller
    # rebinds `state, … = step(state, …)`).  Without donation every
    # in-flight step holds a fresh params+opt copy and a deep async
    # dispatch queue can swamp the HBM allocator.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step(state: GossipTrainState, batch):
        prev_loss = (
            state.loss
            if state.loss is not None
            else jnp.zeros_like(state.clock)
        )
        params, opt_state, model_state, clock, losses, info = mapped(
            state.params,
            state.opt_state,
            state.model_state if with_state else (),
            state.clock,
            prev_loss,
            state.step,
            batch,
        )
        new_state = GossipTrainState(
            params=params,
            opt_state=opt_state,
            clock=clock,
            step=state.step + 1,
            model_state=model_state if with_state else state.model_state,
            loss=losses,
        )
        return new_state, losses, ExchangeInfo(*info)

    # Same CPU run-ahead bound as IciTransport.exchange (see the rationale
    # comment there) — reuse its detection so the rule lives in one place.
    block_per_call = transport._block_per_call

    def train_step(state: GossipTrainState, batch):
        if not with_state and state.model_state is not None:
            raise ValueError(
                "state carries model_state but this step was built with "
                "make_gossip_train_step, which would never update it; use "
                "make_gossip_train_step_with_state instead"
            )
        if with_state and state.model_state is None:
            raise ValueError(
                "step built with make_gossip_train_step_with_state but "
                "state.model_state is None; pass stacked_model_state to "
                "init_gossip_state"
            )
        out = _step(state, batch)
        if block_per_call:
            jax.block_until_ready(out)
        return out

    return train_step


def make_gossip_train_step(
    loss_fn: LossFn,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    exchange_filter: Optional[Callable[[str], bool]] = None,
    overlap: bool = False,
):
    """Returns jitted ``train_step(state, batch) -> (state, losses, info)``.

    ``batch`` is a peer-stacked ``(x[n, b, ...], y[n, b])`` pair; ``losses``
    is float32[n] (per peer) and also becomes the metadata the
    loss-weighted interpolation sees, matching the reference's
    ``update(loss)`` argument.

    ``exchange_filter`` enables subset-pytree gossip (BASELINE.json:11, the
    LoRA config): only leaves whose path matches the predicate enter the
    collective; everything else never moves — neither over ICI nor DCN.

    ``overlap=True`` ships the PRE-update replica ``x_k`` through the
    collective with the PREVIOUS step's loss as metadata, and applies the
    local update to the merged result (``x_{k+1} = merge(x_k) +
    update_k``).  Every collective operand is then ready at step entry —
    nothing gates the ppermute on this step's fwd/bwd — so on a real
    multi-device mesh XLA can schedule the collective-permute's ICI DMA
    concurrently with compute instead of serializing it after the
    optimizer.  (On the single-chip stacked twin there is no second
    engine to hide the gather behind; measured recovery there is ~1 % —
    artifacts/stacked_exchange_profile.json.)  Semantically this is one
    step of partner staleness: exactly what a free-running reference
    process sees when it pulls from a peer that has not finished its
    current step (SURVEY.md §3.2/§3.3 — the Rx thread serves the last
    *published* vector and loss).  The doubly-stochastic
    mean-preservation property is unchanged.

    Raises at call time if ``state.model_state`` is set — that state would
    silently stop updating; use :func:`make_gossip_train_step_with_state`."""
    return _make_step(
        loss_fn, optimizer, transport, exchange_filter, with_state=False,
        overlap=overlap,
    )


def make_gossip_train_step_with_state(
    loss_fn,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    exchange_filter: Optional[Callable[[str], bool]] = None,
    overlap: bool = False,
):
    """Like :func:`make_gossip_train_step`, for models with non-parameter
    variables (BatchNorm running stats etc., the reference's stock torch
    ResNets).

    ``loss_fn(params, model_state, batch) -> (loss, new_model_state)``.
    ``model_state`` is exchanged together with the (filtered) params —
    running statistics belong to the replica, so they merge with the same
    α — but the optimizer never sees it.  ``overlap`` as in
    :func:`make_gossip_train_step`: the PRE-step model_state ships (the
    post-step one is produced by the forward pass the collective must not
    wait on) and this step's statistics delta is re-applied to the merged
    result, mirroring the params' merge-then-update rule."""
    return _make_step(
        loss_fn, optimizer, transport, exchange_filter, with_state=True,
        overlap=overlap,
    )


def make_gossip_eval_fn(
    apply_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
    transport: IciTransport = None,
):
    """Returns jitted ``eval_fn(stacked_params, x, y) -> accuracy[n]``.

    Evaluates every peer's replica on the same (replicated) test set.  With
    a ``transport``, runs as shard_map so each replica is evaluated on its
    own device with zero collectives; without one, falls back to vmap."""

    def one(params, x, y):
        logits = apply_fn(params, x)
        return jnp.mean(jnp.argmax(logits, -1) == y)

    if transport is None:

        @jax.jit
        def eval_fn(stacked_params, x, y):
            return jax.vmap(lambda p: one(p, x, y))(stacked_params)

        return eval_fn

    axis, mesh = transport.axis_name, transport.mesh

    def body(stacked_params, x, y):
        params = jax.tree.map(lambda v: v[0], stacked_params)
        return one(params, x, y)[None]

    mapped = shard_map(
        body, mesh=mesh, in_specs=(P(axis), P(), P()), out_specs=P(axis)
    )
    return jax.jit(mapped)


def make_host_train_step(
    loss_fn: Callable[[PyTree, Any, Any], Any],
    optimizer: optax.GradientTransformation,
):
    """Jitted single-replica host step: ``step_fn(params, opt_state, x,
    y) -> (params, opt_state, loss)``.

    The multi-PROCESS twin of :func:`make_gossip_train_step`: where the
    SPMD loop fuses every peer's fwd/bwd/optimizer and the exchange into
    one ``shard_map`` program, the chaos-certified harness
    (:mod:`dpwa_tpu.run`, docs/training.md) runs one OS process per
    peer — each takes this local step, then hands the result to
    ``DpwaTcpAdapter.update`` for the TCP exchange (the reference's
    ``loss.backward(); optimizer.step(); adapter.update(loss)`` shape).
    One definition serves the harness and the examples' ``--certify``
    arms, so the certified loop and the benched loop cannot drift."""

    @jax.jit
    def step_fn(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step_fn


def consensus_params(stacked_params: PyTree) -> PyTree:
    """Mean over the peer axis — the 'deployed' model after training.

    Gossip preserves this mean at every exchange (doubly-stochastic merges),
    so it is the natural final artifact."""
    return jax.tree.map(lambda v: v.mean(axis=0), stacked_params)


def slice_peer_state(state: GossipTrainState, peer: int) -> GossipTrainState:
    """One peer's view of a peer-stacked state, as host numpy.

    The bootstrap donor payload (``dpwa_tpu/recovery/``): every
    peer-stacked leaf is sliced at ``peer`` on its leading axis; the
    per-peer ``clock``/``loss`` vectors keep their full length (they are
    the gossip metadata every replica already shares each round), and
    the scalar ``step`` rides unchanged.  Pairs with
    :func:`land_peer_state`."""
    import numpy as np

    take = lambda t: jax.tree.map(lambda v: np.asarray(v)[peer], t)
    return GossipTrainState(
        params=take(state.params),
        opt_state=take(state.opt_state),
        clock=np.asarray(state.clock),
        step=np.asarray(state.step),
        model_state=(
            take(state.model_state) if state.model_state is not None else None
        ),
        loss=np.asarray(state.loss) if state.loss is not None else None,
    )


def land_peer_state(
    state: GossipTrainState, peer: int, slice_state: GossipTrainState
) -> GossipTrainState:
    """Write a fetched peer slice back into a peer-stacked state.

    The rejoiner's landing step: its own row of every stacked leaf is
    replaced with the donor slice, and ``clock``/``step`` adopt the
    donor's values so the next participation/pairing draws line up with
    the ring's schedule position."""
    import numpy as np

    def put(stacked, sl):
        return jax.tree.map(
            lambda v, s: jnp.asarray(np.asarray(v)).at[peer].set(
                jnp.asarray(s)
            ),
            stacked,
            sl,
        )

    return GossipTrainState(
        params=put(state.params, slice_state.params),
        opt_state=put(state.opt_state, slice_state.opt_state),
        clock=jnp.asarray(slice_state.clock),
        step=jnp.asarray(slice_state.step),
        model_state=(
            put(state.model_state, slice_state.model_state)
            if state.model_state is not None
            else None
        ),
        loss=(
            jnp.asarray(slice_state.loss)
            if slice_state.loss is not None
            else state.loss
        ),
    )
