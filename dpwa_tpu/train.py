"""The SPMD gossip training loop.

The reference's hot loop (SURVEY.md §3.2) is::

    forward / loss.backward() / optimizer.step()   # local, per process
    adapter.update(loss)                           # publish, fetch, merge

Here the entire loop — per-peer forward/backward, optax update, AND the
gossip exchange — is **one jitted ``shard_map`` program** over the ``peers``
mesh axis (SURVEY.md §3.5).  Manual SPMD, deliberately: auto sharding
propagation through vmapped convolutions makes GSPMD introduce all-gathers
of the per-peer replicas, which is both a performance bug (the whole point
of gossip is that nothing is globally gathered) and a deadlock on
thread-starved CPU test meshes.  Inside ``shard_map`` every peer's
forward/backward/optimizer math is provably local; the **only** collective
in the compiled program is the pairing ``ppermute`` of the exchange."""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import shard_map
from jax.sharding import PartitionSpec as P

from dpwa_tpu.interpolation import PeerMeta
from dpwa_tpu.parallel.ici import (
    ExchangeInfo,
    IciTransport,
    gossip_exchange_local,
)
from dpwa_tpu.parallel.mesh import peer_sharding
from dpwa_tpu.utils.pytree import combine as pytree_combine
from dpwa_tpu.utils.pytree import partition as pytree_partition

PyTree = Any
# loss_fn(single_peer_params, (x, y)) -> scalar loss
LossFn = Callable[[PyTree, Tuple[jnp.ndarray, jnp.ndarray]], jnp.ndarray]


class GossipTrainState(NamedTuple):
    """Peer-stacked training state. Every leaf's leading axis is n_peers.

    ``model_state`` carries non-parameter model variables (e.g. BatchNorm
    ``batch_stats``); it is exchanged alongside params — running statistics
    are part of the replica and must gossip with the same α — but never
    touched by the optimizer."""

    params: PyTree
    opt_state: PyTree
    clock: jnp.ndarray  # float32[n] — steps trained, rides with exchanges
    step: jnp.ndarray  # int32 scalar — global schedule position
    model_state: PyTree = None


def init_gossip_state(
    stacked_params: PyTree,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    stacked_model_state: PyTree = None,
) -> GossipTrainState:
    """Build state from peer-stacked params and shard it over the mesh."""
    n = transport.config.n_peers
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stacked_params)}
    if leading != {n}:
        raise ValueError(
            f"stacked params must have leading peer axis {n}, got {leading}"
        )
    opt_state = jax.vmap(optimizer.init)(stacked_params)
    sh = peer_sharding(transport.mesh, transport.axis_name)
    # The train step donates the state, so it must not alias arrays the
    # caller still holds.  device_put of HOST data always materializes
    # fresh buffers; only an existing jax.Array (possibly already in the
    # target sharding, where device_put can alias) needs the extra copy.
    def own(v):
        out = jax.device_put(v, sh)
        return out.copy() if isinstance(v, jax.Array) else out

    put = lambda t: jax.tree.map(own, t)
    return GossipTrainState(
        params=put(stacked_params),
        opt_state=put(opt_state),
        clock=jax.device_put(jnp.zeros(n, jnp.float32), sh),
        step=jnp.int32(0),
        model_state=put(stacked_model_state)
        if stacked_model_state is not None
        else None,
    )


def stack_params(params: PyTree, n_peers: int) -> PyTree:
    """Replicate one pytree n times along a new leading peer axis —
    identical warm start on every peer (the reference's default: every
    process builds the same model)."""
    return jax.tree.map(
        lambda v: jnp.broadcast_to(v[None], (n_peers,) + v.shape), params
    )


def init_params_per_peer(
    init_fn: Callable[[jax.Array], PyTree], key: jax.Array, n_peers: int
) -> PyTree:
    """Independent random init per peer (diverged cold start)."""
    return jax.vmap(init_fn)(jax.random.split(key, n_peers))


def _make_step(
    loss_fn,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    exchange_filter: Optional[Callable[[str], bool]],
    with_state: bool,
):
    """Shared builder behind both public step factories.

    When ``with_state`` is False, ``model_state`` is threaded through as an
    empty pytree ``()`` — zero leaves, so it adds nothing to the compiled
    program — keeping one body/shard_map/_step implementation for both."""
    grad_fn = jax.value_and_grad(loss_fn, has_aux=with_state)
    schedule, interp = transport.schedule, transport.interp
    axis, mesh = transport.axis_name, transport.mesh
    shard = lambda t: jax.tree.map(lambda v: v[0], t)
    unshard = lambda t: jax.tree.map(lambda v: v[None], t)

    def body(params, opt_state, model_state, clock, step, batch):
        # Local (per-device) values: strip the size-1 peer block axis.
        params, opt_state = shard(params), shard(opt_state)
        if with_state:
            model_state = shard(model_state)
            (loss, new_model_state), grads = grad_fn(
                params, model_state, shard(batch)
            )
        else:
            loss, grads = grad_fn(params, shard(batch))
            new_model_state = ()
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        clock = clock[0] + 1.0
        meta = PeerMeta(clock, loss.astype(jnp.float32))
        if exchange_filter is not None:
            selected, rest = pytree_partition(params, exchange_filter)
            (merged_sel, merged_state), (partner, alpha, part) = (
                gossip_exchange_local(
                    (selected, new_model_state), meta, step,
                    schedule=schedule, interp=interp, axis_name=axis,
                )
            )
            merged = pytree_combine(merged_sel, rest)
        else:
            (merged, merged_state), (partner, alpha, part) = (
                gossip_exchange_local(
                    (params, new_model_state), meta, step,
                    schedule=schedule, interp=interp, axis_name=axis,
                )
            )
        return (
            unshard(merged),
            unshard(opt_state),
            unshard(merged_state),
            clock[None],
            loss[None],
            (partner[None], alpha[None], part[None]),
        )

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(), P(axis)),
        out_specs=(
            P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
        ),
    )

    # Donated: each call consumes the input state's buffers (the caller
    # rebinds `state, … = step(state, …)`).  Without donation every
    # in-flight step holds a fresh params+opt copy and a deep async
    # dispatch queue can swamp the HBM allocator.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step(state: GossipTrainState, batch):
        params, opt_state, model_state, clock, losses, info = mapped(
            state.params,
            state.opt_state,
            state.model_state if with_state else (),
            state.clock,
            state.step,
            batch,
        )
        new_state = GossipTrainState(
            params=params,
            opt_state=opt_state,
            clock=clock,
            step=state.step + 1,
            model_state=model_state if with_state else state.model_state,
        )
        return new_state, losses, ExchangeInfo(*info)

    # Same CPU run-ahead bound as IciTransport.exchange: the in-process
    # collective rendezvous deadlocks a thread-starved host if many steps'
    # collectives are in flight.  TPU meshes stay fully async.
    block_per_call = all(d.platform == "cpu" for d in mesh.devices.flat)

    def train_step(state: GossipTrainState, batch):
        if not with_state and state.model_state is not None:
            raise ValueError(
                "state carries model_state but this step was built with "
                "make_gossip_train_step, which would never update it; use "
                "make_gossip_train_step_with_state instead"
            )
        if with_state and state.model_state is None:
            raise ValueError(
                "step built with make_gossip_train_step_with_state but "
                "state.model_state is None; pass stacked_model_state to "
                "init_gossip_state"
            )
        out = _step(state, batch)
        if block_per_call:
            jax.block_until_ready(out)
        return out

    return train_step


def make_gossip_train_step(
    loss_fn: LossFn,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    exchange_filter: Optional[Callable[[str], bool]] = None,
):
    """Returns jitted ``train_step(state, batch) -> (state, losses, info)``.

    ``batch`` is a peer-stacked ``(x[n, b, ...], y[n, b])`` pair; ``losses``
    is float32[n] (per peer) and also becomes the metadata the
    loss-weighted interpolation sees, matching the reference's
    ``update(loss)`` argument.

    ``exchange_filter`` enables subset-pytree gossip (BASELINE.json:11, the
    LoRA config): only leaves whose path matches the predicate enter the
    collective; everything else never moves — neither over ICI nor DCN.

    Raises at call time if ``state.model_state`` is set — that state would
    silently stop updating; use :func:`make_gossip_train_step_with_state`."""
    return _make_step(
        loss_fn, optimizer, transport, exchange_filter, with_state=False
    )


def make_gossip_train_step_with_state(
    loss_fn,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    exchange_filter: Optional[Callable[[str], bool]] = None,
):
    """Like :func:`make_gossip_train_step`, for models with non-parameter
    variables (BatchNorm running stats etc., the reference's stock torch
    ResNets).

    ``loss_fn(params, model_state, batch) -> (loss, new_model_state)``.
    ``model_state`` is exchanged together with the (filtered) params —
    running statistics belong to the replica, so they merge with the same
    α — but the optimizer never sees it."""
    return _make_step(
        loss_fn, optimizer, transport, exchange_filter, with_state=True
    )


def make_gossip_eval_fn(
    apply_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
    transport: IciTransport = None,
):
    """Returns jitted ``eval_fn(stacked_params, x, y) -> accuracy[n]``.

    Evaluates every peer's replica on the same (replicated) test set.  With
    a ``transport``, runs as shard_map so each replica is evaluated on its
    own device with zero collectives; without one, falls back to vmap."""

    def one(params, x, y):
        logits = apply_fn(params, x)
        return jnp.mean(jnp.argmax(logits, -1) == y)

    if transport is None:

        @jax.jit
        def eval_fn(stacked_params, x, y):
            return jax.vmap(lambda p: one(p, x, y))(stacked_params)

        return eval_fn

    axis, mesh = transport.axis_name, transport.mesh

    def body(stacked_params, x, y):
        params = jax.tree.map(lambda v: v[0], stacked_params)
        return one(params, x, y)[None]

    mapped = shard_map(
        body, mesh=mesh, in_specs=(P(axis), P(), P()), out_specs=P(axis)
    )
    return jax.jit(mapped)


def consensus_params(stacked_params: PyTree) -> PyTree:
    """Mean over the peer axis — the 'deployed' model after training.

    Gossip preserves this mean at every exchange (doubly-stochastic merges),
    so it is the natural final artifact."""
    return jax.tree.map(lambda v: v.mean(axis=0), stacked_params)
