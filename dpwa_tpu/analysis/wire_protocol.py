"""Wire-protocol freeze checker.

Two peers built from different commits still have to interoperate, so
every on-wire constant lives in exactly one reviewed place —
``dpwa_tpu/parallel/protocol_constants.py`` — with its back-compat
notes.  This checker makes scattering structurally impossible:

- ``wire-magic``: a ``bytes`` literal starting with ``DPW``/``DPS``
  (the frame-magic namespaces) anywhere outside the registry is an
  error.  Tests may spell magics out deliberately (to prove the
  registry matches the wire) with an inline ignore.
- ``wire-struct``: in wire-path modules, ``struct.pack/unpack/Struct``
  with an inline format literal is an error — formats are layout
  contracts and belong next to their magic in the registry.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from dpwa_tpu.analysis.core import Finding, SourceFile

REGISTRY_PATH = "dpwa_tpu/parallel/protocol_constants.py"

# dpwalint: ignore[wire-magic] -- the checker's own prefix table, not a frame magic
_MAGIC_PREFIXES = (b"DPW", b"DPS")

# modules that read or write frames: inline struct formats banned here
_WIRE_PATH_MARKERS = (
    "parallel/tcp.py",
    "obs/wire.py",
    "membership/digest.py",
    "recovery/state_transfer.py",
    "health/chaos.py",
    "parallel/protocol_constants.py",
)

_STRUCT_FNS = {"pack", "unpack", "pack_into", "unpack_from",
               "calcsize", "iter_unpack", "Struct"}


def _norm(path: str) -> str:
    return path.replace("\\", "/")


class WireProtocolChecker:
    name = "wire-protocol"
    rules = ("wire-magic", "wire-struct")

    def check(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for src in files:
            if src.tree is None:
                continue
            is_registry = _norm(src.path).endswith(REGISTRY_PATH)
            on_wire_path = any(
                m in _norm(src.path) for m in _WIRE_PATH_MARKERS
            )
            for node in ast.walk(src.tree):
                if (
                    not is_registry
                    and isinstance(node, ast.Constant)
                    and isinstance(node.value, bytes)
                    and node.value.startswith(_MAGIC_PREFIXES)
                ):
                    out.append(Finding(
                        "wire-magic", src.path, node.lineno,
                        repr(node.value),
                        f"wire magic {node.value!r} spelled outside "
                        f"{REGISTRY_PATH} — import the registered "
                        "constant so back-compat notes travel with it",
                    ))
                if (
                    on_wire_path
                    and not is_registry
                    and isinstance(node, ast.Call)
                ):
                    fmt = self._inline_struct_format(node)
                    if fmt is not None:
                        out.append(Finding(
                            "wire-struct", src.path, node.lineno, fmt,
                            f"inline struct format {fmt!r} on the wire "
                            f"path — define it in {REGISTRY_PATH} next "
                            "to its frame magic",
                        ))
        return out

    @staticmethod
    def _inline_struct_format(node: ast.Call) -> Optional[str]:
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name not in _STRUCT_FNS:
            return None
        # struct.pack("<I", ...) / struct.Struct("<I") with a literal fmt
        if node.args and isinstance(node.args[0], ast.Constant) and (
            isinstance(node.args[0].value, (str, bytes))
        ):
            v = node.args[0].value
            return v if isinstance(v, str) else v.decode("ascii", "replace")
        return None
