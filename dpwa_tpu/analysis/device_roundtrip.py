"""Device↔host round-trip checker for the merge path.

The device merge engine's whole point (docs/device.md "Readback
boundaries") is that the replica crosses the numpy↔JAX seam exactly
once per direction: frames go up through
:func:`dpwa_tpu.device.handoff.to_device`, the replica comes back down
through :func:`~dpwa_tpu.device.handoff.to_host` — and only at the
publish/checkpoint/trust boundaries.  One stray
``np.asarray(device_array)`` in a merge-path module silently
reintroduces the per-exchange readback PR 16 deleted, and on a real
accelerator that is a full-replica PCIe DMA per round; ``jnp.asarray``
is the same mistake in the upload direction (a staging copy where the
handoff would have adopted the buffer), and ``.tobytes()`` on a device
array is a readback AND a copy.

``device-host-roundtrip`` makes the boundary structural: in the modules
listed below (plus the device-resident exchange methods of
``parallel/tcp.py``), every ``np.asarray``/``numpy.asarray``/
``jnp.asarray`` call and every ``.tobytes()`` attribute call is an
error unless annotated with the standard suppression grammar and a
reason (``# dpwalint: ignore[device-host-roundtrip] -- why this
crossing is the boundary``).  ``handoff.to_host`` itself carries the
one sanctioned ignore — it IS the boundary.

AST-level honesty: the checker cannot type the operand, so it flags the
*call form*, not proven device arrays.  That is deliberate — on these
few modules every ``asarray`` is either the seam (route it through the
handoff) or a host-side construction that reads identically as
``np.array``/``np.frombuffer``, so the rule stays high-signal.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence

from dpwa_tpu.analysis.core import Finding, SourceFile

# Modules that ARE the merge path: everything under the device engine
# package.  handoff.py is included on purpose — its to_host is the one
# sanctioned readback and carries the one sanctioned suppression.
_MERGE_PATH_MARKERS = (
    "dpwa_tpu/device/",
)

# In parallel/tcp.py only the device-resident exchange methods are
# merge path; the host exchange() legitimately lives in numpy.
_TCP_MARKER = "parallel/tcp.py"
_TCP_FUNCTION_PREFIX = "exchange_on_device"

# numpy/jax module aliases whose ``.asarray`` is a seam crossing.
_ASARRAY_OWNERS = ("np", "numpy", "jnp")


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _enclosing_functions(tree: ast.AST) -> Dict[int, str]:
    """line -> name of the innermost def containing it (module-level
    lines are absent).  Later (deeper) defs overwrite their enclosing
    def's lines, so the innermost name wins."""
    spans: Dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            for line in range(node.lineno, end + 1):
                spans[line] = node.name
    return spans


class DeviceRoundtripChecker:
    name = "device-roundtrip"
    rules = ("device-host-roundtrip",)

    def check(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for src in files:
            if src.tree is None:
                continue
            path = _norm(src.path)
            on_device_pkg = any(m in path for m in _MERGE_PATH_MARKERS)
            on_tcp = _TCP_MARKER in path
            if not (on_device_pkg or on_tcp):
                continue
            owners = _enclosing_functions(src.tree)
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "asarray"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in _ASARRAY_OWNERS
                ):
                    what = f"{fn.value.id}.asarray(...)"
                elif isinstance(fn, ast.Attribute) and fn.attr == "tobytes":
                    what = ".tobytes()"
                else:
                    continue
                sym = owners.get(node.lineno, "<module>")
                if on_tcp and not sym.startswith(_TCP_FUNCTION_PREFIX):
                    continue
                out.append(Finding(
                    "device-host-roundtrip", src.path, node.lineno,
                    f"{sym}:{what}",
                    f"{what} on the merge path is a device-host "
                    "round-trip — route uploads through "
                    "dpwa_tpu.device.handoff.to_device and readbacks "
                    "through handoff.to_host (the one sanctioned "
                    "boundary), or justify the crossing with an inline "
                    "ignore and a reason",
                ))
        return out
