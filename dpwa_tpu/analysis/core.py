"""Shared core for the dpwalint static-analysis framework.

Everything the individual checkers have in common lives here: the
parsed-file model, the ``# dpwalint:`` annotation grammar, the
suppression rules, and the ratchet baseline.  Checkers are plain
classes with a ``rules`` tuple and a ``check(files) -> [Finding]``
method; the runner (``tools/dpwalint.py``) and the tier-1 test both go
through :func:`run_checkers` so there is exactly one definition of
"clean tree".

Annotation grammar (one directive per comment, reasons after ``--``):

- ``# dpwalint: ignore[rule-a,rule-b] -- reason`` — suppress those
  rules on this line (or, when the comment stands alone on its line, on
  the next code line).  The reason is mandatory: an unexplained
  suppression is itself a finding.
- ``# dpwalint: ignore-file[rule] -- reason`` — suppress a rule for the
  whole file (must appear in the first 30 lines).
- ``# dpwalint: guarded_by(lock)`` — on an attribute access, or on a
  ``def`` line to cover the whole function: these accesses are
  protected by ``lock`` even though no lexical ``with`` shows it
  (e.g. a helper only ever called with the lock held).
- ``# dpwalint: double_buffered(attr) -- reason`` — registers ``attr``
  of the enclosing class as a deliberate unsynchronized handoff
  (thread-join ordering, swap-on-publish, …).  Reason mandatory.
- ``# dpwalint: thread_root(domain)`` — on a ``def`` line: this
  function is ALSO entered from the named thread domain (an entry
  point the intra-module call graph cannot see, e.g. a cross-object
  hook).

The ratchet baseline (``tools/dpwalint_baseline.json``) freezes
pre-existing debt by stable key (rule:path:symbol — line numbers are
deliberately not part of the key).  A finding whose key is baselined
is reported as suppressed; a baselined key that no longer fires is a
STALE entry and fails the run, so the baseline only ever shrinks.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from dpwa_tpu.analysis.rules import RULE_IDS

DEFAULT_TARGETS = ("dpwa_tpu", "tools", "bench.py")
_PRUNE_DIRS = {"__pycache__", ".git", "artifacts", "fixtures"}


@dataclasses.dataclass
class Finding:
    """One violation: where, which rule, and a stable identity.

    ``symbol`` is the rule-specific stable name of the violating thing
    (an attribute, a config key, a magic literal…), chosen so the
    baseline key survives unrelated line shifts."""

    rule: str
    path: str
    line: int
    symbol: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "key": self.key,
        }


_DIRECTIVE_RE = re.compile(r"#\s*dpwalint:\s*(.+?)\s*$")
_IGNORE_RE = re.compile(
    r"^(ignore|ignore-file)\[([\w\-, ]+)\]\s*(?:--|—)?\s*(.*)$"
)
_GUARDED_RE = re.compile(r"^guarded_by\(([A-Za-z_][\w.]*)\)\s*$")
_DOUBLE_BUF_RE = re.compile(
    r"^double_buffered\(([A-Za-z_]\w*)\)\s*(?:--|—)\s*(.+)$"
)
_THREAD_ROOT_RE = re.compile(r"^thread_root\(([\w\-]+)\)\s*$")


@dataclasses.dataclass
class Annotations:
    """Parsed ``# dpwalint:`` directives of one file."""

    # line -> set of rule ids suppressed on that line
    ignores: Dict[int, Dict[str, str]]
    # rule -> reason, file-wide
    file_ignores: Dict[str, str]
    # line -> lock name
    guarded_by: Dict[int, str]
    # line -> (attr, reason); class resolution happens in the checker
    double_buffered: Dict[int, Tuple[str, str]]
    # line -> domain name
    thread_roots: Dict[int, str]
    # malformed directives, reported under the dpwalint-annotation rule
    errors: List[Finding]


def _iter_comments(text: str) -> Iterator[Tuple[int, str]]:
    """(line, comment-text) for every real COMMENT token — directives
    quoted inside docstrings are grammar documentation, not directives."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return  # unparseable file: SourceFile reports it separately


def _parse_annotations(path: str, text: str) -> Annotations:
    ann = Annotations({}, {}, {}, {}, {}, [])
    for i, raw in _iter_comments(text):
        m = _DIRECTIVE_RE.search(raw)
        if not m:
            continue
        body = m.group(1)
        im = _IGNORE_RE.match(body)
        if im:
            kind, rule_list, reason = im.groups()
            rules = [r.strip() for r in rule_list.split(",") if r.strip()]
            bad = [r for r in rules if r not in RULE_IDS]
            if bad:
                ann.errors.append(Finding(
                    "dpwalint-annotation", path, i, f"unknown-rule:{bad[0]}",
                    f"suppression names unknown rule(s) {bad}",
                ))
                continue
            if not reason.strip():
                ann.errors.append(Finding(
                    "dpwalint-annotation", path, i, f"no-reason:{rules[0]}",
                    "suppression has no reason — write"
                    " `# dpwalint: ignore[rule] -- why`",
                ))
                continue
            if kind == "ignore-file":
                if i > 30:
                    ann.errors.append(Finding(
                        "dpwalint-annotation", path, i,
                        f"late-ignore-file:{rules[0]}",
                        "ignore-file must appear in the first 30 lines",
                    ))
                    continue
                for r in rules:
                    ann.file_ignores[r] = reason.strip()
            else:
                tgt = dict(ann.ignores.get(i, {}))
                for r in rules:
                    tgt[r] = reason.strip()
                ann.ignores[i] = tgt
            continue
        gm = _GUARDED_RE.match(body)
        if gm:
            ann.guarded_by[i] = gm.group(1)
            continue
        dm = _DOUBLE_BUF_RE.match(body)
        if dm:
            ann.double_buffered[i] = (dm.group(1), dm.group(2).strip())
            continue
        tm = _THREAD_ROOT_RE.match(body)
        if tm:
            ann.thread_roots[i] = tm.group(1)
            continue
        ann.errors.append(Finding(
            "dpwalint-annotation", path, i, "malformed",
            f"malformed dpwalint directive: {body!r}"
            " (a double_buffered/ignore without a `-- reason`?)",
        ))
    return ann


class SourceFile:
    """One parsed python file: text, AST, and its annotations."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[Finding] = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.parse_error = Finding(
                "dpwalint-annotation", path, e.lineno or 0, "syntax-error",
                f"file does not parse: {e.msg}",
            )
        self.annotations = _parse_annotations(path, text)

    def line_is_blank_comment(self, line: int) -> bool:
        """True when ``line`` holds nothing but a comment."""
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].lstrip().startswith("#")
        return False

    def suppression_for(self, rule: str, line: int) -> Optional[str]:
        """Reason string if ``rule`` at ``line`` is suppressed, else None.

        A standalone-comment ignore covers the next code line, so both
        the annotation's own line and the line above are consulted."""
        if rule in self.annotations.file_ignores:
            return self.annotations.file_ignores[rule]
        on_line = self.annotations.ignores.get(line, {})
        if rule in on_line:
            return on_line[rule]
        above = self.annotations.ignores.get(line - 1, {})
        if rule in above and self.line_is_blank_comment(line - 1):
            return above[rule]
        return None


def iter_py_files(targets: Iterable[str]) -> List[str]:
    """All .py files under ``targets`` (dirs walked, files taken as-is),
    pruning caches, VCS internals, artifacts, and test fixtures."""
    out: List[str] = []
    for target in targets:
        if os.path.isfile(target):
            out.append(target)
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(
                d for d in dirnames if d not in _PRUNE_DIRS
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def load_files(paths: Iterable[str]) -> List[SourceFile]:
    files = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as fh:
            files.append(SourceFile(p, fh.read()))
    return files


# --- baseline ratchet ---


def load_baseline(path: str) -> Dict[str, str]:
    """key -> reason.  Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    out: Dict[str, str] = {}
    for entry in data.get("entries", []):
        out[entry["key"]] = entry.get("reason", "")
    return out


def save_baseline(
    path: str, findings: Sequence[Finding], old: Dict[str, str]
) -> None:
    """Write the current findings as the new baseline, carrying forward
    reasons already written for keys that persist."""
    entries = []
    seen = set()
    for f in findings:
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append({
            "key": f.key,
            "reason": old.get(
                f.key, "pre-existing debt (auto-added; document why)"
            ),
            "message": f.message,
        })
    entries.sort(key=lambda e: e["key"])
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"entries": entries}, fh, indent=2, sort_keys=True)
        fh.write("\n")


@dataclasses.dataclass
class RunResult:
    """Outcome of one lint run, pre-split for reporting."""

    errors: List[Finding]  # fail the run
    baselined: List[Finding]  # matched a baseline entry
    suppressed: List[Tuple[Finding, str]]  # inline-ignored, with reason
    stale_baseline: List[str]  # baseline keys that no longer fire

    @property
    def exit_code(self) -> int:
        n = len(self.errors) + len(self.stale_baseline)
        return min(n, 125)


def run_checkers(
    checkers,
    files: Sequence[SourceFile],
    baseline: Optional[Dict[str, str]] = None,
) -> RunResult:
    """Run every checker, then apply suppressions and the baseline."""
    raw: List[Finding] = []
    for f in files:
        if f.parse_error is not None:
            raw.append(f.parse_error)
        raw.extend(f.annotations.errors)
    by_path = {f.path: f for f in files}
    for checker in checkers:
        raw.extend(checker.check(files))
    errors: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    baselined: List[Finding] = []
    baseline = baseline or {}
    fired_keys = set()
    for finding in raw:
        if finding.rule not in RULE_IDS:
            raise AssertionError(
                f"checker emitted unregistered rule {finding.rule!r} — "
                "register it in dpwa_tpu/analysis/rules.py first"
            )
        src = by_path.get(finding.path)
        reason = (
            src.suppression_for(finding.rule, finding.line)
            if src is not None
            else None
        )
        if reason is not None:
            suppressed.append((finding, reason))
            continue
        fired_keys.add(finding.key)
        if finding.key in baseline:
            baselined.append(finding)
        else:
            errors.append(finding)
    stale = sorted(k for k in baseline if k not in fired_keys)
    errors.sort(key=lambda f: (f.path, f.line, f.rule))
    return RunResult(errors, baselined, suppressed, stale)
