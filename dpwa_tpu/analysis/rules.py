"""The frozen rule-id list — ``schema_check.py`` discipline for lint.

Every rule a checker can emit is enumerated here, and
``tests/test_static_checks.py`` pins this set: renaming or deleting a
rule (which would silently orphan that rule's suppressions and baseline
entries across the tree) is an explicit, reviewed act, exactly like
changing a JSONL record schema.
"""

from __future__ import annotations

# rule id -> one-line description (docs/static-analysis.md mirrors this).
RULE_DESCRIPTIONS = {
    # lock-discipline checker
    "lock-discipline": (
        "self._* state reachable from two thread domains must be "
        "accessed under a declared lock, a guarded_by annotation, or a "
        "registered double-buffer"
    ),
    # determinism checker
    "det-random": (
        "no unseeded random.* / np.random.* in merge/partner/trust "
        "decision paths"
    ),
    "det-time": (
        "no wall-clock (time.time/monotonic/perf_counter) inside a "
        "branch condition or comparison on a decision path"
    ),
    "det-dict-order": (
        "no bare dict-order iteration (.items/.keys/.values) in a "
        "decision path — wrap in sorted() or justify"
    ),
    "det-tag-literal": (
        "threefry control-tag arguments must come from "
        "dpwa_tpu/utils/tags.py, never raw int literals"
    ),
    # wire-protocol checker
    "wire-magic": (
        "frame magics (b'DPW…'/b'DPS…') may only be defined in "
        "dpwa_tpu/parallel/protocol_constants.py"
    ),
    "wire-struct": (
        "struct formats on the wire path must come from "
        "protocol_constants, never inline literals"
    ),
    # config-key checker
    "config-unknown-key": (
        "config.<block>.<field> reads must name a schema field of that "
        "block's dataclass"
    ),
    "config-undocumented-key": (
        "every schema field must be mentioned in docs/*.md or README.md"
    ),
    "config-unparsed-block": (
        "every DpwaConfig block must be parsed by config_from_dict"
    ),
    # emit-kind checker (the folded-in lint_emitters pass)
    "emit-kind": (
        "record=/event= emit sites must use kinds registered in "
        "tools/schema_check.py"
    ),
    # zero-copy frame-path checker
    "zerocopy-tobytes": (
        "no .tobytes()/bytes(...) copies on frame-path modules — "
        "decode and serve through memoryviews/np views, or justify "
        "the copy with an inline ignore"
    ),
    # device-host round-trip checker
    "device-host-roundtrip": (
        "no np.asarray/jnp.asarray/.tobytes() crossings in merge-path "
        "modules — uploads go through device.handoff.to_device, "
        "readbacks through handoff.to_host, or justify the crossing "
        "with an inline ignore"
    ),
    # the framework's own hygiene rule
    "dpwalint-annotation": (
        "dpwalint directives must be well-formed, with reasons where "
        "required; files must parse"
    ),
}

RULE_IDS = frozenset(RULE_DESCRIPTIONS)
