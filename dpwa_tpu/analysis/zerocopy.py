"""Zero-copy frame-path checker.

The frame hot path (docs/transport.md "The zero-copy landing zone")
moves payload bytes from socket to merge as memoryviews over ring
buffers; one stray ``.tobytes()`` or ``bytes(...)`` silently
reintroduces a payload-sized copy per frame and the perf regression is
invisible until a bench run.  ``zerocopy-tobytes`` makes the copy
discipline structural: on the frame-path modules listed below, every
``.tobytes()`` attribute call and every ``bytes(...)`` constructor call
is an error unless annotated with the standard suppression grammar and
a reason (``# dpwalint: ignore[zerocopy-tobytes] -- why this copy is
the contract``) — publish-time snapshots and owning-bytes API returns
are legitimate, but each one is a reviewed, justified exception.

``bytearray(n)`` allocation is deliberately NOT flagged: buffers must
come from somewhere; the rule targets copies OUT of existing buffers.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence

from dpwa_tpu.analysis.core import Finding, SourceFile

# Modules whose socket->decode->serve path carries frame payloads.
# chaos.py deliberately absent: fault injection copies frames by design.
_FRAME_PATH_MARKERS = (
    "ops/quantize.py",
    "ops/shard.py",
    "parallel/tcp.py",
    "parallel/reactor.py",
    "parallel/ingest.py",
)


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _enclosing_functions(tree: ast.AST) -> Dict[int, str]:
    """line -> name of the innermost def containing it (module-level
    lines are absent).  Later (deeper) defs overwrite their enclosing
    def's lines, so the innermost name wins."""
    spans: Dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            for line in range(node.lineno, end + 1):
                spans[line] = node.name
    return spans


class ZeroCopyChecker:
    name = "zerocopy"
    rules = ("zerocopy-tobytes",)

    def check(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for src in files:
            if src.tree is None:
                continue
            if not any(
                m in _norm(src.path) for m in _FRAME_PATH_MARKERS
            ):
                continue
            owners = _enclosing_functions(src.tree)
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if isinstance(fn, ast.Attribute) and fn.attr == "tobytes":
                    what = ".tobytes()"
                elif isinstance(fn, ast.Name) and fn.id == "bytes":
                    what = "bytes(...)"
                else:
                    continue
                sym = owners.get(node.lineno, "<module>")
                out.append(Finding(
                    "zerocopy-tobytes", src.path, node.lineno,
                    f"{sym}:{what}",
                    f"{what} on a frame-path module copies payload "
                    "bytes out of the receive/serve path — decode and "
                    "serve through memoryviews/np views (see "
                    "dpwa_tpu/parallel/ingest.py), or justify the copy "
                    "with an inline ignore and a reason",
                ))
        return out
