"""Lock-discipline checker: cross-thread ``self._*`` state needs a lock.

Per class, per module (the unit the reactor refactor will rewrite):

1. Classify functions into THREAD DOMAINS.  ``threading.Thread(target=
   self._m)`` / ``target=<nested def>`` marks the target as a spawned
   root; a ``# dpwalint: thread_root(domain)`` annotation on a ``def``
   marks an entry the call graph cannot see (a cross-object hook like
   the transport's fetch running on an overlap daemon, or a snapshot
   served by the healthz thread).  Public methods and dunders seed the
   ``main`` domain.  Domains flow along the intra-class call graph
   (``self.m()`` edges) to a fixpoint.
2. Collect every ``self.<attr>`` access with its lexical ``with
   self.<lock>:`` context (or a ``guarded_by`` annotation standing in
   for one).
3. An attribute is SHARED when it is accessed from two distinct domains
   and stored outside ``__init__``; every non-``__init__`` access of a
   shared attribute must then be guarded — by one consistent lock — or
   the attribute registered ``double_buffered`` with a reason.

Attributes that are themselves synchronization objects (locks, events,
threads, queues) are exempt: they exist to be touched cross-thread.
Init-only attributes are exempt: ``Thread.start()`` publishes them.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dpwa_tpu.analysis.core import Finding, SourceFile

MAIN_DOMAIN = "main"

_SYNC_FACTORIES = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Event", "Barrier", "Thread", "Timer", "Queue", "SimpleQueue",
    "LifoQueue", "PriorityQueue", "local",
}


@dataclasses.dataclass
class _Access:
    attr: str
    line: int
    is_store: bool
    lock: Optional[str]  # lock name this access is guarded by
    unit: str  # qualified function name within the class


@dataclasses.dataclass
class _Unit:
    """One function body: a method or a function nested inside one."""

    name: str
    node: ast.AST
    def_line: int
    calls: Set[str] = dataclasses.field(default_factory=set)
    accesses: List[_Access] = dataclasses.field(default_factory=list)
    domains: Set[str] = dataclasses.field(default_factory=set)


def _lock_name(expr: ast.expr) -> Optional[str]:
    """``self._lock`` in a with-item -> ``_lock``."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


def _is_thread_ctor(func: ast.expr) -> bool:
    if isinstance(func, ast.Attribute) and func.attr == "Thread":
        return True
    return isinstance(func, ast.Name) and func.id == "Thread"


def _is_sync_ctor(value: ast.expr) -> bool:
    """True when the assigned value constructs a sync primitive."""
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    return name in _SYNC_FACTORIES


class _FunctionWalker(ast.NodeVisitor):
    """Walks one function body, tracking the lexical with-lock stack,
    self-attribute accesses, self-method calls, thread spawns, and
    nested function definitions (which become their own units)."""

    def __init__(self, checker: "_ClassAnalysis", unit: _Unit,
                 default_lock: Optional[str]):
        self.c = checker
        self.unit = unit
        self.lock_stack: List[str] = []
        self.default_lock = default_lock

    def _current_lock(self, line: int) -> Optional[str]:
        ann_lock = self.c.src.annotations.guarded_by.get(line)
        if ann_lock is not None:
            return ann_lock.removeprefix("self.")
        if self.lock_stack:
            return self.lock_stack[-1]
        return self.default_lock

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            ln = _lock_name(item.context_expr)
            if ln is not None:
                self.lock_stack.append(ln)
                pushed += 1
            # the with-expression itself reads the lock attr; skip it
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.lock_stack.pop()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            self.unit.accesses.append(_Access(
                attr=node.attr,
                line=node.lineno,
                is_store=isinstance(node.ctx, (ast.Store, ast.Del)),
                lock=self._current_lock(node.lineno),
                unit=self.unit.name,
            ))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
        ):
            self.unit.calls.add(f.attr)
        if isinstance(f, ast.Name):
            # possible call of a nested function in this scope
            self.unit.calls.add("::" + f.id)
        if _is_thread_ctor(f):
            for kw in node.keywords:
                if kw.arg == "target":
                    self.c.note_spawn(self.unit, kw.value)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.c.add_unit(node, parent=self.unit)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]


class _ClassAnalysis:
    """Full analysis of one ClassDef."""

    def __init__(self, src: SourceFile, node: ast.ClassDef):
        self.src = src
        self.node = node
        self.units: Dict[str, _Unit] = {}
        self.locks: Set[str] = set()
        self.sync_attrs: Set[str] = set()
        self.spawns: List[Tuple[str, str]] = []  # (spawning unit, target)
        self.pending_spawn_names: List[Tuple[_Unit, str]] = []
        self.double_buffered: Dict[str, str] = {}
        # double_buffered annotations inside this class's line span
        end = getattr(node, "end_lineno", None) or node.lineno
        for ln, (attr, reason) in src.annotations.double_buffered.items():
            if node.lineno <= ln <= end:
                self.double_buffered[attr] = reason
        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.add_unit(child, parent=None)

    def add_unit(self, node, parent: Optional[_Unit]) -> None:
        name = node.name if parent is None else (
            parent.name + "." + node.name
        )
        unit = _Unit(name=name, node=node, def_line=node.lineno)
        self.units[name] = unit
        # resolve spawns that referenced this nested function by name
        default_lock = None
        probes = [node.lineno, node.lineno - 1]
        if node.decorator_list:
            probes.append(node.decorator_list[0].lineno - 1)
        for probe in probes:
            ann_lock = self.src.annotations.guarded_by.get(probe)
            if ann_lock is not None:
                default_lock = ann_lock.removeprefix("self.")
                break
        walker = _FunctionWalker(self, unit, default_lock)
        for stmt in node.body:
            walker.visit(stmt)
        # collect lock declarations / sync attrs from assignments
        for acc_stmt in ast.walk(node):
            if isinstance(acc_stmt, ast.Assign) and _is_sync_ctor(
                acc_stmt.value
            ):
                for tgt in acc_stmt.targets:
                    ln = _lock_name(tgt)
                    if ln is not None:
                        self.sync_attrs.add(ln)
                        self.locks.add(ln)
        # thread_root annotation on the def line (or the line above it)
        for probe in (node.lineno, node.lineno - 1):
            dom = self.src.annotations.thread_roots.get(probe)
            if dom is not None:
                unit.domains.add(dom)
                break

    def note_spawn(self, unit: _Unit, target: ast.expr) -> None:
        tname = _lock_name(target)  # self.<method> form
        if tname is not None:
            self.spawns.append((unit.name, tname))
        elif isinstance(target, ast.Name):
            # nested function spawned by local name: unit scope prefix
            self.spawns.append((unit.name, unit.name + "." + target.id))

    def _seed_domains(self) -> None:
        for name, unit in self.units.items():
            base = name.split(".")[0]
            method = self.units.get(base)
            is_public = not base.startswith("_") or (
                base.startswith("__") and base.endswith("__")
            )
            if name == base and is_public and method is not None:
                unit.domains.add(MAIN_DOMAIN)
        for _, target in self.spawns:
            unit = self.units.get(target)
            if unit is not None:
                unit.domains.add("spawned:" + target)

    def _propagate(self) -> None:
        changed = True
        while changed:
            changed = False
            for unit in self.units.values():
                for callee in unit.calls:
                    if callee.startswith("::"):
                        target = self.units.get(
                            unit.name + "." + callee[2:]
                        )
                    else:
                        target = self.units.get(callee)
                    if target is None:
                        continue
                    missing = unit.domains - target.domains
                    if missing:
                        target.domains.update(missing)
                        changed = True

    def findings(self) -> List[Finding]:
        self._seed_domains()
        self._propagate()
        # attr -> (domains, has store outside __init__, accesses)
        per_attr: Dict[str, List[_Access]] = {}
        method_names = {n for n in self.units if "." not in n}
        for unit in self.units.values():
            if not unit.domains:
                continue  # unreached private helper: no evidence
            for acc in unit.accesses:
                if acc.attr in method_names:
                    continue  # method reference, not state
                per_attr.setdefault(acc.attr, []).append(acc)
        out: List[Finding] = []
        for attr, accesses in sorted(per_attr.items()):
            if attr in self.sync_attrs:
                continue
            domains: Set[str] = set()
            for acc in accesses:
                domains.update(self.units[acc.unit].domains)
            if len(domains) < 2:
                continue
            stores_outside_init = [
                a for a in accesses
                if a.is_store and a.unit.split(".")[0] != "__init__"
            ]
            if not stores_outside_init:
                continue  # effectively write-once; Thread.start publishes
            if attr in self.double_buffered:
                continue
            judged = [
                a for a in accesses if a.unit.split(".")[0] != "__init__"
            ]
            unguarded = [a for a in judged if a.lock is None]
            locks_used = {a.lock for a in judged if a.lock is not None}
            bogus = locks_used - self.locks
            if unguarded or len(locks_used) > 1 or bogus:
                first = min(
                    unguarded or judged, key=lambda a: a.line
                )
                detail = []
                if unguarded:
                    detail.append(
                        "unguarded at line(s) "
                        + ", ".join(str(a.line) for a in unguarded[:6])
                    )
                if len(locks_used) > 1:
                    detail.append(
                        f"guarded by MULTIPLE locks {sorted(locks_used)}"
                    )
                if bogus:
                    detail.append(
                        f"guarded_by names undeclared lock(s) "
                        f"{sorted(bogus)}"
                    )
                out.append(Finding(
                    "lock-discipline",
                    self.src.path,
                    first.line,
                    f"{self.node.name}.{attr}",
                    f"self.{attr} is shared across thread domains "
                    f"{sorted(domains)} and stored outside __init__; "
                    + "; ".join(detail)
                    + " — hold a declared lock, annotate guarded_by, or "
                    "register double_buffered with a reason",
                ))
        return out


class LockDisciplineChecker:
    name = "lock-discipline"
    rules = ("lock-discipline",)

    def check(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for src in files:
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    out.extend(_ClassAnalysis(src, node).findings())
        return out
