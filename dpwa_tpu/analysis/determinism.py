"""Determinism checker for replica-identical decision paths.

The gossip algebra only converges when every node makes the SAME
partner/merge/trust decision at the same step, so the decision modules
(schedules, trust, membership, interpolation, the async round loop) must
be pure functions of ``(seed, step, structured state)``:

- ``det-random``: no ambient randomness — ``random.*`` and unseeded
  ``np.random.*`` are forbidden; ``np.random.default_rng(seed)`` with an
  explicit seed argument is fine.
- ``det-time``: wall-clock reads may feed telemetry, but not branch
  conditions or comparisons — two replicas never read the same clock.
- ``det-dict-order``: bare ``.items()/.keys()/.values()`` iteration is
  insertion-order dependent; wrap in ``sorted()`` unless the consumer is
  an order-insensitive aggregate (``sum``/``min``/``max``/``set``/…).
- ``det-tag-literal`` (repo-wide, not just decision modules): the tag
  argument of ``_pair_key`` / ``chaos_draw`` must be a named constant
  from ``dpwa_tpu/utils/tags.py`` — a raw int literal can silently
  collide with another subsystem's stream and correlate draws that the
  paper requires to be independent.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence

from dpwa_tpu.analysis.core import Finding, SourceFile

# modules whose control flow is part of the replicated decision function
_DECISION_MARKERS = (
    "parallel/schedules.py",
    "trust/",
    "membership/",
    "parallel/interpolation.py",
    "parallel/async_loop.py",
    "run/",
    "tune/",
)

# consumers for which iteration order genuinely does not matter
_ORDER_INSENSITIVE = {
    "sorted", "min", "max", "sum", "all", "any", "set", "frozenset",
    "len", "dict", "Counter", "update",
}

_TIME_FNS = {"time", "monotonic", "perf_counter", "process_time"}

_TAG_TAKING_FNS = {"_pair_key", "chaos_draw"}
_TAG_ARG_INDEX = 3  # (seed, step, pair_id, tag)


def _dotted(node: ast.expr) -> Optional[str]:
    """``a.b.c`` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_decision_path(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(m in p for m in _DECISION_MARKERS)


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


class DeterminismChecker:
    name = "determinism"
    rules = ("det-random", "det-time", "det-dict-order", "det-tag-literal")

    def check(self, files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        for src in files:
            if src.tree is None:
                continue
            out.extend(self._check_tags(src))
            if _is_decision_path(src.path):
                out.extend(self._check_decision_module(src))
        return out

    # --- det-tag-literal (repo-wide) ---

    def _check_tags(self, src: SourceFile) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fn_name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if fn_name not in _TAG_TAKING_FNS:
                continue
            tag_expr: Optional[ast.expr] = None
            if len(node.args) > _TAG_ARG_INDEX:
                tag_expr = node.args[_TAG_ARG_INDEX]
            for kw in node.keywords:
                if kw.arg == "tag":
                    tag_expr = kw.value
            if tag_expr is None:
                continue
            if self._is_literal_tag(tag_expr):
                out.append(Finding(
                    "det-tag-literal", src.path, node.lineno,
                    f"{fn_name}:{ast.unparse(tag_expr)}",
                    f"raw tag {ast.unparse(tag_expr)!r} passed to "
                    f"{fn_name}() — use a named TAG_* / CHAOS_* constant "
                    "from dpwa_tpu/utils/tags.py so collisions are "
                    "caught at import time",
                ))
        return out

    @staticmethod
    def _is_literal_tag(expr: ast.expr) -> bool:
        """True when the tag is built purely from int literals."""
        if isinstance(expr, ast.Constant):
            return isinstance(expr.value, int)
        if isinstance(expr, ast.BinOp):
            return (
                DeterminismChecker._is_literal_tag(expr.left)
                and DeterminismChecker._is_literal_tag(expr.right)
            )
        return False

    # --- decision-module rules ---

    def _check_decision_module(self, src: SourceFile) -> List[Finding]:
        out: List[Finding] = []
        parents = _parents(src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                out.extend(self._rand_call(src, node))
                out.extend(self._dict_order(src, node, parents))
            elif isinstance(node, (ast.If, ast.While)):
                out.extend(self._time_in_test(src, node.test))
            elif isinstance(node, ast.Compare):
                out.extend(self._time_in_compare(src, node))
        # a compare inside an if-test is seen by both probes: dedupe
        seen = set()
        deduped = []
        for f in out:
            ident = (f.rule, f.line, f.symbol)
            if ident not in seen:
                seen.add(ident)
                deduped.append(f)
        return deduped

    def _rand_call(self, src: SourceFile, node: ast.Call) -> List[Finding]:
        dotted = _dotted(node.func)
        if dotted is None:
            return []
        is_np_rand = dotted.startswith(("np.random.", "numpy.random."))
        is_py_rand = dotted.startswith("random.")
        if not (is_np_rand or is_py_rand):
            return []
        if dotted.endswith(".default_rng") and (node.args or node.keywords):
            return []  # explicitly seeded generator: replica-identical
        return [Finding(
            "det-random", src.path, node.lineno, dotted,
            f"{dotted}() draws from ambient process randomness on a "
            "decision path — derive draws from the threefry schedule "
            "(participation_draw/_pair_key) or a seeded default_rng",
        )]

    def _time_findings(self, src: SourceFile, sub: ast.AST) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(sub):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted and dotted.startswith("time.") and (
                    dotted.split(".")[-1] in _TIME_FNS
                ):
                    out.append(Finding(
                        "det-time", src.path, node.lineno, dotted,
                        f"{dotted}() feeds a branch/comparison on a "
                        "decision path — replicas read different clocks; "
                        "pass the decision deadline in as data",
                    ))
        return out

    def _time_in_test(self, src: SourceFile, test: ast.expr) -> List[Finding]:
        return self._time_findings(src, test)

    def _time_in_compare(
        self, src: SourceFile, node: ast.Compare
    ) -> List[Finding]:
        return self._time_findings(src, node)

    def _dict_order(
        self,
        src: SourceFile,
        node: ast.Call,
        parents: Dict[ast.AST, ast.AST],
    ) -> List[Finding]:
        fn = node.func
        if not (
            isinstance(fn, ast.Attribute)
            and fn.attr in ("items", "keys", "values")
            and not node.args
            and not node.keywords
        ):
            return []
        # walk ancestors within the statement: exempt when feeding an
        # order-insensitive aggregate or a set comprehension
        cur: Optional[ast.AST] = node
        while cur is not None and not isinstance(cur, ast.stmt):
            parent = parents.get(cur)
            if isinstance(parent, ast.Call) and cur in (
                list(parent.args) + [kw.value for kw in parent.keywords]
            ):
                pfn = parent.func
                pname = pfn.attr if isinstance(pfn, ast.Attribute) else (
                    pfn.id if isinstance(pfn, ast.Name) else None
                )
                if pname in _ORDER_INSENSITIVE:
                    return []
            if isinstance(parent, ast.SetComp):
                return []
            cur = parent
        base = _dotted(fn.value) or "<expr>"
        return [Finding(
            "det-dict-order", src.path, node.lineno,
            f"{base}.{fn.attr}",
            f"bare {base}.{fn.attr}() iteration on a decision path "
            "depends on dict insertion order — wrap in sorted(...) or "
            "feed an order-insensitive aggregate",
        )]
