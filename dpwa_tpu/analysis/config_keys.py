"""Config-key coverage checker.

``dpwa_tpu/config.py`` is the schema: frozen dataclasses, one per YAML
block, every field validated in ``__post_init__``.  This checker keeps
the three surfaces that mention a key — code reads, the schema, and the
operator docs — from drifting apart:

- ``config-unknown-key``: an attribute chain shaped like
  ``config.<block>.<field>`` (base named ``config``/``cfg``) must name a
  real field (or property) of that block's dataclass.  A typo'd read
  (``config.trust.windw``) otherwise raises only on the config path that
  exercises it.
- ``config-undocumented-key``: every schema field must appear in the
  operator-facing docs (``docs/*.md``, ``README.md``, or the schema
  docstring in config.py itself — which mirrors the full YAML layout).
- ``config-unparsed-block``: every block field of ``DpwaConfig`` must be
  named in ``config_from_dict`` — a block that is never popped from the
  YAML mapping silently swallows user configuration.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dpwa_tpu.analysis.core import Finding, SourceFile

CONFIG_PATH_SUFFIX = "dpwa_tpu/config.py"

# attribute-chain bases that mean "this is the DpwaConfig object"
_CONFIG_BASES = {"config", "cfg", "_config", "_cfg", "dpwa_config"}


def _norm(p: str) -> str:
    return p.replace("\\", "/")


def _ann_name(ann: Optional[ast.expr]) -> Optional[str]:
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split("|")[0].strip()
    return None


class _Schema:
    """Block map extracted from config.py's AST."""

    def __init__(self, src: SourceFile):
        self.src = src
        # block name -> dataclass name (from DpwaConfig's fields)
        self.blocks: Dict[str, str] = {}
        # dataclass name -> {field: def line} (AnnAssign fields only)
        self.fields: Dict[str, Dict[str, int]] = {}
        # dataclass name -> readable non-field names (properties, methods)
        self.readables: Dict[str, Set[str]] = {}
        self.parsed_block_names: Set[str] = set()
        if src.tree is None:
            return
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)
            if (
                isinstance(node, ast.FunctionDef)
                and node.name == "config_from_dict"
            ):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str
                    ):
                        self.parsed_block_names.add(sub.value)

    def _scan_class(self, node: ast.ClassDef) -> None:
        fields: Dict[str, int] = {}
        readable: Set[str] = set()
        for child in node.body:
            if isinstance(child, ast.AnnAssign) and isinstance(
                child.target, ast.Name
            ):
                fields[child.target.id] = child.lineno
                if node.name == "DpwaConfig":
                    cls = _ann_name(child.annotation)
                    if cls and cls.endswith("Config"):
                        self.blocks[child.target.id] = cls
            elif isinstance(child, ast.FunctionDef):
                readable.add(child.name)
        self.fields[node.name] = fields
        self.readables[node.name] = readable


def _doc_text(config_path: str) -> str:
    """README.md + docs/*.md + the config.py schema docstring."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(config_path)))
    chunks: List[str] = []
    for p in [os.path.join(root, "README.md")] + sorted(
        glob.glob(os.path.join(root, "docs", "*.md"))
    ):
        try:
            with open(p, "r", encoding="utf-8") as fh:
                chunks.append(fh.read())
        except OSError:
            pass
    return "\n".join(chunks)


class ConfigKeysChecker:
    name = "config-keys"
    rules = (
        "config-unknown-key",
        "config-undocumented-key",
        "config-unparsed-block",
    )

    def check(self, files: Sequence[SourceFile]) -> List[Finding]:
        config_src = next(
            (
                f for f in files
                if _norm(f.path).endswith(CONFIG_PATH_SUFFIX)
            ),
            None,
        )
        if config_src is None or config_src.tree is None:
            return []
        schema = _Schema(config_src)
        out: List[Finding] = []
        out.extend(self._unparsed_blocks(config_src, schema))
        out.extend(self._undocumented(config_src, schema))
        for src in files:
            if src.tree is None:
                continue
            out.extend(self._unknown_keys(src, schema))
        return out

    # --- config-unparsed-block ---

    def _unparsed_blocks(
        self, src: SourceFile, schema: _Schema
    ) -> List[Finding]:
        out = []
        for block, cls in sorted(schema.blocks.items()):
            if block not in schema.parsed_block_names:
                out.append(Finding(
                    "config-unparsed-block", src.path, 1, block,
                    f"DpwaConfig.{block} ({cls}) is never named in "
                    "config_from_dict — YAML under that block is "
                    "silently dropped",
                ))
        return out

    # --- config-undocumented-key ---

    def _undocumented(
        self, src: SourceFile, schema: _Schema
    ) -> List[Finding]:
        docs = _doc_text(src.path)
        # the module docstring mirrors the YAML schema; it counts too
        docstring = ast.get_docstring(src.tree) or ""
        haystack = docs + "\n" + docstring
        out = []
        for block, cls in sorted(schema.blocks.items()):
            for field, line in sorted(schema.fields.get(cls, {}).items()):
                if not re.search(rf"\b{re.escape(field)}\b", haystack):
                    out.append(Finding(
                        "config-undocumented-key", src.path, line,
                        f"{block}.{field}",
                        f"schema field {block}.{field} appears in no "
                        "operator doc (README.md, docs/*.md, or the "
                        "config.py schema docstring)",
                    ))
        return out

    # --- config-unknown-key ---

    def _unknown_keys(
        self, src: SourceFile, schema: _Schema
    ) -> List[Finding]:
        out = []
        for node in ast.walk(src.tree):
            hit = self._config_chain(node, schema)
            if hit is None:
                continue
            block, field, cls = hit
            known = set(schema.fields.get(cls, {})) | schema.readables.get(
                cls, set()
            )
            if field not in known:
                out.append(Finding(
                    "config-unknown-key", src.path, node.lineno,
                    f"{block}.{field}",
                    f"read of config.{block}.{field} but {cls} has no "
                    f"field/property {field!r} — typo, or add it to the "
                    "schema in dpwa_tpu/config.py",
                ))
        return out

    @staticmethod
    def _config_chain(
        node: ast.AST, schema: _Schema
    ) -> Optional[Tuple[str, str, str]]:
        """Match ``<config-ish base>.<block>.<field>`` -> tuple."""
        if not isinstance(node, ast.Attribute):
            return None
        field = node.attr
        blk = node.value
        if not isinstance(blk, ast.Attribute):
            return None
        block = blk.attr
        if block not in schema.blocks:
            return None
        base = blk.value
        base_name = None
        if isinstance(base, ast.Name):
            base_name = base.id
        elif isinstance(base, ast.Attribute):
            base_name = base.attr
        if base_name not in _CONFIG_BASES:
            return None
        return block, field, schema.blocks[block]
