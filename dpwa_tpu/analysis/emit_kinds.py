"""Emit-kind checker: JSONL emit sites use registered record/event kinds.

This is the former ``tools/lint_emitters.py`` pass folded into the
dpwalint framework (one runner, one suppression grammar, one baseline).
``tools/schema_check.py`` validates JSONL files AFTER a run; this pass
closes the other half of the loop at the SOURCE level — every site a
record can be born must name a kind registered in schema_check:

- dict literals with a ``"record"``/``"event"`` key holding a string
  literal;
- ``record="..."`` / ``event="..."`` keyword arguments in any call;
- ``log_event(step, "<kind>", ...)`` / ``self._event("<kind>", ...)``
  calls, where the first string-literal positional is the kind.

Dynamic kinds (variables, f-strings) are skipped: they are re-emission
plumbing, and the records they forward were checked at their literal
birth site.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from dpwa_tpu.analysis.core import Finding, SourceFile

# call names whose first string-literal positional argument is an event
# kind (self._event("kind", ...), metrics.log_event(step, "kind", ...))
_EVENT_CALLS = ("log_event", "_event")


def _kind_sets():
    # imported lazily so the analysis package never needs tools/ on the
    # path at import time (the runner and tests both arrange it)
    from tools.schema_check import EVENT_KINDS, RECORD_KINDS
    return RECORD_KINDS, EVENT_KINDS


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class EmitKindsChecker:
    name = "emit-kinds"
    rules = ("emit-kind",)

    def check(self, files: Sequence[SourceFile]) -> List[Finding]:
        record_kinds, event_kinds = _kind_sets()
        out: List[Finding] = []

        def check_record(src, node, kind):
            if kind not in record_kinds:
                out.append(Finding(
                    "emit-kind", src.path, node.lineno, f"record:{kind}",
                    f"unregistered record kind {kind!r} (register a "
                    "schema in tools/schema_check.py)",
                ))

        def check_event(src, node, kind):
            if kind not in event_kinds:
                out.append(Finding(
                    "emit-kind", src.path, node.lineno, f"event:{kind}",
                    f"unregistered event kind {kind!r} (add it to "
                    "schema_check.EVENT_KINDS)",
                ))

        for src in files:
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Dict):
                    for key, value in zip(node.keys, node.values):
                        k = _str_const(key) if key is not None else None
                        v = _str_const(value) if value is not None else None
                        if v is None:
                            continue
                        if k == "record":
                            check_record(src, value, v)
                        elif k == "event":
                            check_event(src, value, v)
                elif isinstance(node, ast.Call):
                    for kw in node.keywords:
                        v = _str_const(kw.value)
                        if v is None:
                            continue
                        if kw.arg == "record":
                            check_record(src, kw.value, v)
                        elif kw.arg == "event":
                            check_event(src, kw.value, v)
                    fn = node.func
                    name = fn.attr if isinstance(fn, ast.Attribute) else (
                        fn.id if isinstance(fn, ast.Name) else None
                    )
                    if name in _EVENT_CALLS:
                        for arg in node.args:
                            v = _str_const(arg)
                            if v is not None:
                                check_event(src, arg, v)
                                break  # first string literal is the kind
        return out
