"""dpwalint — the repo's own static-analysis framework.

Seven checkers over one shared core (``tools/dpwalint.py`` is the CLI,
``tests/test_static_checks.py`` the tier-1 gate):

- :mod:`.lock_discipline` — cross-thread ``self._*`` state must be
  locked, ``guarded_by``-annotated, or a registered double-buffer;
- :mod:`.determinism` — decision paths stay replica-identical (no
  ambient randomness / wall-clock branches / dict-order iteration) and
  threefry tags come from :mod:`dpwa_tpu.utils.tags`;
- :mod:`.wire_protocol` — wire magics and struct layouts live only in
  :mod:`dpwa_tpu.parallel.protocol_constants`;
- :mod:`.config_keys` — config reads, the schema, and the docs agree;
- :mod:`.emit_kinds` — JSONL emit sites use registered kinds (the old
  ``tools/lint_emitters.py`` pass, folded in);
- :mod:`.zerocopy` — frame-path modules never copy payload bytes with
  ``.tobytes()``/``bytes(...)`` (the zero-copy hot-path discipline);
- :mod:`.device_roundtrip` — merge-path modules never cross the
  numpy↔JAX seam outside :mod:`dpwa_tpu.device.handoff` (the
  device-resident replica discipline).
"""

from __future__ import annotations

from dpwa_tpu.analysis.config_keys import ConfigKeysChecker
from dpwa_tpu.analysis.core import (
    Finding,
    RunResult,
    SourceFile,
    iter_py_files,
    load_baseline,
    load_files,
    run_checkers,
    save_baseline,
)
from dpwa_tpu.analysis.determinism import DeterminismChecker
from dpwa_tpu.analysis.device_roundtrip import DeviceRoundtripChecker
from dpwa_tpu.analysis.emit_kinds import EmitKindsChecker
from dpwa_tpu.analysis.lock_discipline import LockDisciplineChecker
from dpwa_tpu.analysis.rules import RULE_DESCRIPTIONS, RULE_IDS
from dpwa_tpu.analysis.wire_protocol import WireProtocolChecker
from dpwa_tpu.analysis.zerocopy import ZeroCopyChecker


def all_checkers():
    """Fresh instances of every checker, in reporting order."""
    return [
        LockDisciplineChecker(),
        DeterminismChecker(),
        WireProtocolChecker(),
        ConfigKeysChecker(),
        EmitKindsChecker(),
        ZeroCopyChecker(),
        DeviceRoundtripChecker(),
    ]


__all__ = [
    "Finding",
    "RunResult",
    "SourceFile",
    "RULE_DESCRIPTIONS",
    "RULE_IDS",
    "all_checkers",
    "iter_py_files",
    "load_baseline",
    "load_files",
    "run_checkers",
    "save_baseline",
]
