// Native Rx server for the CPU/TCP gossip path.
//
// The reference's always-on listener is a Python thread (SURVEY.md §3.3:
// accept → read framed request → send latest published blob).  Under
// free-running training that thread competes with the train loop for the
// GIL: every fetch served steals interpreter time from fwd/bwd, and a slow
// fetcher can hold the GIL boundary for the whole send.  This is the same
// loop in C++ — one detached native thread per node, zero GIL interaction;
// the training thread only swaps the publish buffer under a mutex.
//
// Protocol (identical to dpwa_tpu/parallel/tcp.py): request is the 5-byte
// magic "DPWA?"; response is the pre-framed payload Python hands to
// dpwa_server_publish (header + raw vector bytes).  Framing stays in
// Python so there is exactly ONE definition of the wire format.
//
// Exposed C ABI (ctypes, see dpwa_tpu/native/__init__.py):
//   dpwa_server_create(host, port) -> handle (NULL on bind failure)
//   dpwa_server_port(h)            -> bound port (resolves port=0)
//   dpwa_server_publish(h, p, n)   -> swap the served payload
//   dpwa_server_close(h)           -> stop thread, close socket, free

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr char kReq[5] = {'D', 'P', 'W', 'A', '?'};

struct DpwaServer {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::vector<uint8_t> payload;
  bool has_payload = false;
  std::thread thread;
};

bool recv_exact(int fd, uint8_t* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = recv(fd, buf + got, n - got, 0);
    if (r <= 0) return false;  // timeout, error, or peer closed
    got += static_cast<size_t>(r);
  }
  return true;
}

bool send_all(int fd, const uint8_t* buf, size_t n,
              const std::atomic<bool>& stop) {
  // Each send() returns within SO_SNDTIMEO (5 s); checking the stop flag
  // between chunks bounds close() at one timeout even when a peer reads
  // at a trickle (each trickled ACK restarts the timeout, so a multi-MB
  // payload could otherwise hold this loop for minutes).
  size_t sent = 0;
  while (sent < n) {
    if (stop.load(std::memory_order_relaxed)) return false;
    ssize_t r = send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

void serve_loop(DpwaServer* s) {
  pollfd pfd{s->listen_fd, POLLIN, 0};
  while (!s->stop.load(std::memory_order_relaxed)) {
    int rc = poll(&pfd, 1, 200);  // 200 ms stop-check cadence
    if (rc <= 0) continue;
    sockaddr_in addr;
    socklen_t alen = sizeof(addr);
    int conn = accept(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    if (conn < 0) continue;
    timeval tv{5, 0};  // per-connection 5 s timeouts, as the Python server
    setsockopt(conn, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(conn, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    uint8_t req[sizeof(kReq)];
    if (recv_exact(conn, req, sizeof(kReq)) &&
        std::memcmp(req, kReq, sizeof(kReq)) == 0) {
      // Copy under the lock, send outside it: a slow fetcher must never
      // block the training thread's publish.
      std::vector<uint8_t> copy;
      bool has;
      {
        std::lock_guard<std::mutex> g(s->mu);
        has = s->has_payload;
        if (has) copy = s->payload;
      }
      if (has) send_all(conn, copy.data(), copy.size(), s->stop);
    }
    close(conn);
  }
}

}  // namespace

extern "C" {

void* dpwa_server_create(const char* host, int port) {
  // getaddrinfo, not inet_pton: the YAML nodes: list may name hosts (the
  // real multi-machine case) — Python's socket.bind resolves them, and
  // the native server must accept exactly the same hosts.
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  hints.ai_flags = AI_PASSIVE;
  addrinfo* res = nullptr;
  if (getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr) {
    return nullptr;
  }
  sockaddr_in addr{};
  std::memcpy(&addr, res->ai_addr, sizeof(addr));
  addr.sin_port = htons(static_cast<uint16_t>(port));
  freeaddrinfo(res);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 16) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* s = new DpwaServer;
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->thread = std::thread(serve_loop, s);
  return s;
}

int dpwa_server_port(void* h) {
  return static_cast<DpwaServer*>(h)->port;
}

void dpwa_server_publish(void* h, const uint8_t* data, size_t n) {
  auto* s = static_cast<DpwaServer*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  s->payload.assign(data, data + n);
  s->has_payload = true;
}

void dpwa_server_close(void* h) {
  auto* s = static_cast<DpwaServer*>(h);
  s->stop.store(true);
  if (s->thread.joinable()) s->thread.join();
  if (s->listen_fd >= 0) close(s->listen_fd);
  delete s;
}

}  // extern "C"
