// Native runtime kernels for the CPU/TCP gossip path.
//
// The reference's merge is numpy `(1-a)*x + a*remote` (SURVEY.md §3.2 hot
// spots) — three full passes over memory plus two temporaries.  This is the
// single-pass fused form, plus a checksum used by the wire format.  Built
// with -O3 so the compiler vectorizes the axpy loop; no external deps.
//
// Exposed C ABI (loaded via ctypes, see dpwa_tpu/native/__init__.py):
//   dpwa_merge_out(dst, local, remote, alpha, n):  dst = (1-a)*local + a*remote
//   dpwa_merge_inplace(dst, remote, alpha, n):     dst = (1-a)*dst + a*remote
//   dpwa_checksum(data, n):                        FNV-1a over bytes

#include <cstddef>
#include <cstdint>

extern "C" {

void dpwa_merge_out(float* dst, const float* local, const float* remote,
                    float alpha, size_t n) {
  const float beta = 1.0f - alpha;
  for (size_t i = 0; i < n; ++i) {
    dst[i] = beta * local[i] + alpha * remote[i];
  }
}

void dpwa_merge_inplace(float* dst, const float* remote, float alpha,
                        size_t n) {
  const float beta = 1.0f - alpha;
  for (size_t i = 0; i < n; ++i) {
    dst[i] = beta * dst[i] + alpha * remote[i];
  }
}

uint64_t dpwa_checksum(const uint8_t* data, size_t n) {
  uint64_t h = 1469598103934665603ull;  // FNV offset basis
  for (size_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= 1099511628211ull;  // FNV prime
  }
  return h;
}

}  // extern "C"
