// Native runtime kernels for the CPU/TCP gossip path.
//
// The reference's merge is numpy `(1-a)*x + a*remote` (SURVEY.md §3.2 hot
// spots) — three full passes over memory plus two temporaries.  This is the
// single-pass fused form, plus a checksum used by the wire format.  Built
// with -O3 so the compiler vectorizes the axpy loop; no external deps.
//
// Exposed C ABI (loaded via ctypes, see dpwa_tpu/native/__init__.py):
//   dpwa_merge_out(dst, local, remote, alpha, n):  dst = (1-a)*local + a*remote
//   dpwa_merge_inplace(dst, remote, alpha, n):     dst = (1-a)*dst + a*remote
//   dpwa_checksum(data, n):                        FNV-1a over bytes

#include <cstddef>
#include <cstdint>

extern "C" {

void dpwa_merge_out(float* dst, const float* local, const float* remote,
                    float alpha, size_t n) {
  const float beta = 1.0f - alpha;
  for (size_t i = 0; i < n; ++i) {
    dst[i] = beta * local[i] + alpha * remote[i];
  }
}

void dpwa_merge_inplace(float* dst, const float* remote, float alpha,
                        size_t n) {
  const float beta = 1.0f - alpha;
  for (size_t i = 0; i < n; ++i) {
    dst[i] = beta * dst[i] + alpha * remote[i];
  }
}

uint64_t dpwa_checksum(const uint8_t* data, size_t n) {
  uint64_t h = 1469598103934665603ull;  // FNV offset basis
  for (size_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= 1099511628211ull;  // FNV prime
  }
  return h;
}

// int8 stochastic-rounding quantizer (the wire_dtype: int8 codec's hot
// loop — ops/quantize.py).  Per-`chunk` absmax scales; the dither is a
// counter-based splitmix64 of (key, element index), so the result is
// deterministic for a given key, order-independent, and the loop stays a
// single streaming pass (numpy's Generator.random alone costs more than
// the localhost byte saving; this runs at memory bandwidth).
static inline uint64_t dpwa_mix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

void dpwa_quantize_sr(const float* src, size_t n, size_t chunk, int8_t* q,
                      float* scales, uint64_t k0, uint64_t k1) {
  const size_t nchunks = (n + chunk - 1) / chunk;
  const uint64_t key = dpwa_mix64(k0) ^ (k1 * 0xD1B54A32D192ED03ull);
  const float inv24 = 1.0f / 16777216.0f;  // 2^-24: 24-bit uniform [0,1)
  for (size_t c = 0; c < nchunks; ++c) {
    const size_t lo = c * chunk;
    const size_t hi = lo + chunk < n ? lo + chunk : n;
    float m = 0.0f;
    for (size_t i = lo; i < hi; ++i) {
      const float a = src[i] < 0 ? -src[i] : src[i];
      if (a > m) m = a;
    }
    const float s = m / 127.0f;
    scales[c] = s;
    if (s == 0.0f) {
      for (size_t i = lo; i < hi; ++i) q[i] = 0;
      continue;
    }
    const float inv = 1.0f / s;
    // One mix64 feeds TWO elements (24-bit slices of the 64-bit hash —
    // independent uniform dithers): unrolled so the hash, the loop's
    // hot cost, genuinely runs once per pair instead of hoping the
    // optimizer CSEs it across iterations.  Pairing is by GLOBAL index
    // (i>>1), so the dither for element i never depends on its chunk.
    size_t i = lo;
    if (i < hi && (i & 1)) {  // odd leading element: high slice alone
      const uint64_t r = dpwa_mix64(key + (i >> 1));
      const float u = (float)((r >> 24) & 0xFFFFFFull) * inv24;
      float t = __builtin_floorf(src[i] * inv + u);
      if (t > 127.0f) t = 127.0f;
      if (t < -127.0f) t = -127.0f;
      q[i] = (int8_t)t;
      ++i;
    }
    for (; i + 1 < hi; i += 2) {
      const uint64_t r = dpwa_mix64(key + (i >> 1));
      const float u0 = (float)(r & 0xFFFFFFull) * inv24;
      const float u1 = (float)((r >> 24) & 0xFFFFFFull) * inv24;
      float t0 = __builtin_floorf(src[i] * inv + u0);
      float t1 = __builtin_floorf(src[i + 1] * inv + u1);
      if (t0 > 127.0f) t0 = 127.0f;
      if (t0 < -127.0f) t0 = -127.0f;
      if (t1 > 127.0f) t1 = 127.0f;
      if (t1 < -127.0f) t1 = -127.0f;
      q[i] = (int8_t)t0;
      q[i + 1] = (int8_t)t1;
    }
    if (i < hi) {  // even trailing element: low slice alone
      const uint64_t r = dpwa_mix64(key + (i >> 1));
      const float u = (float)(r & 0xFFFFFFull) * inv24;
      float t = __builtin_floorf(src[i] * inv + u);
      if (t > 127.0f) t = 127.0f;
      if (t < -127.0f) t = -127.0f;
      q[i] = (int8_t)t;
    }
  }
}

void dpwa_dequantize(const int8_t* q, const float* scales, size_t n,
                     size_t chunk, float* dst) {
  const size_t nchunks = (n + chunk - 1) / chunk;
  for (size_t c = 0; c < nchunks; ++c) {
    const size_t lo = c * chunk;
    const size_t hi = lo + chunk < n ? lo + chunk : n;
    const float s = scales[c];
    for (size_t i = lo; i < hi; ++i) dst[i] = (float)q[i] * s;
  }
}

}  // extern "C"
