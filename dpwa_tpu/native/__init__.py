"""Native (C++) runtime kernels, loaded via ctypes.

Compiled on first use with the system ``g++`` (no pybind11/pip needed) and
cached beside this module; every entry point has a numpy fallback so the
framework runs unchanged where no toolchain exists.  The reference is pure
Python (SURVEY.md §2 'Native components — none'); this accelerates the
reference-equivalent CPU path — the TPU path's "native layer" is XLA/Pallas.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import sys
import tempfile
import threading
from typing import Optional

import numpy as np

_SRCS = [
    os.path.join(os.path.dirname(__file__), "dpwa_native.cpp"),
    os.path.join(os.path.dirname(__file__), "rx_server.cpp"),
]
_LIB = os.path.join(os.path.dirname(__file__), "_libdpwa_native.so")
_HOSTINFO = _LIB + ".host"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _host_fingerprint() -> str:
    """ISA identity of this machine.

    ``-march=native`` bakes host-specific instructions into the cached
    .so; a copy carried to a different machine (tar/rsync preserves
    mtimes, so the source-staleness check never fires) would dlopen
    cleanly — symbol presence says nothing about ISA — and then SIGILL
    mid-training.  The cpuinfo flags/Features line IS the capability set
    on x86/arm, so (arch, flags) pins exactly what -march=native keyed
    the build on."""
    parts = [platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    parts.append(line.strip())
                    break
    except OSError:
        pass
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def _sidecar_content() -> str:
    """What a valid host record must say: this host's ISA fingerprint
    tied to the EXACT .so bytes (so a freshly rsync'ed foreign .so can't
    ride a stale record, wherever the record lives)."""
    h = hashlib.sha256()
    try:
        with open(_LIB, "rb") as f:
            h.update(f.read())
    except OSError:
        return ""
    return _host_fingerprint() + "|" + h.hexdigest()


def _hostinfo_paths() -> list:
    """Candidate record locations: beside the .so, else a PER-USER
    subdirectory of the tempdir (read-only installs can't write the
    package dir; without a fallback every process would re-pay the
    failed-build + subprocess-smoke sequence at startup, forever).

    The fallback must not live in the world-writable tempdir root: any
    local user could pre-create the record file there and vouch for a
    .so this host never validated (the record is what SKIPS the SIGILL
    smoke test).  ``dpwa_<uid>`` at mode 0700 scopes trust to the user;
    a directory with the wrong owner or group/other access is rejected
    outright rather than trusted."""
    key = hashlib.sha256(_LIB.encode()).hexdigest()[:16]
    paths = [_HOSTINFO]
    user_dir = os.path.join(
        tempfile.gettempdir(), f"dpwa_{os.getuid()}"
    ) if hasattr(os, "getuid") else None
    if user_dir is not None and _own_private_dir(user_dir):
        paths.append(os.path.join(user_dir, f"dpwa_native_{key}.host"))
    return paths


def _own_private_dir(path: str) -> bool:
    """Ensure ``path`` is a directory owned by this uid with no group/
    other permissions, creating it 0700 if absent.  False means the
    location can't be trusted (symlinked, squatted, or loosened by
    another user) and the caller must skip it."""
    try:
        os.makedirs(path, mode=0o700, exist_ok=True)
        # makedirs applies the umask on creation and does nothing on an
        # existing dir — stat, then tighten only if we own it.
        st = os.lstat(path)
        import stat as _stat

        if not _stat.S_ISDIR(st.st_mode) or st.st_uid != os.getuid():
            return False
        if st.st_mode & 0o077:
            os.chmod(path, 0o700)
            st = os.lstat(path)
            if st.st_mode & 0o077:
                return False
        return True
    except OSError:
        return False


def _write_hostinfo() -> None:
    """Record the validated (host, .so) pair at the first writable
    location (atomic, like the .so install itself); best-effort — if
    nowhere is writable the next load just re-validates."""
    content = _sidecar_content()
    if not content:
        return
    for path in _hostinfo_paths():
        tmp = None
        try:
            fd, tmp = tempfile.mkstemp(
                suffix=".host.tmp", dir=os.path.dirname(path)
            )
            with os.fdopen(fd, "w") as f:
                f.write(content)
            os.replace(tmp, path)
            return
        except OSError:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass


def _hostinfo_matches() -> bool:
    want = _sidecar_content()
    if not want:
        return False
    for path in _hostinfo_paths():
        try:
            with open(path) as f:
                if f.read().strip() == want:
                    return True
        except OSError:
            continue
    return False


def _smoke_ok() -> bool:
    """Execute the cached .so's hot loops in a THROWAWAY subprocess.

    Last resort for a foreign .so when no toolchain can rebuild it: if
    the code contains instructions this CPU lacks, the child dies with
    SIGILL and the caller degrades to numpy instead of crashing the
    training process."""
    code = (
        "import ctypes\n"
        f"lib = ctypes.CDLL({_LIB!r})\n"
        "lib.dpwa_checksum.restype = ctypes.c_uint64\n"
        "lib.dpwa_checksum((ctypes.c_uint8 * 8)(*range(8)),"
        " ctypes.c_size_t(8))\n"
        "dst = (ctypes.c_float * 512)()\n"
        "src = (ctypes.c_float * 512)(*([1.5] * 512))\n"
        "lib.dpwa_merge_inplace(dst, src, ctypes.c_float(0.5),"
        " ctypes.c_size_t(512))\n"
        "if hasattr(lib, 'dpwa_quantize_sr'):\n"
        "    q = (ctypes.c_int8 * 512)()\n"
        "    s = (ctypes.c_float * 2)()\n"
        "    lib.dpwa_quantize_sr(src, ctypes.c_size_t(512),"
        " ctypes.c_size_t(256), q, s,"
        " ctypes.c_uint64(1), ctypes.c_uint64(2))\n"
        # rx_server.cpp is a separate translation unit: its loops can use
        # ISA the kernel TU happens to avoid, so a pass must cover it too.
        "if hasattr(lib, 'dpwa_server_create'):\n"
        "    lib.dpwa_server_create.restype = ctypes.c_void_p\n"
        "    h = lib.dpwa_server_create(b'127.0.0.1', 0)\n"
        "    if h:\n"
        "        lib.dpwa_server_port.argtypes = [ctypes.c_void_p]\n"
        "        lib.dpwa_server_port(ctypes.c_void_p(h))\n"
        "        lib.dpwa_server_publish(ctypes.c_void_p(h), b'x' * 64,"
        " ctypes.c_size_t(64))\n"
        "        lib.dpwa_server_close(ctypes.c_void_p(h))\n"
    )
    try:
        return (
            subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                timeout=60,
            ).returncode
            == 0
        )
    except subprocess.SubprocessError:
        return False


def _build() -> bool:
    # Compile to a temp file and os.replace() over _LIB: rename keeps the
    # old inode alive for any mapping already dlopen'ed in this (or another)
    # process — truncating the .so in place risks SIGBUS on unfaulted pages —
    # and gives the path a fresh inode so a re-dlopen actually loads the new
    # code instead of returning the cached mapping.
    # Per-process unique temp name: concurrently launched peers otherwise
    # race g++ on one shared tmp file and can install a truncated .so whose
    # fresh mtime suppresses every future rebuild.
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(
            suffix=".so.tmp", dir=os.path.dirname(_LIB)
        )
        os.close(fd)
        # -march=native is safe here (the .so is built on the machine
        # that runs it, never shipped) and ~1.7x the quantizer via
        # auto-vectorization; retry plain -O3 for toolchains that
        # reject the flag.
        for extra in (["-march=native"], []):
            try:
                subprocess.run(
                    ["g++", "-O3", *extra, "-shared", "-fPIC", "-pthread",
                     "-o", tmp, *_SRCS],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                break
            except subprocess.SubprocessError:
                if not extra:
                    raise
        os.replace(tmp, _LIB)
        _write_hostinfo()
        return True
    except (OSError, subprocess.SubprocessError):
        # Covers an unwritable package dir (mkstemp) the same as a failed
        # compile: callers degrade to the numpy fallback.
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it if necessary; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or any(
            os.path.getmtime(_LIB) < os.path.getmtime(src) for src in _SRCS
        ):
            if not _build():
                return None
        elif not _hostinfo_matches():
            # Fresh-looking .so but no record it was built on THIS host
            # (or the record disagrees): likely carried over from another
            # machine with -march=native ISA baked in.  Rebuild; if no
            # toolchain, prove executability in a sacrificial subprocess
            # before trusting it in-process.
            if _build():
                pass
            elif _smoke_ok():
                _write_hostinfo()
            else:
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        if not hasattr(lib, "dpwa_server_create") or not hasattr(
            lib, "dpwa_quantize_sr"
        ):
            # Stale cached .so predating rx_server.cpp / the quantizer
            # (mtime checks can
            # miss when files arrive via tar/rsync with preserved times):
            # rebuild once.  _build() replaces the path with a fresh inode,
            # so this re-dlopen loads the new code rather than the cached
            # mapping; if the rebuild fails, merge/checksum keep working on
            # the old handle and NativeRxServer reports unavailable
            # (Python server fallback).
            if _build():
                try:
                    lib = ctypes.CDLL(_LIB)
                except OSError:
                    return None
        # Signature setup happens AFTER any rebuild so it is applied to
        # whichever CDLL object is ultimately stored (a handle swapped in by
        # the rebuild would otherwise default dpwa_checksum.restype to c_int,
        # silently truncating the 64-bit FNV).
        lib.dpwa_merge_out.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_float,
            ctypes.c_size_t,
        ]
        lib.dpwa_merge_inplace.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_float,
            ctypes.c_size_t,
        ]
        lib.dpwa_checksum.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]
        lib.dpwa_checksum.restype = ctypes.c_uint64
        if hasattr(lib, "dpwa_quantize_sr"):
            lib.dpwa_quantize_sr.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_size_t,
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_int8),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_uint64,
                ctypes.c_uint64,
            ]
            lib.dpwa_dequantize.argtypes = [
                ctypes.POINTER(ctypes.c_int8),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_size_t,
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_float),
            ]
        if hasattr(lib, "dpwa_server_create"):
            lib.dpwa_server_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.dpwa_server_create.restype = ctypes.c_void_p
            lib.dpwa_server_port.argtypes = [ctypes.c_void_p]
            lib.dpwa_server_port.restype = ctypes.c_int
            # c_char_p: the C side only READS the payload, so the
            # immutable bytes object passes zero-copy (no per-publish
            # ctypes buffer).
            lib.dpwa_server_publish.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.dpwa_server_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class NativeRxServer:
    """ctypes handle to the C++ Rx server (rx_server.cpp).

    Same observable behavior as the Python ``PeerServer`` thread — serves
    the latest pre-framed payload to any peer sending the request magic —
    but the serve loop is a native thread that never touches the GIL.
    Construction raises if the native library (or the bind) is
    unavailable; callers fall back to the Python server."""

    def __init__(self, host: str, port: int):
        lib = load()
        if lib is None or not hasattr(lib, "dpwa_server_create"):
            raise RuntimeError("native Rx server unavailable")
        self._lib = lib
        self._handle = lib.dpwa_server_create(host.encode(), int(port))
        if not self._handle:
            raise RuntimeError(f"native Rx server failed to bind {host}:{port}")
        self.port = int(lib.dpwa_server_port(self._handle))

    def publish_framed(self, payload: bytes) -> None:
        if not self._handle:
            return  # after close(): harmless no-op, like the Python server
        self._lib.dpwa_server_publish(self._handle, payload, len(payload))

    def close(self) -> None:
        if self._handle:
            self._lib.dpwa_server_close(self._handle)
            self._handle = None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def merge_out(
    local: np.ndarray, remote: np.ndarray, alpha: float
) -> np.ndarray:
    """``(1-alpha)*local + alpha*remote`` — native single pass when
    possible, numpy otherwise.  float32 contiguous fast path."""
    lib = load()
    if (
        lib is not None
        and local.dtype == np.float32
        and remote.dtype == np.float32
        and local.flags.c_contiguous
        and remote.flags.c_contiguous
    ):
        dst = np.empty_like(local)
        lib.dpwa_merge_out(
            _fptr(dst), _fptr(local), _fptr(remote),
            ctypes.c_float(alpha), dst.size,
        )
        return dst
    return ((1.0 - alpha) * local.astype(np.float32)
            + alpha * remote.astype(np.float32)).astype(local.dtype)


def checksum(data: bytes) -> int:
    """FNV-1a of a byte string (wire-format integrity); pure-python
    fallback matches bit-for-bit."""
    lib = load()
    if lib is not None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        return int(lib.dpwa_checksum(buf, len(data)))
    h = 1469598103934665603
    for b in data:
        h = ((h ^ b) * 1099511628211) % (1 << 64)
    return h


def quantize_sr(
    vec: np.ndarray, chunk: int, k0: int, k1: int
):
    """int8 stochastic-rounding quantize (ops/quantize.py's codec hot
    loop) — native single pass; returns None if the library is
    unavailable (caller uses the numpy path).

    Dither is counter-based splitmix64 of (key, index): deterministic
    for a key, unbiased, and fast enough that the int8 wire's codec cost
    no longer eats its byte saving on cheap fabrics."""
    lib = load()
    if (
        lib is None
        or not hasattr(lib, "dpwa_quantize_sr")
        or vec.dtype != np.float32
        or not vec.flags.c_contiguous
    ):
        return None
    n = vec.size
    if n == 0:
        # The C kernel writes nothing for n=0 while the numpy path emits
        # one zero scale — return the numpy-contract result directly
        # (np.empty would hand back uninitialized heap as the scale).
        return np.empty(0, np.int8), np.zeros(1, np.float32)
    nchunks = -(-n // chunk)
    q = np.empty(n, np.int8)
    scales = np.empty(nchunks, np.float32)
    lib.dpwa_quantize_sr(
        _fptr(vec),
        n,
        chunk,
        q.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        _fptr(scales),
        ctypes.c_uint64(k0 & 0xFFFFFFFFFFFFFFFF),
        ctypes.c_uint64(k1 & 0xFFFFFFFFFFFFFFFF),
    )
    return q, scales


def dequantize(q: np.ndarray, scales: np.ndarray, chunk: int):
    """int8 -> f32 decode, one native pass; None if unavailable."""
    lib = load()
    if (
        lib is None
        or not hasattr(lib, "dpwa_dequantize")
        or q.dtype != np.int8
        or not q.flags.c_contiguous
        or scales.dtype != np.float32
        or not scales.flags.c_contiguous
        # A short scales array would be an out-of-bounds read in C;
        # fall back to numpy, which raises a proper shape error.
        or scales.size * chunk < q.size
    ):
        return None
    if q.size == 0:
        return np.empty(0, np.float32)
    dst = np.empty(q.size, np.float32)
    lib.dpwa_dequantize(
        q.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        _fptr(scales),
        q.size,
        chunk,
        _fptr(dst),
    )
    return dst
