"""Native (C++) runtime kernels, loaded via ctypes.

Compiled on first use with the system ``g++`` (no pybind11/pip needed) and
cached beside this module; every entry point has a numpy fallback so the
framework runs unchanged where no toolchain exists.  The reference is pure
Python (SURVEY.md §2 'Native components — none'); this accelerates the
reference-equivalent CPU path — the TPU path's "native layer" is XLA/Pallas.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "dpwa_native.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "_libdpwa_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it if necessary; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or os.path.getmtime(
            _LIB
        ) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.dpwa_merge_out.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_float,
            ctypes.c_size_t,
        ]
        lib.dpwa_merge_inplace.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_float,
            ctypes.c_size_t,
        ]
        lib.dpwa_checksum.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]
        lib.dpwa_checksum.restype = ctypes.c_uint64
        _lib = lib
        return _lib


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def merge_out(
    local: np.ndarray, remote: np.ndarray, alpha: float
) -> np.ndarray:
    """``(1-alpha)*local + alpha*remote`` — native single pass when
    possible, numpy otherwise.  float32 contiguous fast path."""
    lib = load()
    if (
        lib is not None
        and local.dtype == np.float32
        and remote.dtype == np.float32
        and local.flags.c_contiguous
        and remote.flags.c_contiguous
    ):
        dst = np.empty_like(local)
        lib.dpwa_merge_out(
            _fptr(dst), _fptr(local), _fptr(remote),
            ctypes.c_float(alpha), dst.size,
        )
        return dst
    return ((1.0 - alpha) * local.astype(np.float32)
            + alpha * remote.astype(np.float32)).astype(local.dtype)


def checksum(data: bytes) -> int:
    """FNV-1a of a byte string (wire-format integrity); pure-python
    fallback matches bit-for-bit."""
    lib = load()
    if lib is not None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        return int(lib.dpwa_checksum(buf, len(data)))
    h = 1469598103934665603
    for b in data:
        h = ((h ^ b) * 1099511628211) % (1 << 64)
    return h
