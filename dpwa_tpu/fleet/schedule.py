"""Deterministic churn schedules: WHO joins/leaves/restarts WHEN.

A churn schedule is a pure function of ``(seed, round, peer)`` threefry
draws (:mod:`dpwa_tpu.parallel.schedules` — tags registered in
:mod:`dpwa_tpu.utils.tags`), so a fixed seed replays the identical
elasticity episode bit-for-bit, the same counter-based-RNG discipline
every other control decision in this repo follows.  Four event families
(docs/fleet.md has the grammar):

- **leaves** — each live peer independently departs with
  ``leave_probability`` per round (``churn_leave_draw``), floored so the
  fleet never shrinks below ``min_live``;
- **joins** — each departed peer independently returns with
  ``join_probability`` per round (``churn_join_draw``);
- **cohorts** — every ``cohort_every`` rounds an autoscale-style batch
  arrival admits up to ``cohort_max`` departed peers at once
  (``churn_cohort_draw`` sizes the batch);
- **restarts** — every ``restart_every`` rounds one live peer is
  rolling-restarted (leave + rejoin in the same round, state restored
  from a donor — ``churn_restart_draw`` picks the victim).

Plus **chaos windows**: round intervals ``[start, stop)`` during which
named fault classes (``partition`` / ``byzantine`` / ``straggler``,
concurrently — the *mixed* windows ROADMAP asks for) are active.  The
schedule only names the active classes; the orchestrator maps them onto
:class:`~dpwa_tpu.health.chaos.ChaosEngine` draws.

The draws are keyed on ``(seed, round, peer)`` alone — NOT on the
evolving live set — so event decisions for any peer can be replayed
without replaying the whole episode; the live/departed sets merely
select which draws are consulted.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

from dpwa_tpu.parallel.schedules import (
    churn_cohort_draw,
    churn_join_draw,
    churn_leave_draw,
    churn_restart_draw,
)


@dataclasses.dataclass(frozen=True)
class ChaosWindow:
    """Rounds ``[start, stop)`` with the named fault classes active.

    ``kinds`` ⊆ {"partition", "byzantine", "straggler"}; ``group`` is
    the partition's minority side (peer ids) when "partition" is in
    ``kinds`` — explicit, so a test can assert exactly which links were
    cut."""

    start: int
    stop: int
    kinds: Tuple[str, ...]
    group: Tuple[int, ...] = ()

    def active(self, round_: int) -> bool:
        return self.start <= round_ < self.stop


_KNOWN_KINDS = frozenset({"partition", "byzantine", "straggler"})


@dataclasses.dataclass(frozen=True)
class ChurnSpec:
    """The schedule's knobs (one YAML-able block; docs/fleet.md)."""

    seed: int = 0
    leave_probability: float = 0.0
    join_probability: float = 0.0
    cohort_every: int = 0  # 0 = no cohort arrivals
    cohort_max: int = 0
    restart_every: int = 0  # 0 = no rolling restarts
    min_live: int = 2
    protected: Tuple[int, ...] = (0,)  # never churned (the observer)
    chaos_windows: Tuple[ChaosWindow, ...] = ()

    def __post_init__(self) -> None:
        if not 0.0 <= self.leave_probability <= 1.0:
            raise ValueError(
                f"leave_probability must be in [0, 1], "
                f"got {self.leave_probability}"
            )
        if not 0.0 <= self.join_probability <= 1.0:
            raise ValueError(
                f"join_probability must be in [0, 1], "
                f"got {self.join_probability}"
            )
        if self.min_live < 1:
            raise ValueError(f"min_live must be >= 1, got {self.min_live}")
        for w in self.chaos_windows:
            unknown = set(w.kinds) - _KNOWN_KINDS
            if unknown:
                raise ValueError(
                    f"unknown chaos window kinds {sorted(unknown)}; "
                    f"known: {sorted(_KNOWN_KINDS)}"
                )
            if "partition" in w.kinds and not w.group:
                raise ValueError(
                    "a partition chaos window needs an explicit group"
                )


@dataclasses.dataclass(frozen=True)
class ChurnEvents:
    """One round's resolved churn (every field already sorted)."""

    round: int
    leaves: Tuple[int, ...]
    joins: Tuple[int, ...]
    cohort: Tuple[int, ...]
    restart: Tuple[int, ...]  # () or (peer,)
    chaos: Tuple[str, ...]  # active fault classes, sorted

    @property
    def quiet(self) -> bool:
        return not (
            self.leaves or self.joins or self.cohort or self.restart
            or self.chaos
        )


class ChurnSchedule:
    """Resolve :class:`ChurnSpec` draws against a live/departed split."""

    def __init__(self, spec: ChurnSpec, n_peers: int):
        self.spec = spec
        self.n_peers = int(n_peers)

    def partition_group(self, round_: int) -> Tuple[int, ...]:
        """The minority side of the partition active at ``round_``
        (empty when none is)."""
        for w in self.spec.chaos_windows:
            if w.active(round_) and "partition" in w.kinds:
                return tuple(sorted(w.group))
        return ()

    def events(
        self,
        round_: int,
        live: Sequence[int],
        departed: Sequence[int],
    ) -> ChurnEvents:
        """This round's churn given the CURRENT live/departed split.

        Deterministic: iteration is over sorted peer ids and every
        decision is a threefry draw keyed on ``(seed, round, peer)``."""
        spec = self.spec
        protected = set(spec.protected)
        live_sorted = sorted(live)
        departed_sorted = sorted(departed)

        leaves = []
        if spec.leave_probability > 0.0:
            # The min_live floor caps departures in peer-id order, so
            # the cap itself is deterministic too.
            allowed = max(0, len(live_sorted) - spec.min_live)
            for p in live_sorted:
                if p in protected or allowed <= 0:
                    continue
                if (
                    float(churn_leave_draw(spec.seed, round_, p))
                    < spec.leave_probability
                ):
                    leaves.append(p)
                    allowed -= 1

        joins = []
        if spec.join_probability > 0.0:
            for p in departed_sorted:
                if (
                    float(churn_join_draw(spec.seed, round_, p))
                    < spec.join_probability
                ):
                    joins.append(p)

        cohort = []
        if (
            spec.cohort_every > 0
            and round_ > 0
            and round_ % spec.cohort_every == 0
        ):
            pool = [p for p in departed_sorted if p not in joins]
            n_max = min(spec.cohort_max, len(pool))
            k = churn_cohort_draw(spec.seed, round_, n_max)
            cohort = pool[:k]

        restart = []
        if (
            spec.restart_every > 0
            and round_ > 0
            and round_ % spec.restart_every == 0
        ):
            candidates = [
                p
                for p in live_sorted
                if p not in protected and p not in leaves
            ]
            if candidates:
                idx = churn_restart_draw(spec.seed, round_, len(candidates))
                restart = [candidates[idx]]

        chaos = sorted(
            {
                k
                for w in spec.chaos_windows
                if w.active(round_)
                for k in w.kinds
            }
        )
        return ChurnEvents(
            round=int(round_),
            leaves=tuple(leaves),
            joins=tuple(joins),
            cohort=tuple(cohort),
            restart=tuple(restart),
            chaos=tuple(chaos),
        )
