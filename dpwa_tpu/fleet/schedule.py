"""Deterministic churn schedules: WHO joins/leaves/restarts WHEN.

A churn schedule is a pure function of ``(seed, round, peer)`` threefry
draws (:mod:`dpwa_tpu.parallel.schedules` — tags registered in
:mod:`dpwa_tpu.utils.tags`), so a fixed seed replays the identical
elasticity episode bit-for-bit, the same counter-based-RNG discipline
every other control decision in this repo follows.  Four event families
(docs/fleet.md has the grammar):

- **leaves** — each live peer independently departs with
  ``leave_probability`` per round (``churn_leave_draw``), floored so the
  fleet never shrinks below ``min_live``;
- **joins** — each departed peer independently returns with
  ``join_probability`` per round (``churn_join_draw``);
- **cohorts** — every ``cohort_every`` rounds an autoscale-style batch
  arrival admits up to ``cohort_max`` departed peers at once
  (``churn_cohort_draw`` sizes the batch);
- **restarts** — every ``restart_every`` rounds one live peer is
  rolling-restarted (leave + rejoin in the same round, state restored
  from a donor — ``churn_restart_draw`` picks the victim).

Hierarchical fleets (a :class:`~dpwa_tpu.hier.topology.Topology` handed
to :class:`ChurnSchedule`) add two island-granular families
(docs/hierarchy.md):

- **island churn** — every ``island_churn_every`` rounds each island
  draws ``island_churn_draw``; under ``island_churn_probability`` the
  WHOLE island toggles (live → leaves as one cohort, fully-departed →
  rejoins as one), modeling a rack/pod power event rather than
  uncorrelated peer exits;
- **leader restarts** — every ``leader_restart_every`` rounds the
  rotation lands on the next island; the schedule names the ISLAND only
  (``leader_restart_islands``) because who its leader is at that round
  is the orchestrator's live :class:`LeaderBoard` state, not a pure
  function of the seed.

Plus **chaos windows**: round intervals ``[start, stop)`` during which
named fault classes (``partition`` / ``byzantine`` / ``straggler``,
concurrently — the *mixed* windows ROADMAP asks for) are active.  The
schedule only names the active classes; the orchestrator maps them onto
:class:`~dpwa_tpu.health.chaos.ChaosEngine` draws.

The draws are keyed on ``(seed, round, peer)`` alone — NOT on the
evolving live set — so event decisions for any peer can be replayed
without replaying the whole episode; the live/departed sets merely
select which draws are consulted.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

from dpwa_tpu.parallel.schedules import (
    churn_cohort_draw,
    churn_join_draw,
    churn_leave_draw,
    churn_restart_draw,
    island_churn_draw,
)


@dataclasses.dataclass(frozen=True)
class ChaosWindow:
    """Rounds ``[start, stop)`` with the named fault classes active.

    ``kinds`` ⊆ {"partition", "byzantine", "straggler"}; ``group`` is
    the partition's minority side (peer ids) when "partition" is in
    ``kinds`` — explicit, so a test can assert exactly which links were
    cut."""

    start: int
    stop: int
    kinds: Tuple[str, ...]
    group: Tuple[int, ...] = ()

    def active(self, round_: int) -> bool:
        return self.start <= round_ < self.stop


_KNOWN_KINDS = frozenset({"partition", "byzantine", "straggler"})


@dataclasses.dataclass(frozen=True)
class ChurnSpec:
    """The schedule's knobs (one YAML-able block; docs/fleet.md)."""

    seed: int = 0
    leave_probability: float = 0.0
    join_probability: float = 0.0
    cohort_every: int = 0  # 0 = no cohort arrivals
    cohort_max: int = 0
    restart_every: int = 0  # 0 = no rolling restarts
    min_live: int = 2
    protected: Tuple[int, ...] = (0,)  # never churned (the observer)
    chaos_windows: Tuple[ChaosWindow, ...] = ()
    # Island-granular churn (needs a Topology on the ChurnSchedule).
    island_churn_every: int = 0  # 0 = no whole-island churn
    island_churn_probability: float = 0.5
    leader_restart_every: int = 0  # 0 = no rolling leader restarts

    def __post_init__(self) -> None:
        if not 0.0 <= self.island_churn_probability <= 1.0:
            raise ValueError(
                f"island_churn_probability must be in [0, 1], "
                f"got {self.island_churn_probability}"
            )
        if not 0.0 <= self.leave_probability <= 1.0:
            raise ValueError(
                f"leave_probability must be in [0, 1], "
                f"got {self.leave_probability}"
            )
        if not 0.0 <= self.join_probability <= 1.0:
            raise ValueError(
                f"join_probability must be in [0, 1], "
                f"got {self.join_probability}"
            )
        if self.min_live < 1:
            raise ValueError(f"min_live must be >= 1, got {self.min_live}")
        for w in self.chaos_windows:
            unknown = set(w.kinds) - _KNOWN_KINDS
            if unknown:
                raise ValueError(
                    f"unknown chaos window kinds {sorted(unknown)}; "
                    f"known: {sorted(_KNOWN_KINDS)}"
                )
            if "partition" in w.kinds and not w.group:
                raise ValueError(
                    "a partition chaos window needs an explicit group"
                )


@dataclasses.dataclass(frozen=True)
class ChurnEvents:
    """One round's resolved churn (every field already sorted)."""

    round: int
    leaves: Tuple[int, ...]
    joins: Tuple[int, ...]
    cohort: Tuple[int, ...]
    restart: Tuple[int, ...]  # () or (peer,)
    chaos: Tuple[str, ...]  # active fault classes, sorted
    # Hierarchical families (empty on flat fleets — the flat record
    # stream stays byte-identical, docs/hierarchy.md).
    island_leaves: Tuple[int, ...] = ()  # peers, whole islands at once
    island_joins: Tuple[int, ...] = ()
    churned_islands: Tuple[int, ...] = ()  # island indices this round
    leader_restart_islands: Tuple[int, ...] = ()  # rotation targets

    @property
    def quiet(self) -> bool:
        return not (
            self.leaves or self.joins or self.cohort or self.restart
            or self.chaos or self.island_leaves or self.island_joins
            or self.leader_restart_islands
        )


class ChurnSchedule:
    """Resolve :class:`ChurnSpec` draws against a live/departed split.

    ``topology`` (a :class:`~dpwa_tpu.hier.topology.Topology`) arms the
    island-granular families; None keeps the flat families only."""

    def __init__(self, spec: ChurnSpec, n_peers: int, topology=None):
        self.spec = spec
        self.n_peers = int(n_peers)
        self.topology = topology
        if topology is None and (
            spec.island_churn_every > 0 or spec.leader_restart_every > 0
        ):
            raise ValueError(
                "island_churn_every / leader_restart_every need a"
                " topology on the ChurnSchedule"
            )

    def partition_group(self, round_: int) -> Tuple[int, ...]:
        """The minority side of the partition active at ``round_``
        (empty when none is)."""
        for w in self.spec.chaos_windows:
            if w.active(round_) and "partition" in w.kinds:
                return tuple(sorted(w.group))
        return ()

    def events(
        self,
        round_: int,
        live: Sequence[int],
        departed: Sequence[int],
    ) -> ChurnEvents:
        """This round's churn given the CURRENT live/departed split.

        Deterministic: iteration is over sorted peer ids and every
        decision is a threefry draw keyed on ``(seed, round, peer)``."""
        spec = self.spec
        protected = set(spec.protected)
        live_sorted = sorted(live)
        departed_sorted = sorted(departed)

        leaves = []
        if spec.leave_probability > 0.0:
            # The min_live floor caps departures in peer-id order, so
            # the cap itself is deterministic too.
            allowed = max(0, len(live_sorted) - spec.min_live)
            for p in live_sorted:
                if p in protected or allowed <= 0:
                    continue
                if (
                    float(churn_leave_draw(spec.seed, round_, p))
                    < spec.leave_probability
                ):
                    leaves.append(p)
                    allowed -= 1

        joins = []
        if spec.join_probability > 0.0:
            for p in departed_sorted:
                if (
                    float(churn_join_draw(spec.seed, round_, p))
                    < spec.join_probability
                ):
                    joins.append(p)

        cohort = []
        if (
            spec.cohort_every > 0
            and round_ > 0
            and round_ % spec.cohort_every == 0
        ):
            pool = [p for p in departed_sorted if p not in joins]
            n_max = min(spec.cohort_max, len(pool))
            k = churn_cohort_draw(spec.seed, round_, n_max)
            cohort = pool[:k]

        restart = []
        if (
            spec.restart_every > 0
            and round_ > 0
            and round_ % spec.restart_every == 0
        ):
            candidates = [
                p
                for p in live_sorted
                if p not in protected and p not in leaves
            ]
            if candidates:
                idx = churn_restart_draw(spec.seed, round_, len(candidates))
                restart = [candidates[idx]]

        island_leaves: list = []
        island_joins: list = []
        churned_islands: list = []
        topo = self.topology
        if (
            topo is not None
            and spec.island_churn_every > 0
            and round_ > 0
            and round_ % spec.island_churn_every == 0
        ):
            live_set = set(live_sorted) - set(leaves)
            departed_set = set(departed_sorted) | set(leaves)
            taken = set(leaves) | set(joins) | set(cohort) | set(restart)
            for g in range(topo.n_islands):
                members = topo.members_of(g)
                if protected & set(members) or taken & set(members):
                    continue
                draw = float(island_churn_draw(spec.seed, round_, g))
                if draw >= spec.island_churn_probability:
                    continue
                live_members = [p for p in members if p in live_set]
                if live_members:
                    # Whole-island power event, floored like leaves.
                    remaining = len(live_set) - len(live_members)
                    if remaining < spec.min_live:
                        continue
                    island_leaves.extend(live_members)
                    live_set -= set(live_members)
                    churned_islands.append(g)
                elif all(p in departed_set for p in members):
                    island_joins.extend(members)
                    churned_islands.append(g)

        leader_restart_islands: list = []
        if (
            topo is not None
            and spec.leader_restart_every > 0
            and round_ > 0
            and round_ % spec.leader_restart_every == 0
        ):
            # Rolling rotation over islands; the orchestrator resolves
            # the island's CURRENT leader (LeaderBoard state) and skips
            # islands whose leader is protected or already churned.
            g = (round_ // spec.leader_restart_every - 1) % topo.n_islands
            if g not in churned_islands:
                leader_restart_islands.append(g)

        chaos = sorted(
            {
                k
                for w in spec.chaos_windows
                if w.active(round_)
                for k in w.kinds
            }
        )
        return ChurnEvents(
            round=int(round_),
            leaves=tuple(leaves),
            joins=tuple(joins),
            cohort=tuple(cohort),
            restart=tuple(restart),
            chaos=tuple(chaos),
            island_leaves=tuple(sorted(island_leaves)),
            island_joins=tuple(sorted(island_joins)),
            churned_islands=tuple(sorted(churned_islands)),
            leader_restart_islands=tuple(leader_restart_islands),
        )
