"""Plane-level elastic-fleet simulator: churn the CONTROL planes at 256.

``tests/fleet_worker.py`` proved one Rx server can feed a 256-peer ring;
this module proves the *decision planes* survive 256 peers CHURNING.  It
deliberately simulates the wire (an exchange is a numpy average + an
Outcome string) while running the REAL control-plane objects per node —
:class:`~dpwa_tpu.health.scoreboard.Scoreboard`,
:class:`~dpwa_tpu.membership.manager.MembershipManager` (real digests
through ``encode``/``merge``), and the observer's
:class:`~dpwa_tpu.obs.incidents.IncidentPlane` — because those are where
the O(N)-forever assumptions lived (ROADMAP "Elastic fleet churn").  256
full TCP transports would measure socket limits; this measures the
eviction/readmission/digest machinery that PR 11 hardens.

Single-threaded by construction: one loop drives every node in sorted
peer order, every control decision is a threefry draw keyed on round
counters (:mod:`dpwa_tpu.fleet.schedule`), and wall time is only ever
*reported* (``wall_s``) — never consulted — so the churn record stream
is bit-identical across reruns of a seed.

Emits the frozen-schema ``fleet`` JSONL stream (tools/schema_check.py):

- ``kind: churn`` — one per non-quiet round; deterministic fields only
  (the bit-identity anchor tests replay);
- ``kind: round`` — one per round; adds measured fields (``wall_s``,
  ``rel_rms``) that vary run to run;
- ``kind: episode`` — one per run; convergence + incident summary
  (``tools/fleet_report.py`` joins it with trace/incident streams).
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dpwa_tpu.config import (
    ChaosConfig,
    HealthConfig,
    ObsConfig,
    MembershipConfig,
)
from dpwa_tpu.fleet.schedule import ChurnSchedule, ChurnSpec
from dpwa_tpu.health.chaos import ChaosEngine
from dpwa_tpu.hier.leader import LeaderBoard
from dpwa_tpu.hier.topology import Topology
from dpwa_tpu.health.detector import Outcome
from dpwa_tpu.health.scoreboard import Scoreboard
from dpwa_tpu.membership.manager import MembershipManager
from dpwa_tpu.obs.incidents import IncidentPlane
from dpwa_tpu.parallel.schedules import Schedule, _ring_pull
from dpwa_tpu.recovery.bootstrap import choose_donor


class SimNode:
    """One fleet member: a numpy replica plus its real control planes.

    ``boot`` builds FRESH Scoreboard/MembershipManager instances — a
    rejoiner has no memory of its past life except the monotonically
    bumped incarnation (which is what lets it refute stale DEAD claims,
    docs/membership.md)."""

    def __init__(
        self,
        peer: int,
        n_peers: int,
        seed: int,
        topology: Optional[Topology] = None,
    ):
        self.peer = int(peer)
        self.n_peers = int(n_peers)
        self.seed = int(seed)
        self.topology = topology
        self.alive = False
        self.boots = 0
        self.next_incarnation = 0
        self.vec: Optional[np.ndarray] = None
        self.board: Optional[Scoreboard] = None
        self.membership: Optional[MembershipManager] = None

    def boot(
        self,
        vec: np.ndarray,
        health: HealthConfig,
        member: MembershipConfig,
    ) -> None:
        self.board = Scoreboard(
            self.n_peers, self.peer, config=health, seed=self.seed
        )
        # With a topology the node's manager owns a per-node LeaderBoard
        # (built inside MembershipManager) and speaks the v2 digest:
        # every node converges on leadership through gossip, the way the
        # live transport does — the orchestrator's own board is just the
        # ground-truth copy the schedule resolves restarts against.
        self.membership = MembershipManager(
            self.n_peers,
            self.peer,
            self.board,
            config=member,
            seed=self.seed,
            topology=self.topology,
        )
        self.membership.incarnation = self.next_incarnation
        self.next_incarnation += 1
        self.vec = np.array(vec, dtype=np.float64, copy=True)
        self.alive = True
        self.boots += 1

    def stop(self) -> None:
        """Departure: the process is gone.  The replica is kept frozen
        (a restarting supervisor may resurrect the box) but the control
        planes are dropped — a rejoiner gets fresh ones."""
        self.alive = False
        self.board = None
        self.membership = None


@dataclasses.dataclass
class EpisodeResult:
    """What :meth:`FleetOrchestrator.run` hands back (and logs)."""

    records: List[dict]
    episode: dict

    @property
    def churn_records(self) -> List[dict]:
        return [r for r in self.records if r.get("kind") == "churn"]


class FleetOrchestrator:
    """Drive one elastic-churn episode over ``n_peers`` simulated nodes.

    The observer (``spec.protected[0]``, default peer 0) is never
    churned; its scoreboard/membership/incident planes are the ones the
    episode summary reads — one stable vantage point, the way a soak's
    operator watches one node's /healthz."""

    def __init__(
        self,
        n_peers: int,
        spec: ChurnSpec,
        dim: int = 32,
        health: Optional[HealthConfig] = None,
        membership: Optional[MembershipConfig] = None,
        chaos: Optional[ChaosConfig] = None,
        incidents: Optional[ObsConfig] = None,
        path: Optional[str] = None,
        initial_live: Optional[int] = None,
        topology: Optional[Topology] = None,
    ):
        self.n_peers = int(n_peers)
        self.spec = spec
        if topology is not None and topology.n_peers != self.n_peers:
            raise ValueError(
                f"topology covers {topology.n_peers} peers, fleet has"
                f" {self.n_peers}"
            )
        self.topology = topology
        self.seed = int(spec.seed)
        self.dim = int(dim)
        self.health = health if health is not None else HealthConfig()
        self.membership_cfg = (
            membership if membership is not None else MembershipConfig()
        )
        # Fault DRAW probabilities for chaos windows; the window's kind
        # list gates which draws take effect (schedule.py).
        self.chaos_cfg = (
            chaos
            if chaos is not None
            else ChaosConfig(
                enabled=True,
                seed=self.seed,
                delay_probability=0.5,
                throttle_probability=0.25,
                byzantine_sign_probability=0.3,
                byzantine_scale_probability=0.2,
                byzantine_zero_probability=0.1,
            )
        )
        self.schedule = ChurnSchedule(spec, self.n_peers, topology=topology)
        # Ground-truth leadership view the orchestrator itself maintains
        # (resolves leader restarts, stamps island records); per-node
        # boards live inside each SimNode's MembershipManager and
        # converge on this through v2 digests.
        self.leader_board = (
            LeaderBoard(topology, seed=self.seed)
            if topology is not None
            else None
        )
        self._board_events: List[dict] = (
            list(self.leader_board.initial_events())
            if self.leader_board is not None
            else []
        )
        self.observer = spec.protected[0] if spec.protected else 0
        self._path = path
        self._file = (
            open(path, "a", encoding="utf-8") if path else None
        )
        self.records: List[dict] = []
        # One engine per SERVING peer: fault draws are (seed, round,
        # server)-keyed, exactly like the wire chaos harness.
        self._engines = [
            ChaosEngine(self.chaos_cfg, peer=p)
            for p in range(self.n_peers)
        ]
        # Gossip pairing: the one-sided pull ring the TCP transport uses
        # (remap_partner gives the health-aware fallback).
        self._sched = Schedule(
            pool=np.stack(
                [_ring_pull(self.n_peers, 0), _ring_pull(self.n_peers, 1)]
            ),
            n_peers=self.n_peers,
            fetch_probability=1.0,
            seed=self.seed,
            name="ring",
            mode="pull",
        )
        self.nodes = [
            SimNode(p, self.n_peers, self.seed, topology=topology)
            for p in range(self.n_peers)
        ]
        n_live = (
            self.n_peers if initial_live is None else int(initial_live)
        )
        for p in range(n_live):
            self.nodes[p].boot(
                self._init_vec(p), self.health, self.membership_cfg
            )
        inc_cfg = incidents
        if inc_cfg is None:
            inc_cfg = ObsConfig()
        self.incidents = IncidentPlane(
            self.observer, self.n_peers, inc_cfg, path=None,
            topology=topology,
        )
        # Convergence bookkeeping: (event round, peer) -> resolved round.
        self._leave_pending: Dict[int, int] = {}  # peer -> left round
        self._join_pending: Dict[int, int] = {}  # peer -> joined round
        self._leave_convergence: List[int] = []
        self._join_convergence: List[int] = []

    # ------------------------------------------------------------------
    # Node lifecycle
    # ------------------------------------------------------------------

    def _init_vec(self, peer: int) -> np.ndarray:
        """Deterministic per-peer initial replica (seeded, no wall
        clock): distinct vectors so rel_rms measures real convergence."""
        rng = np.random.default_rng([self.seed, peer])
        return rng.standard_normal(self.dim)

    def _live(self) -> List[int]:
        return [n.peer for n in self.nodes if n.alive]

    def _departed(self) -> List[int]:
        return [n.peer for n in self.nodes if not n.alive]

    def _donor_vec(self, joiner: int, round_: int) -> np.ndarray:
        """Bootstrap the joiner's replica from a deterministically
        elected live donor (the PR 2 donor draw), falling back to the
        joiner's frozen/initial replica when nobody can serve."""
        healthy = [n.alive for n in self.nodes]
        donor = choose_donor(
            joiner, self.n_peers, round_, self.seed, healthy
        )
        if donor is not None and self.nodes[donor].vec is not None:
            return self.nodes[donor].vec
        node = self.nodes[joiner]
        if node.vec is not None:
            return node.vec
        return self._init_vec(joiner)

    def _boot_peer(self, peer: int, round_: int) -> None:
        self.nodes[peer].boot(
            self._donor_vec(peer, round_),
            self.health,
            self.membership_cfg,
        )
        # A rejoin before ring-wide eviction cancels the pending leave:
        # there is no ghost left to evict, so the departure is no longer
        # a convergence event (it would otherwise sit "unresolved"
        # forever and poison the episode summary).
        self._leave_pending.pop(peer, None)
        self._join_pending.setdefault(peer, int(round_))
        if self.leader_board is not None:
            self._board_events.extend(self.leader_board.note_alive(peer))

    def _stop_peer(self, peer: int, round_: int) -> None:
        self.nodes[peer].stop()
        self._leave_pending.setdefault(peer, int(round_))
        self._join_pending.pop(peer, None)
        if self.leader_board is not None:
            # Leader deaths bump the island's term and draw a successor
            # — the ground-truth copy of what each node's board does
            # once its scoreboard notices (docs/hierarchy.md).
            self._board_events.extend(self.leader_board.note_dead(peer))

    # ------------------------------------------------------------------
    # One gossip exchange (plane-level wire)
    # ------------------------------------------------------------------

    def _blocked(
        self, src: int, dst: int, group: Tuple[int, ...]
    ) -> bool:
        """Whether the active partition window cuts the src<->dst link
        (links inside either side stay up)."""
        if not group:
            return False
        return (src in group) != (dst in group)

    def _fetch_outcome(
        self,
        fetcher: SimNode,
        target: int,
        round_: int,
        chaos_kinds: Tuple[str, ...],
        group: Tuple[int, ...],
    ) -> str:
        """Classify one fetch the way the transport's wire path would."""
        if self._blocked(fetcher.peer, target, group):
            return Outcome.TIMEOUT
        node = self.nodes[target]
        if not node.alive:
            return Outcome.TIMEOUT
        if chaos_kinds:
            plan = self._engines[target].plan(round_)
            if "byzantine" in chaos_kinds and plan.byzantine != "none":
                # The trust plane screens the lying frame: classified
                # poisoned, payload discarded (docs/trust.md).
                return Outcome.POISONED
            if "straggler" in chaos_kinds and (
                plan.kind in ("delay", "throttle") or plan.stall_s > 0.0
            ):
                return Outcome.SLOW
        return Outcome.SUCCESS

    # ------------------------------------------------------------------
    # The round loop
    # ------------------------------------------------------------------

    def run(self, rounds: int) -> EpisodeResult:
        outcome_totals: Dict[str, int] = {}
        max_digest = 0
        max_wall = 0.0
        alerts_total: Dict[str, int] = {}
        incidents_opened = 0
        for r in range(int(rounds)):
            t0 = time.perf_counter()
            ev = self.schedule.events(r, self._live(), self._departed())
            group = self.schedule.partition_group(r)
            # -- churn application ------------------------------------
            for p in ev.leaves:
                self._stop_peer(p, r)
            for p in ev.joins:
                self._boot_peer(p, r)
            for p in ev.cohort:
                self._boot_peer(p, r)
            for p in ev.restart:
                # Rolling restart: down and back within the round, state
                # restored through the donor path (the supervisor's
                # crash->bootstrap cycle compressed to one round).
                self._stop_peer(p, r)
                self._boot_peer(p, r)
            # Island-granular families (hier fleets only; empty tuples
            # on flat fleets keep this a no-op).
            for p in ev.island_leaves:
                self._stop_peer(p, r)
            for p in ev.island_joins:
                self._boot_peer(p, r)
            leader_restarts: List[int] = []
            for g in ev.leader_restart_islands:
                # The schedule names the ISLAND; the orchestrator's
                # ground-truth board resolves who its leader is NOW.
                leader = self.leader_board.leader_of(g)
                if (
                    leader is None
                    or leader in self.spec.protected
                    or not self.nodes[leader].alive
                ):
                    continue
                self._stop_peer(leader, r)
                self._boot_peer(leader, r)
                leader_restarts.append(leader)
            live = self._live()
            # -- gossip exchanges -------------------------------------
            digests: Dict[int, bytes] = {}
            exchanges = 0
            failures = 0
            obs_outcome: Optional[str] = None
            obs_partner: Optional[int] = None
            round_outcomes: Dict[str, int] = {}
            use_view = self.membership_cfg.view.enabled
            for f in sorted(live):
                node = self.nodes[f]
                partner = self._sched.partner(r, f)
                if partner != f and node.board.is_quarantined(
                    partner, r
                ):
                    if use_view:
                        # Bounded remap (membership.view): the fallback
                        # draw ranges over the node's active view and an
                        # O(active) healthy map — never an O(N) mask.
                        cands = node.membership.partner_candidates()
                        partner = self._sched.remap_partner(
                            r, f, partner,
                            node.board.healthy_map(cands, r), cands,
                        )
                    else:
                        partner = self._sched.remap_partner(
                            r, f, partner, node.board.healthy_mask(r)
                        )
                if partner == f:
                    continue
                outcome = self._fetch_outcome(
                    node, partner, r, ev.chaos, group
                )
                latency = 0.05 if outcome == Outcome.SLOW else 0.005
                node.board.record(
                    partner, outcome, latency_s=latency, round=r
                )
                round_outcomes[outcome] = (
                    round_outcomes.get(outcome, 0) + 1
                )
                if outcome in (Outcome.SUCCESS, Outcome.SLOW):
                    exchanges += 1
                    node.vec = 0.5 * (
                        node.vec + self.nodes[partner].vec
                    )
                    blob = digests.get(partner)
                    if blob is None:
                        blob = digests[partner] = self.nodes[
                            partner
                        ].membership.encode(r)
                        max_digest = max(max_digest, len(blob))
                    node.membership.merge(blob, r)
                else:
                    failures += 1
                if f == self.observer:
                    obs_outcome = outcome
                    obs_partner = partner
            # -- probes (readmission + evicted-ghost reprobe) ---------
            for f in sorted(live):
                node = self.nodes[f]
                # O(quarantined + tombstones) walk: probe_candidates()
                # returns exactly the peers probe_due() would flag, so
                # this stays byte-identical to the full range(N) scan
                # while making 4096-peer rounds affordable.
                for q in node.board.probe_candidates(r):
                    if q == f:
                        continue
                    ok = self.nodes[q].alive and not self._blocked(
                        f, q, group
                    )
                    node.board.record_probe(q, ok, round=r)
            # -- membership round end ---------------------------------
            for f in sorted(live):
                self.nodes[f].membership.end_round(r)
            # -- observer planes --------------------------------------
            obs = self.nodes[self.observer]
            obs_events: List[dict] = []
            for f in sorted(live):
                events = self.nodes[f].membership.pop_events()
                if f == self.observer:
                    obs_events = events
            if self._board_events:
                # Leadership events from this round's churn (elections,
                # failover successions) reach the observer alongside its
                # own membership events — the incident plane classifies
                # leader_failover as a root cause (docs/incidents.md).
                obs_events = obs_events + self._board_events
                self._board_events = []
            rel_rms = self._rel_rms(live)
            wall = time.perf_counter() - t0
            max_wall = max(max_wall, wall)
            view = obs.membership.view_snapshot()
            inc = self.incidents.observe_round(
                r,
                outcome=obs_outcome,
                peer=obs_partner,
                board=obs.board.snapshot(r),
                events=obs_events,
                rel_rms=rel_rms,
                wall_s=wall,
                partition_state=view.get("partition_state"),
                component=view.get("component"),
            )
            for kind in inc["alerts"]:
                alerts_total[kind] = alerts_total.get(kind, 0) + 1
            if inc["opened"]:
                incidents_opened += 1
            for k, v in sorted(round_outcomes.items()):
                outcome_totals[k] = outcome_totals.get(k, 0) + v
            self._settle_convergence(r)
            # -- records ----------------------------------------------
            evicted = obs.board.evicted_peers()
            if not ev.quiet:
                churn_rec = {
                    "record": "fleet",
                    "kind": "churn",
                    "round": r,
                    "leaves": list(ev.leaves),
                    "joins": list(ev.joins),
                    "cohort": list(ev.cohort),
                    "restart": list(ev.restart),
                    "chaos": list(ev.chaos),
                    "live": len(live),
                    "evicted": evicted,
                }
                if self.topology is not None:
                    # Hier-only optional fields — a flat fleet's churn
                    # stream stays byte-identical to pre-hierarchy runs.
                    churn_rec["island_leaves"] = list(ev.island_leaves)
                    churn_rec["island_joins"] = list(ev.island_joins)
                    churn_rec["churned_islands"] = list(
                        ev.churned_islands
                    )
                    churn_rec["leader_restarts"] = leader_restarts
                self._emit(churn_rec)
            if self.topology is not None:
                for g in range(self.topology.n_islands):
                    members = self.topology.members_of(g)
                    live_m = [p for p in members if self.nodes[p].alive]
                    island_rec = {
                        "record": "island",
                        "round": r,
                        "island": self.topology.island_name(g),
                        "term": self.leader_board.term_of(g),
                        "live": len(live_m),
                        "rel_rms": round(self._rel_rms(live_m), 9),
                    }
                    leader = self.leader_board.leader_of(g)
                    if leader is not None:
                        island_rec["leader"] = int(leader)
                    self._emit(island_rec)
            self._emit(
                {
                    "record": "fleet",
                    "kind": "round",
                    "round": r,
                    "live": len(live),
                    "exchanges": exchanges,
                    "failures": failures,
                    "outcomes": dict(sorted(round_outcomes.items())),
                    "rel_rms": round(rel_rms, 9),
                    "wall_s": round(wall, 6),
                    "digest_bytes": max_digest,
                    "evicted": len(evicted),
                    "alerts": inc["alerts"],
                }
            )
        episode = self._finish(int(rounds), outcome_totals, max_digest,
                               max_wall, alerts_total, incidents_opened)
        return EpisodeResult(records=self.records, episode=episode)

    # ------------------------------------------------------------------
    # Summaries
    # ------------------------------------------------------------------

    def _rel_rms(self, live: Sequence[int]) -> float:
        """Relative RMS disagreement of live replicas (the sketch
        board's convergence figure, computed exactly here)."""
        if len(live) < 2:
            return 0.0
        vecs = np.stack([self.nodes[p].vec for p in sorted(live)])
        mean = vecs.mean(axis=0)
        num = float(np.sqrt(np.mean((vecs - mean) ** 2)))
        den = float(np.sqrt(np.mean(mean**2))) + 1e-12
        return num / den

    def residency_snapshot(self, peer: int) -> dict:
        """Resident per-peer control-plane state for one live node.

        Returns entry counts and an approximate resident byte figure
        (``sys.getsizeof`` sums over the per-peer containers) for the
        scoreboard and membership planes — the quantity the fleet bench
        leg records per node to prove the ``membership.view``
        ``state_cap`` bound holds at 4096 (docs/membership.md).  The
        byte figure is an approximation, but a consistent one across N,
        which is all an O(sample)-vs-O(N) verdict needs.
        """
        node = self.nodes[peer]
        board, member = node.board, node.membership
        if board is None or member is None:
            return {"peer": peer, "alive": False}
        board_maps = [
            board._state, board._quarantine_streak, board._quarantines,
            board._degrades, board._probe_attempts, board._last_contact,
            board._evicted, board.detector._peers,
        ]
        member_maps: list = [member._view, member._evicted, member._capped]
        part = member.partial
        if part is not None:
            member_maps.extend([part.active, part.passive, part._last_touch])
        nbytes = 0
        for m in board_maps + member_maps:
            nbytes += sys.getsizeof(m)
            if isinstance(m, dict):
                for v in m.values():
                    nbytes += sys.getsizeof(v)
        snap = {
            "peer": peer,
            "alive": True,
            "board_tracked": len(board.tracked_peers()),
            "board_tombstones": len(board._evicted),
            "member_tracked": len(member._view),
            "member_capped": len(member._capped),
            "digest_entries": member._digest_entries_last,
            "resident_bytes": nbytes,
        }
        if part is not None:
            snap["view_active"] = len(part.active)
            snap["view_passive"] = len(part.passive)
        return snap

    def _settle_convergence(self, r: int) -> None:
        """Resolve pending leave/join events against the OBSERVER's
        view: a leave converges when the observer evicts the ghost, a
        join when the observer's mask admits the rejoiner."""
        obs = self.nodes[self.observer]
        if obs.board is None:
            return
        evicted = set(obs.board.evicted_peers())
        mask = obs.board.healthy_mask(r)
        for p in sorted(self._leave_pending):
            if p in evicted:
                self._leave_convergence.append(r - self._leave_pending[p])
                del self._leave_pending[p]
        for p in sorted(self._join_pending):
            if self.nodes[p].alive and p < len(mask) and mask[p]:
                self._join_convergence.append(r - self._join_pending[p])
                del self._join_pending[p]

    def _finish(
        self,
        rounds: int,
        outcome_totals: Dict[str, int],
        max_digest: int,
        max_wall: float,
        alerts_total: Dict[str, int],
        incidents_opened: int,
    ) -> dict:
        live = self._live()
        obs = self.nodes[self.observer]
        episode = {
            "record": "fleet",
            "kind": "episode",
            "rounds": rounds,
            "n_peers": self.n_peers,
            "seed": self.seed,
            "final_live": len(live),
            "final_rel_rms": round(self._rel_rms(live), 9),
            "outcomes": dict(sorted(outcome_totals.items())),
            "max_digest_bytes": max_digest,
            "max_wall_s": round(max_wall, 6),
            "evicted": obs.board.evicted_peers(),
            "leave_convergence_rounds": sorted(self._leave_convergence),
            "join_convergence_rounds": sorted(self._join_convergence),
            "unresolved_leaves": sorted(self._leave_pending),
            "unresolved_joins": sorted(self._join_pending),
            "alerts": dict(sorted(alerts_total.items())),
            "incidents_opened": incidents_opened,
        }
        if self.membership_cfg.view.enabled:
            # View-only optional fields (legacy episodes byte-identical):
            # worst-case residency across live nodes — the O(state_cap)
            # figures the fleet bench gate rides on (docs/membership.md).
            res = [self.residency_snapshot(p) for p in live]
            episode["view_max_resident_bytes"] = max(
                (s["resident_bytes"] for s in res), default=0
            )
            episode["view_max_tracked"] = max(
                (max(s["board_tracked"], s["member_tracked"]) for s in res),
                default=0,
            )
            episode["view_max_digest_entries"] = max(
                (s["digest_entries"] for s in res), default=0
            )
        if self.topology is not None:
            # Hier-only optional fields (flat episodes byte-identical).
            episode["islands"] = self.topology.n_islands
            episode["leader_terms"] = {
                self.topology.island_name(g): self.leader_board.term_of(g)
                for g in range(self.topology.n_islands)
            }
        self._emit(episode)
        if self._file is not None:
            self._file.close()
            self._file = None
        return episode

    def _emit(self, rec: dict) -> None:
        self.records.append(rec)
        if self._file is not None:
            self._file.write(json.dumps(rec, sort_keys=True) + "\n")
            self._file.flush()
