"""Elastic-fleet churn orchestration (docs/fleet.md).

Deterministic churn schedules (:mod:`~dpwa_tpu.fleet.schedule`) driven
over real per-node control planes (:mod:`~dpwa_tpu.fleet.orchestrator`):
continuous joins/leaves, autoscale cohort arrivals, rolling restarts,
and mixed partition+byzantine+straggler chaos windows, emitting the
frozen-schema ``fleet`` JSONL stream that ``tools/fleet_report.py``
digests."""

from dpwa_tpu.fleet.orchestrator import (  # noqa: F401
    EpisodeResult,
    FleetOrchestrator,
    SimNode,
)
from dpwa_tpu.fleet.schedule import (  # noqa: F401
    ChaosWindow,
    ChurnEvents,
    ChurnSchedule,
    ChurnSpec,
)

__all__ = [
    "ChaosWindow",
    "ChurnEvents",
    "ChurnSchedule",
    "ChurnSpec",
    "EpisodeResult",
    "FleetOrchestrator",
    "SimNode",
]
