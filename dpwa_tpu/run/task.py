"""Training tasks for the chaos-certified harness (docs/training.md).

A :class:`TrainTask` bundles what one gossip node needs to train for
real: a dataset (offline — this box has zero network egress), a pure
``loss_fn(params, x, y)``, and a seeded ``init``.  Three registered
tasks cover the BASELINE.json regimes the harness certifies on CPU:

- ``digits`` — the MNIST-class image task: :class:`SmallNet` on
  sklearn's bundled 8×8 digits (the offline stand-in the repo's test
  suite already trains).
- ``blobs`` — a logistic-regression head on Gaussian blobs; converges
  in tens of steps, so the tier-1 legs stay fast.
- ``lora`` — the LoRA-style adapter-only exchange: a FROZEN random
  feature backbone (never gossiped, the 25M-param stand-in) with a
  trainable low-rank head ``A @ B`` of ~100K params.  Only the adapter
  pytree rides the wire, so every frame is ~400 KB — the small-frame
  regime the zero-copy ring's sub-megabyte classes serve.

Init is a function of the SEED only, so every peer cold-starts on the
same replica (pairwise averaging assumes one consensus trajectory, not
an ensemble).  Per-peer data order comes from the harness's threefry
draw (:func:`dpwa_tpu.parallel.schedules.data_shuffle_draw`), never from
the task.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Tuple

import numpy as np

PyTree = Any
Array = np.ndarray


@dataclasses.dataclass(frozen=True)
class TrainTask:
    """One trainable workload: dataset + pure loss + seeded init.

    ``d`` is the number of EXCHANGED floats (the gossip frame size in
    f32 elements) — for adapter-only tasks this is far below the full
    model's parameter count."""

    name: str
    dataset: str
    x_train: Array
    y_train: Array
    x_test: Array
    y_test: Array
    init: Callable[[int], PyTree]
    loss_fn: Callable[[PyTree, Array, Array], Any]
    d: int


def _cross_entropy(logits, y):
    import jax.numpy as jnp

    logp = logits - jnp.max(logits, axis=-1, keepdims=True)
    logp = logp - jnp.log(jnp.sum(jnp.exp(logp), axis=-1, keepdims=True))
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))


def _param_count(params: PyTree) -> int:
    from dpwa_tpu.utils.pytree import ravel

    return int(np.asarray(ravel(params)[0]).size)


def digits_task(seed: int = 0) -> TrainTask:
    """MNIST-class image classification: SmallNet on 8×8 digits."""
    import jax

    from dpwa_tpu.data import load_digits_dataset
    from dpwa_tpu.models.mnist import SmallNet

    x_tr, y_tr, x_te, y_te = load_digits_dataset(seed=seed)
    model = SmallNet()

    def init(s: int) -> PyTree:
        return model.init(jax.random.key(s), x_tr[:1])["params"]

    def loss_fn(params, x, y):
        return _cross_entropy(model.apply({"params": params}, x), y)

    return TrainTask(
        name="digits",
        dataset="digits",
        x_train=x_tr,
        y_train=y_tr,
        x_test=x_te,
        y_test=y_te,
        init=init,
        loss_fn=loss_fn,
        d=_param_count(init(seed)),
    )


def blobs_task(
    seed: int = 0, n_classes: int = 4, dim: int = 16, n_per_class: int = 256
) -> TrainTask:
    """Fast logistic-regression task for tier-1 legs (converges in tens
    of steps on CPU; d = dim*classes + classes)."""
    import jax
    import jax.numpy as jnp

    from dpwa_tpu.data import gaussian_blobs

    x, y = gaussian_blobs(
        n_classes=n_classes, dim=dim, n_per_class=n_per_class, seed=seed
    )
    n_test = len(x) // 5
    x_tr, y_tr, x_te, y_te = x[n_test:], y[n_test:], x[:n_test], y[:n_test]

    def init(s: int) -> PyTree:
        k = jax.random.key(s)
        return {
            "w": 0.01 * jax.random.normal(k, (dim, n_classes), jnp.float32),
            "b": jnp.zeros((n_classes,), jnp.float32),
        }

    def loss_fn(params, xb, yb):
        return _cross_entropy(xb @ params["w"] + params["b"], yb)

    return TrainTask(
        name="blobs",
        dataset="blobs",
        x_train=x_tr,
        y_train=y_tr,
        x_test=x_te,
        y_test=y_te,
        init=init,
        loss_fn=loss_fn,
        d=dim * n_classes + n_classes,
    )


# LoRA-leg geometry: a frozen feature lift to ``hidden`` dims stands in
# for the full backbone, and the trainable low-rank head A[h,r] @ B[r,c]
# (+ bias) is the ONLY pytree the adapter gossips — d = h*r + r*c + c.
# rank 190 at 512×16 lands on 100,336 exchanged floats ≈ 392 KiB/frame,
# the d≈100K small-frame regime of the Llama-LoRA BASELINE config.
LORA_HIDDEN = 512
LORA_RANK = 190
LORA_CLASSES = 16
LORA_INPUT_DIM = 64


def lora_task(seed: int = 0, n_per_class: int = 128) -> TrainTask:
    """Adapter-only exchange: frozen random-feature backbone + trainable
    low-rank head.  Only the head (~100K floats) is gossiped."""
    import jax
    import jax.numpy as jnp

    from dpwa_tpu.data import gaussian_blobs

    x, y = gaussian_blobs(
        n_classes=LORA_CLASSES,
        dim=LORA_INPUT_DIM,
        n_per_class=n_per_class,
        seed=seed,
    )
    n_test = len(x) // 5
    x_tr, y_tr, x_te, y_te = x[n_test:], y[n_test:], x[:n_test], y[:n_test]
    # The backbone is a function of the seed alone: every peer (and a
    # crash-restarted rejoiner) reconstructs the identical frozen lift,
    # so it never has to ride a frame or a checkpoint.
    backbone = jax.random.normal(
        jax.random.key(seed + 1), (LORA_INPUT_DIM, LORA_HIDDEN), jnp.float32
    ) / np.sqrt(LORA_INPUT_DIM)

    def init(s: int) -> PyTree:
        k = jax.random.key(s)
        ka, _ = jax.random.split(k)
        return {
            "a": 0.01 * jax.random.normal(
                ka, (LORA_HIDDEN, LORA_RANK), jnp.float32
            ),
            # B starts at zero (the standard LoRA init): the head's
            # initial output is exactly zero, so all early signal flows
            # through the gradient, not a random projection.
            "b": jnp.zeros((LORA_RANK, LORA_CLASSES), jnp.float32),
            "bias": jnp.zeros((LORA_CLASSES,), jnp.float32),
        }

    def loss_fn(params, xb, yb):
        feats = jnp.tanh(xb @ backbone)
        logits = feats @ (params["a"] @ params["b"]) + params["bias"]
        return _cross_entropy(logits, yb)

    return TrainTask(
        name="lora",
        dataset="blobs16",
        x_train=x_tr,
        y_train=y_tr,
        x_test=x_te,
        y_test=y_te,
        init=init,
        loss_fn=loss_fn,
        d=LORA_HIDDEN * LORA_RANK + LORA_RANK * LORA_CLASSES + LORA_CLASSES,
    )


_TASKS = {
    "digits": digits_task,
    "blobs": blobs_task,
    "lora": lora_task,
}


def make_task(name: str, seed: int = 0) -> TrainTask:
    """Build a registered task (``digits`` / ``blobs`` / ``lora``)."""
    if name not in _TASKS:
        raise ValueError(
            f"unknown task {name!r}; registered: {sorted(_TASKS)}"
        )
    return _TASKS[name](seed=seed)


def make_train_step(
    task: TrainTask, lr: float, momentum: float = 0.0
) -> Tuple[Any, Callable]:
    """A jitted SGD step for ``task``: returns ``(optimizer, step_fn)``
    where ``step_fn(params, opt_state, x, y) -> (params, opt_state,
    loss)``.  One compilation serves every node — all replicas share
    shapes.  The step itself is :func:`dpwa_tpu.train.make_host_train_
    step` — the same definition the examples' ``--certify`` arms use."""
    import optax

    from dpwa_tpu.train import make_host_train_step

    tx = optax.sgd(lr, momentum=momentum if momentum > 0.0 else None)
    return tx, make_host_train_step(task.loss_fn, tx)
