"""Per-node training loop + in-process lock-step driver (docs/training.md).

One :class:`TrainNode` is a real gossip worker: a jitted SGD step on its
own data shard, then one ``DpwaTcpAdapter.update`` — guard, rollback,
exchange over the real TCP wire (hier × shard × topk composed, async on
or off), trust screening, obs — per optimizer step.  The node emits the
frozen-schema ``run`` / ``loss`` JSONL records (tools/schema_check.py)
that ``tools/run_report.py`` joins with the obs/incident planes.

Determinism is structural, not best-effort:

- **data order** is a threefry draw
  (:func:`~dpwa_tpu.parallel.schedules.data_shuffle_draw`) keyed on
  ``(seed, epoch, node)`` — a pure function of the step, with no RNG
  stream to checkpoint and nothing for a crash to lose;
- **time stamps** on harness records come from a :class:`VirtualClock`
  (one tick per round), so a seeded rerun's loss JSONL is
  **byte-identical**, not merely statistically equal;
- **replica trajectory** is pinned by the transport's own seeded
  draws (schedules, chaos, trust) under the lock-step round loop.

Checkpointing (``run.checkpoint_every``) writes a :class:`RunState`
through :func:`dpwa_tpu.checkpoint.save_checkpoint`; a restarted worker
restores the newest structurally-valid one
(:func:`~dpwa_tpu.checkpoint.restore_latest_valid`) and THEN refines via
the PR 2 peer STATE transfer — disk gives a warm local start, the wire
gives the cohort's current consensus."""

from __future__ import annotations

import os
import shutil
import time
from typing import Any, Callable, NamedTuple, Optional, Tuple

import numpy as np

from dpwa_tpu.config import DpwaConfig
from dpwa_tpu.metrics import MetricsLogger
from dpwa_tpu.run.task import TrainTask, make_train_step

PyTree = Any

# Loss-curve smoothing for time-to-quality verdicts: per-step minibatch
# loss is noisy at batch 32; the EWMA is what crosses ``target_loss``.
EWMA_BETA = 0.2


class VirtualClock:
    """Deterministic time source for harness records: one tick per
    round.  Not wall time — exists so seeded reruns stamp identical
    ``t`` fields and the loss JSONL diffs byte-for-byte."""

    def __init__(self, dt: float = 1.0):
        self.t = 0.0
        self.dt = float(dt)

    def now(self) -> float:
        return self.t

    def tick(self) -> None:
        self.t += self.dt


class RunState(NamedTuple):
    """Checkpointed per-node training state (Orbax, via
    dpwa_tpu/checkpoint.py).  ``step`` doubles as the data-order cursor:
    the threefry shuffle makes the batch sequence a pure function of it,
    so no data-stream sidecar is needed."""

    params: PyTree
    opt_state: PyTree
    step: Any
    clock: Any
    loss: Any


def epoch_perm(seed: int, epoch: int, me: int, n: int) -> np.ndarray:
    """This node's shard permutation for ``epoch`` (threefry; pure)."""
    from dpwa_tpu.parallel.schedules import data_shuffle_draw

    return data_shuffle_draw(seed, epoch, me, n)


def batch_for_step(
    n_shard: int, batch_size: int, step: int
) -> Tuple[int, int, int]:
    """Map a global step to ``(epoch, lo, hi)`` positions within the
    epoch permutation.  Pure arithmetic — a rejoiner at step k replays
    node k's exact data order from its step alone."""
    per_epoch = max(1, n_shard // batch_size)
    epoch, pos = divmod(int(step), per_epoch)
    lo = pos * batch_size
    return epoch, lo, min(lo + batch_size, n_shard)


def _checkpoint_candidates(ckpt_dir: str) -> list:
    """Oldest→newest checkpoint paths under ``ckpt_dir``."""
    if not os.path.isdir(ckpt_dir):
        return []
    names = sorted(
        n for n in os.listdir(ckpt_dir)
        if n.startswith("ckpt-") and not n.endswith(".json")
    )
    return [os.path.join(ckpt_dir, n) for n in names]


def _state_like(params: PyTree, opt_state: PyTree) -> RunState:
    return RunState(
        params=params,
        opt_state=opt_state,
        step=np.asarray(0),
        clock=np.asarray(0.0),
        loss=np.asarray(0.0),
    )


def save_node_checkpoint(
    ckpt_dir: str,
    params: PyTree,
    opt_state: PyTree,
    step: int,
    clock: float,
    loss: float,
    keep: int = 3,
) -> str:
    """Write ``ckpt_dir/ckpt-<step>`` and prune to the newest ``keep``."""
    from dpwa_tpu.checkpoint import save_checkpoint

    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"ckpt-{int(step):08d}")
    save_checkpoint(
        path,
        RunState(
            params=params,
            opt_state=opt_state,
            step=np.asarray(int(step)),
            clock=np.asarray(float(clock)),
            loss=np.asarray(float(loss)),
        ),
    )
    stale = _checkpoint_candidates(ckpt_dir)[: -max(1, int(keep))]
    for old in stale:
        shutil.rmtree(old, ignore_errors=True)
        for sidecar in (old + "-meta.json", old + "-data.json"):
            if os.path.exists(sidecar):
                os.remove(sidecar)
    return path


def restore_node_checkpoint(
    ckpt_dir: str, params: PyTree, opt_state: PyTree
):
    """Restore the newest valid checkpoint, or ``None`` when nothing
    survives (cold start / pure peer bootstrap).  Corrupt newest
    checkpoints fall back to older ones — the satellite acceptance."""
    from dpwa_tpu.checkpoint import restore_latest_valid

    paths = _checkpoint_candidates(ckpt_dir)
    if not paths:
        return None
    try:
        return restore_latest_valid(
            paths, like=_state_like(params, opt_state)
        )
    except FileNotFoundError:
        return None


def _outcome_str(outcome: Any) -> Optional[str]:
    if outcome is None:
        return None
    value = getattr(outcome, "value", outcome)
    return str(value)


class TrainNode:
    """One training node over the real stack (or solo when
    ``gossip=False`` — the single-process SGD control arm)."""

    def __init__(
        self,
        me: int,
        n_peers: int,
        config: DpwaConfig,
        task: TrainTask,
        workdir: str,
        leg: str,
        *,
        gossip: bool = True,
        train_step: Optional[Callable] = None,
        tx: Any = None,
        bootstrap: Optional[bool] = None,
        restore: bool = False,
    ):
        from dpwa_tpu.data import peer_split

        self.me = int(me)
        self.n_peers = int(n_peers)
        self.config = config
        self.task = task
        self.leg = leg
        run = config.run
        self.run_cfg = run
        seed = config.protocol.seed
        self.seed = seed
        xs, ys = peer_split(task.x_train, task.y_train, n_peers, seed=seed)
        self.shard_x, self.shard_y = xs[self.me], ys[self.me]
        if tx is None or train_step is None:
            tx, train_step = make_train_step(task, run.lr, run.momentum)
        self.train_step = train_step
        self.params = task.init(seed)
        self.opt_state = tx.init(self.params)
        self.ckpt_dir = (
            os.path.join(run.checkpoint_dir, f"node{self.me}")
            if run.checkpoint_dir
            else None
        )
        self.restored_step = 0
        if restore and self.ckpt_dir:
            state = restore_node_checkpoint(
                self.ckpt_dir, self.params, self.opt_state
            )
            if state is not None:
                self.params = state.params
                self.opt_state = state.opt_state
                self.restored_step = int(np.asarray(state.step))
        os.makedirs(workdir, exist_ok=True)
        self.metrics = MetricsLogger(
            path=os.path.join(workdir, f"node{self.me}.jsonl")
        )
        self.adapter = None
        if gossip:
            from dpwa_tpu.adapters.tcp_adapter import DpwaTcpAdapter

            # The adapter gets its OWN events file: bootstrap / rollback /
            # trust / membership events carry wall-clock stamps, and the
            # harness's node{me}.jsonl must stay byte-identical across
            # seeded reruns.
            self.adapter = DpwaTcpAdapter(
                self.params,
                f"node{self.me}",
                config,
                metrics=os.path.join(workdir, f"node{self.me}.events.jsonl"),
                bootstrap=bootstrap,
                state_extra=lambda: {"leg": self.leg},
            )
            self.params = self.adapter.params
            if self.adapter.last_bootstrap is not None:
                # Landing on the donor's step: keep the checkpoint's
                # optimizer state (momentum is node-local) but take the
                # cohort's replica and schedule position.
                self._solo_step = int(self.adapter.step)
            else:
                # Cold or checkpoint-only start: hand the restored step
                # to the adapter so the schedule resumes where the
                # checkpoint left off.
                self.adapter._step = self.restored_step
                self.adapter._clock = float(self.restored_step)
                self._solo_step = self.restored_step
        else:
            self._solo_step = self.restored_step
        self._perm_epoch = -1
        self._perm: Optional[np.ndarray] = None
        self.ewma: Optional[float] = None
        self.best_loss: Optional[float] = None
        self.final_loss: Optional[float] = None
        self.steps_to_target: Optional[int] = None
        self.time_to_target_s: Optional[float] = None
        self.wall_s = 0.0
        self.epoch = 0

    # ------------------------------------------------------------------

    @property
    def step(self) -> int:
        return self.adapter.step if self.adapter is not None else self._solo_step

    def log_start(self, vt: Optional[VirtualClock] = None) -> None:
        run = self.run_cfg
        fields = {
            "model": self.task.name,
            "dataset": self.task.dataset,
            "d": int(self.task.d),
            "steps": int(run.steps),
            "batch_size": int(run.batch_size),
            "lr": float(run.lr),
            "target_loss": float(run.target_loss),
            "async_rounds": bool(self.config.protocol.async_rounds.enabled),
            "rx_server": str(self.config.protocol.rx_server),
        }
        if self.restored_step:
            fields["checkpoint_restored_step"] = self.restored_step
        self.metrics.log_run(
            self.step, self.me, self.leg, "start",
            peers=self.n_peers, seed=self.seed,
            _t=vt.now() if vt is not None else None, **fields,
        )

    def log_crashed(self, vt: Optional[VirtualClock] = None) -> None:
        """Record the PREDECESSOR incarnation's death (a SIGKILL'd
        process writes nothing; its replacement writes the obituary)."""
        self.metrics.log_run(
            self.restored_step, self.me, self.leg, "crashed",
            peers=self.n_peers, seed=self.seed,
            _t=vt.now() if vt is not None else None,
        )

    def _batch(self, step: int) -> Tuple[np.ndarray, np.ndarray]:
        epoch, lo, hi = batch_for_step(
            len(self.shard_x), self.run_cfg.batch_size, step
        )
        if epoch != self._perm_epoch:
            self._perm = epoch_perm(
                self.seed, epoch, self.me, len(self.shard_x)
            )
            self._perm_epoch = epoch
        self.epoch = epoch
        idx = self._perm[lo:hi]
        return self.shard_x[idx], self.shard_y[idx]

    def run_step(self, vt: Optional[VirtualClock] = None) -> float:
        """One optimizer step + one gossip round; returns the loss."""
        step = self.step
        x, y = self._batch(step)
        t0 = time.perf_counter()
        self.params, self.opt_state, loss = self.train_step(
            self.params, self.opt_state, x, y
        )
        loss_f = float(loss)
        alpha: Optional[float] = None
        partner: Optional[int] = None
        outcome: Optional[str] = None
        if self.adapter is not None:
            self.params = self.adapter.update(loss_f, self.params)
            alpha = float(self.adapter.last_alpha)
            info = self.adapter.transport.last_round
            partner = info.get("partner")
            outcome = _outcome_str(info.get("outcome"))
        else:
            self._solo_step = step + 1
        wall = time.perf_counter() - t0
        self.wall_s += wall
        self.ewma = (
            loss_f
            if self.ewma is None
            else (1.0 - EWMA_BETA) * self.ewma + EWMA_BETA * loss_f
        )
        if self.best_loss is None or self.ewma < self.best_loss:
            self.best_loss = self.ewma
        self.final_loss = self.ewma
        target = self.run_cfg.target_loss
        if (
            target > 0.0
            and self.steps_to_target is None
            and self.ewma <= target
        ):
            self.steps_to_target = step + 1
            self.time_to_target_s = self.wall_s
        if step % self.run_cfg.loss_every == 0:
            self.metrics.log_loss(
                step, loss_f, self.me,
                epoch=self.epoch, alpha=alpha, partner=partner,
                outcome=outcome,
                _t=vt.now() if vt is not None else None,
            )
        every = self.run_cfg.checkpoint_every
        if self.ckpt_dir and every and (step + 1) % every == 0:
            save_node_checkpoint(
                self.ckpt_dir, self.params, self.opt_state,
                step + 1, float(step + 1), loss_f,
                keep=self.run_cfg.checkpoint_keep,
            )
        return loss_f

    def log_done(self, vt: Optional[VirtualClock] = None) -> None:
        fields = {
            "wall_s": round(self.wall_s, 4),
            "steps_to_target": self.steps_to_target,
            "time_to_target_s": (
                round(self.time_to_target_s, 4)
                if self.time_to_target_s is not None else None
            ),
        }
        if self.final_loss is not None:
            fields["final_loss"] = round(self.final_loss, 6)
        if self.best_loss is not None:
            fields["best_loss"] = round(self.best_loss, 6)
        self.metrics.log_run(
            self.step, self.me, self.leg, "done",
            peers=self.n_peers, seed=self.seed,
            _t=vt.now() if vt is not None else None, **fields,
        )

    def summary(self) -> dict:
        out = {
            "me": self.me,
            "final_loss": self.final_loss,
            "best_loss": self.best_loss,
            "steps_to_target": self.steps_to_target,
            "time_to_target_s": self.time_to_target_s,
            "wall_s": round(self.wall_s, 4),
            "restored_step": self.restored_step,
        }
        if self.adapter is not None:
            out["health"] = self.adapter.health_snapshot()
        return out

    def test_loss(self, limit: int = 512) -> Optional[float]:
        """Held-out loss on (up to) ``limit`` test samples."""
        x, y = self.task.x_test[:limit], self.task.y_test[:limit]
        if len(x) == 0:
            return None
        return float(self.task.loss_fn(self.params, x, y))

    def close(self) -> None:
        if self.adapter is not None:
            self.adapter.close()
        self.metrics.close()


def run_training(
    config: DpwaConfig,
    task: TrainTask,
    workdir: str,
    *,
    leg: str = "clean",
    virtual_time: bool = True,
    eval_test: bool = True,
    round_hook: Optional[Callable[[int, list], None]] = None,
) -> dict:
    """Lock-step in-process drive of ``n`` :class:`TrainNode` s.

    Every node takes one SGD step then one gossip exchange per round,
    in node order — the deterministic round loop the bit-identity
    acceptance pins.  ``round_hook(step, nodes)`` runs after each round
    (legs use it to snapshot trust state mid-run)."""
    n = len(config.nodes)
    vt = VirtualClock() if virtual_time else None
    tx, train_step = make_train_step(
        task, config.run.lr, config.run.momentum
    )
    nodes = [
        TrainNode(
            i, n, config, task, workdir, leg,
            train_step=train_step, tx=tx,
        )
        for i in range(n)
    ]
    try:
        for node in nodes:
            node.log_start(vt)
        for step in range(config.run.steps):
            for node in nodes:
                node.run_step(vt)
            if round_hook is not None:
                round_hook(step, nodes)
            if vt is not None:
                vt.tick()
        test = nodes[0].test_loss() if eval_test else None
        for node in nodes:
            node.log_done(vt)
        return {
            "leg": leg,
            "peers": n,
            "seed": config.protocol.seed,
            "steps": config.run.steps,
            "workdir": os.path.abspath(workdir),
            "observer_test_loss": test,
            "nodes": [node.summary() for node in nodes],
        }
    finally:
        for node in nodes:
            node.close()


def run_single(
    config: DpwaConfig,
    task: TrainTask,
    workdir: str,
    *,
    leg: str = "single",
    virtual_time: bool = True,
) -> dict:
    """The control arm: single-process SGD, no transport, equal total
    optimizer steps — what the clean leg's time-to-loss is judged
    against."""
    vt = VirtualClock() if virtual_time else None
    node = TrainNode(0, 1, config, task, workdir, leg, gossip=False)
    try:
        node.log_start(vt)
        for _ in range(config.run.steps):
            node.run_step(vt)
            if vt is not None:
                vt.tick()
        node.log_done(vt)
        return {
            "leg": leg,
            "peers": 1,
            "seed": config.protocol.seed,
            "steps": config.run.steps,
            "workdir": os.path.abspath(workdir),
            "observer_test_loss": node.test_loss(),
            "nodes": [node.summary()],
        }
    finally:
        node.close()
