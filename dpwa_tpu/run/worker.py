"""Subprocess training worker — the crash leg's unit of failure.

One OS process per gossip node, free-running (no lock-step barrier —
the deployment shape of the reference).  The crash leg's driver spawns
``n`` of these under ``tools/supervisor.py``; the victim SIGKILLs
itself at a scripted step (an abrupt death: no flush, no goodbye), the
supervisor restarts it with ``DPWA_BOOTSTRAP=1``, and the replacement

1. restores the newest structurally-valid local checkpoint
   (:func:`dpwa_tpu.run.harness.restore_node_checkpoint` — warm params
   and optimizer state, ``run.checkpoint_every`` cadence),
2. refines via the PR 2 STATE transfer (the ``DpwaTcpAdapter``
   constructor's bootstrap path — the cohort's CURRENT replica and
   schedule step), and
3. writes the predecessor's ``status: "crashed"`` run record before its
   own ``"start"`` (a SIGKILL'd process writes no obituary; its
   replacement does).

Run spec is a JSON file (written by :func:`dpwa_tpu.run.legs.crash_leg`)
so the whole config — run block, recovery cadence, chaos, protocol
knobs — crosses the process boundary without a YAML round-trip::

    python -m dpwa_tpu.run.worker --spec run.json --index 2
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import Optional, Sequence


def build_config(spec: dict):
    """``make_local_config`` from a worker spec dict."""
    from dpwa_tpu.config import make_local_config

    return make_local_config(
        int(spec["n"]),
        schedule=spec.get("schedule", "ring"),
        interpolation=spec.get("interpolation", "constant"),
        factor=float(spec.get("factor", 0.5)),
        seed=int(spec.get("seed", 0)),
        base_port=int(spec.get("base_port", 45000)),
        health=spec.get("health"),
        chaos=spec.get("chaos"),
        recovery=spec.get("recovery"),
        membership=spec.get("membership"),
        trust=spec.get("trust"),
        flowctl=spec.get("flowctl"),
        obs=spec.get("obs"),
        run=spec.get("run"),
        **dict(spec.get("protocol") or {}),
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--spec", required=True, help="run spec JSON path")
    ap.add_argument("--index", type=int, required=True, help="node index")
    args = ap.parse_args(argv)
    with open(args.spec, encoding="utf-8") as f:
        spec = json.load(f)

    from dpwa_tpu.run.harness import TrainNode
    from dpwa_tpu.run.task import make_task

    me = int(args.index)
    restarted = os.environ.get("DPWA_BOOTSTRAP", "0") == "1"
    config = build_config(spec)
    task = make_task(spec.get("task", "blobs"), seed=config.protocol.seed)
    node = TrainNode(
        me,
        int(spec["n"]),
        config,
        task,
        spec["workdir"],
        spec.get("leg", "crash"),
        restore=restarted,
    )
    crash_at = (spec.get("crash_at_step") or {}).get(str(me))
    step_sleep = float(spec.get("step_sleep_s", 0.0))
    try:
        if restarted:
            node.log_crashed()
        node.log_start()
        steps = config.run.steps
        while node.step < steps:
            if (
                crash_at is not None
                and not restarted
                and node.step == int(crash_at)
            ):
                # Abrupt death, mid-training: SIGKILL ourselves so no
                # atexit/finally path gets to flush or say goodbye —
                # exactly what the recovery planes must survive.
                os.kill(os.getpid(), signal.SIGKILL)
            node.run_step()
            if step_sleep > 0.0:
                time.sleep(step_sleep)
        node.log_done()
    finally:
        node.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
