"""Chaos-certified end-to-end training harness (docs/training.md).

Everything before this package proved the planes in isolation — health,
recovery, trust, flowctl, obs — against synthetic vectors.  This package
drives the REAL stack (``DpwaTcpAdapter`` over ``TcpTransport``, both Rx
servers, async rounds on or off) through real optimizer steps on the CPU
backend, and certifies robustness in the only currency that matters for
a training system: **time-to-quality on a loss curve**.

- :mod:`dpwa_tpu.run.task` — the model/dataset zoo (an MNIST-class
  ConvNet, a fast blobs head for tests, a LoRA-style adapter-only task);
- :mod:`dpwa_tpu.run.harness` — per-node train loop + lock-step
  in-process driver, with checkpointing and frozen-schema ``run`` /
  ``loss`` JSONL emission;
- :mod:`dpwa_tpu.run.legs` — the four acceptance legs (clean /
  byzantine / crash / straggler) plus the LoRA small-frame leg;
- :mod:`dpwa_tpu.run.worker` — the subprocess entry the crash leg's
  supervisor restarts.
"""

from dpwa_tpu.run.harness import (  # noqa: F401
    RunState,
    TrainNode,
    VirtualClock,
    batch_for_step,
    restore_node_checkpoint,
    run_single,
    run_training,
)
from dpwa_tpu.run.legs import (  # noqa: F401
    LegResult,
    byzantine_leg,
    clean_leg,
    crash_leg,
    lora_leg,
    straggler_leg,
)
from dpwa_tpu.run.task import TrainTask, make_task, make_train_step  # noqa: F401
