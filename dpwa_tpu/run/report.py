"""Join a training run's loss curves with the obs/incident planes.

The chaos-certified question is never "did an incident fire" alone —
it is *did the incident plane bracket the actual loss damage, and which
plane saw it first?*  This module answers it from a harness workdir's
artifacts, all frozen-schema JSONL (tools/schema_check.py):

- ``node<i>.jsonl`` — ``run`` envelopes + per-step ``loss`` records
  (with merge metadata: alpha / partner / outcome columns);
- ``node<i>.events.jsonl`` — the adapter's event stream (bootstrap,
  rollback, trust/membership events) + periodic ``health`` snapshots;
- ``incidents-<i>.jsonl`` — the obs plane's alert/incident stream.

``tools/run_report.py`` is the CLI shim over :func:`build_report` /
:func:`render_report` (the lint_emitters.py pattern: logic lives in the
package, the tool stays a runnable veneer)."""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional

from dpwa_tpu.run.harness import EWMA_BETA

_NODE_RE = re.compile(r"node(\d+)\.jsonl$")
_INCIDENT_RE = re.compile(r"incidents-(\d+)\.jsonl")

# A dent is an EWMA excursion at least this far above the running
# minimum (relative); the window closes when the curve comes back
# within half the excursion threshold.
DENT_REL = 0.25


def load_jsonl(path: str) -> List[dict]:
    """Parse one JSONL file, skipping unparseable lines (a crashed
    writer's final partial line must not sink the report)."""
    rows: List[dict] = []
    if not os.path.exists(path):
        return rows
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict):
                rows.append(row)
    return rows


def load_run_dir(workdir: str) -> dict:
    """All planes of one harness workdir, keyed by node index."""
    nodes: Dict[int, dict] = {}
    incidents: Dict[int, List[dict]] = {}
    for name in sorted(os.listdir(workdir)):
        path = os.path.join(workdir, name)
        m = _NODE_RE.match(name)
        if m is not None:
            rows = load_jsonl(path)
            nodes[int(m.group(1))] = {
                "loss": [r for r in rows if r.get("record") == "loss"],
                "runs": [r for r in rows if r.get("record") == "run"],
            }
            continue
        if name.endswith(".events.jsonl"):
            idx = int(re.search(r"node(\d+)", name).group(1))
            nodes.setdefault(idx, {}).setdefault(
                "events", load_jsonl(path)
            )
            continue
        m = _INCIDENT_RE.match(name)
        if m is not None:
            incidents[int(m.group(1))] = load_jsonl(path)
    for idx in sorted(nodes):
        nodes[idx].setdefault("loss", [])
        nodes[idx].setdefault("runs", [])
        nodes[idx].setdefault("events", [])
    return {"workdir": os.path.abspath(workdir), "nodes": nodes,
            "incidents": incidents}


def ewma_series(
    loss_rows: List[dict], beta: float = EWMA_BETA
) -> List[tuple]:
    """``[(step, ewma), ...]`` over a node's loss records (step order)."""
    out: List[tuple] = []
    ewma: Optional[float] = None
    for row in sorted(loss_rows, key=lambda r: int(r.get("step", 0))):
        loss = row.get("loss")
        if not isinstance(loss, (int, float)):
            continue
        ewma = (
            float(loss)
            if ewma is None
            else (1.0 - beta) * ewma + beta * float(loss)
        )
        out.append((int(row["step"]), ewma))
    return out


def dent_window(
    series: List[tuple], rel: float = DENT_REL
) -> Optional[dict]:
    """The loss dent: the first window where the EWMA rises ``rel``
    above its running minimum, until it comes back within ``rel/2``.
    ``None`` when the curve never dents (a clean run)."""
    running_min: Optional[float] = None
    start: Optional[int] = None
    base: Optional[float] = None
    peak = 0.0
    peak_step: Optional[int] = None
    end: Optional[int] = None
    for step, val in series:
        if running_min is None or val < running_min:
            if start is None:
                running_min = val
        if start is None:
            if val > running_min * (1.0 + rel) + 1e-12:
                start, base = step, running_min
                peak, peak_step = val, step
        else:
            if val > peak:
                peak, peak_step = val, step
            if val <= base * (1.0 + rel / 2.0) + 1e-12:
                end = step
                break
    if start is None:
        return None
    last_step = series[-1][0] if series else start
    return {
        "start": start,
        "end": end if end is not None else last_step,
        "recovered": end is not None,
        "baseline": round(base, 6),
        "peak": round(peak, 6),
        "peak_step": peak_step,
        "excursion": round(peak / base, 4) if base else None,
    }


def incident_clusters(records: List[dict]) -> List[dict]:
    """Fold one node's incident stream into per-incident clusters
    (open → updates → resolved), keyed by the incident ``id``."""
    clusters: Dict[str, dict] = {}
    order: List[str] = []
    for rec in records:
        if rec.get("record") != "incident":
            continue
        cid = rec.get("id")
        if cid not in clusters:
            clusters[cid] = {
                "id": cid,
                "kind": rec.get("kind"),
                "severity": rec.get("severity"),
                "opened_step": rec.get("opened_step", rec.get("step")),
                "resolved_step": None,
                "alerts": 0,
                "peers": [],
            }
            order.append(cid)
        c = clusters[cid]
        c["kind"] = rec.get("kind", c["kind"])
        c["severity"] = rec.get("severity", c["severity"])
        c["alerts"] = max(c["alerts"], int(rec.get("alerts", 0)))
        for p in rec.get("peers", ()):
            if p not in c["peers"]:
                c["peers"].append(p)
        if rec.get("status") == "resolved":
            c["resolved_step"] = rec.get(
                "resolved_step", rec.get("step")
            )
    return [clusters[cid] for cid in order]


def cluster_brackets(cluster: dict, dent: dict, slack: int = 8) -> bool:
    """Does the incident cluster bracket the loss dent?  Open no later
    than ``slack`` steps after the dent starts, resolved (or still open)
    no earlier than the dent's recovery."""
    opened = cluster.get("opened_step")
    if opened is None or opened > dent["start"] + slack:
        return False
    resolved = cluster.get("resolved_step")
    if resolved is None:
        return True  # still open at end of run: covers the dent's tail
    return resolved + slack >= dent["end"]


def first_signal(
    node: dict, incidents: List[dict]
) -> Optional[dict]:
    """The earliest fault signal any plane raised on this node, and
    which plane raised it — trust (an ``untrusted`` merge column),
    health (a non-success outcome), or the obs incident plane."""
    candidates: List[dict] = []
    for row in node.get("loss", []):
        out = row.get("outcome")
        if out == "untrusted":
            candidates.append(
                {"plane": "trust", "step": int(row["step"]),
                 "detail": "untrusted merge"}
            )
            break
    for row in node.get("loss", []):
        out = row.get("outcome")
        if out is not None and out not in ("success", "untrusted"):
            candidates.append(
                {"plane": "health", "step": int(row["step"]),
                 "detail": f"outcome {out}"}
            )
            break
    for rec in incidents:
        if rec.get("record") == "incident" and rec.get("status") == "open":
            candidates.append(
                {"plane": "incidents", "step": int(rec["step"]),
                 "detail": f"incident {rec.get('kind')}"}
            )
            break
    if not candidates:
        return None
    return min(candidates, key=lambda c: c["step"])


def build_report(workdir: str, observer: int = 0) -> dict:
    """The full loss/incident join for one harness workdir."""
    data = load_run_dir(workdir)
    nodes_out = {}
    for idx in sorted(data["nodes"]):
        node = data["nodes"][idx]
        series = ewma_series(node["loss"])
        done = [r for r in node["runs"] if r.get("status") == "done"]
        crashed = [r for r in node["runs"] if r.get("status") == "crashed"]
        starts = [r for r in node["runs"] if r.get("status") == "start"]
        inc = data["incidents"].get(idx, [])
        dent = dent_window(series)
        clusters = incident_clusters(inc)
        nodes_out[idx] = {
            "steps_logged": len(node["loss"]),
            "final_ewma": round(series[-1][1], 6) if series else None,
            "done": done[-1] if done else None,
            "crashes": len(crashed),
            "restarts": max(0, len(starts) - 1),
            "restored_step": max(
                (r.get("checkpoint_restored_step", 0) for r in starts),
                default=0,
            ),
            "dent": dent,
            "incident_clusters": clusters,
            "bracketed": (
                [cluster_brackets(c, dent) for c in clusters]
                if dent is not None
                else []
            ),
            "first_signal": first_signal(node, inc),
        }
    return {
        "workdir": data["workdir"],
        "observer": observer,
        "nodes": nodes_out,
    }


def render_report(report: dict) -> str:
    """Human-readable summary of :func:`build_report` output."""
    lines = [f"run report: {report['workdir']}"]
    for idx in sorted(report["nodes"]):
        node = report["nodes"][idx]
        done = node["done"] or {}
        lines.append(
            f"  node{idx}: {node['steps_logged']} loss records, "
            f"final ewma {node['final_ewma']}, "
            f"crashes {node['crashes']}, restarts {node['restarts']}"
            + (
                f" (restored step {node['restored_step']})"
                if node["restored_step"]
                else ""
            )
        )
        if done:
            lines.append(
                f"    done: steps_to_target {done.get('steps_to_target')}, "
                f"final_loss {done.get('final_loss')}, "
                f"wall {done.get('wall_s')}s"
            )
        dent = node["dent"]
        if dent is not None:
            lines.append(
                f"    loss dent: steps [{dent['start']}, {dent['end']}] "
                f"peak {dent['peak']} ({dent['excursion']}x baseline, "
                f"{'recovered' if dent['recovered'] else 'NOT recovered'})"
            )
        for c, br in zip(
            node["incident_clusters"],
            node["bracketed"] or [None] * len(node["incident_clusters"]),
        ):
            span = (
                f"[{c['opened_step']}, {c['resolved_step']}]"
                if c["resolved_step"] is not None
                else f"[{c['opened_step']}, open)"
            )
            lines.append(
                f"    incident {c['kind']} ({c['severity']}) {span} "
                f"peers {c['peers']} alerts {c['alerts']}"
                + (
                    f" — {'brackets' if br else 'MISSES'} the dent"
                    if br is not None
                    else ""
                )
            )
        sig = node["first_signal"]
        if sig is not None:
            lines.append(
                f"    first signal: {sig['plane']} at step {sig['step']} "
                f"({sig['detail']})"
            )
    return "\n".join(lines)
