"""The four chaos-certified acceptance legs + the LoRA small-frame leg.

Each leg drives real training through the real stack and renders a
**verdict dict** whose booleans are the acceptance criteria
(docs/training.md):

- :func:`clean_leg` — time-to-loss within tolerance of single-process
  SGD at equal total optimizer steps;
- :func:`byzantine_leg` — a chaos byzantine window dents the observer's
  curve boundedly, trust quarantines the offender within K rounds, the
  incident plane brackets the dent, and the curve re-converges;
- :func:`crash_leg` — a worker SIGKILLs mid-training; the supervisor
  restarts it, it restores its newest valid checkpoint, refines over
  the STATE wire, and its loss rejoins the cohort;
- :func:`straggler_leg` — a trickle-shaped peer must not throttle the
  honest peers' time-to-loss when async rounds are on;
- :func:`lora_leg` — the d≈100K adapter-only exchange (small-frame
  regime) learns through the zero-copy ring.

``bench.py --train-leg`` runs the clean leg at BASELINE-ish shapes and
records the ``train_gate`` verdict in ``artifacts/bench_history.jsonl``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
from typing import Any, Dict, List, Optional

from dpwa_tpu.config import make_local_config
from dpwa_tpu.run.harness import run_single, run_training
from dpwa_tpu.run.report import build_report
from dpwa_tpu.run.task import make_task

# Per-task training hyperparameters that reach the target in tens of
# steps on CPU (calibrated; the legs' runtime budget is tier-1's).
TASK_DEFAULTS = {
    "blobs": {"steps": 48, "batch_size": 32, "lr": 0.5, "target_loss": 0.4},
    "digits": {"steps": 80, "batch_size": 32, "lr": 0.1, "target_loss": 0.7},
    "lora": {"steps": 40, "batch_size": 32, "lr": 0.3, "target_loss": 1.5},
}


@dataclasses.dataclass
class LegResult:
    """One leg's outcome: ``ok`` is the AND of every acceptance bool in
    ``verdict``; ``summary`` is the raw harness output; ``report`` the
    loss/incident join."""

    leg: str
    ok: bool
    verdict: Dict[str, Any]
    summary: Dict[str, Any]
    report: Dict[str, Any]
    workdir: str

    def to_record(self) -> dict:
        """The compact form bench.py embeds in its history record."""
        return {"leg": self.leg, "ok": self.ok, "verdict": self.verdict}


def _run_block(task_name: str, **overrides) -> dict:
    run = dict(TASK_DEFAULTS[task_name])
    for key in sorted(overrides):
        if overrides[key] is not None:
            run[key] = overrides[key]
    return run


def _median(values: List[float]) -> Optional[float]:
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    mid = len(vals) // 2
    if len(vals) % 2:
        return float(vals[mid])
    return (vals[mid - 1] + vals[mid]) / 2.0


def _verdict_ok(verdict: dict) -> bool:
    return all(
        bool(verdict[k]) for k in sorted(verdict) if k.endswith("_ok")
    )


# ---------------------------------------------------------------------------
# Clean leg
# ---------------------------------------------------------------------------


def clean_leg(
    workdir: str,
    *,
    n_peers: int = 8,
    task: str = "blobs",
    seed: int = 11,
    base_port: int = 46600,
    steps: Optional[int] = None,
    target_loss: Optional[float] = None,
    steps_tol: float = 1.6,
    rx_server: str = "threaded",
) -> LegResult:
    """Gossip time-to-loss vs single-process SGD at equal total steps.

    Equal TOTAL OPTIMIZER STEPS per replica: both arms take the same
    number of SGD steps; the gossip arm additionally pays a full
    exchange (publish → fetch → guard → trust → merge) each step.  The
    leg passes when the gossip cohort's median steps-to-target is
    within ``steps_tol`` of the single run's — pairwise averaging must
    not wreck the curve."""
    run = _run_block(task, steps=steps, target_loss=target_loss)
    task_obj = make_task(task, seed=seed)
    gossip_dir = os.path.join(workdir, "gossip")
    single_dir = os.path.join(workdir, "single")
    config = make_local_config(
        n_peers,
        seed=seed,
        base_port=base_port,
        run=run,
        rx_server=rx_server,
        obs=dict(
            incidents=True,
            incident_path=os.path.join(gossip_dir, "incidents-{me}.jsonl"),
        ),
    )
    summary = run_training(config, task_obj, gossip_dir, leg="clean")
    # The control arm reuses the same config (run block + seed); with
    # gossip off no transport is built, so the node list is inert.
    single = run_single(config, task_obj, single_dir)
    report = build_report(gossip_dir)
    gossip_stt = _median(
        [n["steps_to_target"] for n in summary["nodes"]]
    )
    single_stt = single["nodes"][0]["steps_to_target"]
    incidents = sum(
        len(n["incident_clusters"]) for n in report["nodes"].values()
    )
    verdict = {
        "gossip_steps_to_target": gossip_stt,
        "single_steps_to_target": single_stt,
        "steps_tol": steps_tol,
        "gossip_final_loss": _median(
            [n["final_loss"] for n in summary["nodes"]]
        ),
        "single_final_loss": single["nodes"][0]["final_loss"],
        "incident_clusters": incidents,
        "converged_ok": gossip_stt is not None,
        "single_converged_ok": single_stt is not None,
        "time_to_quality_ok": (
            gossip_stt is not None
            and single_stt is not None
            and gossip_stt <= steps_tol * single_stt
        ),
        "quiet_incidents_ok": incidents == 0,
    }
    summary["single"] = single
    return LegResult(
        leg="clean",
        ok=_verdict_ok(verdict),
        verdict=verdict,
        summary=summary,
        report=report,
        workdir=workdir,
    )


# ---------------------------------------------------------------------------
# Byzantine leg
# ---------------------------------------------------------------------------


def byzantine_leg(
    workdir: str,
    *,
    n_peers: int = 4,
    task: str = "blobs",
    seed: int = 23,
    base_port: int = 46700,
    attacker: int = 1,
    attack_from: Optional[int] = None,
    kind: str = "sign",
    quarantine_k: int = 8,
    steps: Optional[int] = None,
) -> LegResult:
    """A byzantine window mid-run: bounded dent, quarantine within K
    rounds, incident plane brackets the dent, curve re-converges."""
    run = _run_block(task, steps=steps)
    if attack_from is None:
        attack_from = run["steps"] // 3
    task_obj = make_task(task, seed=seed)
    config = make_local_config(
        n_peers,
        seed=seed,
        base_port=base_port,
        run=run,
        timeout_ms=800,
        trust=dict(window=16, min_window=4),
        health=dict(jitter_rounds=1, quarantine_base_rounds=4),
        chaos=dict(
            enabled=True,
            seed=seed + 17,
            byzantine_peers=(attacker,),
            byzantine_start_round=attack_from,
            **{f"byzantine_{kind}_probability": 1.0},
        ),
        obs=dict(
            incidents=True,
            incident_path=os.path.join(workdir, "incidents-{me}.jsonl"),
        ),
    )
    summary = run_training(config, task_obj, workdir, leg="byzantine")
    report = build_report(workdir)
    honest = [i for i in range(n_peers) if i != attacker]
    # Quarantine evidence from the final health snapshots: every honest
    # node quarantined the attacker (by its own screening OR by adopting
    # the quarantine epidemically — a node the ring never pairs with the
    # attacker still learns to avoid it), and the nodes that DID screen
    # it personally collapsed its trust.
    quarantined = []
    screened = 0
    for i in honest:
        peer = summary["nodes"][i]["health"]["peers"][attacker]
        quarantined.append(peer.get("quarantines", 0) >= 1)
        if peer.get("trust_rejected", 0) >= 1:
            screened += 1
            quarantined[-1] = (
                quarantined[-1] and peer.get("trust", 1.0) < 0.5
            )
    # Time-to-quarantine from the observers' merge columns: the first
    # ``untrusted`` outcome any honest node logged.
    first_untrusted: Optional[int] = None
    for i in honest:
        sig = report["nodes"][i]["first_signal"]
        if sig is not None and sig["plane"] == "trust":
            if first_untrusted is None or sig["step"] < first_untrusted:
                first_untrusted = sig["step"]
    # The observer's dent and its incident bracket.
    obs_node = report["nodes"][0]
    dent = obs_node["dent"]
    clusters = obs_node["incident_clusters"]
    bracketing = [
        c for c, br in zip(clusters, obs_node["bracketed"]) if br
    ]
    final = _median([summary["nodes"][i]["final_loss"] for i in honest])
    target = run["target_loss"]
    verdict = {
        "attacker": attacker,
        "attack_from": attack_from,
        "first_untrusted_step": first_untrusted,
        "quarantine_k": quarantine_k,
        "dent": dent,
        "incident_clusters": len(clusters),
        "bracketing_clusters": len(bracketing),
        "honest_final_loss": final,
        "screening_nodes": screened,
        "quarantined_ok": all(quarantined)
        and len(quarantined) > 0
        and screened >= 2,
        # The publish clock leads the step by one, so the first lying
        # frame can land at step attack_from - 1.
        "quarantine_time_ok": (
            first_untrusted is not None
            and attack_from - 1
            <= first_untrusted
            <= attack_from + quarantine_k
        ),
        "dent_bounded_ok": dent is None or dent["excursion"] <= 20.0,
        "reconverged_ok": (
            final is not None
            and final <= max(2.0 * target, target + 0.2)
            and (dent is None or dent["recovered"])
        ),
        "incident_bracket_ok": (
            dent is None or len(bracketing) >= 1
        ),
        "single_cluster_ok": len(clusters) <= 1,
    }
    return LegResult(
        leg="byzantine",
        ok=_verdict_ok(verdict),
        verdict=verdict,
        summary=summary,
        report=report,
        workdir=workdir,
    )


# ---------------------------------------------------------------------------
# Crash leg (subprocess workers under the restart supervisor)
# ---------------------------------------------------------------------------


def crash_leg(
    workdir: str,
    *,
    n_peers: int = 4,
    task: str = "blobs",
    seed: int = 31,
    base_port: int = 46800,
    victim: int = 1,
    crash_at: int = 12,
    checkpoint_every: int = 5,
    steps: int = 90,
    step_sleep_s: float = 0.08,
    timeout_s: float = 120.0,
    rejoin_loss_factor: float = 3.0,
) -> LegResult:
    """SIGKILL a worker mid-training; prove checkpoint restore + STATE
    rejoin land its loss back in the cohort.

    Free-running subprocess workers (one per node, the real deployment
    shape) under ``tools/supervisor.py``.  The victim kills itself —
    SIGKILL, nothing flushes — at ``crash_at``; the supervisor restarts
    it with ``DPWA_BOOTSTRAP=1``."""
    from tools.supervisor import Supervisor, WorkerSpec

    os.makedirs(workdir, exist_ok=True)
    run = _run_block(task, steps=steps)
    run["checkpoint_every"] = checkpoint_every
    run["checkpoint_dir"] = os.path.join(workdir, "ckpt")
    spec = {
        "n": n_peers,
        "seed": seed,
        "base_port": base_port,
        "task": task,
        "leg": "crash",
        "workdir": workdir,
        "run": run,
        "protocol": {"timeout_ms": 800},
        "health": {"jitter_rounds": 1},
        "obs": {
            "incidents": True,
            "incident_path": os.path.join(workdir, "incidents-{me}.jsonl"),
        },
        "crash_at_step": {str(victim): crash_at},
        "step_sleep_s": step_sleep_s,
    }
    spec_path = os.path.join(workdir, "run.json")
    with open(spec_path, "w", encoding="utf-8") as f:
        json.dump(spec, f, indent=2)
    workers = [
        WorkerSpec(
            name=f"node{i}",
            argv=[
                sys.executable, "-m", "dpwa_tpu.run.worker",
                "--spec", spec_path, "--index", str(i),
            ],
        )
        for i in range(n_peers)
    ]
    sup = Supervisor(
        workers, max_restarts=3, backoff_base_s=0.2, backoff_max_s=2.0
    )
    sup.start()
    final = sup.run(timeout_s=timeout_s)
    report = build_report(workdir)
    victim_node = report["nodes"].get(victim, {})
    honest = [i for i in sorted(report["nodes"]) if i != victim]
    honest_final = _median(
        [report["nodes"][i]["final_ewma"] for i in honest]
    )
    victim_final = victim_node.get("final_ewma")
    victim_done = victim_node.get("done")
    crash_events = [
        e for e in sup.events if e["event"] == "crashed"
    ]
    verdict = {
        "supervisor": final,
        "crash_events": len(crash_events),
        "victim_crashes_logged": victim_node.get("crashes", 0),
        "victim_restored_step": victim_node.get("restored_step", 0),
        "victim_final_ewma": victim_final,
        "honest_final_ewma": honest_final,
        "crashed_ok": len(crash_events) >= 1,
        "restarted_ok": final["restarts"].get(f"node{victim}", 0) >= 1
        and final["gave_up"] == 0,
        "checkpoint_restored_ok": (
            victim_node.get("restored_step", 0) >= checkpoint_every
        ),
        "completed_ok": victim_done is not None,
        "rejoined_ok": (
            victim_final is not None
            and honest_final is not None
            and victim_final
            <= max(rejoin_loss_factor * honest_final, honest_final + 0.3)
        ),
    }
    return LegResult(
        leg="crash",
        ok=_verdict_ok(verdict),
        verdict=verdict,
        summary={"supervisor_events": sup.events, "spec": spec},
        report=report,
        workdir=workdir,
    )


# ---------------------------------------------------------------------------
# Straggler leg
# ---------------------------------------------------------------------------


def straggler_leg(
    workdir: str,
    *,
    n_peers: int = 4,
    task: str = "blobs",
    seed: int = 41,
    base_port: int = 46900,
    steps: Optional[int] = None,
    trickle_bytes_per_s: float = 512.0,
    wall_tol: float = 2.0,
    steps_tol: float = 1.5,
) -> LegResult:
    """A trickle-shaped peer must not throttle honest time-to-loss with
    async rounds on.

    Two seeded runs, identical but for chaos: a baseline (async on, no
    shaping) and the straggler run (peer ``n-1``'s SERVING trickles for
    the whole run).  Honest nodes' own wall time and steps-to-target
    must stay within tolerance — barrier-free rounds mean a slow peer
    costs its own frames, not the cohort's round rate."""
    run = _run_block(task, steps=steps)
    task_obj = make_task(task, seed=seed)
    async_block = {"enabled": True, "max_staleness": 6}
    base_dir = os.path.join(workdir, "baseline")
    slow_dir = os.path.join(workdir, "straggler")
    straggler = n_peers - 1
    base_cfg = make_local_config(
        n_peers,
        seed=seed,
        base_port=base_port,
        run=run,
        timeout_ms=800,
        async_rounds=async_block,
    )
    baseline = run_training(base_cfg, task_obj, base_dir, leg="straggler")
    slow_cfg = make_local_config(
        n_peers,
        seed=seed,
        base_port=base_port + n_peers,
        run=run,
        timeout_ms=800,
        async_rounds=async_block,
        chaos=dict(
            enabled=True,
            seed=seed + 5,
            trickle_windows=((straggler, 0, run["steps"]),),
            trickle_bytes_per_s=trickle_bytes_per_s,
        ),
    )
    shaped = run_training(slow_cfg, task_obj, slow_dir, leg="straggler")
    honest = [i for i in range(n_peers) if i != straggler]
    base_wall = _median(
        [baseline["nodes"][i]["wall_s"] for i in honest]
    )
    slow_wall = _median([shaped["nodes"][i]["wall_s"] for i in honest])
    base_stt = _median(
        [baseline["nodes"][i]["steps_to_target"] for i in honest]
    )
    slow_stt = _median(
        [shaped["nodes"][i]["steps_to_target"] for i in honest]
    )
    verdict = {
        "straggler": straggler,
        "honest_wall_s_baseline": base_wall,
        "honest_wall_s_straggler": slow_wall,
        "honest_steps_to_target_baseline": base_stt,
        "honest_steps_to_target_straggler": slow_stt,
        "wall_tol": wall_tol,
        "steps_tol": steps_tol,
        "converged_ok": slow_stt is not None and base_stt is not None,
        "unthrottled_wall_ok": (
            base_wall is not None
            and slow_wall is not None
            and slow_wall <= wall_tol * max(base_wall, 0.05)
        ),
        "time_to_quality_ok": (
            base_stt is not None
            and slow_stt is not None
            and slow_stt <= steps_tol * base_stt
        ),
    }
    return LegResult(
        leg="straggler",
        ok=_verdict_ok(verdict),
        verdict=verdict,
        summary={"baseline": baseline, "straggler": shaped},
        report=build_report(slow_dir),
        workdir=workdir,
    )


# ---------------------------------------------------------------------------
# LoRA small-frame leg
# ---------------------------------------------------------------------------


def lora_leg(
    workdir: str,
    *,
    n_peers: int = 4,
    seed: int = 53,
    base_port: int = 47000,
    steps: Optional[int] = None,
    rx_server: str = "threaded",
) -> LegResult:
    """Adapter-only exchange at d≈100K (~392 KiB frames) through the
    zero-copy ring: the small-frame regime must learn, exchange, and
    stay incident-free.  (The O(header) decode-allocation gate for this
    frame class lives in ``bench.py --copy-leg``.)"""
    run = _run_block("lora", steps=steps)
    task_obj = make_task("lora", seed=seed)
    config = make_local_config(
        n_peers,
        seed=seed,
        base_port=base_port,
        run=run,
        rx_server=rx_server,
        obs=dict(
            incidents=True,
            incident_path=os.path.join(workdir, "incidents-{me}.jsonl"),
        ),
    )
    summary = run_training(config, task_obj, workdir, leg="lora")
    report = build_report(workdir)
    merged = 0
    for node in summary["nodes"]:
        for _, peer in sorted(node["health"]["peers"].items()):
            merged += int(peer.get("successes", 0))
    incidents = sum(
        len(n["incident_clusters"]) for n in report["nodes"].values()
    )
    stt = _median([n["steps_to_target"] for n in summary["nodes"]])
    verdict = {
        "d": task_obj.d,
        "frame_bytes": task_obj.d * 4,
        "steps_to_target": stt,
        "exchanges_succeeded": merged,
        "incident_clusters": incidents,
        "adapter_only_ok": 90_000 <= task_obj.d <= 110_000,
        "converged_ok": stt is not None,
        "exchanged_ok": merged > 0,
        "quiet_incidents_ok": incidents == 0,
    }
    return LegResult(
        leg="lora",
        ok=_verdict_ok(verdict),
        verdict=verdict,
        summary=summary,
        report=report,
        workdir=workdir,
    )
