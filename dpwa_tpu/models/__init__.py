from dpwa_tpu.models.mnist import ConvNet, SmallNet  # noqa: F401
