"""MNIST-class ConvNets (Flax).

The reference's only example model is a stock PyTorch MNIST ConvNet wired to
the adapter (SURVEY.md §2 "MNIST example"; reference ``examples/mnist`` —
mount empty).  :class:`ConvNet` is the TPU-native equivalent for 28×28
inputs; :class:`SmallNet` is a scaled-down sibling for the 8×8
``sklearn.datasets.load_digits`` images used by the offline test suite."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class ConvNet(nn.Module):
    """Conv(32)→Conv(64)→pool→Dense(128)→Dense(classes), for 28×28×1."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


class SmallNet(nn.Module):
    """Tiny net for 8×8 digits: one conv + one hidden dense."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Conv(16, (3, 3))(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(64)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)
