"""BERT-style masked-LM encoder (Flax) — gossip config 4.

BASELINE.json:10: "BERT-base MLM (Flax), 64-peer gossip, hierarchical
intra/inter-host averaging".  Clean-room implementation of the standard
architecture (Devlin et al. 2018: learned positions, post-LN encoder blocks,
GELU FF, tied-free MLM head); :func:`bert_base_config` carries the real
BERT-base dimensions, tests use tiny ones — identical code and pytree paths.

The hierarchical averaging itself is a *schedule*, not a model property:
``protocol.schedule: hierarchical`` with ``group_size`` = chips per host
makes intra-group slots ride ICI and the sparse inter-group slots cross DCN
(see dpwa_tpu.parallel.schedules._hierarchical_pool)."""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    dtype: jnp.dtype = jnp.float32


def bert_base_config(dtype=None) -> BertConfig:
    return BertConfig(**({} if dtype is None else {"dtype": dtype}))


def bert_tiny_config(dtype=None) -> BertConfig:
    return BertConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        max_seq_len=64,
        **({} if dtype is None else {"dtype": dtype}),
    )


class EncoderBlock(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        attn_out = nn.MultiHeadDotProductAttention(
            num_heads=cfg.n_heads, dtype=cfg.dtype, name="attn"
        )(x, x, mask=mask)
        x = nn.LayerNorm(name="attn_ln")(x + attn_out)
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, name="ff_in")(x)
        h = nn.gelu(h)
        h = nn.Dense(cfg.d_model, dtype=cfg.dtype, name="ff_out")(h)
        return nn.LayerNorm(name="ff_ln")(x + h)


class BertMLM(nn.Module):
    """Encoder + MLM head; returns logits [B, T, vocab]."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, attention_mask=None):
        cfg = self.cfg
        B, T = tokens.shape
        x = nn.Embed(cfg.vocab_size, cfg.d_model, name="tok_embed")(tokens)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (cfg.max_seq_len, cfg.d_model),
        )
        x = x + pos[None, :T]
        x = nn.LayerNorm(name="embed_ln")(x)
        if attention_mask is None:
            mask = None
        else:
            mask = attention_mask[:, None, None, :].astype(bool)
        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"layer_{i}")(x, mask)
        x = nn.Dense(cfg.d_model, dtype=cfg.dtype, name="mlm_dense")(x)
        x = nn.gelu(x)
        x = nn.LayerNorm(name="mlm_ln")(x)
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32, name="mlm_head")(x)


MASK_TOKEN = 0  # convention for the synthetic MLM task


def mlm_mask_batch(
    tokens: np.ndarray, rng: np.random.Generator, mask_prob: float = 0.15
):
    """Standard MLM corruption: returns (inputs, targets, loss_weights)."""
    mask = rng.random(tokens.shape) < mask_prob
    inputs = np.where(mask, MASK_TOKEN, tokens)
    return inputs.astype(np.int32), tokens.astype(np.int32), mask.astype(
        np.float32
    )


def mlm_loss_fn(model: BertMLM):
    """Per-peer masked-LM loss for the gossip train step."""
    import optax

    def loss_fn(params, batch):
        inputs, targets, weights = batch
        logits = model.apply(params, inputs)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        )
        return (losses * weights).sum() / jnp.maximum(weights.sum(), 1.0)

    return loss_fn
