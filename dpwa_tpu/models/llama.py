"""Compact Llama-family decoder with LoRA, for subset-pytree gossip.

BASELINE.json:11 (config 5): "Llama-3-8B LoRA fine-tune, pairwise-avg of
LoRA adapters across v5p-128" — only the LoRA adapter weights enter the
gossip exchange; base weights never move.  The reference never touches model
internals (it sees a flat parameter vector, SURVEY.md §5 "Long-context"), so
this is a clean-room Flax implementation of the standard architecture:
RMSNorm, rotary position embeddings, multi-head causal attention, SwiGLU
MLP.  ``llama3_8b_config()`` gives the real dimensions; tests and the
dry-run use tiny configs — same code, same pytree paths.

LoRA: :class:`LoRADense` adds ``lora_a``/``lora_b`` factors beside the
frozen base kernel.  Every LoRA leaf's path contains ``lora_``, so the
subset predicate :func:`lora_filter` selects exactly the adapter state for
the exchange (``dpwa_tpu.utils.pytree.partition``)."""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None  # GQA; None = MHA
    d_ff: int = 1376
    max_seq_len: int = 2048
    rope_theta: float = 500000.0
    lora_rank: int = 0  # 0 = no LoRA
    lora_alpha: float = 16.0
    dtype: jnp.dtype = jnp.float32
    # Sequence-parallel: name of the mesh axis the sequence is sharded
    # over.  When set, the model must run INSIDE shard_map over that axis
    # (each device holds a contiguous T_local block); attention becomes
    # exact ring attention over the axis and rope positions are globally
    # offset by the device's block index.  None = single-device attention.
    sp_axis: Optional[str] = None
    # Sequence layout over sp_axis: "contiguous" (device i holds block i)
    # or "zigzag" (device i holds global chunks i and 2n-1-i — the
    # causal-load-balanced layout of ops/zigzag_ring.py; callers shard
    # tokens/targets with zigzag_shard, and the model supplies matching
    # rope positions internally).
    sp_layout: str = "contiguous"
    # Sequence-parallel strategy over sp_axis: "ring" (K/V blocks rotate
    # by ppermute — ops/ring_attention.py and friends) or "a2a"
    # (Ulysses-style: all-to-all to head-sharded attention over the full
    # sequence — ops/ulysses.py; needs n_heads % sp == 0).
    sp_strategy: str = "ring"
    # Single-device attention implementation: "auto" uses the Pallas TPU
    # flash kernel when the backend is TPU and the shapes fit its tiling
    # (T and head_dim multiples of 128), else the dense O(T^2) einsum;
    # "flash" forces the kernel (raises off-TPU), "dense" forces einsum.
    # The sp path is unaffected (ring attention is already blockwise).
    attn_impl: str = "auto"

    def __post_init__(self):
        if self.attn_impl not in ("auto", "flash", "dense"):
            raise ValueError(
                f"attn_impl must be auto|flash|dense, got {self.attn_impl!r}"
            )
        if self.sp_layout not in ("contiguous", "zigzag"):
            raise ValueError(
                f"sp_layout must be contiguous|zigzag, got {self.sp_layout!r}"
            )
        if self.sp_layout != "contiguous" and self.sp_axis is None:
            # Silently ignoring the layout would train single-device
            # attention on zigzag-permuted data — scrambled sequences.
            raise ValueError(
                "sp_layout='zigzag' requires sp_axis (the layout only "
                "exists for the sequence-parallel ring)"
            )
        if self.sp_strategy not in ("ring", "a2a"):
            raise ValueError(
                f"sp_strategy must be ring|a2a, got {self.sp_strategy!r}"
            )
        if self.sp_strategy == "a2a" and self.sp_layout != "contiguous":
            raise ValueError(
                "sp_strategy='a2a' shards heads, not sequence stripes — "
                "the zigzag layout only applies to the ring strategy"
            )

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def llama3_8b_config(lora_rank: int = 16) -> LlamaConfig:
    """The real Llama-3-8B dimensions (public architecture constants)."""
    return LlamaConfig(
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        max_seq_len=8192,
        rope_theta=500000.0,
        lora_rank=lora_rank,
        dtype=jnp.bfloat16,
    )


def lora_filter(path: str) -> bool:
    """Subset predicate: the LoRA adapter leaves (and nothing else)."""
    return "lora_" in path


class LoRADense(nn.Module):
    """Dense with a rank-r LoRA delta: ``y = x·W + (α/r)·x·A·B``.

    The base kernel is ordinary Flax state (frozen by the optimizer mask in
    LoRA fine-tuning); ``lora_a``/``lora_b`` are the trainable, gossiped
    adapter."""

    features: int
    rank: int
    alpha: float = 16.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (in_features, self.features),
        )
        y = x @ kernel.astype(self.dtype)
        if self.rank > 0:
            lora_a = self.param(
                "lora_a",
                nn.initializers.normal(stddev=0.02),
                (in_features, self.rank),
            )
            lora_b = self.param(
                "lora_b", nn.initializers.zeros, (self.rank, self.features)
            )
            scale = self.alpha / self.rank
            y = y + (x @ lora_a.astype(self.dtype)) @ lora_b.astype(
                self.dtype
            ) * scale
        return y


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(self.dtype) * scale


def rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary embedding over the last (head_dim) axis. x: [..., T, H, D]."""
    d = x.shape[-1]
    freqs = 1.0 / (
        theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    )
    angles = positions[..., None].astype(jnp.float32) * freqs  # [T, D/2]
    cos = jnp.cos(angles)[..., None, :]  # [T, 1, D/2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., ::2], x[..., 1::2]
    out1 = x1 * cos - x2 * sin
    out2 = x1 * sin + x2 * cos
    return jnp.stack([out1, out2], axis=-1).reshape(x.shape).astype(x.dtype)


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        B, T, _ = x.shape
        H, KV, D = cfg.n_heads, cfg.kv_heads, cfg.head_dim
        dense = lambda feats, name: LoRADense(
            feats, cfg.lora_rank, cfg.lora_alpha, cfg.dtype, name=name
        )
        q = dense(H * D, "wq")(x).reshape(B, T, H, D)
        k = dense(KV * D, "wk")(x).reshape(B, T, KV, D)
        v = dense(KV * D, "wv")(x).reshape(B, T, KV, D)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        if cfg.sp_axis is not None:
            # Sequence-parallel: exact ring attention over the sp mesh
            # axis — K/V blocks rotate by ppermute, online softmax
            # accumulates; causality is enforced on GLOBAL positions
            # inside the kernel (long-context path; SURVEY.md §5).  K/V
            # stay GROUPED (KV heads) through the ring — expanded per
            # block inside the kernel — so GQA's bandwidth saving holds
            # on the fabric.
            if cfg.sp_strategy == "a2a":
                # Ulysses-style: all-to-all to head-sharded attention
                # over the full sequence (ops/ulysses.py), then back.
                from dpwa_tpu.ops.ulysses import ulysses_attention_local

                out = ulysses_attention_local(
                    q, k, v, axis_name=cfg.sp_axis, causal=True,
                    impl=cfg.attn_impl,
                ).reshape(B, T, H * D)
                return dense(cfg.d_model, "wo")(out)
            if cfg.sp_layout == "zigzag":
                # Causal-load-balanced layout: every device computes the
                # same number of half-length panels per hop
                # (ops/zigzag_ring.py) — no device idles on skipped
                # future blocks.
                from dpwa_tpu.ops.zigzag_ring import (
                    zigzag_ring_attention_local,
                )

                # attn_impl maps onto the panel kernels: dense pins the
                # jnp einsum panels; auto/flash let the resolver pick
                # the Pallas kernels on TPU (jnp twins elsewhere).
                out = zigzag_ring_attention_local(
                    q, k, v, axis_name=cfg.sp_axis,
                    impl="jnp" if cfg.attn_impl == "dense" else None,
                ).reshape(B, T, H * D)
                return dense(cfg.d_model, "wo")(out)
            from dpwa_tpu.ops.ring_attention import ring_attention_local

            # attn_impl maps onto the ring hop implementation: auto/flash
            # run each hop through the Pallas flash kernel (VMEM score
            # tiles) when eligible; dense keeps the q-chunked einsum hop.
            out = ring_attention_local(
                q, k, v, axis_name=cfg.sp_axis, causal=True,
                impl="xla" if cfg.attn_impl == "dense" else cfg.attn_impl,
            ).reshape(B, T, H * D)
            return dense(cfg.d_model, "wo")(out)
        # The framework's ONE single-device attention (GQA expansion,
        # flash-vs-dense dispatch, f32 accumulation) — shared with the
        # a2a strategy's per-device compute.  Flash: O(T) memory, score
        # panels in VMEM tiles, never HBM (what makes long single-device
        # sequences fit at all; artifacts/attention_memory.json).
        from dpwa_tpu.ops.ulysses import single_device_attention

        out = single_device_attention(
            q, k, v, causal=True, impl=cfg.attn_impl
        ).reshape(B, T, H * D)
        return dense(cfg.d_model, "wo")(out)


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, name: LoRADense(
            feats, cfg.lora_rank, cfg.lora_alpha, cfg.dtype, name=name
        )
        gate = dense(cfg.d_ff, "w_gate")(x)
        up = dense(cfg.d_ff, "w_up")(x)
        return dense(cfg.d_model, "w_down")(nn.silu(gate) * up)


class Block(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        x = x + Attention(cfg, name="attn")(
            RMSNorm(dtype=cfg.dtype, name="attn_norm")(x), positions
        )
        x = x + MLP(cfg, name="mlp")(
            RMSNorm(dtype=cfg.dtype, name="mlp_norm")(x)
        )
        return x


class Llama(nn.Module):
    """Decoder-only LM; returns logits [B, T, vocab]."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        B, T = tokens.shape
        x = nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, name="embed"
        )(tokens)
        positions = jnp.arange(T)
        if cfg.sp_axis is not None:
            if cfg.sp_layout == "zigzag":
                # Device holds global chunks (i, 2n-1-i); rope positions
                # must follow the same zigzag map as the data.
                from dpwa_tpu.ops.zigzag_ring import zigzag_positions_local

                positions = zigzag_positions_local(T, cfg.sp_axis)
            else:
                # Inside shard_map: ``tokens`` is this device's contiguous
                # sequence block; rope needs the GLOBAL positions.
                positions = positions + jax.lax.axis_index(cfg.sp_axis) * T
        for i in range(cfg.n_layers):
            x = Block(cfg, name=f"layer_{i}")(x, positions)
        x = RMSNorm(dtype=cfg.dtype, name="final_norm")(x)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=jnp.float32, name="lm_head"
        )(x)
        return logits


def lora_mask(params) -> object:
    """Pytree of bools: True on LoRA leaves (trainable), False on base."""
    from dpwa_tpu.utils.pytree import _path_str

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(
        treedef, [lora_filter(_path_str(p)) for p, _ in flat]
    )


def lora_optimizer(base_opt, params):
    """LoRA fine-tune optimizer: train adapters, hard-freeze base weights.

    (``optax.masked(opt, mask)`` alone is NOT a freeze — it passes unmasked
    gradients through as raw updates.  Base leaves here get
    ``set_to_zero``, so they stay bit-identical to init, matching config
    5's 'full base weights untouched'.)"""
    import optax

    labels = jax.tree.map(
        lambda is_lora: "train" if is_lora else "freeze", lora_mask(params)
    )
    return optax.multi_transform(
        {"train": base_opt, "freeze": optax.set_to_zero()}, labels
    )
