"""ResNets for the CIFAR-10 / ImageNet gossip benchmarks (Flax).

BASELINE.json configs 2–3: CIFAR-10 ResNet-20 8-peer ring gossip (the
headline benchmark) and ImageNet ResNet-50 32-peer random-pair.  The
reference never defines these models itself — it wraps stock torchvision
models through its adapter — so these are clean-room Flax implementations of
the standard architectures (He et al. 2015; CIFAR variant per section 4.2 of
the paper).

TPU-first choices:

- NHWC layout and 3×3 convs → XLA maps convs onto the MXU directly.
- ``norm='group'`` (default) keeps the forward pass a pure function of
  params — no mutable batch-stats collection — which keeps the whole gossip
  train step a single fused SPMD program and avoids cross-replica stat
  entanglement (each gossip peer would otherwise carry diverging BN stats
  that the exchange must also merge).  ``norm='batch'`` is available for
  strict parity experiments; its ``batch_stats`` ride along as ordinary
  merged state.
- bfloat16 compute / float32 params via the ``dtype`` knob.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


def _norm(norm: str, dtype, train: bool = True) -> Callable[..., nn.Module]:
    if norm == "group":
        return partial(nn.GroupNorm, num_groups=None, group_size=16, dtype=dtype)
    if norm == "batch":
        return partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            dtype=dtype,
        )
    raise ValueError(f"unknown norm {norm!r}")


class BasicBlock(nn.Module):
    """3×3 + 3×3 residual block (ResNet-20/32/44/56 family)."""

    filters: int
    strides: int
    norm: ModuleDef
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(
            self.filters, (3, 3), (self.strides, self.strides),
            use_bias=False, dtype=self.dtype,
        )(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), use_bias=False, dtype=self.dtype)(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters, (1, 1), (self.strides, self.strides),
                use_bias=False, dtype=self.dtype,
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1×1 → 3×3 → 1×1 bottleneck (ResNet-50 family)."""

    filters: int
    strides: int
    norm: ModuleDef
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(
            self.filters, (3, 3), (self.strides, self.strides),
            use_bias=False, dtype=self.dtype,
        )(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters * 4, (1, 1), (self.strides, self.strides),
                use_bias=False, dtype=self.dtype,
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(y + residual)


class CifarResNet(nn.Module):
    """CIFAR-style ResNet: 3×3 stem, 3 stages of n blocks at 16/32/64."""

    depth: int = 20
    num_classes: int = 10
    norm_type: str = "group"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        if (self.depth - 2) % 6 != 0:
            raise ValueError("CIFAR ResNet depth must be 6n+2")
        n = (self.depth - 2) // 6
        norm = _norm(self.norm_type, self.dtype, train)
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), use_bias=False, dtype=self.dtype)(x)
        x = norm()(x)
        x = nn.relu(x)
        for stage, filters in enumerate((16, 32, 64)):
            for block in range(n):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BasicBlock(filters, strides, norm, self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def ResNet20(**kw) -> CifarResNet:
    return CifarResNet(depth=20, **kw)


def ResNet56(**kw) -> CifarResNet:
    return CifarResNet(depth=56, **kw)


class ImageNetResNet(nn.Module):
    """ImageNet-style ResNet with bottleneck blocks (ResNet-50 default)."""

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000
    norm_type: str = "group"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = _norm(self.norm_type, self.dtype, train)
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), (2, 2), use_bias=False, dtype=self.dtype)(x)
        x = norm()(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, (size, filters) in enumerate(
            zip(self.stage_sizes, (64, 128, 256, 512))
        ):
            for block in range(size):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(filters, strides, norm, self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def ResNet50(**kw) -> ImageNetResNet:
    return ImageNetResNet(stage_sizes=(3, 4, 6, 3), **kw)
