"""Checkpoint / resume (Orbax).

The reference has no checkpointing — users call ``torch.save`` themselves
(SURVEY.md §5 "Checkpoint/resume").  The rebuild ships it first-class: the
full :class:`~dpwa_tpu.train.GossipTrainState` — params, optimizer state,
per-peer clocks, and the global schedule position ``step`` — is saved
atomically and restored sharded.  Saving ``step`` matters specifically for
gossip: the pairing schedule and the participation draws are deterministic
functions of it, so a resumed run continues the exact exchange sequence.

Per-peer divergence is preserved: replicas legitimately differ between
exchanges, and the peer-stacked leaves capture every replica, not one
canonical copy."""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from dpwa_tpu.train import GossipTrainState

PyTree = Any


def save_checkpoint(path: str, state: GossipTrainState) -> None:
    """Atomically save a gossip training state to ``path`` (a directory)."""
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, dict(state._asdict()), force=True)


def restore_checkpoint(
    path: str, like: Optional[GossipTrainState] = None
) -> GossipTrainState:
    """Restore a state saved by :func:`save_checkpoint`.

    ``like`` (same treedef/shapes/shardings as the saved state) restores
    arrays onto the right devices/shardings; without it, arrays come back
    as host numpy."""
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            target = jax.tree.map(
                ocp.utils.to_shape_dtype_struct, dict(like._asdict())
            )
            restored = ckptr.restore(path, target)
        else:
            restored = ckptr.restore(path)
    return GossipTrainState(**restored)
