"""Checkpoint / resume (Orbax).

The reference has no checkpointing — users call ``torch.save`` themselves
(SURVEY.md §5 "Checkpoint/resume").  The rebuild ships it first-class: the
full :class:`~dpwa_tpu.train.GossipTrainState` — params, optimizer state,
per-peer clocks, and the global schedule position ``step`` — is saved
atomically and restored sharded.  Saving ``step`` matters specifically for
gossip: the pairing schedule and the participation draws are deterministic
functions of it, so a resumed run continues the exact exchange sequence.

Per-peer divergence is preserved: replicas legitimately differ between
exchanges, and the peer-stacked leaves capture every replica, not one
canonical copy."""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from dpwa_tpu.train import GossipTrainState

PyTree = Any

# State fields that post-date the first checkpoint format; restores of
# checkpoints written before a field existed backfill it from ``like``
# (or leave it defaulted when restoring without ``like``).
_OPTIONAL_FIELDS = ("loss",)


def save_checkpoint(path: str, state) -> None:
    """Atomically save a training state to ``path`` (a directory).

    Accepts either peer-layout: :class:`~dpwa_tpu.train.GossipTrainState`
    (mesh-sharded SPMD) or
    :class:`~dpwa_tpu.parallel.stacked.StackedTrainState` (single-device
    virtual peers) — both carry the same fields, so a run can even be
    saved from one layout and resumed in the other (pass the matching
    ``like`` at restore)."""
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, dict(state._asdict()), force=True)


def restore_checkpoint(path: str, like: Optional[Any] = None):
    """Restore a state saved by :func:`save_checkpoint`.

    ``like`` (same treedef/shapes/shardings as the saved state) restores
    arrays onto the right devices/shardings, and its type decides the
    returned state class; without it, arrays come back as host numpy in a
    :class:`GossipTrainState` REGARDLESS of which layout saved the
    checkpoint (the file records no layout; the two state classes carry
    identical fields).  To re-label, rewrap:
    ``StackedTrainState(**restored._asdict())``.  Pass ``like`` whenever
    the class identity matters."""
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            target = jax.tree.map(
                ocp.utils.to_shape_dtype_struct, dict(like._asdict())
            )
            # Fields added to the state AFTER a checkpoint was written
            # (round 2 added per-peer ``loss``) are absent from old saves,
            # and Orbax refuses a target whose structure disagrees with
            # the save.  On mismatch, retry with the optional fields
            # dropped from the target and backfill them from ``like``, so
            # old checkpoints keep restoring.
            try:
                restored = ckptr.restore(path, target)
            except (ValueError, KeyError):
                backfill = {
                    f: getattr(like, f)
                    for f in _OPTIONAL_FIELDS
                    if f in target
                }
                if not backfill:
                    raise
                for f in backfill:
                    del target[f]
                restored = ckptr.restore(path, target)
                restored.update(backfill)
            # ``step`` is a host-scalar in spirit: leave it uncommitted so
            # it can join a jitted computation under ANY sharding layout (a
            # restored committed-to-one-device scalar would conflict with
            # mesh-sharded params when resuming in a different layout than
            # the save ran in).  Without ``like`` everything stays host
            # numpy, per the contract above.
            restored["step"] = jnp.asarray(np.asarray(restored["step"]))
        else:
            restored = ckptr.restore(path)
    cls = type(like) if like is not None else GossipTrainState
    # Old checkpoints simply lack optional fields here; the state classes
    # default them (loss=None is accepted by both train steps).
    return cls(**restored)
