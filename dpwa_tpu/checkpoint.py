"""Checkpoint / resume (Orbax).

The reference has no checkpointing — users call ``torch.save`` themselves
(SURVEY.md §5 "Checkpoint/resume").  The rebuild ships it first-class: the
full :class:`~dpwa_tpu.train.GossipTrainState` — params, optimizer state,
per-peer clocks, and the global schedule position ``step`` — is saved
atomically and restored sharded.  Saving ``step`` matters specifically for
gossip: the pairing schedule and the participation draws are deterministic
functions of it, so a resumed run continues the exact exchange sequence.

Per-peer divergence is preserved: replicas legitimately differ between
exchanges, and the peer-stacked leaves capture every replica, not one
canonical copy."""

from __future__ import annotations

import json
import os
import warnings
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from dpwa_tpu.train import GossipTrainState

PyTree = Any

# State fields that post-date the first checkpoint format; restores of
# checkpoints written before a field existed backfill it from ``like``
# (or leave it defaulted when restoring without ``like``).
_OPTIONAL_FIELDS = ("loss",)


def _data_state_path(path: str) -> str:
    """Sidecar for the data-stream state — a sibling of the Orbax dir
    (never inside it: Orbax owns that directory's contents)."""
    return path.rstrip(os.sep) + "-data.json"


def _layout_path(path: str) -> str:
    """Sidecar recording which state class was saved (Orbax stores only
    the array tree; both layouts share one field set)."""
    return path.rstrip(os.sep) + "-meta.json"


def _state_class(name: str):
    if name == "StackedTrainState":
        from dpwa_tpu.parallel.stacked import StackedTrainState

        return StackedTrainState
    return GossipTrainState


def save_checkpoint(path: str, state, data_stream=None) -> None:
    """Atomically save a training state to ``path`` (a directory).

    Accepts either peer-layout: :class:`~dpwa_tpu.train.GossipTrainState`
    (mesh-sharded SPMD) or
    :class:`~dpwa_tpu.parallel.stacked.StackedTrainState` (single-device
    virtual peers) — both carry the same fields, so a run can even be
    saved from one layout and resumed in the other (pass the matching
    ``like`` at restore).

    ``data_stream`` (anything with ``state_dict()``, e.g.
    :class:`~dpwa_tpu.data.PeerBatchStream`) additionally captures the
    per-peer dataset cursor/RNG position in a JSON sidecar next to the
    Orbax directory, so a resumed run replays the EXACT batch sequence —
    without it, saving ``step`` pins the exchange schedule but the data
    trajectory diverges on resume."""
    path = os.path.abspath(path)
    sidecar = _data_state_path(path)
    # The previous save's sidecar is deliberately left in place until the
    # new one atomically replaces it: pre-deleting would mean a crash
    # during the Orbax write leaves the SURVIVING old checkpoint (Orbax
    # writes atomically) with no sidecar — the last good resume point
    # irrecoverably lost.  Stale-pairing protection comes from the
    # ``ckpt_step`` stamp instead: restore refuses a sidecar whose stamp
    # disagrees with the restored checkpoint's ``step``.  The ONE case
    # still pre-deleted is a legacy UNSTAMPED sidecar (pre-stamp format):
    # it cannot be verified against the new state, so a crash mid-save
    # would silently pair it with the overwritten checkpoint — for that
    # transition save only, keep the old fail-safe (restore raises
    # FileNotFoundError rather than replaying the wrong batches).
    if os.path.exists(sidecar):
        try:
            with open(sidecar) as f:
                old = json.load(f)
        except (OSError, json.JSONDecodeError):
            old = None
        if not (isinstance(old, dict) and "ckpt_step" in old):
            os.remove(sidecar)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, dict(state._asdict()), force=True)
    meta_tmp = _layout_path(path) + ".tmp"
    with open(meta_tmp, "w") as f:
        json.dump({"layout": type(state).__name__}, f)
    os.replace(meta_tmp, _layout_path(path))
    if data_stream is not None:
        tmp = sidecar + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "ckpt_step": int(np.asarray(jax.device_get(state.step))),
                    "data": data_stream.state_dict(),
                },
                f,
            )
        os.replace(tmp, sidecar)  # atomic write
    elif os.path.exists(sidecar):
        # A no-stream re-save at the same path: drop the previous save's
        # sidecar, but only AFTER the new Orbax write succeeded — a crash
        # above leaves the old checkpoint+sidecar pair fully intact.
        os.remove(sidecar)


def _saved_keys(ckptr, path) -> Optional[set]:
    """Top-level keys recorded in the checkpoint's metadata, or None if
    the metadata cannot be read (older Orbax layouts)."""
    try:
        return set(_metadata_tree(ckptr, path).keys())
    except Exception:
        return None


def _metadata_tree(ckptr, path) -> dict:
    """The checkpoint's top-level metadata tree across Orbax versions
    (0.7 returns the dict directly; newer wraps it in item_metadata)."""
    meta = ckptr.metadata(path)
    return meta if isinstance(meta, dict) else meta.item_metadata.tree


def restore_checkpoint(path: str, like: Optional[Any] = None, data_stream=None):
    """Restore a state saved by :func:`save_checkpoint`.

    ``like`` (same treedef/shapes/shardings as the saved state) restores
    arrays onto the right devices/shardings, and its type decides the
    returned state class; without it, arrays come back as host numpy in
    the class recorded by the save's layout sidecar
    (``<path>-meta.json``; checkpoints predating it default to
    :class:`GossipTrainState` — the two layouts carry identical fields,
    so rewrapping is always safe).  Pass ``like`` whenever
    devices/shardings matter.

    ``data_stream`` (``load_state_dict()``-capable): restore the dataset
    position saved alongside this checkpoint.  Raises if the checkpoint
    has no data sidecar — silently continuing with a fresh stream would
    replay different batches, the exact bug the sidecar exists to stop."""
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            target = jax.tree.map(
                ocp.utils.to_shape_dtype_struct, dict(like._asdict())
            )
            # Fields added to the state AFTER a checkpoint was written
            # (round 2 added per-peer ``loss``) are absent from old saves,
            # and Orbax refuses a target whose structure disagrees with
            # the save.  Before retrying, check the save's OWN metadata:
            # only a genuinely absent optional field justifies dropping it
            # from the target — any other mismatch re-raises the original
            # Orbax diagnostic untouched (an unrelated error retried
            # against a mutated target would mask it).
            try:
                restored = ckptr.restore(path, target)
            except (ValueError, KeyError) as first_err:
                saved = _saved_keys(ckptr, path)
                missing = [
                    f
                    for f in _OPTIONAL_FIELDS
                    if f in target and (saved is None or f not in saved)
                ]
                if not missing:
                    raise
                backfill = {f: getattr(like, f) for f in missing}
                for f in missing:
                    del target[f]
                try:
                    restored = ckptr.restore(path, target)
                except (ValueError, KeyError):
                    raise first_err from None
                restored.update(backfill)
            # ``step`` is a host-scalar in spirit: leave it uncommitted so
            # it can join a jitted computation under ANY sharding layout (a
            # restored committed-to-one-device scalar would conflict with
            # mesh-sharded params when resuming in a different layout than
            # the save ran in).  Without ``like`` everything stays host
            # numpy, per the contract above.
            restored["step"] = jnp.asarray(np.asarray(restored["step"]))
        else:
            restored = ckptr.restore(path)
    if data_stream is not None:
        sidecar = _data_state_path(path)
        if not os.path.exists(sidecar):
            raise FileNotFoundError(
                f"checkpoint {path} has no data-stream sidecar ({sidecar}); "
                "it was saved without data_stream= — resuming this stream "
                "would replay different batches"
            )
        with open(sidecar) as f:
            payload = json.load(f)
        if isinstance(payload, dict) and "ckpt_step" in payload:
            ckpt_step = int(np.asarray(jax.device_get(restored["step"])))
            if int(payload["ckpt_step"]) != ckpt_step:
                raise ValueError(
                    f"data-stream sidecar {sidecar} was written for step "
                    f"{payload['ckpt_step']} but the checkpoint holds step "
                    f"{ckpt_step}; refusing to pair a stale stream position "
                    "with this state (a crash likely interrupted the save "
                    "that would have replaced the sidecar)"
                )
            data_stream.load_state_dict(payload["data"])
        else:
            # Sidecar predating the ckpt_step stamp: raw state_dict.
            data_stream.load_state_dict(payload)
    if like is not None:
        cls = type(like)
    else:
        layout = _layout_path(path)
        name = ""
        if os.path.exists(layout):
            with open(layout) as f:
                name = json.load(f).get("layout", "")
        cls = _state_class(name)
    # Old checkpoints simply lack optional fields here; the state classes
    # default them (loss=None is accepted by both train steps).
    return cls(**restored)


def validate_checkpoint(path: str, data_stream: bool = False) -> Optional[str]:
    """Cheap structural health check; ``None`` when sound, else a reason.

    A checkpoint written while the writer was being killed (the crash
    scenarios the recovery subsystem exists for, docs/recovery.md) can
    be missing its Orbax commit marker, hold an unreadable metadata
    tree, or carry a sidecar that disagrees with the saved ``step``.
    This inspects exactly those seams WITHOUT restoring any array data,
    so callers can vet a whole directory of checkpoints in milliseconds:

    - the path is an Orbax directory whose metadata tree is readable and
      non-empty (an interrupted save is detected by Orbax's own
      atomic-commit protocol and surfaces here as unreadable metadata);
    - the layout sidecar (``<path>-meta.json``), when present, is valid
      JSON;
    - with ``data_stream=True``, the data sidecar exists, parses, and —
      when step-stamped — matches the checkpoint's saved ``step``.
    """
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return "not a directory"
    try:
        with ocp.StandardCheckpointer() as ckptr:
            tree = _metadata_tree(ckptr, path)
            if not tree:
                return "empty metadata tree"
            saved_step: Optional[int] = None
            step_meta = tree.get("step")
            # Array metadata has no value; only the sidecar needs the
            # step, and then a 0-d scalar is cheap to restore alone.
            if data_stream and step_meta is not None:
                restored = ckptr.restore(
                    path,
                    {
                        "step": ocp.utils.to_shape_dtype_struct(
                            jnp.zeros(
                                step_meta.shape, dtype=step_meta.dtype
                            )
                        )
                    },
                )
                saved_step = int(np.asarray(restored["step"]))
    except Exception as e:  # Orbax raises a zoo of types on corruption
        return f"unreadable Orbax metadata: {type(e).__name__}: {e}"
    layout = _layout_path(path)
    if os.path.exists(layout):
        try:
            with open(layout) as f:
                json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            return f"corrupt layout sidecar: {e}"
    if data_stream:
        sidecar = _data_state_path(path)
        if not os.path.exists(sidecar):
            return "missing data-stream sidecar"
        try:
            with open(sidecar) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            return f"corrupt data-stream sidecar: {e}"
        if (
            isinstance(payload, dict)
            and "ckpt_step" in payload
            and saved_step is not None
            and int(payload["ckpt_step"]) != saved_step
        ):
            return (
                f"data-stream sidecar stamped step {payload['ckpt_step']} "
                f"!= checkpoint step {saved_step}"
            )
    return None


def restore_latest_valid(
    paths: Sequence[str],
    like: Optional[Any] = None,
    data_stream=None,
):
    """Restore the newest structurally-valid checkpoint from ``paths``.

    ``paths`` is ordered oldest → newest (the natural order of a save
    cadence); candidates are tried newest-first, each vetted with
    :func:`validate_checkpoint` (including the data sidecar when
    ``data_stream`` is given) and then actually restored — a candidate
    that passes the cheap check but still fails restore is skipped too.
    Every skip emits a :class:`UserWarning` naming the casualty and why,
    because silently resuming from an older state than the operator
    expects is worth a visible trace.  Raises ``FileNotFoundError`` when
    nothing survives — the caller decides between cold start and
    peer-assisted bootstrap (:mod:`dpwa_tpu.recovery`).

    This is deliberately a SEPARATE entry point: :func:`restore_checkpoint`
    keeps its strict raise-on-anything-wrong contract for callers that
    name one specific checkpoint and need to know it was unusable."""
    reasons: List[str] = []
    for path in reversed(list(paths)):
        reason = validate_checkpoint(path, data_stream=data_stream is not None)
        if reason is None:
            try:
                return restore_checkpoint(
                    path, like=like, data_stream=data_stream
                )
            except Exception as e:
                reason = f"restore failed: {type(e).__name__}: {e}"
        reasons.append(f"{path}: {reason}")
        warnings.warn(
            f"skipping checkpoint {path} ({reason}); "
            "falling back to an earlier one",
            stacklevel=2,
        )
    raise FileNotFoundError(
        "no valid checkpoint among candidates: " + "; ".join(reasons)
        if reasons
        else "no checkpoint candidates given"
    )
