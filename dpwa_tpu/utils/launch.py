"""Shared example-launcher plumbing: transport + device policy selection.

Every example (the reference keeps one per benchmark config,
``examples/mnist`` etc. — SURVEY.md §2) exposes the same two knobs:

- ``--transport ici|stacked`` — ``ici`` runs one SPMD process over a device
  mesh (one device per peer, the real multi-chip layout); ``stacked`` runs
  every peer on ONE device as a stacked leading axis (the single-chip
  benchmarking mode, SURVEY.md §7 note: the dev box has one chip).
- ``--devices auto|cpu|native`` — device policy.  For ``ici``: ``native``
  requires a real accelerator mesh, ``cpu`` forces the emulated host mesh,
  ``auto`` picks.  For ``stacked``: ``auto`` keeps jax's default device
  (the real chip when present), ``cpu`` forces the CPU backend, ``native``
  errors rather than silently reporting a CPU fallback's steps/sec as a
  single-chip number.

:func:`build_transport` returns the transport plus the matching
state-init / train-step constructors, so an example's training loop is
identical across transports.
"""

from __future__ import annotations

import argparse
import os
from typing import NamedTuple, Optional, Tuple


def child_process_env(
    repo_root: Optional[str] = None,
    *,
    strip: Tuple[str, ...] = (
        "XLA_FLAGS",
        "JAX_PLATFORMS",
        "JAX_NUM_PROCESSES",
    ),
    platform: Optional[str] = "cpu",
) -> dict:
    """Environment for a spawned JAX worker process.

    Launchers that fork multi-process legs (the TCP free-run experiment,
    the multi-process DCN test) must not leak the parent's frozen platform
    choices: ``XLA_FLAGS``'s forced device count and ``JAX_PLATFORMS`` are
    parsed once at the child's first backend init, so inherited values
    silently misconfigure it.  Strips those, pins ``platform`` (None keeps
    the child's default resolution), and prepends ``repo_root`` to
    ``PYTHONPATH`` so in-repo imports work from any cwd."""
    env = {k: v for k, v in os.environ.items() if k not in strip}
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    if repo_root is not None:
        env["PYTHONPATH"] = os.pathsep.join(
            filter(None, (repo_root, env.get("PYTHONPATH")))
        )
    return env


def add_transport_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument(
        "--transport", choices=("ici", "stacked"), default="ici",
        help="'ici': SPMD over a device mesh (one device per peer); "
        "'stacked': all peers on ONE device as a stacked axis — the "
        "single-chip benchmarking mode",
    )
    ap.add_argument(
        "--devices", default="auto", choices=("auto", "cpu", "native"),
        help="device policy; see dpwa_tpu.utils.launch",
    )
    ap.add_argument(
        "--wire-dtype", default=None, choices=("f32", "bf16", "int8"),
        help="override protocol.wire_dtype: compress the SHIPPED replica "
        "(bf16: half the exchange bytes; int8: ~3.9x fewer, unbiased "
        "stochastic rounding — ops/quantize.py); default keeps the "
        "config file's setting",
    )


def apply_wire_dtype(cfg, wire_dtype: Optional[str]):
    """Return ``cfg`` with ``protocol.wire_dtype`` overridden (None =
    unchanged).  Configs are frozen dataclasses; ``dataclasses.replace``
    re-runs validation."""
    if wire_dtype is None:
        return cfg
    import dataclasses

    return dataclasses.replace(
        cfg, protocol=dataclasses.replace(cfg.protocol, wire_dtype=wire_dtype)
    )


class TransportBundle(NamedTuple):
    transport: object
    init_state: object  # (stacked_params, opt, transport, ...) -> state
    make_step: object  # (loss_fn, opt, transport, ...) -> step_fn
    eval_transport: Optional[object]  # None => single-device eval
    batch_sharding: Optional[object]  # peer sharding for staged batches
    config: object = None  # the EFFECTIVE config (wire_dtype applied)


def apply_device_policy(cfg, transport: str, devices: str) -> None:
    """Enforce the ``--devices`` policy BEFORE jax initializes a backend."""
    from dpwa_tpu.utils.devices import ensure_devices

    if transport == "ici":
        ensure_devices(cfg.n_peers, mode=devices)
        return
    # Stacked needs one device and should keep jax's native pick (the
    # real chip) — ensure_devices' auto mode would force the emulated
    # CPU mesh, which is for multi-device ICI runs.  The policy still
    # applies: 'cpu' forces CPU, 'native' must not silently report a
    # CPU fallback's steps/sec as a single-chip number.
    if devices == "cpu":
        ensure_devices(1, mode="cpu")
    elif devices == "native":
        import jax

        if jax.devices()[0].platform == "cpu":
            raise RuntimeError(
                "--devices native: no accelerator available (jax picked "
                "cpu); drop --devices or use --devices cpu explicitly"
            )


def build_transport(
    cfg,
    transport: str = "ici",
    devices: str = "auto",
    wire_dtype: Optional[str] = None,
):
    """Select + construct the transport; returns a :class:`TransportBundle`.

    Call before creating any arrays: the device policy may decide the JAX
    platform, which is frozen at first backend use.

    ``wire_dtype`` (the ``--wire-dtype`` flag from
    :func:`add_transport_args`) is applied HERE so a caller can never
    accept the flag yet silently ignore it; read the effective config
    back from ``bundle.config``."""
    cfg = apply_wire_dtype(cfg, wire_dtype)
    apply_device_policy(cfg, transport, devices)
    if transport == "stacked":
        from dpwa_tpu.parallel.stacked import (
            StackedTransport,
            init_stacked_state,
            make_stacked_train_step,
        )

        return TransportBundle(
            transport=StackedTransport(cfg),
            init_state=init_stacked_state,
            make_step=make_stacked_train_step,
            eval_transport=None,
            batch_sharding=None,
            config=cfg,
        )
    from dpwa_tpu.parallel.ici import IciTransport
    from dpwa_tpu.parallel.mesh import make_mesh, peer_sharding
    from dpwa_tpu.train import init_gossip_state, make_gossip_train_step

    t = IciTransport(cfg, mesh=make_mesh(cfg))
    # Stage batches peer-sharded for the mesh path (a whole batch committed
    # to one device would be resharded inside the jitted shard_map, which
    # the thread-starved forced-CPU mesh cannot always service).
    return TransportBundle(
        transport=t,
        init_state=init_gossip_state,
        make_step=make_gossip_train_step,
        eval_transport=t,
        batch_sharding=peer_sharding(t.mesh),
        config=cfg,
    )
