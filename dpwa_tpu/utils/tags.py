"""Central registry of threefry control-tag allocations.

Every host-side control decision (participation, faults, partner pools,
relay probes, chaos, replica sketches …) draws from a counter-based
threefry stream keyed by ``schedules._pair_key(seed, step, pair_id, tag)``.
The ``tag`` is what keeps the streams independent: two draws that share a
tag share a stream, and a collision silently correlates decisions that
the convergence analysis assumes are independent.  This module is the
single place tags are allocated — registering the same integer twice
raises at import time, and ``dpwalint``'s determinism checker rejects any
raw tag literal that does not come from here.

Layout of the tag space:

- ``0 .. 15``  first control-plane block (below) — FULL as of the
  island-churn draw; new control draws go in the second block.
- ``16 .. 31`` chaos fault-kind streams: ``CHAOS_TAG_BASE + kind`` where
  ``kind`` is one of the ``CHAOS_KIND_*`` indices below (13 of 16 kinds
  allocated; the remaining three stay reserved for future fault kinds so
  chaos never has to renumber).
- ``32 .. 47`` second control-plane block (``CONTROL_TAG_BASE_2``),
  opened for the shard-schedule draw once 0..15 filled.  Allocate new
  control draws here, bottom-up; when THIS block fills, open 48..63 and
  extend this comment.

The int8 stochastic-rounding stream in ``ops/quantize.py`` is keyed on a
separate ``fold_in(fold_in(key, step), sender)`` chain (no control tag)
and deliberately does not live in this space.
"""

from __future__ import annotations

from typing import Dict

_TAG_REGISTRY: Dict[int, str] = {}


def _register(name: str, value: int) -> int:
    """Allocate control tag ``value`` to ``name``; collision = error."""
    if value in _TAG_REGISTRY:
        raise ValueError(
            "threefry control-tag collision: tag %d already registered as"
            " %r, cannot also register %r"
            % (value, _TAG_REGISTRY[value], name)
        )
    _TAG_REGISTRY[value] = name
    return value


# Control-plane draws (one tag per independent decision stream).
TAG_PARTICIPATION = _register("participation_draw", 0)
TAG_FAULT = _register("fault_draw", 1)
TAG_POOL_BRANCH = _register("pool_branch_draw", 2)
TAG_FALLBACK = _register("fallback_draw", 3)
TAG_BACKOFF_JITTER = _register("backoff_jitter_draw", 4)
TAG_DONOR = _register("bootstrap_donor_draw", 5)
TAG_RELAY_PROBE = _register("relay_probe_draw", 6)
TAG_HEAL_DONOR = _register("heal_donor_draw", 7)
TAG_DEGRADE_SHED = _register("degrade_shed_draw", 8)
TAG_SKETCH = _register("replica_sketch_draw", 9)
# Fleet churn-schedule draws (dpwa_tpu/fleet): per-(round, peer) leave /
# join decisions, per-round cohort-arrival sizing, and the rolling-restart
# cursor.  Independent streams so a leave-heavy schedule does not skew
# which peers restart.
TAG_CHURN_LEAVE = _register("churn_leave_draw", 10)
TAG_CHURN_JOIN = _register("churn_join_draw", 11)
TAG_CHURN_COHORT = _register("churn_cohort_draw", 12)
TAG_CHURN_RESTART = _register("churn_restart_draw", 13)
# Hierarchical gossip (dpwa_tpu/hier): the per-(island, term) leader
# election draw and the fleet's whole-island churn decisions.  Separate
# streams so island membership churn cannot skew which member wins the
# leadership draw.
TAG_LEADER = _register("leader_draw", 14)
TAG_ISLAND_CHURN = _register("island_churn_draw", 15)

# Chaos fault-kind streams occupy CHAOS_TAG_BASE + kind.
CHAOS_TAG_BASE = 16

_CHAOS_KIND_REGISTRY: Dict[int, str] = {}


def _register_chaos_kind(name: str, kind: int) -> int:
    """Allocate chaos kind ``kind``; collides against both registries."""
    if kind in _CHAOS_KIND_REGISTRY:
        raise ValueError(
            "chaos fault-kind collision: kind %d already registered as"
            " %r, cannot also register %r"
            % (kind, _CHAOS_KIND_REGISTRY[kind], name)
        )
    _CHAOS_KIND_REGISTRY[kind] = name
    # The kind's absolute tag must not shadow a control tag either.
    _register("chaos:" + name, CHAOS_TAG_BASE + kind)
    return kind


# Wire faults (health/chaos.py _PRIORITY order is behavioral priority,
# not tag order).
CHAOS_KIND_DROP = _register_chaos_kind("drop", 0)
CHAOS_KIND_DELAY = _register_chaos_kind("delay", 1)
CHAOS_KIND_THROTTLE = _register_chaos_kind("throttle", 2)
CHAOS_KIND_TRUNCATE = _register_chaos_kind("truncate", 3)
CHAOS_KIND_CORRUPT = _register_chaos_kind("corrupt", 4)
# Drawn partitions: kind 5 decides whether a time block is split (drawn
# once per block, peer key 0); kind 6 assigns each peer a side.
CHAOS_KIND_PARTITION = _register_chaos_kind("partition", 5)
CHAOS_KIND_PARTITION_SIDE = _register_chaos_kind("partition_side", 6)
# Byzantine content faults (served frame stays wire-valid; only the
# vector content lies — see health/chaos.py byzantine_frame).
CHAOS_KIND_BYZ_SIGN = _register_chaos_kind("byz_sign", 7)
CHAOS_KIND_BYZ_SCALE = _register_chaos_kind("byz_scale", 8)
CHAOS_KIND_BYZ_REPLAY = _register_chaos_kind("byz_replay", 9)
CHAOS_KIND_BYZ_ZERO = _register_chaos_kind("byz_zero", 10)
# Flowctl shaping (slow-peer chaos): STALL decides whether this
# (round, peer) stalls mid-frame, STALL_LEN draws the stall length as a
# fraction of ``stall_ms_max`` — both independent of the wire-fault
# draws, so a trickled peer can ALSO stall, like a real overloaded box.
CHAOS_KIND_STALL = _register_chaos_kind("stall", 11)
CHAOS_KIND_STALL_LEN = _register_chaos_kind("stall_len", 12)
# Link-quality flapping (health/chaos.py bandwidth_bps): BANDWIDTH_FLAP
# gates whether a (round-block, peer) is inside a flap window at all,
# BANDWIDTH_RATE draws where inside [bandwidth_bps_min, max] the shaped
# throughput lands.  Two streams so the flap duty cycle cannot skew how
# deep the shaping goes — the tune controller's escalate→backoff→dwell
# path is exercised against both axes independently.
CHAOS_KIND_BANDWIDTH_FLAP = _register_chaos_kind("bandwidth_flap", 13)
CHAOS_KIND_BANDWIDTH_RATE = _register_chaos_kind("bandwidth_rate", 14)

# Second control-plane block (0..15 filled; 16..31 belongs to chaos).
CONTROL_TAG_BASE_2 = 32

# Sharded gossip (ops/shard.py + schedules.shard_draw): the per-epoch
# shard-visit permutation.  Keyed on the publish clock, so a pair of
# free-running peers lands on the same shard each round without any
# negotiation, and every shard is visited exactly once per k rounds.
TAG_SHARD = _register("shard_draw", CONTROL_TAG_BASE_2 + 0)

# Barrier-free async rounds (parallel/async_loop.py +
# schedules.async_drain_draw): tie-break rotation for the deterministic
# drain order when several peers have frames pending at the same publish
# clock.  Keyed on the local step, so a rerun of the same soak drains
# queues in the same order regardless of arrival timing.
TAG_ASYNC_DRAIN = _register("async_drain_draw", CONTROL_TAG_BASE_2 + 1)

# Bounded partial views (membership/partial_view.py +
# schedules.view_sample_draw): which tracked peers land in this frame's
# truncated digest.  Keyed on the publish clock, so a seeded rerun
# publishes byte-identical digests and two observers of the same node
# see the same sample.
TAG_VIEW_SAMPLE = _register("view_sample_draw", CONTROL_TAG_BASE_2 + 2)

# Passive-view shuffle (schedules.passive_shuffle_draw): which passive
# candidate is promoted into the active view when an active peer fails,
# and which resident it displaces when the reservoir is full.  A stream
# separate from the sample draw so digest truncation cannot skew
# replacement choices.
TAG_PASSIVE_SHUFFLE = _register("passive_shuffle_draw", CONTROL_TAG_BASE_2 + 3)

# Training-harness data order (run/harness.py +
# schedules.data_shuffle_draw): each node's per-epoch shard permutation.
# Keyed on ``(seed, epoch, node)``, so a seeded rerun replays the exact
# batch sequence with no stream state to checkpoint, and a rejoining
# node lands on the same data order as the run it crashed out of.
TAG_DATA_SHUFFLE = _register("data_shuffle_draw", CONTROL_TAG_BASE_2 + 4)

# Self-tuning wire (tune/controller.py + schedules.tune_jitter_draw):
# the per-(link, clock) dwell-jitter offset that desynchronizes ladder
# escalations across links.  Without it, every wire-bound link clears
# its dwell on the same round and the whole fleet's codecs step in
# lock-step — a thundering herd the per-link controller exists to avoid.
# Keyed on the publish clock like shard_draw, so both ends of a link
# (and a seeded rerun) draw the same offset with no negotiation.
TAG_TUNE_JITTER = _register("tune_jitter_draw", CONTROL_TAG_BASE_2 + 5)


def registered_tags() -> Dict[int, str]:
    """A copy of the full tag → name allocation map (chaos included)."""
    return dict(_TAG_REGISTRY)


def registered_chaos_kinds() -> Dict[int, str]:
    """A copy of the chaos kind → name allocation map."""
    return dict(_CHAOS_KIND_REGISTRY)
