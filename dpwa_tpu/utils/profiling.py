"""Tracing / profiling (SURVEY.md §5: absent in the reference; first-class
here).

- :func:`trace` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable trace of the training loop (XLA ops, collectives,
  host callbacks).
- :func:`measure_exchange_bandwidth` — the GB/s/chip counter around the
  averaging collective, the headline metric (BASELINE.json:2).  Used by
  ``bench.py`` and available to users against their own models.
- :func:`measure_sync_rtt` / :func:`timed_loop` — the one correct timing
  idiom for this box's tunneled chip, shared by the bench and the
  experiments (see ``timed_loop``'s docstring for why naive timing lies
  twice here).
"""

from __future__ import annotations

import contextlib
import sys
import time
from typing import Callable, Iterator, Optional

import jax
import numpy as np


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """``with trace("/tmp/trace"):`` — profile everything inside."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def measure_sync_rtt(samples: int = 10) -> float:
    """Median seconds of one scalar host readback (the timing sync).

    On a tunneled/async backend a ``float(x.sum())`` readback — the only
    reliable completion barrier (``block_until_ready`` can return at
    enqueue) — costs a fixed round trip (~63 ms through this box's chip
    tunnel).  Timed loops end in exactly one such readback; subtracting
    this constant removes a pure measurement artifact without touching
    device-side time."""
    import jax.numpy as jnp

    s = jnp.float32(1.0)
    for _ in range(3):
        float(s.sum())
    times = []
    for _ in range(samples):
        t0 = time.perf_counter()
        float(s.sum())
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


class TimedResult(float):
    """Seconds-per-iteration that also carries measurement validity.

    A plain float to every existing consumer; ``valid`` is False when the
    subtracted sync RTT exceeded half the raw loop time — the corrected
    figure is then noise-dominated and must not be recorded as a
    benchmark number (``bench.py`` refuses and retries with more iters).
    ``dt_raw``/``sync_rtt`` preserve the inputs for diagnostics."""

    valid: bool
    dt_raw: float
    sync_rtt: float

    def __new__(cls, seconds: float, valid: bool, dt_raw: float, rtt: float):
        self = super().__new__(cls, seconds)
        self.valid = valid
        self.dt_raw = dt_raw
        self.sync_rtt = rtt
        return self


def timed_loop(
    run_iter: Callable,
    sync: Callable,
    carry,
    iters: int,
    *,
    warmup: int = 3,
    sync_rtt: Optional[float] = None,
    label: str = "timed_loop",
):
    """Mean wall seconds per iteration of ``carry = run_iter(carry, k)``.

    Correct timing on this box needs two things at once:

    1. ``sync(carry)`` must force REAL completion via a host readback of an
       on-device reduction — ``jax.block_until_ready`` returns at enqueue
       time through the chip tunnel, so naive per-call timing observes only
       the dispatch.
    2. That readback costs a fixed round trip (``sync_rtt``; measured via
       :func:`measure_sync_rtt` when not supplied), paid exactly once per
       loop, which must be subtracted or short loops are dominated by it.

    When the RTT exceeds half the raw measurement the corrected figure is
    mostly noise; the returned :class:`TimedResult` carries
    ``valid=False`` (and a warning is printed to stderr) so callers can
    refuse to record it rather than publish an absurd number (clamped at
    a 1 ns floor).

    Returns ``(seconds_per_iter: TimedResult, final_carry)``.
    """
    if sync_rtt is None:
        sync_rtt = measure_sync_rtt()
    for k in range(warmup):
        carry = run_iter(carry, k)
    sync(carry)
    t0 = time.perf_counter()
    for k in range(iters):
        carry = run_iter(carry, k)
    sync(carry)
    dt_raw = time.perf_counter() - t0
    valid = sync_rtt <= 0.5 * dt_raw
    if not valid:
        print(
            f"WARNING [{label}]: sync RTT {sync_rtt*1e3:.1f} ms exceeds "
            f"half the raw measurement {dt_raw*1e3:.1f} ms over {iters} "
            "iters — the corrected time is noise-dominated; raise iters",
            file=sys.stderr,
            flush=True,
        )
    return (
        TimedResult(
            max(dt_raw - sync_rtt, 1e-9) / iters, valid, dt_raw, sync_rtt
        ),
        carry,
    )


def measure_exchange_bandwidth(
    transport,
    params,
    meta,
    *,
    iters: int = 20,
    start_step: int = 0,
) -> dict:
    """Time `transport.exchange` and report per-chip averaging bandwidth.

    Accounting per SURVEY.md §7: one exchange moves 2 × payload bytes per
    peer (receive partner's copy, write the merge).  Completion is forced
    by a host readback of a scalar reduction — plain ``block_until_ready``
    can observe only the enqueue on async/tunneled backends."""
    from dpwa_tpu.utils.pytree import tree_size_bytes

    payload = tree_size_bytes(jax.tree.map(lambda v: v[0], params))
    merged, _ = transport.exchange(params, meta, start_step)  # warmup
    _readback(merged)
    t0 = time.perf_counter()
    cur = params
    for i in range(iters):
        cur, _ = transport.exchange(cur, meta, start_step + i)
    _readback(cur)
    dt = time.perf_counter() - t0
    per_chip_bytes = 2 * payload * iters
    return {
        "payload_bytes": payload,
        "iters": iters,
        "seconds": dt,
        "gbps_per_chip": per_chip_bytes / dt / 1e9,
    }


def _readback(tree) -> None:
    leaf = jax.tree.leaves(tree)[0]
    np.asarray(leaf.sum())
