"""Tracing / profiling (SURVEY.md §5: absent in the reference; first-class
here).

- :func:`trace` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable trace of the training loop (XLA ops, collectives,
  host callbacks).
- :func:`measure_exchange_bandwidth` — the GB/s/chip counter around the
  averaging collective, the headline metric (BASELINE.json:2).  Used by
  ``bench.py`` and available to users against their own models.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax
import numpy as np


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """``with trace("/tmp/trace"):`` — profile everything inside."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def measure_exchange_bandwidth(
    transport,
    params,
    meta,
    *,
    iters: int = 20,
    start_step: int = 0,
) -> dict:
    """Time `transport.exchange` and report per-chip averaging bandwidth.

    Accounting per SURVEY.md §7: one exchange moves 2 × payload bytes per
    peer (receive partner's copy, write the merge).  Completion is forced
    by a host readback of a scalar reduction — plain ``block_until_ready``
    can observe only the enqueue on async/tunneled backends."""
    from dpwa_tpu.utils.pytree import tree_size_bytes

    payload = tree_size_bytes(jax.tree.map(lambda v: v[0], params))
    merged, _ = transport.exchange(params, meta, start_step)  # warmup
    _readback(merged)
    t0 = time.perf_counter()
    cur = params
    for i in range(iters):
        cur, _ = transport.exchange(cur, meta, start_step + i)
    _readback(cur)
    dt = time.perf_counter() - t0
    per_chip_bytes = 2 * payload * iters
    return {
        "payload_bytes": payload,
        "iters": iters,
        "seconds": dt,
        "gbps_per_chip": per_chip_bytes / dt / 1e9,
    }


def _readback(tree) -> None:
    leaf = jax.tree.leaves(tree)[0]
    np.asarray(leaf.sum())
