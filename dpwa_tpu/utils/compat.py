"""Version compatibility shims for the baked-in toolchain.

The container pins whatever jax the image shipped with; APIs that moved
between jax releases are resolved here ONCE so the rest of the codebase
imports one stable name.  Keep each shim tiny and documented with the
version boundary it bridges.
"""

from __future__ import annotations

try:  # jax >= 0.4.35 exports shard_map at the top level
    from jax import shard_map  # type: ignore[attr-defined]  # noqa: F401
except ImportError:  # older jax: the experimental path is the same object
    from jax.experimental.shard_map import shard_map  # noqa: F401

try:  # jax >= 0.4.38 has lax.axis_size
    from jax.lax import axis_size  # type: ignore[attr-defined]  # noqa: F401
except ImportError:
    import jax.lax as _lax

    def axis_size(axis_name):
        """Size of a mapped axis, via the classic psum(1) identity."""
        return _lax.psum(1, axis_name)

def shard_map_unchecked(f, **kwargs):
    """``shard_map`` with output-replication checking disabled.

    Older jax's replication checker cannot statically infer replication
    for some multi-axis out_specs that newer jax accepts; ``check_rep``
    itself was later removed, so probe for it."""
    try:
        return shard_map(f, check_rep=False, **kwargs)
    except TypeError:
        return shard_map(f, **kwargs)


__all__ = ["shard_map", "shard_map_unchecked", "axis_size"]
