"""Pytree flatten/unflatten and parameter-subset selection.

The reference flattens ``model.parameters()`` into one contiguous numpy vector
before every exchange (SURVEY.md §3.2 — reference ``dpwa/adapters/pytorch.py``,
mount empty).  Here the equivalents are built on ``jax.flatten_util``:

- :func:`ravel` — whole-pytree flatten, used by the TCP wire format and the
  bandwidth benchmark.  The ICI fast path deliberately does **not** ravel:
  ``ppermute`` runs per-leaf inside one jitted program and XLA fuses the merge,
  so there is no copy to amortize.
- :func:`subset_ravel` / :func:`partition` — select a subset of leaves by
  path predicate.  This powers subset-pytree gossip (BASELINE.json:11 —
  Llama-3-8B LoRA fine-tune where only LoRA adapter weights enter the
  exchange and base weights never move).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import flatten_util

PyTree = Any
PathPredicate = Callable[[str], bool]


def ravel(tree: PyTree) -> Tuple[jnp.ndarray, Callable[[jnp.ndarray], PyTree]]:
    """Flatten a pytree to one 1-D vector; returns (vector, unravel_fn)."""
    flat, unravel = flatten_util.ravel_pytree(tree)
    return flat, unravel


def _path_str(path: Tuple[Any, ...]) -> str:
    """Render a jax key-path as 'a/b/0/c' for predicate matching."""
    parts = []
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def partition(tree: PyTree, pred: PathPredicate) -> Tuple[PyTree, PyTree]:
    """Split ``tree`` into (selected, rest) by path predicate.

    Both outputs keep the full tree structure; non-matching leaves are
    ``None`` in ``selected`` and vice versa, so :func:`combine` can zip them
    back together losslessly.
    """
    paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    treedef = jax.tree_util.tree_structure(tree)
    sel_leaves = []
    rest_leaves = []
    for path, leaf in paths_leaves:
        if pred(_path_str(path)):
            sel_leaves.append(leaf)
            rest_leaves.append(None)
        else:
            sel_leaves.append(None)
            rest_leaves.append(leaf)
    selected = jax.tree_util.tree_unflatten(treedef, sel_leaves)
    rest = jax.tree_util.tree_unflatten(treedef, rest_leaves)
    return selected, rest


def combine(selected: PyTree, rest: PyTree) -> PyTree:
    """Inverse of :func:`partition`: overlay two complementary trees."""
    sel_leaves, treedef = jax.tree_util.tree_flatten(
        selected, is_leaf=lambda x: x is None
    )
    rest_leaves = jax.tree_util.tree_flatten(rest, is_leaf=lambda x: x is None)[0]
    merged = []
    for a, b in zip(sel_leaves, rest_leaves):
        if (a is None) == (b is None):
            raise ValueError("partition trees are not complementary")
        merged.append(a if a is not None else b)
    return jax.tree_util.tree_unflatten(treedef, merged)


def subset_ravel(
    tree: PyTree, pred: PathPredicate
) -> Tuple[jnp.ndarray, Callable[[jnp.ndarray], PyTree]]:
    """Ravel only the leaves whose path matches ``pred``.

    Returns (vector, restore_fn) where ``restore_fn(vec)`` rebuilds the FULL
    tree with updated selected leaves and untouched rest leaves — the
    LoRA-only exchange: base weights never enter the wire/collective.
    """
    selected, rest = partition(tree, pred)
    sel_leaves, sel_def = jax.tree_util.tree_flatten(selected)
    if not sel_leaves:
        raise ValueError("subset predicate matched no leaves")
    flat, unravel_sel = flatten_util.ravel_pytree(sel_leaves)

    def restore(vec: jnp.ndarray) -> PyTree:
        new_leaves = unravel_sel(vec)
        new_selected = jax.tree_util.tree_unflatten(sel_def, new_leaves)
        return combine(new_selected, rest)

    return flat, restore


def leaf_sizes(tree: PyTree) -> Tuple[int, ...]:
    """Per-leaf element counts in ``tree_leaves`` order — the layout of
    the :func:`ravel` vector (``ravel_pytree`` concatenates leaves in
    exactly this order).  The trust plane's per-leaf screening statistic
    uses these boundaries so a poisoned embedding table is judged
    against ITS OWN leaf, not diluted into a global norm."""
    return tuple(
        int(leaf.size) for leaf in jax.tree_util.tree_leaves(tree)
    )


def tree_size_bytes(tree: PyTree) -> int:
    """Total payload bytes of a pytree — the per-exchange wire/ICI volume."""
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def tree_wire_bytes(
    tree: PyTree,
    wire_dtype: str = "f32",
    padded: bool = True,
    wire_codec: str = "dense",
    topk_fraction: float = 0.05,
    topk_values: str = "int8",
) -> int:
    """Per-exchange bytes actually SHIPPED at a wire format.

    ``protocol.wire_dtype`` compresses only f32 leaves (bf16: 2 bytes/
    element; int8: 1 byte per element plus one f32 scale per
    :data:`dpwa_tpu.ops.quantize.CHUNK`-element chunk); other dtypes
    ship as-is.  This is the number ``exchanged_bytes`` metrics should
    report under a compressed wire — ``tree_size_bytes`` is the
    uncompressed replica size.

    ``padded`` selects WHICH transport's int8 accounting you get (the
    two ship genuinely different byte counts; bf16/f32 are identical
    either way):

    - ``padded=True`` (default) — the ICI collective's figure: each f32
      leaf quantized and shipped as its own code block, padded to whole
      chunks (``(CHUNK + 4) · n_chunks(leaf.size)``).  Exact for the
      SPMD path; for trees with many small f32 leaves it overstates TCP
      traffic (up to CHUNK−1 padding bytes per leaf, one whole chunk
      for a zero-size leaf) and omits framing.
    - ``padded=False`` — the TCP transport's figure: the FLATTENED
      concatenation of all f32 leaves quantized as ONE stream
      (``ops/quantize.encode_int8_payload``: 8-byte length + 4 bytes
      per chunk of the total + UNPADDED codes), exact to the byte for
      the payload ``TcpTransport.publish`` frames under
      ``wire_dtype: int8`` (the fixed 30-byte frame header is not
      included).  Non-f32 leaves still ship as-is.

    ``wire_codec="topk"`` (``protocol.wire_codec``, TCP only) overrides
    the f32-leaf accounting entirely: the flattened concatenation of all
    f32 leaves ships as ONE sparse top-k frame —
    ``topk_nbytes(n, topk_k(n, topk_fraction), topk_values)``, exact to
    the byte for ``TcpTransport.publish`` under the codec (frame header
    again excluded); non-f32 leaves ship as-is and ``wire_dtype`` is
    ignored for f32 leaves (the codec's value-block precision is
    ``topk_values``)."""
    if wire_dtype not in ("f32", "bf16", "int8"):
        raise ValueError(f"unknown wire_dtype {wire_dtype!r}")
    if wire_codec not in ("dense", "topk"):
        raise ValueError(f"unknown wire_codec {wire_codec!r}")
    if wire_codec == "topk":
        from dpwa_tpu.ops.quantize import topk_k, topk_nbytes

        total = 0
        f32_elems = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            if leaf.dtype == jnp.float32:
                f32_elems += leaf.size
            else:
                total += leaf.size * leaf.dtype.itemsize
        if f32_elems:
            total += topk_nbytes(
                f32_elems, topk_k(f32_elems, topk_fraction), topk_values
            )
        return total
    if wire_dtype == "f32":
        return tree_size_bytes(tree)
    from dpwa_tpu.ops.quantize import CHUNK, _n_chunks

    total = 0
    f32_elems = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if leaf.dtype == jnp.float32:
            if wire_dtype == "bf16":
                total += leaf.size * 2
            elif padded:  # int8: per-leaf padded blocks, as ICI ships
                total += (CHUNK + 4) * _n_chunks(leaf.size)
            else:  # int8 unpadded: f32 leaves pool into one TCP stream
                f32_elems += leaf.size
        else:
            total += leaf.size * leaf.dtype.itemsize
    if wire_dtype == "int8" and not padded and f32_elems:
        # u64 length | f32 scale per chunk of the TOTAL | unpadded codes
        total += 8 + 4 * _n_chunks(f32_elems) + f32_elems
    return total
