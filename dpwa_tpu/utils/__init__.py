from dpwa_tpu.utils.pytree import (  # noqa: F401
    ravel,
    subset_ravel,
    partition,
    combine,
)
