"""Device bootstrap helpers for examples and entry points.

JAX freezes its platform choice at first backend initialization, so "run an
n-peer mesh on whatever this host has" needs the decision made BEFORE
anything touches ``jax.devices()``.  :func:`ensure_devices` centralizes the
policy:

- ``native``: use the platform jax picked (real TPU slice); error if it has
  fewer than n devices.
- ``cpu``: force an n-device host-platform (emulated) mesh — the SURVEY.md
  §4 test topology.
- ``auto`` (default): if the environment already provides ≥n devices, use
  them; otherwise, if no backend is initialized yet, fall back to the
  emulated CPU mesh (dev boxes); otherwise raise with the fix.
"""

from __future__ import annotations

import os


def repoint_to_host_mesh(n: int):
    """Make an ≥n-device forced-CPU host mesh effective and return devices.

    Raises the ``--xla_force_host_platform_device_count`` value in
    ``XLA_FLAGS`` to at least ``n`` (XLA parses the env var at first client
    creation, so this must run before the CPU client exists), then probes
    the live backend: if it can't supply ``n`` devices (e.g. a
    site-registered TPU plugin overrode ``jax_platforms``), repoints jax at
    CPU and rebuilds the backend set.  Rebuilding invalidates arrays created
    on the old backend — call this at process start."""
    import re

    import jax
    from jax._src import xla_bridge as xb

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None or int(m.group(1)) < n:
        want = f"--xla_force_host_platform_device_count={n}"
        flags = flags.replace(m.group(0), want) if m else f"{flags} {want}"
        os.environ["XLA_FLAGS"] = flags.strip()
    if not xb.backends_are_initialized():
        # Decide the platform BEFORE the first backend probe: the caller
        # wants a host mesh, so never initialize a site-registered
        # accelerator plugin just to count its devices — plugin init can
        # block indefinitely (e.g. a tunneled chip whose relay is down).
        jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n:
        import jax.extend.backend

        jax.config.update("jax_platforms", "cpu")
        jax.extend.backend.clear_backends()
    return jax.devices()


def ensure_devices(n: int, mode: str = "auto"):
    """Return a list of ≥n jax devices, forcing a CPU mesh if allowed.

    ``auto``-mode flag precedence: a ``--xla_force_host_platform_device_count``
    flag in ``XLA_FLAGS`` ALWAYS wins when no backend is initialized yet —
    the run goes to the emulated CPU mesh even on a host whose accelerator
    plugin could have supplied ≥n real devices.  Rationale: probing the
    accelerator to find out would initialize it irreversibly, and a
    site-registered plugin can block indefinitely at init (the dev box's
    tunneled chip does); the flag is taken as explicit host-mesh intent.
    Accelerator users must not set the flag, or should pass
    ``mode='native'``.

    (Uses the private ``jax._src.xla_bridge.backends_are_initialized`` —
    there is no public "is a backend up yet?" probe; every public API would
    trigger the initialization this function exists to avoid.)"""
    import jax
    from jax._src import xla_bridge as xb

    def force_cpu() -> None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
        jax.config.update("jax_platforms", "cpu")

    if mode == "cpu":
        if xb.backends_are_initialized():
            if jax.default_backend() != "cpu" or len(jax.devices()) < n:
                raise RuntimeError(
                    "jax already initialized on "
                    f"{jax.default_backend()} x{len(jax.devices())}; "
                    "set XLA_FLAGS=--xla_force_host_platform_device_count="
                    f"{n} JAX_PLATFORMS=cpu before starting python"
                )
        else:
            force_cpu()
        return jax.devices()[:n]

    if mode == "native":
        devices = jax.devices()
        if len(devices) < n:
            raise RuntimeError(
                f"need {n} devices, have {len(devices)} "
                f"({devices[0].platform})"
            )
        return devices[:n]

    # auto — checking the native platform would initialize it irreversibly,
    # so with no backend up yet: honor an existing force-flag, else default
    # to the emulated CPU mesh (dev-box friendly; real-slice users pass
    # mode='native').
    if not xb.backends_are_initialized():
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            # The flag expresses host-mesh intent; make it effective even
            # if a site-registered TPU plugin overrode jax_platforms.
            devices = repoint_to_host_mesh(n)
            if len(devices) >= n:
                return devices[:n]
            raise RuntimeError(
                f"XLA_FLAGS provides {len(devices)} devices but config "
                f"names {n} peers"
            )
        force_cpu()
        return jax.devices()[:n]
    devices = jax.devices()
    if len(devices) >= n:
        return devices[:n]
    raise RuntimeError(
        f"need {n} devices, have {len(devices)}; relaunch with "
        f"XLA_FLAGS=--xla_force_host_platform_device_count={n} "
        f"JAX_PLATFORMS=cpu for an emulated mesh"
    )
