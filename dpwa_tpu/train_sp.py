"""Gossip + sequence-parallel training: one SPMD program on a 2-D mesh.

Long-context is first-class (SURVEY.md §5): a ``(peers, sp)`` mesh runs
gossip data-parallelism across replicas while EACH replica's sequences
span its ``sp`` sub-axis via exact ring attention
(:mod:`dpwa_tpu.ops.ring_attention`).  The whole step — sp-sharded
forward/backward (ring-attention ppermutes inside), gradient ``psum``
over ``sp``, optax update, and the gossip ``ppermute`` over ``peers`` —
is ONE ``shard_map`` program.  Layout:

- params: ``P(peers)`` — sharded over replicas, replicated over ``sp``;
- batch:  ``[n_peers, B, T]`` with ``P(peers, None, sp)`` — every device
  holds its replica's contiguous sequence block;
- collectives: ring-attention ``ppermute`` + gradient ``psum`` ride the
  ``sp`` sub-axis (ICI-local when sp maps to intra-host chips), the
  pairing ``ppermute`` rides ``peers``.

The gossip semantics (schedule pools, participation/fault draws,
interpolation, pull mode, bf16 wire) are exactly
:func:`dpwa_tpu.parallel.ici.gossip_exchange_local` — replicated over the
``sp`` axis, every sp rank of a replica executes the identical exchange.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dpwa_tpu.config import DpwaConfig
from dpwa_tpu.interpolation import PeerMeta
from dpwa_tpu.parallel.ici import (
    ExchangeInfo,
    IciTransport,
    gossip_exchange_local,
)
from dpwa_tpu.parallel.mesh import PEER_AXIS
from dpwa_tpu.train import GossipTrainState

PyTree = Any
SP_AXIS = "sp"

# loss_fn(single_replica_params, local_batch_block) -> (loss_sum, count):
# the SUM of token losses over this device's sequence block and the
# number of tokens it covers; the step psums both over ``sp``.
SpLossFn = Callable[[PyTree, Any], Tuple[jnp.ndarray, jnp.ndarray]]


def make_sp_mesh(
    config: DpwaConfig, sp: int, devices=None, sp_axis: str = SP_AXIS
) -> Mesh:
    """A ``(peers, sp)`` mesh: ``len(config.nodes) * sp`` devices.

    The sp axis is innermost, so a replica's sequence blocks sit on
    CONTIGUOUS devices — on real hardware that keeps the per-hop
    ring-attention ppermute on neighboring chips (ICI)."""
    n = config.n_peers
    if devices is None:
        devices = jax.devices()
    if len(devices) < n * sp:
        raise RuntimeError(
            f"(peers={n}) x (sp={sp}) needs {n * sp} devices, have "
            f"{len(devices)}"
        )
    arr = np.asarray(devices[: n * sp]).reshape(n, sp)
    return Mesh(arr, (PEER_AXIS, sp_axis))


def init_gossip_sp_state(
    stacked_params: PyTree,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
) -> GossipTrainState:
    """Identical to :func:`dpwa_tpu.train.init_gossip_state` — the peer
    sharding on a 2-D mesh replicates every leaf over ``sp`` for free."""
    from dpwa_tpu.train import init_gossip_state

    return init_gossip_state(stacked_params, optimizer, transport)


def make_gossip_sp_train_step(
    loss_fn: SpLossFn,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    sp_axis: str = SP_AXIS,
):
    """Jitted ``train_step(state, batch) -> (state, losses, info)`` on a
    ``(peers, sp)`` mesh.

    ``transport`` must be an :class:`IciTransport` built over a 2-D mesh
    from :func:`make_sp_mesh`.  ``batch`` is ``(inputs, targets)`` of
    shape ``[n_peers, B, T]`` (the host pre-shifts targets, so block
    boundaries need no cross-shard fix-up); ``T`` is sharded over ``sp``.
    ``losses`` is the per-replica mean token loss, float32[n_peers].
    """
    mesh, peers_axis = transport.mesh, transport.axis_name
    if sp_axis not in mesh.shape:
        raise ValueError(
            f"transport mesh {dict(mesh.shape)} has no {sp_axis!r} axis; "
            "build it with make_sp_mesh"
        )
    schedule, interp = transport.schedule, transport.interp
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    shard = lambda t: jax.tree.map(lambda v: v[0], t)
    unshard = lambda t: jax.tree.map(lambda v: v[None], t)

    def body(params, opt_state, clock, step, batch):
        params, opt_state = shard(params), shard(opt_state)
        inputs, targets = jax.tree.map(lambda v: v[0], batch)
        (loss_sum, count), grads = grad_fn(params, (inputs, targets))
        # NO manual psum on grads: ``params`` enter replicated over
        # ``sp`` (spec names only ``peers``), and the transpose rule for
        # a replicated operand ALREADY sums its cotangents across the
        # axis — ``grads`` comes back sp-invariant and equal to
        # d(sum of all blocks' losses)/d(params).  (Ring-attention
        # cross-block terms flow through the transposed ppermutes.)  A
        # manual psum here would multiply the gradient by sp.
        loss_sum = lax.psum(loss_sum, sp_axis)
        count = lax.psum(count, sp_axis)
        loss = (loss_sum / jnp.maximum(count, 1.0)).astype(jnp.float32)
        grads = jax.tree.map(
            lambda g: g / jnp.maximum(count, 1.0).astype(g.dtype), grads
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        clock = clock[0] + 1.0
        meta = PeerMeta(clock, loss)
        # Gossip across replicas: every sp rank of a replica holds the
        # identical post-update params and runs the identical ppermute
        # over ``peers`` — the exchange stays sp-replicated by
        # construction.
        merged, (partner, alpha, part) = gossip_exchange_local(
            params, meta, step,
            schedule=schedule, interp=interp, axis_name=peers_axis,
        )
        return (
            unshard(merged),
            unshard(opt_state),
            clock[None],
            loss[None],
            (partner[None], alpha[None], part[None]),
        )

    batch_spec = P(peers_axis, None, sp_axis)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(peers_axis),
            P(peers_axis),
            P(peers_axis),
            P(),
            (batch_spec, batch_spec),
        ),
        out_specs=(
            P(peers_axis),
            P(peers_axis),
            P(peers_axis),
            P(peers_axis),
            (P(peers_axis), P(peers_axis), P(peers_axis)),
        ),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step(state: GossipTrainState, batch):
        params, opt_state, clock, losses, info = mapped(
            state.params, state.opt_state, state.clock, state.step, batch
        )
        new_state = GossipTrainState(
            params=params,
            opt_state=opt_state,
            clock=clock,
            step=state.step + 1,
            model_state=state.model_state,
            loss=losses,
        )
        return new_state, losses, ExchangeInfo(*info)

    # CPU run-ahead bound: reuse the transport's detection (see the
    # rationale comment in IciTransport.__init__).
    block_per_call = transport._block_per_call

    def train_step(state: GossipTrainState, batch):
        if state.model_state is not None:
            # Same misuse guard as the 1-D step factories: this step
            # would neither update nor exchange model_state, silently
            # freezing BatchNorm-style statistics at init.
            raise ValueError(
                "state carries model_state but the sp train step does not "
                "support non-parameter model variables yet; use a "
                "stateless model (e.g. GroupNorm/RMSNorm) on the sp path"
            )
        out = _step(state, batch)
        if block_per_call:
            jax.block_until_ready(out)
        return out

    return train_step


def sp_batch_sharding(mesh: Mesh, sp_axis: str = SP_AXIS) -> NamedSharding:
    """Sharding for ``[n_peers, B, T]`` batches: peers x sequence blocks."""
    return NamedSharding(mesh, P(PEER_AXIS, None, sp_axis))
