"""Gossip + sequence-parallel training: one SPMD program on a 2-D mesh.

Long-context is first-class (SURVEY.md §5): a ``(peers, sp)`` mesh runs
gossip data-parallelism across replicas while EACH replica's sequences
span its ``sp`` sub-axis via exact ring attention
(:mod:`dpwa_tpu.ops.ring_attention`).  The whole step — sp-sharded
forward/backward (ring-attention ppermutes inside), gradient ``psum``
over ``sp``, optax update, and the gossip ``ppermute`` over ``peers`` —
is ONE ``shard_map`` program.  Layout:

- params: ``P(peers)`` — sharded over replicas, replicated over ``sp``;
- batch:  ``[n_peers, B, T]`` with ``P(peers, None, sp)`` — every device
  holds its replica's contiguous sequence block;
- collectives: ring-attention ``ppermute`` + gradient ``psum`` ride the
  ``sp`` sub-axis (ICI-local when sp maps to intra-host chips), the
  pairing ``ppermute`` rides ``peers``.

The gossip semantics (schedule pools, participation/fault draws,
interpolation, pull mode, bf16 wire) are exactly
:func:`dpwa_tpu.parallel.ici.gossip_exchange_local` — replicated over the
``sp`` axis, every sp rank of a replica executes the identical exchange.
The step composes with the full 1-D feature set
(:mod:`dpwa_tpu.train`): ``exchange_filter`` (config 5's long-context
LoRA layout — adapters gossip over ``peers`` while the frozen base rides
only the sp collectives), ``model_state`` (sp-reduced so replicas stay
consistent), and ``overlap`` (ship the pre-update replica).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from dpwa_tpu.utils.compat import shard_map_unchecked as shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dpwa_tpu.config import DpwaConfig
from dpwa_tpu.interpolation import PeerMeta
from dpwa_tpu.parallel.ici import (
    ExchangeInfo,
    IciTransport,
    gossip_exchange_local,
)
from dpwa_tpu.parallel.mesh import PEER_AXIS
from dpwa_tpu.train import GossipTrainState
from dpwa_tpu.utils.pytree import combine as pytree_combine
from dpwa_tpu.utils.pytree import partition as pytree_partition

PyTree = Any
SP_AXIS = "sp"

# loss_fn(single_replica_params, local_batch_block) -> (loss_sum, count):
# the SUM of token losses over this device's sequence block and the
# number of tokens it covers; the step psums both over ``sp``.
SpLossFn = Callable[[PyTree, Any], Tuple[jnp.ndarray, jnp.ndarray]]


def make_sp_mesh(
    config: DpwaConfig, sp: int, devices=None, sp_axis: str = SP_AXIS
) -> Mesh:
    """A ``(peers, sp)`` mesh: ``len(config.nodes) * sp`` devices.

    The sp axis is innermost, so a replica's sequence blocks sit on
    CONTIGUOUS devices — on real hardware that keeps the per-hop
    ring-attention ppermute on neighboring chips (ICI)."""
    n = config.n_peers
    if devices is None:
        devices = jax.devices()
    if len(devices) < n * sp:
        raise RuntimeError(
            f"(peers={n}) x (sp={sp}) needs {n * sp} devices, have "
            f"{len(devices)}"
        )
    arr = np.asarray(devices[: n * sp]).reshape(n, sp)
    return Mesh(arr, (PEER_AXIS, sp_axis))


def init_gossip_sp_state(
    stacked_params: PyTree,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    stacked_model_state: PyTree = None,
) -> GossipTrainState:
    """Identical to :func:`dpwa_tpu.train.init_gossip_state` — the peer
    sharding on a 2-D mesh replicates every leaf over ``sp`` for free."""
    from dpwa_tpu.train import init_gossip_state

    return init_gossip_state(
        stacked_params, optimizer, transport, stacked_model_state
    )


def _make_sp_step(
    loss_fn,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    exchange_filter: Optional[Callable[[str], bool]],
    with_state: bool,
    overlap: bool,
    sp_axis: str,
    debug_sp_invariance: bool,
):
    """Shared builder behind both public sp step factories.

    Mirrors :func:`dpwa_tpu.train._make_step` with the sp additions: the
    loss arrives as a (sum, count) pair psummed over ``sp``; gradients
    come back sp-invariant through the replicated-operand transpose; and
    ``model_state`` is ``pmean``-ed over ``sp`` after the forward pass
    (each sp rank computes statistics on its own sequence block — the
    reduction is what keeps every rank of a replica bit-identical before
    the exchange)."""
    mesh, peers_axis = transport.mesh, transport.axis_name
    if sp_axis not in mesh.shape:
        raise ValueError(
            f"transport mesh {dict(mesh.shape)} has no {sp_axis!r} axis; "
            "build it with make_sp_mesh"
        )
    schedule, interp = transport.schedule, transport.interp
    if with_state:
        # loss_fn returns ((loss_sum, count), new_model_state); grad needs
        # a scalar primal, so fold count in with the aux.
        def _scalarized(params, model_state, batch):
            (loss_sum, count), new_ms = loss_fn(params, model_state, batch)
            return loss_sum, (count, new_ms)

        grad_fn = jax.value_and_grad(_scalarized, has_aux=True)
    else:
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    shard = lambda t: jax.tree.map(lambda v: v[0], t)
    unshard = lambda t: jax.tree.map(lambda v: v[None], t)

    def body(params, opt_state, model_state, clock, prev_loss, step, batch):
        params, opt_state = shard(params), shard(opt_state)
        old_params, old_model_state = params, model_state
        local_batch = shard(batch)
        if with_state:
            model_state = shard(model_state)
            (loss_sum, (count, new_model_state)), grads = grad_fn(
                params, model_state, local_batch
            )
            # Each sp rank saw only its sequence block: reduce the updated
            # statistics across ``sp`` so the replica stays consistent.
            new_model_state = jax.tree.map(
                lambda v: lax.pmean(v, sp_axis), new_model_state
            )
            old_model_state = model_state
        else:
            (loss_sum, count), grads = grad_fn(params, local_batch)
            new_model_state = ()
        # NO manual psum on grads: ``params`` enter replicated over
        # ``sp`` (spec names only ``peers``), and the transpose rule for
        # a replicated operand ALREADY sums its cotangents across the
        # axis — ``grads`` comes back sp-invariant and equal to
        # d(sum of all blocks' losses)/d(params).  (Ring-attention
        # cross-block terms flow through the transposed ppermutes.)  A
        # manual psum here would multiply the gradient by sp.
        if debug_sp_invariance:
            # Pin the no-manual-psum rule explicitly (ADVICE r2): the
            # max relative deviation of this rank's grads from the sp
            # mean must be ~0.  Exposed to the caller per peer; a JAX
            # upgrade that breaks the transpose rule trips the gate
            # test before it silently mistrains.
            devs = [
                jnp.max(
                    jnp.abs(g - lax.pmean(g, sp_axis))
                    / (jnp.abs(lax.pmean(g, sp_axis)) + 1e-8)
                )
                for g in jax.tree.leaves(grads)
            ]
            sp_grad_dev = jnp.max(jnp.stack(devs)).astype(jnp.float32)
        else:
            sp_grad_dev = jnp.float32(0.0)
        loss_sum = lax.psum(loss_sum, sp_axis)
        count = lax.psum(count, sp_axis)
        loss = (loss_sum / jnp.maximum(count, 1.0)).astype(jnp.float32)
        grads = jax.tree.map(
            lambda g: g / jnp.maximum(count, 1.0).astype(g.dtype), grads
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        clock = clock[0] + 1.0
        if overlap:
            # Ship the PRE-update replica with the PREVIOUS step's loss —
            # every collective operand is ready at step entry, so the
            # peers-axis ppermute needs nothing from this step's fwd/bwd
            # (same semantics as the 1-D overlap: one step of partner
            # staleness, exactly the reference's stale Rx publish).
            exchange_params, exchange_state = old_params, old_model_state
            meta = PeerMeta(clock, prev_loss[0])
        else:
            exchange_params, exchange_state = params, new_model_state
            meta = PeerMeta(clock, loss)
        if exchange_filter is not None:
            exchange_params, _ = pytree_partition(
                exchange_params, exchange_filter
            )
        (merged_sel, merged_state), (partner, alpha, part) = (
            gossip_exchange_local(
                (exchange_params, exchange_state), meta, step,
                schedule=schedule, interp=interp, axis_name=peers_axis,
            )
        )
        if overlap:
            # x_{k+1} = merge(x_k) + own update; model_state analogously
            # re-applies this step's statistics delta to the merge.
            if exchange_filter is not None:
                sel_updates, _ = pytree_partition(updates, exchange_filter)
                merged_sel = optax.apply_updates(merged_sel, sel_updates)
            else:
                merged_sel = optax.apply_updates(merged_sel, updates)
            merged_state = jax.tree.map(
                lambda m, new, old: m + (new - old),
                merged_state, new_model_state, old_model_state,
            )
        if exchange_filter is not None:
            _, rest = pytree_partition(params, exchange_filter)
            merged = pytree_combine(merged_sel, rest)
        else:
            merged = merged_sel
        return (
            unshard(merged),
            unshard(opt_state),
            unshard(merged_state),
            clock[None],
            loss[None],
            (partner[None], alpha[None], part[None]),
            sp_grad_dev[None],
        )

    # A single spec is a valid pytree prefix for any batch structure whose
    # leaves are [n_peers, B, T] blocks.
    batch_spec = P(peers_axis, None, sp_axis)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(peers_axis),
            P(peers_axis),
            P(peers_axis),
            P(peers_axis),
            P(peers_axis),
            P(),
            batch_spec,
        ),
        out_specs=(
            P(peers_axis),
            P(peers_axis),
            P(peers_axis),
            P(peers_axis),
            P(peers_axis),
            (P(peers_axis), P(peers_axis), P(peers_axis)),
            P(peers_axis),
        ),
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step(state: GossipTrainState, batch):
        prev_loss = (
            state.loss
            if state.loss is not None
            else jnp.zeros_like(state.clock)
        )
        params, opt_state, model_state, clock, losses, info, sp_dev = mapped(
            state.params,
            state.opt_state,
            state.model_state if with_state else (),
            state.clock,
            prev_loss,
            state.step,
            batch,
        )
        new_state = GossipTrainState(
            params=params,
            opt_state=opt_state,
            clock=clock,
            step=state.step + 1,
            model_state=model_state if with_state else state.model_state,
            loss=losses,
        )
        return new_state, losses, ExchangeInfo(*info), sp_dev

    # CPU run-ahead bound: reuse the transport's detection (see the
    # rationale comment in IciTransport.__init__).
    block_per_call = transport._block_per_call

    def train_step(state: GossipTrainState, batch):
        if not with_state and state.model_state is not None:
            raise ValueError(
                "state carries model_state but this step was built with "
                "make_gossip_sp_train_step, which would never update it; "
                "use make_gossip_sp_train_step_with_state instead"
            )
        if with_state and state.model_state is None:
            raise ValueError(
                "step built with make_gossip_sp_train_step_with_state but "
                "state.model_state is None; pass stacked_model_state to "
                "init_gossip_sp_state"
            )
        new_state, losses, info, sp_dev = _step(state, batch)
        if block_per_call:
            jax.block_until_ready((new_state, losses, info, sp_dev))
        if debug_sp_invariance:
            return new_state, losses, info, sp_dev
        return new_state, losses, info

    return train_step


def make_gossip_sp_train_step(
    loss_fn: SpLossFn,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    exchange_filter: Optional[Callable[[str], bool]] = None,
    overlap: bool = False,
    sp_axis: str = SP_AXIS,
    debug_sp_invariance: bool = False,
):
    """Jitted ``train_step(state, batch) -> (state, losses, info)`` on a
    ``(peers, sp)`` mesh.

    ``transport`` must be an :class:`IciTransport` built over a 2-D mesh
    from :func:`make_sp_mesh`.  ``batch`` is a pytree of ``[n_peers, B,
    T]`` leaves (e.g. ``(inputs, targets)``; the host pre-shifts targets,
    so block boundaries need no cross-shard fix-up); ``T`` is sharded
    over ``sp``.  ``losses`` is the per-replica mean token loss,
    float32[n_peers].

    ``exchange_filter`` composes subset-pytree gossip with sp — config
    5's actual long-context layout (BASELINE.json:11): LoRA adapters
    gossip over ``peers`` while the frozen base weights never enter the
    collective.  ``overlap`` ships the pre-update replica exactly as in
    :func:`dpwa_tpu.train.make_gossip_train_step`.

    ``debug_sp_invariance=True`` adds a fourth return — per-peer max
    relative deviation of this step's gradients across sp ranks, which
    must be ~0 (the no-manual-psum correctness invariant, pinned by
    ``tests/test_sp_train.py``)."""
    return _make_sp_step(
        loss_fn, optimizer, transport, exchange_filter, with_state=False,
        overlap=overlap, sp_axis=sp_axis,
        debug_sp_invariance=debug_sp_invariance,
    )


def make_gossip_sp_train_step_with_state(
    loss_fn,
    optimizer: optax.GradientTransformation,
    transport: IciTransport,
    exchange_filter: Optional[Callable[[str], bool]] = None,
    overlap: bool = False,
    sp_axis: str = SP_AXIS,
    debug_sp_invariance: bool = False,
):
    """Like :func:`make_gossip_sp_train_step`, for models with
    non-parameter variables.

    ``loss_fn(params, model_state, batch) -> ((loss_sum, count),
    new_model_state)``.  Each sp rank computes statistics on its own
    sequence block; the step ``pmean``s ``new_model_state`` over ``sp``
    so every rank of a replica stays bit-identical, then exchanges it
    alongside the (filtered) params with the same α, exactly as the 1-D
    :func:`dpwa_tpu.train.make_gossip_train_step_with_state`."""
    return _make_sp_step(
        loss_fn, optimizer, transport, exchange_filter, with_state=True,
        overlap=overlap, sp_axis=sp_axis,
        debug_sp_invariance=debug_sp_invariance,
    )


def sp_batch_sharding(mesh: Mesh, sp_axis: str = SP_AXIS) -> NamedSharding:
    """Sharding for ``[n_peers, B, T]`` batches: peers x sequence blocks."""
    return NamedSharding(mesh, P(PEER_AXIS, None, sp_axis))
