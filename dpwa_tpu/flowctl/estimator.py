"""Per-peer adaptive fetch deadlines from observed success latencies.

The estimator keeps, per peer, a bounded window of SUCCESS latencies (the
only samples that measure the peer's actual service time — failures
measure our own budget) and derives from it:

- the **adaptive deadline**: ``quantile(window) * margin`` clamped to
  ``[min_ms, max_ms]``.  Cold (fewer than ``warmup`` samples) it falls
  back to the configured static ``timeout_ms``, so behavior before any
  evidence exists is exactly the pre-flowctl transport.
- the **hedge launch point**: the un-margined quantile — the moment the
  fetch has statistically already failed; the margin above it is the
  headroom in which the hedge races the original.

Failures still feed the window's *counters* (busy/slow/hedge
accounting for observability) but never its latencies: a run of timeouts
must not teach the estimator that the peer is "slow but fine", it must
leave the deadline resting on the last known-good behavior.

Thread safety: fetches run on the overlapped-exchange thread (and hedge
threads) while the training thread reads snapshots, so all public methods
take the internal lock.  Nothing here reads the wall clock — latencies
come in as arguments — so the estimator itself adds no nondeterminism to
outcome classification.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Callable, Deque, Dict, Optional

from dpwa_tpu.config import FlowctlConfig
from dpwa_tpu.flowctl.vclock import monotonic_now
from dpwa_tpu.health.detector import Outcome


class DeadlineEstimator:
    """Latency-quantile deadlines + hedge/busy accounting, per peer."""

    def __init__(
        self,
        config: Optional[FlowctlConfig] = None,
        timeout_ms: float = 500.0,
        now: Optional[Callable[[], float]] = None,
    ):
        self.config = config if config is not None else FlowctlConfig()
        self.timeout_ms = float(timeout_ms)
        # The flowctl stack's shared time seam (dpwa_tpu/flowctl/vclock):
        # the estimator itself never reads it — latencies arrive as
        # arguments, which is what keeps outcome classification
        # deterministic — but the async round engine stamps its
        # staleness/pending-wait spans from THIS callable, so injecting
        # a VirtualClock here governs every wall-derived span in the
        # async plane at once (docs/async.md determinism contract).
        self.now: Callable[[], float] = (
            now if now is not None else monotonic_now
        )
        self._lock = threading.Lock()
        self._window: Dict[int, Deque[float]] = {}
        self._counts: Dict[int, Dict[str, int]] = {}
        self._hedges = 0
        self._hedge_wins = 0

    # ------------------------------------------------------------------
    # Ingestion
    # ------------------------------------------------------------------

    def _peer_counts(self, peer: int) -> Dict[str, int]:
        c = self._counts.get(peer)
        if c is None:
            c = self._counts[peer] = {
                "busy": 0, "slow": 0, "hedges": 0, "hedge_wins": 0,
            }
        return c

    # dpwalint: thread_root(fetch)
    def observe(
        self,
        peer: int,
        outcome: str,
        latency_s: Optional[float] = None,
        nbytes: int = 0,
    ) -> None:
        """Feed one classified fetch outcome (same shape as the detector)."""
        with self._lock:
            counts = self._peer_counts(peer)
            if outcome == Outcome.SUCCESS:
                if latency_s is not None and latency_s >= 0.0:
                    win = self._window.get(peer)
                    if win is None:
                        win = self._window[peer] = deque(
                            maxlen=self.config.window
                        )
                    win.append(float(latency_s) * 1e3)
            elif outcome == Outcome.BUSY:
                counts["busy"] += 1
            elif outcome == Outcome.SLOW:
                counts["slow"] += 1

    def note_hedge(self, peer: int) -> None:
        """A hedged retry was launched because ``peer`` lapsed its budget."""
        with self._lock:
            self._hedges += 1
            self._peer_counts(peer)["hedges"] += 1

    def evict_peer(self, peer: int) -> None:
        """Drop ``peer``'s latency window and counters (membership
        eviction — docs/fleet.md): a rejoiner warms up from scratch
        under the static ``timeout_ms``, exactly like a cold peer."""
        with self._lock:
            self._window.pop(peer, None)
            self._counts.pop(peer, None)

    def tracked_peers(self) -> list:
        """Every peer with a resident latency window or counters — the
        residency set the partial-view ``state_cap`` bounds
        (docs/membership.md)."""
        with self._lock:
            return sorted(set(self._window) | set(self._counts))

    def note_hedge_win(self, peer: int) -> None:
        """The hedge against ``peer`` won the race (fallback's payload
        merged; ``peer``'s fetch was cancelled and classified slow)."""
        with self._lock:
            self._hedge_wins += 1
            self._peer_counts(peer)["hedge_wins"] += 1

    # ------------------------------------------------------------------
    # Queries
    # ------------------------------------------------------------------

    def _quantile_ms(self, peer: int, q: float) -> Optional[float]:
        win = self._window.get(peer)
        if win is None or len(win) < self.config.warmup:
            return None
        samples = sorted(win)
        idx = min(len(samples) - 1, max(0, math.ceil(q * len(samples)) - 1))
        return samples[idx]

    def warm(self, peer: int) -> bool:
        """True once ``peer`` has ``warmup`` success samples."""
        with self._lock:
            win = self._window.get(peer)
            return win is not None and len(win) >= self.config.warmup

    def deadline_ms(self, peer: int) -> float:
        """The cumulative fetch budget for ``peer``'s next fetch."""
        with self._lock:
            q = self._quantile_ms(peer, self.config.quantile)
            if q is None:
                return self.timeout_ms
            return min(
                self.config.max_ms,
                max(self.config.min_ms, q * self.config.margin),
            )

    def hedge_launch_ms(self, peer: int) -> Optional[float]:
        """When (ms into the fetch) the hedge should launch, or None while
        cold — a cold estimator never hedges (there is no budget whose
        lapse means anything yet)."""
        with self._lock:
            q = self._quantile_ms(peer, self.config.quantile)
            if q is None:
                return None
            return min(self.config.max_ms, max(self.config.min_ms, q))

    # ------------------------------------------------------------------
    # Observability
    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready: per-peer deadline/quantiles + hedge/busy counters."""
        with self._lock:
            peers = {}
            for peer in sorted(set(self._window) | set(self._counts)):
                win = self._window.get(peer)
                counts = self._peer_counts(peer)
                p50 = self._quantile_ms(peer, 0.5)
                q = self._quantile_ms(peer, self.config.quantile)
                deadline = (
                    self.timeout_ms
                    if q is None
                    else min(
                        self.config.max_ms,
                        max(self.config.min_ms, q * self.config.margin),
                    )
                )
                peers[peer] = {
                    "samples": len(win) if win is not None else 0,
                    "p50_ms": round(p50, 3) if p50 is not None else None,
                    "q_ms": round(q, 3) if q is not None else None,
                    "deadline_ms": round(deadline, 3),
                    "hedges": counts["hedges"],
                    "hedge_wins": counts["hedge_wins"],
                    "busy": counts["busy"],
                    "slow": counts["slow"],
                }
            return {
                "quantile": self.config.quantile,
                "hedges": self._hedges,
                "hedge_wins": self._hedge_wins,
                "peers": peers,
            }


def register_metrics(registry, estimator: "DeadlineEstimator") -> None:
    """Expose adaptive deadlines + hedging on a MetricsRegistry."""
    from dpwa_tpu.obs.prometheus import Family

    def collect():
        snap = estimator.snapshot()
        deadline = Family(
            "dpwa_flowctl_deadline_ms", "gauge",
            "Adaptive cumulative fetch deadline per peer",
        )
        p50 = Family(
            "dpwa_flowctl_latency_p50_ms", "gauge",
            "Median observed success latency per peer",
        )
        for p, info in sorted((snap.get("peers") or {}).items()):
            labels = {"peer": p}
            deadline.sample(info.get("deadline_ms"), labels)
            p50.sample(info.get("p50_ms"), labels)
        return [
            deadline,
            p50,
            Family(
                "dpwa_flowctl_hedges_total", "counter",
                "Hedged retries launched",
            ).sample(snap.get("hedges")),
            Family(
                "dpwa_flowctl_hedge_wins_total", "counter",
                "Hedged retries that beat the primary",
            ).sample(snap.get("hedge_wins")),
        ]

    registry.register(collect)
