"""Injectable time source for the flowctl + async-round stack.

Everything in the flowctl plane that touches wall time does so through
an injected zero-arg clock callable rather than reading ``time.*``
directly: the :class:`~dpwa_tpu.flowctl.estimator.DeadlineEstimator`
receives latencies as arguments and exposes the shared ``now`` seam,
and the :class:`~dpwa_tpu.parallel.async_loop.AsyncExchangeEngine`
stamps its staleness/pending-wait spans with the same callable.  In
production that callable is :data:`monotonic_now`; in tests it is a
:class:`VirtualClock`, which makes every wall-derived quantity in an
async soak a pure function of the harness's ``advance`` calls — the
determinism contract docs/async.md pins (a rerun of the same soak is
bit-identical, telemetry included).

None of the DECISION state in the gossip control plane may depend on
this clock (dpwalint's ``det-time`` rule enforces it on the decision
modules); the clock governs telemetry spans only.
"""

from __future__ import annotations

import threading
import time

# The production clock: module-level alias so decision-path modules can
# take it as a default argument without spelling ``time.monotonic`` (and
# without importing ``time``) themselves.
monotonic_now = time.monotonic


class VirtualClock:
    """A clock that advances only when told to.

    Thread-safe: async fetch slots stamp arrival spans from their own
    threads while the harness advances from the training thread.  A
    ``VirtualClock`` instance is itself a zero-arg callable, so it drops
    in anywhere ``time.monotonic`` would."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._t

    def __call__(self) -> float:
        return self.now()

    def advance(self, dt: float) -> float:
        """Move time forward by ``dt`` seconds; returns the new time."""
        if dt < 0:
            raise ValueError(f"virtual clock cannot rewind (dt={dt})")
        with self._lock:
            self._t += float(dt)
            return self._t

    def sleep(self, dt: float) -> None:
        """A virtual sleep: advances the clock, costs no wall time."""
        self.advance(dt)
