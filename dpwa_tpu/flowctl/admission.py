"""Serving-side admission control for the Python Rx server.

Four independent gates, each shedding load *explicitly* (the server
answers a ``DPWB`` busy frame and closes) instead of queueing work it
cannot finish:

- a **global concurrent-connection cap** (``max_connections``): the
  thread-per-connection server never holds more live handlers than this;
- **per-remote token buckets** (``token_rate``/``token_burst`` keyed on
  the remote address): one aggressive fetcher cannot starve the rest of
  the ring of serving capacity;
- an **in-flight-bytes ceiling** (``max_inflight_bytes``): payload bytes
  reserved for the duration of each blob send, bounding serving memory
  under fan-in;
- **slow-loris eviction**: the request read runs under a cumulative
  deadline extended per byte at ``min_ingest_bytes_per_s`` — a client
  trickling its request is cut off and counted, not waited on.

Unlike every health-plane decision, admission reads the wall clock
(token refill is a rate, rates are wall time) — that is sound because
admission never feeds the deterministic state machines directly: a shed
request becomes a ``busy`` outcome on the *fetcher*, whose low weight
soft-degrades, and soft evidence never quarantines.  The clock is
injectable for tests.

Thread safety: gates are consulted from the accept loop and per-connection
handler threads concurrently; all public methods take the internal lock.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from dpwa_tpu.config import FlowctlConfig


class AdmissionController:
    """The Rx server's shed-or-serve gatekeeper."""

    def __init__(
        self,
        config: Optional[FlowctlConfig] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config if config is not None else FlowctlConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._active = 0
        self._peak_active = 0
        self._inflight_bytes = 0
        # host -> (tokens, last_refill_time)
        self._buckets: Dict[str, Tuple[float, float]] = {}
        self._admitted = 0
        self._sheds: Dict[str, int] = {
            "connections": 0, "tokens": 0, "bytes": 0,
        }
        self._evictions = 0

    # ------------------------------------------------------------------
    # Connection admission (accept path)
    # ------------------------------------------------------------------

    def _refill(self, host: str, now: float) -> float:
        tokens, last = self._buckets.get(
            host, (float(self.config.token_burst), now)
        )
        tokens = min(
            float(self.config.token_burst),
            tokens + (now - last) * self.config.token_rate,
        )
        return tokens

    # dpwalint: thread_root(rx)
    def admit(self, host: str) -> Tuple[bool, int]:
        """Try to admit one connection from ``host``.

        Returns ``(True, 0)`` and counts the connection active, or
        ``(False, retry_ms)`` with the hint to embed in the busy frame.
        Every admit must be paired with exactly one :meth:`release`."""
        with self._lock:
            now = self._clock()
            if self._active >= self.config.max_connections:
                self._sheds["connections"] += 1
                return False, self.config.busy_retry_ms
            tokens = self._refill(host, now)
            if tokens < 1.0:
                self._sheds["tokens"] += 1
                self._buckets[host] = (tokens, now)
                retry_ms = int(
                    math.ceil((1.0 - tokens) / self.config.token_rate * 1e3)
                )
                return False, max(retry_ms, self.config.busy_retry_ms)
            self._buckets[host] = (tokens - 1.0, now)
            self._active += 1
            self._peak_active = max(self._peak_active, self._active)
            self._admitted += 1
            return True, 0

    def release(self, host: str) -> None:
        with self._lock:
            self._active = max(0, self._active - 1)

    # ------------------------------------------------------------------
    # In-flight payload bytes (blob send path)
    # ------------------------------------------------------------------

    def reserve_bytes(self, nbytes: int) -> bool:
        """Reserve ``nbytes`` of serving budget for one blob send; False
        (counted as a ``bytes`` shed) when the ceiling would be crossed."""
        with self._lock:
            if self._inflight_bytes + nbytes > self.config.max_inflight_bytes:
                self._sheds["bytes"] += 1
                return False
            self._inflight_bytes += nbytes
            return True

    def release_bytes(self, nbytes: int) -> None:
        with self._lock:
            self._inflight_bytes = max(0, self._inflight_bytes - nbytes)

    # ------------------------------------------------------------------
    # Slow-loris accounting (request-read path)
    # ------------------------------------------------------------------

    def note_eviction(self) -> None:
        """A request read missed its minimum-ingest deadline and the
        connection was cut (counted; the client never gets a busy frame —
        it was not speaking the protocol fast enough to receive one)."""
        with self._lock:
            self._evictions += 1

    # ------------------------------------------------------------------
    # Observability
    # ------------------------------------------------------------------

    @property
    def shed_total(self) -> int:
        with self._lock:
            return sum(self._sheds.values())

    def snapshot(self) -> dict:
        """JSON-ready admission counters for /healthz and log_health."""
        with self._lock:
            return {
                "active": self._active,
                "peak_active": self._peak_active,
                "inflight_bytes": self._inflight_bytes,
                "admitted": self._admitted,
                "sheds": dict(self._sheds),
                "shed_total": sum(self._sheds.values()),
                "evictions": self._evictions,
            }


def register_metrics(registry, admission: "AdmissionController") -> None:
    """Expose serving-side admission control on a MetricsRegistry."""
    from dpwa_tpu.obs.prometheus import Family

    def collect():
        snap = admission.snapshot()
        sheds = Family(
            "dpwa_admission_sheds_total", "counter",
            "Requests shed by the serving admission gates, by reason",
        )
        for reason, n in sorted((snap.get("sheds") or {}).items()):
            sheds.sample(n, {"reason": reason})
        return [
            Family(
                "dpwa_admission_active_connections", "gauge",
                "Rx connections currently being served",
            ).sample(snap.get("active")),
            Family(
                "dpwa_admission_inflight_bytes", "gauge",
                "Payload bytes currently in flight to fetchers",
            ).sample(snap.get("inflight_bytes")),
            Family(
                "dpwa_admission_admitted_total", "counter",
                "Requests admitted past the serving gates",
            ).sample(snap.get("admitted")),
            Family(
                "dpwa_admission_evictions_total", "counter",
                "Slow-loris connections evicted mid-read",
            ).sample(snap.get("evictions")),
            sheds,
        ]

    registry.register(collect)
