"""Flow control plane: adaptive deadlines, hedging, serving admission.

The plane between the gossip scheduler and the TCP wire (docs/flowctl.md).
Two halves, both configured by the ``flowctl:`` block
(:class:`dpwa_tpu.config.FlowctlConfig`):

- **Fetcher side** (:class:`DeadlineEstimator`): every outcome-classified
  fetch feeds a per-peer latency window; the tracked quantile (times a
  margin, clamped to ``[min_ms, max_ms]``) becomes the next fetch's
  cumulative deadline, so a straggler costs its own observed latency — not
  the static ``protocol.timeout_ms`` — per scheduled round.  Once the
  un-margined quantile budget lapses, the transport launches one hedged
  retry against the schedule's deterministic fallback partner and the
  first success wins.

- **Serving side** (:class:`AdmissionController`): the Python Rx server
  sheds excess load *explicitly* — a global concurrent-connection cap,
  per-remote token-bucket pacing, an in-flight-bytes ceiling, and
  slow-loris eviction on request reads — by answering with a tiny
  ``DPWB`` busy frame instead of queueing unboundedly.  New readers
  classify it as the low-weight ``busy`` outcome (soft-degrade, never
  quarantine); old readers see EOF short of a full header and fall into
  their existing ``short_read`` handling.

Neither half holds references into the transport: the estimator is fed by
``TcpTransport.fetch`` and the controller by ``PeerServer``, keeping this
package importable without the wire (config is its only dependency).
"""

from dpwa_tpu.flowctl.admission import AdmissionController
from dpwa_tpu.flowctl.estimator import DeadlineEstimator

__all__ = [
    "AdmissionController",
    "DeadlineEstimator",
]
