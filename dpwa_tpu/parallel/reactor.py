"""Reactor-core Rx server: one event-loop thread serves every peer.

:class:`ReactorPeerServer` is the ``selectors``-based replacement for
the thread-per-connection :class:`~dpwa_tpu.parallel.tcp.PeerServer`,
selected by ``protocol.rx_server: reactor``.  Ring size under the
threaded server is capped by thread explosion long before wire
bandwidth matters — every admitted connection costs a worker thread —
while here an admitted connection costs one registered socket plus a
small state machine, so a single process can serve 256-peer rings
(ROADMAP: fleet / sharded-gossip scale) without spawning anything.

Wire behavior is byte-for-byte the threaded server's: the reactor
reuses ``tcp.py``'s frame builders and the frozen constants in
:mod:`~dpwa_tpu.parallel.protocol_constants` (the wire-freeze checker
keeps it that way), so old fetchers cannot tell the servers apart.

Per-connection state machine (one-shot protocol — request in, one
framed response out, close)::

    REQ ── DPWA? ──────────────────────────▶ WRITE (blob | DPWB busy)
     │──── DPWA@ ──▶ STATE_BODY ───────────▶ WRITE (one DPWS chunk)
     │──── DPWA! ──▶ RELAY_BODY ─▶ RELAY_HOST ─▶ RELAY_WAIT ─▶ WRITE
     └──── anything else ──────────────────▶ close (garbage request)

Each readable/writable callback runs the plane-hook pipeline the
threaded handler ran inline: decode (frame grammar above) → flowctl
admission (token bucket + connection cap at accept, in-flight-bytes
ceiling at serve, DPWB shed on refusal) → membership digest / trust
screen (both ride the published frame: the transport bakes the DPWM /
DPWT trailers into the payload at publish time, so serving them is the
same buffered write) → serve/merge handoff (the one-shot response).
Token buckets, busy shedding, and slow-loris eviction thereby become
*scheduler* decisions: a hashed timer wheel holds every connection's
effective deadline — ``base + ingested_bytes * per_byte`` during the
request read, idle-refreshed during writes — and the loop evicts
expired connections instead of each worker thread policing its own
socket timeout.

Threads: the event loop itself, plus ONE helper thread for relay
probes (``DPWA!`` asks us to synchronously probe a third peer, up to
``MAX_RELAY_TIMEOUT_MS`` of blocking the loop cannot afford); probe
completions post back through a queue and a self-pipe wakeup.  That is
O(1) threads regardless of ring size, vs O(connections) threaded.

Data movement is the pure-Python zero-copy pass shipped with the frame
hot path (docs/transport.md "The zero-copy landing zone"): reads land
via ``recv_into`` on one preallocated loop-thread scratch buffer, the
decode loop parses requests in place (``startswith`` / ``unpack_from``
against the connection buffer, no per-request ``bytes`` copies), and
blob/state responses go out as segment lists — header and payload are
never concatenated; the writable callback walks them with a
non-blocking ``sendmsg``.  The eventual native landing zone is
``native/rx_server.cpp`` — the same reactor shape with the GIL out of
the serve path entirely.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import selectors
import socket
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from dpwa_tpu.config import FlowctlConfig
from dpwa_tpu.flowctl import AdmissionController
from dpwa_tpu.health.detector import Outcome

# The threaded server's module owns the frame builders and the aliases
# into protocol_constants; reusing them (never re-deriving) is what
# makes "byte-for-byte identical responses" true by construction.
from dpwa_tpu.parallel import tcp as _tcp
from dpwa_tpu.parallel import ingest as _ingest

# Connection phases (strings, compared by identity in the hot loop).
_PH_REQ = "req"
_PH_STATE_BODY = "state_body"
_PH_RELAY_BODY = "relay_body"
_PH_RELAY_HOST = "relay_host"
_PH_RELAY_WAIT = "relay_wait"
_PH_WRITE = "write"

# Phases whose deadline expiry counts as a slow-loris EVICTION (the
# threaded server's note_eviction fires only for the request/STATE-body
# read; a stalled relay body or write lands in its silent OSError
# path).  Keeping that split keeps flowctl counters identical.
_INGEST_PHASES = (_PH_REQ, _PH_STATE_BODY)

_ACCEPT_BATCH = 64  # accepts drained per readiness event
_RECV_CHUNK = 65536
_SHED_TIMEOUT_S = 0.5  # budget for the best-effort DPWB busy reply
_RELAY_SLACK_S = 5.0  # queue slack on top of the clamped probe budget


class _Conn:
    """One accepted connection's state machine (loop-thread only)."""

    __slots__ = (
        "sock", "host", "admitted", "phase", "inbuf", "need", "outbuf",
        "outsegs", "sent", "base_deadline", "deadline", "per_byte",
        "ingested", "write_timeout", "reserved", "is_blob", "trace_id",
        "t0", "relay", "seq", "slot", "closed",
    )

    def __init__(self, sock: socket.socket, host: str, admitted: bool):
        self.sock = sock
        self.host = host
        self.admitted = admitted
        self.phase = _PH_REQ
        self.inbuf = bytearray()
        self.need = len(_tcp._REQ)
        # Exactly one response representation is active at a time:
        # ``outbuf`` for single-buffer replies (busy, relay, chaos),
        # ``outsegs`` for scatter-gather blob/state serves — a list of
        # memoryviews the writable callback advances in place.
        self.outbuf: Optional[memoryview] = None
        self.outsegs: Optional[List[memoryview]] = None
        self.sent = 0
        self.base_deadline = 0.0
        self.deadline = 0.0
        self.per_byte = 0.0
        self.ingested = 0
        self.write_timeout = 0.0
        self.reserved = 0  # bytes held against the in-flight ceiling
        self.is_blob = False
        self.trace_id: Optional[str] = None
        self.t0 = 0.0
        self.relay: Optional[Tuple[int, int, int]] = None
        self.seq = 0
        self.slot = -1  # timer-wheel slot, -1 = not filed
        self.closed = False


class _TimerWheel:
    """Hashed timer wheel with lazy re-filing.

    Connections are filed by ``deadline // granularity`` modulo the
    slot count; a slot firing re-checks each member's CURRENT deadline
    and re-files the not-yet-due (deadlines refreshed by ingest/write
    progress never have to touch the wheel on the hot path — the stale
    entry is corrected when its slot comes around)."""

    def __init__(self, granularity: float = 0.05, nslots: int = 128):
        self.granularity = granularity
        self.nslots = nslots
        self.slots: List[set] = [set() for _ in range(nslots)]
        self.tick = 0  # next absolute tick to process

    def start(self, now: float) -> None:
        self.tick = int(now / self.granularity)

    def file(self, conn: _Conn, min_tick: Optional[int] = None) -> None:
        idx = max(
            int(conn.deadline / self.granularity),
            self.tick if min_tick is None else min_tick,
        )
        slot = idx % self.nslots
        if conn.slot == slot:
            return
        self.unfile(conn)
        conn.slot = slot
        self.slots[slot].add(conn)

    def unfile(self, conn: _Conn) -> None:
        if conn.slot >= 0:
            self.slots[conn.slot].discard(conn)
            conn.slot = -1

    def expired(self, now: float) -> List[_Conn]:
        out: List[_Conn] = []
        target = int(now / self.granularity)
        while self.tick <= target:
            slot = self.slots[self.tick % self.nslots]
            if slot:
                for conn in list(slot):
                    if conn.deadline <= now:
                        slot.discard(conn)
                        conn.slot = -1
                        out.append(conn)
                    else:
                        # Refreshed or far-future (wrapped) deadline.
                        # Re-file STRICTLY AFTER the tick being
                        # processed: its member snapshot is already
                        # taken, so landing back in it would defer the
                        # deadline a full wheel revolution.
                        slot.discard(conn)
                        conn.slot = -1
                        self.file(conn, min_tick=self.tick + 1)
            self.tick += 1
        return out


class ReactorPeerServer:
    """Single-threaded event-loop Rx server (``protocol.rx_server:
    reactor``).  Public surface mirrors :class:`tcp.PeerServer`:
    ``publish`` / ``publish_state`` / ``close`` / ``port`` /
    ``admission`` / ``relay_guard`` / ``obs_serve_hook``."""

    # Same optional hooks as the threaded server (docs there).
    relay_guard = None
    obs_serve_hook = None

    def __init__(
        self,
        host: str,
        port: int,
        flowctl: Optional[FlowctlConfig] = None,
    ):
        self._lock = threading.Lock()
        # Pre-framed (header, payload[, digest][, obs]) segment tuple;
        # the _payload property joins them for chaos/test readers.
        self._segments: Optional[Tuple[bytes, ...]] = None
        self._payload_nbytes = 0
        self._payload_trace_id: Optional[str] = None
        self._state: Optional[bytes] = None
        self._state_gen = 0
        self.flowctl = flowctl if flowctl is not None else FlowctlConfig()
        if self.flowctl.enabled:
            # Same admission semantics as threaded, but the connection
            # cap is lifted to reactor_max_connections: the threaded
            # cap bounds worker THREADS, this one bounds registered
            # sockets.  Token pacing, the in-flight-bytes ceiling, and
            # eviction accounting are shared knob-for-knob.
            self.admission: Optional[AdmissionController] = (
                AdmissionController(
                    dataclasses.replace(
                        self.flowctl,
                        max_connections=(
                            self.flowctl.reactor_max_connections
                        ),
                    )
                )
            )
        else:
            self.admission = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        # Deep backlog: a 256-peer ring round-start is an accept BURST,
        # and unlike the threaded server the loop drains it in batches
        # rather than one thread spawn at a time.
        self._sock.listen(256)
        self._sock.setblocking(False)
        self._sel = selectors.DefaultSelector()
        # Self-pipe: the relay worker (and close()) nudge the sleeping
        # selector awake without waiting out its poll granularity.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._relay_jobs: queue.SimpleQueue = queue.SimpleQueue()
        self._relay_done: queue.SimpleQueue = queue.SimpleQueue()
        self._relay_pending: Dict[int, _Conn] = {}  # loop thread only
        self._relay_seq = itertools.count(1)
        # Loop-thread-only receive scratch: every readable callback
        # recv_intos here, so the read path allocates nothing per chunk
        # (requests are tiny; the bytes that matter leave via inbuf).
        self._scratch = bytearray(_RECV_CHUNK)
        self._scratch_view = memoryview(self._scratch)
        self._wheel = _TimerWheel()
        self._stats_lock = threading.Lock()
        self._stats = {
            "accepted": 0,
            "open": 0,
            "peak_open": 0,
            "evicted": 0,
            "busy_shed": 0,
            "frames": 0,
            "relay_pending": 0,
            "loop_lag_ms": 0.0,
            "ready_depth": 0,
        }
        self._stop = threading.Event()
        self._relay_thread = threading.Thread(
            target=self._relay_worker,
            name=f"dpwa-rx-relay:{self.port}",
            daemon=True,
        )
        self._relay_thread.start()
        self._thread = threading.Thread(
            target=self._run,
            name=f"dpwa-rx-reactor:{self.port}",
            daemon=True,
        )
        self._thread.start()

    # --- publish surface (identical to the threaded server) ---

    def publish(
        self,
        vec: np.ndarray,
        clock: float,
        loss: float,
        code: Optional[int] = None,
        digest: Optional[bytes] = None,
        obs: Optional[bytes] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        segments = _tcp._frame_segments(vec, clock, loss, code, digest, obs)
        with self._lock:
            self._segments = segments
            self._payload_nbytes = sum(len(s) for s in segments)
            self._payload_trace_id = trace_id

    @property
    def _payload(self) -> Optional[bytes]:
        """The published frame as one bytes object — back-compat for
        chaos wrappers and tests that inspect the served frame.  Reads
        the segments tuple atomically; deliberately lock-free so chaos
        callers already holding ``_lock`` can use it."""
        segs = self._segments
        return b"".join(segs) if segs is not None else None

    def publish_state(self, blob: bytes) -> None:
        with self._lock:
            # dpwalint: ignore[zerocopy-tobytes] -- publish-time snapshot: served views must outlive the caller's buffer
            self._state = bytes(blob)
            self._state_gen = (self._state_gen + 1) & 0xFFFFFFFF

    # --- observability surface ---

    def reactor_snapshot(self) -> dict:
        """JSON-ready scheduler state: the payload behind the
        ``dpwa_reactor_*`` gauges, healthz's ``reactor`` sub-document,
        and metrics' ``reactor_*`` columns."""
        with self._stats_lock:
            s = dict(self._stats)
        return {
            "open": s["open"],
            "peak_open": s["peak_open"],
            "accepted": s["accepted"],
            "evicted": s["evicted"],
            "busy_shed": s["busy_shed"],
            "frames": s["frames"],
            "relay_pending": s["relay_pending"],
            "loop_lag_ms": round(s["loop_lag_ms"], 3),
            "ready_depth": s["ready_depth"],
        }

    def close(self) -> None:
        self._stop.set()
        self._relay_jobs.put(None)  # unpark the relay worker
        try:
            self._wake_w.send(b"\0")  # unpark the selector
        except OSError:
            pass
        self._thread.join(timeout=2.0)
        self._relay_thread.join(timeout=2.0)
        for sock in (self._sock, self._wake_w, self._wake_r):
            try:
                sock.close()
            except OSError:
                pass

    # --- event loop ---

    # dpwalint: thread_root(reactor)
    def _run(self) -> None:
        sel = self._sel
        try:
            sel.register(self._sock, selectors.EVENT_READ, None)
            sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        except (OSError, ValueError):
            return
        self._wheel.start(time.monotonic())
        granularity = self._wheel.granularity
        while not self._stop.is_set():
            try:
                events = sel.select(granularity)
            except OSError:
                break
            t0 = time.monotonic()
            depth = 0
            for key, mask in events:
                data = key.data
                if data is None:
                    self._on_accept(t0)
                elif data == "wake":
                    self._drain_wake()
                else:
                    depth += 1
                    self._on_event(data, mask)
            self._drain_relay_done()
            now = time.monotonic()
            for conn in self._wheel.expired(now):
                self._on_deadline(conn, now)
            # Loop lag = time this iteration spent processing its ready
            # batch; under an overloaded loop it grows toward the poll
            # period and beyond, which is the saturation signal.
            lag_ms = (time.monotonic() - t0) * 1000.0
            with self._stats_lock:
                st = self._stats
                st["loop_lag_ms"] += 0.1 * (lag_ms - st["loop_lag_ms"])
                st["ready_depth"] = depth
        self._shutdown()

    def _shutdown(self) -> None:
        for key in list(self._sel.get_map().values()):
            if isinstance(key.data, _Conn):
                self._close_conn(key.data)
        try:
            self._sel.close()
        except (OSError, RuntimeError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    # --- accept + admission (plane hook #1: flowctl) ---

    def _on_accept(self, now: float) -> None:
        for _ in range(_ACCEPT_BATCH):
            try:
                sock, addr = self._sock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            host = addr[0] if addr else ""
            try:
                sock.setblocking(False)
            except OSError:
                continue
            if self.admission is not None:
                ok, retry_ms = self.admission.admit(host)
                if not ok:
                    self._shed(sock, host, retry_ms, now)
                    continue
            conn = _Conn(sock, host, admitted=self.admission is not None)
            fc = self.flowctl
            conn.base_deadline = now + fc.request_timeout_ms / 1000.0
            conn.deadline = conn.base_deadline
            conn.write_timeout = fc.request_timeout_ms / 1000.0
            if fc.enabled and fc.min_ingest_bytes_per_s > 0:
                conn.per_byte = 1.0 / fc.min_ingest_bytes_per_s
            if not self._register(conn, selectors.EVENT_READ):
                continue
            with self._stats_lock:
                st = self._stats
                st["accepted"] += 1
                st["open"] += 1
                st["peak_open"] = max(st["peak_open"], st["open"])

    def _shed(
        self, sock: socket.socket, host: str, retry_ms: int, now: float
    ) -> None:
        """Busy-shed an unadmitted connection: queue the tiny DPWB
        frame as a normal write (best-effort, short budget) — the
        threaded server's _shed with the blocking send replaced by the
        scheduler."""
        conn = _Conn(sock, host, admitted=False)
        conn.phase = _PH_WRITE
        conn.outbuf = memoryview(_tcp._busy_frame(retry_ms))
        conn.write_timeout = _SHED_TIMEOUT_S
        conn.deadline = now + _SHED_TIMEOUT_S
        if not self._register(conn, selectors.EVENT_WRITE):
            return
        with self._stats_lock:
            st = self._stats
            st["busy_shed"] += 1
            st["open"] += 1
            st["peak_open"] = max(st["peak_open"], st["open"])
        self._on_writable(conn)  # common case: one immediate send

    def _register(self, conn: _Conn, mask: int) -> bool:
        try:
            self._sel.register(conn.sock, mask, conn)
        except (OSError, ValueError, KeyError):
            try:
                conn.sock.close()
            except OSError:
                pass
            if conn.admitted and self.admission is not None:
                self.admission.release(conn.host)
            return False
        self._wheel.file(conn)
        return True

    # --- readiness dispatch ---

    def _on_event(self, conn: _Conn, mask: int) -> None:
        if conn.closed:
            return
        if mask & selectors.EVENT_READ:
            self._on_readable(conn)
        if not conn.closed and mask & selectors.EVENT_WRITE:
            self._on_writable(conn)

    def _on_readable(self, conn: _Conn) -> None:
        try:
            got = conn.sock.recv_into(self._scratch)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not got:
            # EOF: mid-request it is the client abandoning us; during
            # RELAY_WAIT it means nobody is left to answer.
            self._close_conn(conn)
            return
        if conn.phase == _PH_WRITE or conn.phase == _PH_RELAY_WAIT:
            # Bytes past the request are ignored, exactly like the
            # threaded handler that simply never reads them.
            return
        conn.ingested += got
        conn.inbuf += self._scratch_view[:got]
        now = time.monotonic()
        if conn.per_byte > 0.0 and conn.phase in _INGEST_PHASES:
            # Slow-loris discipline (flowctl): cumulative deadline
            # extended per byte at the minimum ingest rate — the
            # reactor form of _recv_exact's accounting.
            conn.deadline = (
                conn.base_deadline + conn.ingested * conn.per_byte
            )
        else:
            # No flowctl (or a relay body): plain idle timeout,
            # refreshed on progress — the threaded socket timeout.
            conn.deadline = now + conn.write_timeout
        self._advance(conn, now)

    def _advance(self, conn: _Conn, now: float) -> None:
        """Run the decode pipeline as far as the buffered bytes allow
        (plane hook #2: frame grammar decode + dispatch)."""
        # Requests parse IN PLACE against the connection buffer
        # (startswith prefix compares, struct.unpack_from at offset 0)
        # before the consumed bytes are deleted — no per-request
        # ``bytes`` copies, and no memoryview may be held across the
        # ``del`` (a live exported view makes bytearray resize raise).
        while not conn.closed and len(conn.inbuf) >= conn.need:
            if conn.phase == _PH_REQ:
                # The three request magics share one length, so the
                # prefix compare over ``need`` bytes IS the equality
                # compare the threaded handler does.
                if conn.inbuf.startswith(_tcp._REQ):
                    del conn.inbuf[: conn.need]
                    self._serve_blob(conn, now)
                    return
                if conn.inbuf.startswith(_tcp._STATE_REQ):
                    del conn.inbuf[: conn.need]
                    conn.phase = _PH_STATE_BODY
                    conn.need = _tcp._STATE_REQ_BODY.size
                    continue
                if conn.inbuf.startswith(_tcp._RELAY_REQ):
                    del conn.inbuf[: conn.need]
                    conn.phase = _PH_RELAY_BODY
                    conn.need = _tcp._RELAY_BODY.size
                    continue
                # Garbage request: close, same as the threaded return.
                self._close_conn(conn)
                return
            if conn.phase == _PH_STATE_BODY:
                offset, max_chunk = _tcp._STATE_REQ_BODY.unpack_from(
                    conn.inbuf, 0
                )
                del conn.inbuf[: conn.need]
                self._serve_state(conn, offset, max_chunk, now)
                return
            if conn.phase == _PH_RELAY_BODY:
                target, port, timeout_ms, hostlen = (
                    _tcp._RELAY_BODY.unpack_from(conn.inbuf, 0)
                )
                del conn.inbuf[: conn.need]
                conn.relay = (int(target), int(port), int(timeout_ms))
                if hostlen:
                    conn.phase = _PH_RELAY_HOST
                    conn.need = int(hostlen)
                    continue
                self._start_relay(conn, "127.0.0.1", now)
                return
            if conn.phase == _PH_RELAY_HOST:
                host = conn.inbuf[: conn.need].decode("ascii", "replace")
                del conn.inbuf[: conn.need]
                self._start_relay(conn, host, now)
                return
            return

    # --- serve handoff (plane hook #5) ---

    def _serve_blob(self, conn: _Conn, now: float) -> None:
        """Queue the published frame (header + payload + optional DPWM
        digest + DPWT obs trailers, baked in at publish time — plane
        hooks #3/#4 ride the buffer) under the in-flight ceiling."""
        with self._lock:
            segments = self._segments
            nbytes = self._payload_nbytes
            trace_id = self._payload_trace_id
        if segments is None:
            self._close_conn(conn)  # nothing published yet: clean EOF
            return
        adm = self.admission
        if adm is not None and not adm.reserve_bytes(nbytes):
            self._queue_busy(conn, self.flowctl.busy_retry_ms, now)
            return
        conn.reserved = nbytes
        conn.is_blob = True
        conn.trace_id = trace_id
        conn.t0 = now
        self._queue_segments(conn, segments, now)

    def _queue_busy(self, conn: _Conn, retry_ms: int, now: float) -> None:
        with self._stats_lock:
            self._stats["busy_shed"] += 1
        self._queue_write(conn, _tcp._busy_frame(retry_ms), now)

    def _serve_state(
        self, conn: _Conn, offset: int, max_chunk: int, now: float
    ) -> None:
        """One DPWS chunk per connection — byte-identical to the
        threaded _handle_state (empty blob = well-formed total=0)."""
        with self._lock:
            blob = self._state if self._state is not None else b""
            gen = self._state_gen
        total = len(blob)
        off = min(max(offset, 0), total)
        n = min(max(max_chunk, 0), total - off, _tcp._MAX_STATE_CHUNK)
        # A view of the published blob, never a slice copy: the blob is
        # immutable bytes and a republish swaps the OBJECT, so the view
        # stays valid for the life of this response.
        chunk = memoryview(blob)[off : off + n]
        header = _tcp._STATE_HDR.pack(
            _tcp._STATE_MAGIC, 1, gen, total, off, len(chunk),
            zlib.crc32(chunk),
        )
        self._queue_segments(conn, (header, chunk), now)

    # --- relay probes (the one blocking verb, offloaded) ---

    def _start_relay(self, conn: _Conn, host: str, now: float) -> None:
        target, port, timeout_ms = conn.relay
        timeout_ms = min(max(timeout_ms, 1), _tcp._MAX_RELAY_TIMEOUT_MS)
        guard = self.relay_guard
        if guard is not None and guard(target):
            self._relay_reply(conn, Outcome.REFUSED, None, now)
            return
        conn.phase = _PH_RELAY_WAIT
        # EVENT_READ stays registered: an EOF while we probe means the
        # requester is gone and the answer can be dropped.
        conn.seq = next(self._relay_seq)
        self._relay_pending[conn.seq] = conn
        conn.deadline = now + timeout_ms / 1000.0 + _RELAY_SLACK_S
        self._wheel.file(conn)
        with self._stats_lock:
            self._stats["relay_pending"] += 1
        self._relay_jobs.put((conn.seq, host, port, timeout_ms))

    def _relay_worker(self) -> None:
        """The single relay helper thread: blocking header probes run
        here so the loop never does; completions post back via queue +
        self-pipe."""
        while True:
            job = self._relay_jobs.get()
            if job is None:
                return
            seq, host, port, timeout_ms = job
            try:
                outcome, clock = _tcp.probe_header_classified(
                    host, port, timeout_ms
                )
            except Exception:
                outcome, clock = None, None  # loop closes the conn
            self._relay_done.put((seq, outcome, clock))
            try:
                self._wake_w.send(b"\0")
            except OSError:
                return

    def _drain_relay_done(self) -> None:
        while True:
            try:
                seq, outcome, clock = self._relay_done.get_nowait()
            except queue.Empty:
                return
            with self._stats_lock:
                self._stats["relay_pending"] -= 1
            conn = self._relay_pending.pop(seq, None)
            if conn is None or conn.closed:
                continue
            if outcome is None:
                self._close_conn(conn)
                continue
            self._relay_reply(conn, outcome, clock, time.monotonic())

    def _relay_reply(
        self,
        conn: _Conn,
        outcome: Outcome,
        clock: Optional[float],
        now: float,
    ) -> None:
        frame = _tcp._RELAY_HDR.pack(
            _tcp._RELAY_MAGIC,
            1,
            _tcp._RELAY_OUTCOMES.index(outcome),
            float(clock) if clock is not None else -1.0,
        )
        self._queue_write(conn, frame, now)

    # --- buffered writes ---

    def _queue_write(self, conn: _Conn, data: bytes, now: float) -> None:
        conn.outbuf = memoryview(data)
        conn.outsegs = None
        self._arm_write(conn, now)

    def _queue_segments(
        self, conn: _Conn, segments, now: float
    ) -> None:
        """Scatter-gather response: the segments go out as-is (header,
        payload, trailers), never concatenated into a scratch buffer."""
        conn.outbuf = None
        conn.outsegs = [
            memoryview(s).cast("B") for s in segments if len(s)
        ]
        self._arm_write(conn, now)

    def _arm_write(self, conn: _Conn, now: float) -> None:
        conn.phase = _PH_WRITE
        conn.sent = 0
        conn.deadline = now + conn.write_timeout
        self._wheel.file(conn)
        try:
            self._sel.modify(conn.sock, selectors.EVENT_WRITE, conn)
        except (OSError, ValueError, KeyError):
            self._close_conn(conn)
            return
        self._on_writable(conn)  # short responses finish in one call

    def _on_writable(self, conn: _Conn) -> None:
        if conn.outsegs is not None:
            self._write_segments(conn)
            return
        buf = conn.outbuf
        if buf is None:
            return
        progressed = False
        while conn.sent < len(buf):
            try:
                n = conn.sock.send(buf[conn.sent :])
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close_conn(conn)
                return
            if n <= 0:
                break
            conn.sent += n
            progressed = True
        if conn.sent >= len(buf):
            if conn.is_blob:
                with self._stats_lock:
                    self._stats["frames"] += 1
            # One-shot protocol: response out, connection done (the
            # close also releases reserved bytes and fires the serve
            # span hook, the threaded worker's ``finally``).
            self._close_conn(conn)
            return
        if progressed:
            # A draining peer keeps its connection; a stalled one hits
            # the unrefreshed deadline on the wheel.
            conn.deadline = time.monotonic() + conn.write_timeout

    def _write_segments(self, conn: _Conn) -> None:
        """Drain ``conn.outsegs`` with non-blocking ``sendmsg``: one
        syscall covers every remaining segment; partial sends advance
        the view list in place (fully-sent heads pop, a split head is
        sliced).  Falls back to plain ``send`` of the head segment
        where ``sendmsg`` is unavailable or refused."""
        segs = conn.outsegs
        sendmsg = getattr(conn.sock, "sendmsg", None)
        progressed = False
        while segs:
            try:
                if sendmsg is not None:
                    n = sendmsg(segs)
                else:
                    n = conn.sock.send(segs[0])
            except (BlockingIOError, InterruptedError):
                break
            except OSError as exc:
                if (
                    sendmsg is not None
                    and exc.errno in _ingest._SENDMSG_UNSUPPORTED
                ):
                    sendmsg = None
                    continue
                self._close_conn(conn)
                return
            if n <= 0:
                break
            conn.sent += n
            progressed = True
            while n > 0 and segs:
                head = segs[0]
                if n >= len(head):
                    n -= len(head)
                    segs.pop(0)
                else:
                    segs[0] = head[n:]
                    n = 0
        if not segs:
            if conn.is_blob:
                with self._stats_lock:
                    self._stats["frames"] += 1
            self._close_conn(conn)
            return
        if progressed:
            conn.deadline = time.monotonic() + conn.write_timeout

    # --- deadlines + teardown ---

    def _on_deadline(self, conn: _Conn, now: float) -> None:
        if conn.closed or conn.deadline > now:
            return
        evict = (
            conn.phase in _INGEST_PHASES and self.flowctl.enabled
        )
        if evict and self.admission is not None:
            # Slow-loris eviction: identical accounting to the
            # threaded socket.timeout → note_eviction path.
            self.admission.note_eviction()
        self._close_conn(conn, timed_out=True)

    def _close_conn(self, conn: _Conn, timed_out: bool = False) -> None:
        if conn.closed:
            return
        conn.closed = True
        self._wheel.unfile(conn)
        if conn.seq:
            self._relay_pending.pop(conn.seq, None)
        try:
            self._sel.unregister(conn.sock)
        except (OSError, ValueError, KeyError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        adm = self.admission
        if conn.reserved and adm is not None:
            adm.release_bytes(conn.reserved)
        hook = self.obs_serve_hook
        if conn.is_blob and hook is not None and conn.trace_id is not None:
            try:
                hook(
                    conn.trace_id,
                    conn.reserved,
                    time.monotonic() - conn.t0,
                )
            except Exception:
                pass  # observability must never break a serve
        if conn.admitted and adm is not None:
            adm.release(conn.host)
        with self._stats_lock:
            st = self._stats
            st["open"] -= 1
            if timed_out:
                st["evicted"] += 1


def register_metrics(registry, server: ReactorPeerServer) -> None:
    """Expose the reactor scheduler on /metrics as ``dpwa_reactor_*``."""
    from dpwa_tpu.obs.prometheus import Family

    def _collect():
        snap = server.reactor_snapshot()
        return [
            Family(
                "dpwa_reactor_loop_lag_ms",
                "gauge",
                "EWMA of event-loop iteration processing time.",
            ).sample(snap["loop_lag_ms"]),
            Family(
                "dpwa_reactor_ready_depth",
                "gauge",
                "Ready connections dispatched in the last iteration.",
            ).sample(snap["ready_depth"]),
            Family(
                "dpwa_reactor_open_connections",
                "gauge",
                "Connections currently registered with the loop.",
            ).sample(snap["open"]),
            Family(
                "dpwa_reactor_peak_connections",
                "gauge",
                "High-water mark of concurrently open connections.",
            ).sample(snap["peak_open"]),
            Family(
                "dpwa_reactor_accepted_total",
                "counter",
                "Connections admitted past flowctl at accept.",
            ).sample(snap["accepted"]),
            Family(
                "dpwa_reactor_evicted_total",
                "counter",
                "Connections closed by a timer-wheel deadline.",
            ).sample(snap["evicted"]),
            Family(
                "dpwa_reactor_busy_shed_total",
                "counter",
                "DPWB busy frames sent (admission + byte-ceiling sheds).",
            ).sample(snap["busy_shed"]),
            Family(
                "dpwa_reactor_frames_served_total",
                "counter",
                "Published blob frames fully written to a peer.",
            ).sample(snap["frames"]),
            Family(
                "dpwa_reactor_relay_pending",
                "gauge",
                "Relay probes in flight on the helper thread.",
            ).sample(snap["relay_pending"]),
        ]

    registry.register(_collect)
