"""Single registry of every constant that appears on the gossip wire.

Frame magics, struct layouts, payload codes, outcome tables, and size
clamps all live HERE and only here: the wire protocol is a compatibility
contract between peers running different builds, so its constants must
be impossible to fork by editing one call site.  ``dpwalint``'s
wire-protocol checker rejects any ``b"DPW…"`` literal or struct format
string that appears on the wire path outside this module, and
registering the same magic twice raises at import time.

This module also carries the back-compat ledger that used to be buried
in comments next to the literals — see the notes on each constant and
:data:`BACK_COMPAT`.  It imports nothing from the rest of the package
(stdlib ``struct`` only), so every plane can depend on it without
cycles.

Request dispatch: a client's first write is a 5-byte request magic; the
Rx server reads exactly 5 bytes and dispatches on them, which is why all
request magics share one length.  Response frames lead with a 4-byte
magic inside a fixed struct header.
"""

from __future__ import annotations

import struct
from typing import Dict, Tuple

_MAGIC_REGISTRY: Dict[bytes, str] = {}


def _magic(name: str, value: bytes) -> bytes:
    """Register a frame magic; collision (or prefix reuse) = error."""
    if value in _MAGIC_REGISTRY:
        raise ValueError(
            "wire magic collision: %r already registered as %r, cannot"
            " also register %r" % (value, _MAGIC_REGISTRY[value], name)
        )
    _MAGIC_REGISTRY[value] = name
    return value


# --- request magics (5 bytes: first — and for relay, only — client write) ---
# Gossip blob fetch: response is BLOB_HDR + payload (+ optional trailers).
BLOB_REQ = _magic("blob_request", b"DPWA?")
# State transfer (crash recovery): followed by STATE_REQ_BODY.
STATE_REQ = _magic("state_request", b"DPWA@")
# Relay probe (epidemic membership): followed by RELAY_BODY + host bytes.
RELAY_REQ = _magic("relay_request", b"DPWA!")

# --- response / section magics (4 bytes, first field of the header) ---
BLOB_MAGIC = _magic("blob_frame", b"DPWA")
STATE_MAGIC = _magic("state_frame", b"DPWS")
RELAY_MAGIC = _magic("relay_report", b"DPWR")
BUSY_MAGIC = _magic("busy_nack", b"DPWB")
DIGEST_MAGIC = _magic("membership_digest", b"DPWM")
OBS_MAGIC = _magic("obs_section", b"DPWT")

# --- struct layouts (little-endian throughout) ---
# Gossip blob response header:
#   magic(4s) version(B) dtype(B) clock(d) loss(d) nbytes(Q)
BLOB_HDR_FMT = "<4sBBddQ"
# State request body after STATE_REQ: <Q offset><I max_chunk>.
STATE_REQ_BODY_FMT = "<QI"
# State response header (ONE chunk per connection — resumable transfer):
#   magic(4s) version(B) generation(I) total(Q) offset(Q)
#   chunk_len(I) crc32(I)
STATE_HDR_FMT = "<4sBIQQII"
# Relay request body after RELAY_REQ:
#   <H target_index><H target_port><I probe_timeout_ms><B hostlen> + host
RELAY_BODY_FMT = "<HHIB"
# Relay response: magic(4s) version(B) outcome(B) clock(d), where
# ``outcome`` indexes RELAY_OUTCOME_NAMES.
RELAY_HDR_FMT = "<4sBBd"
# Busy shed reply: magic(4s) version(B) retry_hint_ms(H).
BUSY_HDR_FMT = "<4sBH"
# Membership digest trailer header: magic(4s) version(B) entry_count(H)
# incarnation_clock(I) sender(H), then entry_count packed entries.
DIGEST_HDR_FMT = "<4sBHIH"
# One digest entry: peer(H) state(B) incarnation(I) suspicion(f).
DIGEST_ENTRY_FMT = "<HBIf"
# Version-2 (hierarchical) digest entry: the v1 fields, then
# island(H) leader_term(H) flags(B) — flags bit0 = "is the island's
# elected leader".  The header's u8 version field selects the entry
# width; see BACK_COMPAT["digest_v2_hier_entries"].
DIGEST_ENTRY_V2_FMT = "<HBIfHHB"
# Observability trailer header: magic(4s) version(B) sketch_count(H)
# trace_id(I) loss_ema(f) reserved(H), then sketch_count f32 values.
OBS_HDR_FMT = "<4sBHIfH"
# Sharded-payload preamble (payload code 6), prepended to the inner
# payload bytes: shard_idx(I) k(I) d(Q) inner_code(B), where ``d`` is
# the FULL flattened-replica length, ``shard_idx < k`` names which
# contiguous slice the body carries, and ``inner_code`` is the payload
# code of the body's encoding (f32 / bf16 / int8_chunked / topk_delta
# — over the slice, never another shard).
SHARD_HDR_FMT = "<IIQB"
# Length prefix used by recovery/state_transfer.py when packing leaves
# into the opaque state blob served under STATE_MAGIC.
STATE_PACK_LEN_FMT = "<I"

# Inner magic of the packed state blob itself (recovery/state_transfer):
# the blob rides opaquely inside DPWS chunks, but a donor and a rejoiner
# from different builds must agree on its framing, so it is part of the
# frozen contract too.
STATE_PACK_MAGIC = _magic("state_pack", b"DPST")

# Pre-compiled structs (import these, not struct.Struct(<literal>)).
BLOB_HDR = struct.Struct(BLOB_HDR_FMT)
STATE_REQ_BODY = struct.Struct(STATE_REQ_BODY_FMT)
STATE_HDR = struct.Struct(STATE_HDR_FMT)
RELAY_BODY = struct.Struct(RELAY_BODY_FMT)
RELAY_HDR = struct.Struct(RELAY_HDR_FMT)
BUSY_HDR = struct.Struct(BUSY_HDR_FMT)
DIGEST_HDR = struct.Struct(DIGEST_HDR_FMT)
DIGEST_ENTRY = struct.Struct(DIGEST_ENTRY_FMT)
DIGEST_ENTRY_V2 = struct.Struct(DIGEST_ENTRY_V2_FMT)
OBS_HDR = struct.Struct(OBS_HDR_FMT)
SHARD_HDR = struct.Struct(SHARD_HDR_FMT)
STATE_PACK_LEN = struct.Struct(STATE_PACK_LEN_FMT)

# --- payload (dtype) codes: the B ``dtype`` field of BLOB_HDR ---
_PAYLOAD_REGISTRY: Dict[int, str] = {}


def _payload(name: str, code: int) -> int:
    if code in _PAYLOAD_REGISTRY:
        raise ValueError(
            "payload code collision: %d already registered as %r, cannot"
            " also register %r" % (code, _PAYLOAD_REGISTRY[code], name)
        )
    _PAYLOAD_REGISTRY[code] = name
    return code


# Flat numpy dtypes (raw little-endian vector bytes follow the header).
PAYLOAD_F32 = _payload("f32", 0)
PAYLOAD_F64 = _payload("f64", 1)
PAYLOAD_U16 = _payload("u16", 2)
PAYLOAD_BF16 = _payload("bf16", 3)
# Code 4 is NOT a flat numpy dtype: int8-chunked payload
# (u64 n | f32 scales | int8 q — ops/quantize.py), decoded to f32 by
# fetch_blob.  protocol.wire_dtype: int8.
PAYLOAD_INT8_CHUNKED = _payload("int8_chunked", 4)
# Code 5: top-k delta payload (u64 n | u32 k | u8 value_code | sorted
# u32 idx[k] | f32-or-int8 values — ops/quantize.py).  fetch_blob_full
# returns it as a SPARSE TopkPayload object in the vector slot: only the
# receiver holds the replica the frame splices into, so densification
# happens in TcpTransport.fetch against the receiver's own published
# view.  protocol.wire_codec: topk.
PAYLOAD_TOPK_DELTA = _payload("topk_delta", 5)
# Code 6: sharded payload (SHARD_HDR preamble | inner payload —
# ops/shard.py).  The body carries ONE contiguous slice of the flattened
# replica, itself encoded by any flat dtype or codec above (the
# preamble's inner_code byte), so top-k and int8 compose per shard.
# fetch_blob_full returns it as a ShardPayload object in the vector
# slot — like top-k, only the receiver holds the replica the slice
# merges into.  shard: {k: >1}.
PAYLOAD_SHARD = _payload("shard", 6)
# Codec payloads: codes whose body is NOT a flat dtype cast.
CODEC_PAYLOAD_CODES: Tuple[int, ...] = (
    PAYLOAD_INT8_CHUNKED,
    PAYLOAD_TOPK_DELTA,
    PAYLOAD_SHARD,
)

# --- relay outcome codes: the B ``outcome`` field of RELAY_HDR ---
# Index → health-detector outcome name (tcp.py maps these onto the
# Outcome enum; the NAMES are the wire contract, the enum is not).
RELAY_OUTCOME_NAMES: Tuple[str, ...] = (
    "success",  # 0
    "timeout",  # 1
    "refused",  # 2
    "short_read",  # 3
    "corrupt",  # 4
    "busy",  # 5 — appended, see BACK_COMPAT["relay_busy_outcome"]
)

# --- size clamps (DoS bounds, part of the served contract) ---
MAX_BLOB_BYTES = 1 << 34  # 16 GiB sanity bound on advertised payload size
MAX_STATE_CHUNK_BYTES = 1 << 26  # 64 MiB server-side clamp on one chunk
MAX_DIGEST_BYTES = 1 << 20  # 1 MiB bound on a digest trailer
MAX_SKETCH_VALUES = 4096  # cap on f32 values in a DPWT section
# A malicious relay requester must not pin the relay's Rx thread with a
# huge probe timeout.
MAX_RELAY_TIMEOUT_MS = 500

# --- back-compat ledger ---
# Notes that explain why the layouts above are the way they are.  These
# were previously inline comments next to the literals; they are part of
# the frozen contract and the reactor rewrite must preserve every one.
BACK_COMPAT: Dict[str, str] = {
    "busy_nack_short_frame": (
        "The DPWB frame is 7 bytes, deliberately SHORTER than the "
        "30-byte blob header: an old fetcher blocked in its header read "
        "hits EOF when the server closes and lands in its existing "
        "short_read classification (wire compatible both directions), "
        "while a flowctl-aware fetcher peeks the 4-byte magic, reads "
        "the remaining 3, and records the low-weight busy outcome that "
        "soft-degrades the peer instead of quarantining it."
    ),
    "relay_busy_outcome": (
        "Relay outcome code 5 (busy) was appended by the flowctl plane: "
        "a relay may find the target alive but shedding.  Old readers "
        "reject code 5 as corrupt, which is the safe direction — they "
        "never vouch for a shedding peer."
    ),
    "digest_trailer_optional": (
        "The DPWM digest rides as an OPTIONAL trailing section AFTER "
        "the nbytes payload: the blob header's nbytes still counts only "
        "the vector, so a pre-membership fetcher reads exactly header + "
        "payload and never sees the trailer, while a digest-aware "
        "fetcher attempts a tolerant trailing read — version-gated wire "
        "compatibility in both directions (docs/membership.md)."
    ),
    "obs_after_digest": (
        "The DPWT observability section rides AFTER the digest when "
        "both are present.  Ordering matters for back-compat: a "
        "digest-aware pre-obs fetcher reads the digest it wants, then "
        "its next read fails the DPWM magic check on the DPWT header "
        "and stops harmlessly; obs-aware fetchers dispatch trailers by "
        "magic and handle every presence combination."
    ),
    "digest_v2_hier_entries": (
        "Digest version 2 (hierarchical gossip) widens each entry from "
        "11 to 16 bytes by APPENDING island id, leader term, and a "
        "leader flag after the v1 fields.  The header layout is "
        "unchanged and still carries the entry count, so a v2-aware "
        "reader sizes the body per version, while a v1-only reader "
        "rejects the unknown version and skips the whole trailer — the "
        "digest is optional, so that degrades to 'no membership "
        "piggyback', never a mis-framed stream.  Flat (no topology) "
        "rings keep emitting version 1 byte-identically."
    ),
    "state_one_chunk_per_connection": (
        "The state transfer serves ONE chunk per connection, which "
        "keeps the transfer resumable: a short read just reconnects at "
        "the next unacknowledged offset.  ``generation`` increments per "
        "publish_state, so a client detects a donor re-publishing "
        "mid-transfer (splicing two states would corrupt the bootstrap) "
        "and restarts cleanly."
    ),
    "request_magic_length": (
        "All request magics are 5 bytes so the Rx server reads exactly "
        "5 bytes and dispatches — adding a request type must keep that "
        "length or old servers mis-frame the connection."
    ),
    "shard_payload_code": (
        "Payload code 6 (shard) was appended by the sharded-gossip "
        "plane: the body is a SHARD_HDR preamble plus one slice of the "
        "replica in any inner encoding.  Old readers reject the unknown "
        "code as corrupt, which is the safe direction — they never "
        "merge a slice as if it were the full vector.  ``shard:`` "
        "absent or ``k: 1`` never takes this path, so mixed fleets "
        "interoperate by leaving sharding off until everyone upgrades; "
        "frames are then byte-identical to pre-shard builds."
    ),
}


def registered_magics() -> Dict[bytes, str]:
    """A copy of the magic → name registry."""
    return dict(_MAGIC_REGISTRY)


def registered_payload_codes() -> Dict[int, str]:
    """A copy of the payload code → name registry."""
    return dict(_PAYLOAD_REGISTRY)
