from dpwa_tpu.parallel.schedules import build_schedule  # noqa: F401
