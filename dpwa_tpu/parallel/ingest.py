"""Buffered zero-copy ingest: ``recv_into`` reads, the receive-buffer
ring, and scatter-gather sends.

This module is the single landing place for the frame hot path's data
movement (docs/transport.md "The zero-copy landing zone").  Before it
existed, every byte crossed Python 4-5 times per hop: ``_recv_n`` grew a
bytearray chunk-by-chunk (once in the gossip fetch, duplicated in the
state-transfer fetch), the serve path concatenated ``header + payload``
before ``sendall``, and the codec decoders round-tripped through
``.tobytes()``.  The three primitives here remove those copies:

- :func:`recv_exact_into` — the one buffered read loop.  Fills a
  caller-supplied buffer via ``sock.recv_into(view[filled:])`` with the
  exact cumulative-deadline / per-byte-budget / progress-cell semantics
  the old ``_recv_exact`` had (same exception types and messages, so
  outcome classification upstream is unchanged).
- :class:`BufferRing` — a preallocated, size-classed pool of receive
  buffers.  Fetchers lease a buffer per frame, decode views directly out
  of it, and either *release* it back to the ring (payload fully
  consumed, e.g. int8 dequantize materialized a fresh f32 array),
  *detach* it (decoded views escape to the caller; ownership transfers
  to the views and the refcount keeps the buffer alive), or *recycle*
  it onto one owning escaping object (detach semantics now, automatic
  return to the pool when the owner dies — the dense-frame path, where
  a plain detach would pin the ring's hit rate at zero).
- :func:`sendall_segments` — scatter-gather egress.  ``socket.sendmsg``
  over ``[header, payload, digest, obs]`` so headers are never
  concatenated onto multi-MB payloads, with partial-send completion and
  a per-segment ``sendall`` fallback where ``sendmsg`` is unavailable.

Ownership rule (enforced by tests/test_zerocopy.py): a memoryview of a
leased buffer must never outlive the lease unless the lease was
detached.  Releasing while views escape would let the ring hand the
same bytes to the next frame and corrupt a decoded vector in place.

The module also keeps the process-wide rx copy tally behind
``wire_snapshot()``'s ``copies_per_frame`` column: decoders report how
many payload-sized copies a frame's decode performed (0 for dense f32 /
top-k f32 views, 1 for an int8 dequantize or a bf16 upcast).
"""

from __future__ import annotations

import ctypes
import errno
import socket
import threading
import time
import weakref
from typing import List, Optional, Sequence, Union

Buffer = Union[bytearray, memoryview]

# Smallest size class: header-ish reads don't each get a 1 MiB buffer.
_MIN_CLASS = 4096
# Free buffers kept per size class; beyond this, released buffers are
# dropped and the allocator reclaims them.  Gossip is one frame per
# peer per round, so a handful per class covers hedged + prefetch legs.
_MAX_FREE_PER_CLASS = 4
# Lease views start 64-byte aligned (one cacheline): dense f32 payloads
# land at offset 0 of their lease, so the decoded vector view is dlpack-
# eligible and crosses to the device by pointer adoption instead of a
# staging copy (dpwa_tpu/device/handoff.py's ALIGN — the two constants
# are the same contract).  bytearray gives no alignment promise of its
# own (pymalloc is 8-byte, large mallocs 16), so each pooled buffer
# carries LEASE_ALIGN slack and the lease view starts at the first
# aligned byte.
LEASE_ALIGN = 64


def _aligned_offset(buf: bytearray) -> int:
    """Offset of the first LEASE_ALIGN-aligned byte of ``buf`` (stable
    for the buffer's lifetime — CPython never relocates a bytearray's
    storage unless it is resized, and pooled buffers never are)."""
    base = ctypes.addressof(ctypes.c_char.from_buffer(buf))
    return (-base) % LEASE_ALIGN


def recv_exact_into(
    sock: socket.socket,
    n: int,
    deadline: Optional[float] = None,
    per_byte_s: float = 0.0,
    progress: Optional[list] = None,
    out: Optional[Buffer] = None,
) -> memoryview:
    """Read exactly ``n`` bytes into ``out`` (allocated if ``None``).

    Returns a writable memoryview of the first ``n`` bytes of ``out``.
    Deadline / per-byte / progress semantics are the gossip fetch
    contract (see the old ``_recv_exact`` docstring, now in
    tcp.py:_recv_exact which wraps this): ``deadline`` is a
    ``time.monotonic`` instant the WHOLE read must finish by,
    ``per_byte_s`` grows the budget with bytes actually received, and
    ``progress`` (a single-cell ``[int]``) survives the timeout this
    function raises so the caller can tell ``slow`` from ``timeout``.
    """
    if out is None:
        out = bytearray(n)
    view = memoryview(out)[:n]
    filled = 0
    while filled < n:
        if deadline is not None:
            remaining = deadline + filled * per_byte_s - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("cumulative fetch deadline exceeded")
            sock.settimeout(remaining)
        cap = min(1 << 20, n - filled)
        got = sock.recv_into(view[filled : filled + cap])
        if not got:
            raise ConnectionError("peer closed mid-message")
        filled += got
        if progress is not None:
            progress[0] += got
    return view


class Lease:
    """One checked-out ring buffer.  ``view`` is sized to the request;
    call :meth:`release` when every decoded view of it is dead, or
    :meth:`detach` when views escape to the caller."""

    __slots__ = ("_ring", "_buf", "view", "_done")

    def __init__(self, ring: "BufferRing", buf: bytearray, n: int) -> None:
        self._ring = ring
        self._buf = buf
        off = _aligned_offset(buf)
        self.view = memoryview(buf)[off:off + n]
        self._done = False

    def release(self) -> None:
        """Return the buffer to the ring for reuse.  Idempotent."""
        if self._done:
            return
        self._done = True
        self.view.release()
        self._ring._put(self._buf)

    def detach(self) -> None:
        """Transfer ownership to the escaping views: the buffer is never
        pooled again; the views' refcounts keep it alive.  Idempotent."""
        if self._done:
            return
        self._done = True
        self._ring._forget(self._buf)

    def recycle(self, owner: object) -> None:
        """Transfer ownership to ``owner`` AND return the buffer to the
        ring once ``owner`` is garbage-collected (``weakref.finalize``).

        The pooled alternative to :meth:`detach` for the dense frame
        path, where every escaping view hangs off one ndarray's
        ``.base`` chain: a plain detach means every small gossip frame
        costs a fresh allocation (the ring's hit rate pins at zero —
        the small-class waste the copy leg's KiB cells expose), while
        recycle makes the next lease of that class a pool hit.

        ONLY safe when ``owner`` transitively owns every escaping view
        of the buffer (an ``np.frombuffer`` result does: derived slices
        keep it alive through ``.base``).  Payload objects whose member
        views can be extracted and outlive them (top-k / shard frames)
        must keep using :meth:`detach` — pooling while a stray view
        aliases the bytes would hand the next frame the same storage
        and corrupt a decoded vector in place.  Idempotent."""
        if self._done:
            return
        self._done = True
        # The lease view is NOT released here: the owner's views export
        # it (frombuffer holds a buffer export; releasing would raise
        # BufferError).  It dies with the owner.
        ring, buf = self._ring, self._buf
        # The buffer stays accounted as leased until the owner dies;
        # _recycle then both decrements and (capacity permitting) pools.
        weakref.finalize(owner, ring._recycle, buf)


class BufferRing:
    """Size-classed pool of receive buffers (powers of two ≥ 4 KiB).

    ``lease(n)`` hands back a :class:`Lease` whose ``view`` is exactly
    ``n`` bytes of a pooled (or freshly allocated) buffer, starting on
    a ``LEASE_ALIGN`` boundary (the device-handoff dlpack contract —
    each buffer carries the slack to guarantee it).  Stats feed
    the ``ring_occupancy`` health column: occupancy is the fraction of
    ring-managed bytes currently leased out — near zero when fetchers
    release promptly, climbing when decoded views pin buffers."""

    def __init__(
        self,
        min_class: int = _MIN_CLASS,
        max_free_per_class: int = _MAX_FREE_PER_CLASS,
    ) -> None:
        self._min_class = max(int(min_class), 16)
        self._max_free = max(int(max_free_per_class), 0)
        self._lock = threading.Lock()
        self._free: dict = {}  # class size -> [bytearray, ...]
        self._leased_bytes = 0
        self._hits = 0
        self._misses = 0
        self._recycled = 0

    def _class_for(self, n: int) -> int:
        size = self._min_class
        while size < n:
            size <<= 1
        return size

    def lease(self, n: int) -> Lease:
        if n < 0:
            raise ValueError(f"cannot lease {n} bytes")
        size = self._class_for(max(n, 1))
        with self._lock:
            pool = self._free.get(size)
            if pool:
                buf = pool.pop()
                self._hits += 1
            else:
                buf = None
                self._misses += 1
            self._leased_bytes += size
        if buf is None:
            # LEASE_ALIGN slack so the lease view can start on the first
            # aligned byte whatever base address the allocator hands out.
            buf = bytearray(size + LEASE_ALIGN)
        return Lease(self, buf, n)

    def _put(self, buf: bytearray) -> None:
        size = len(buf) - LEASE_ALIGN
        with self._lock:
            self._leased_bytes -= size
            pool = self._free.setdefault(size, [])
            if len(pool) < self._max_free:
                pool.append(buf)

    def _forget(self, buf: bytearray) -> None:
        with self._lock:
            self._leased_bytes -= len(buf) - LEASE_ALIGN

    def _recycle(self, buf: bytearray) -> None:
        """Finalizer target for :meth:`Lease.recycle`: the recycled
        lease's owner died, so the buffer comes home to the pool."""
        with self._lock:
            self._recycled += 1
        self._put(buf)

    def stats(self) -> dict:
        with self._lock:
            pooled = sum(
                len(b) - LEASE_ALIGN
                for p in self._free.values()
                for b in p
            )
            leased = self._leased_bytes
            total = leased + pooled
            return {
                "leased_bytes": leased,
                "pooled_bytes": pooled,
                "occupancy": (leased / total) if total else 0.0,
                "hits": self._hits,
                "misses": self._misses,
                "recycled": self._recycled,
            }


# Process-wide default ring + rx copy tally.  One ring per process is
# the right granularity: fetch legs, hedges, and prefetch threads all
# share it, and the health columns are per-process anyway.
_DEFAULT_RING = BufferRing()
_RX_LOCK = threading.Lock()
_RX_FRAMES = 0
_RX_COPIES = 0


def default_ring() -> BufferRing:
    return _DEFAULT_RING


def note_rx_frame(copies: int) -> None:
    """Record one decoded frame and how many payload-sized copies its
    decode performed (0 = view straight out of the receive buffer)."""
    global _RX_FRAMES, _RX_COPIES
    with _RX_LOCK:
        _RX_FRAMES += 1
        _RX_COPIES += max(int(copies), 0)


def rx_stats() -> dict:
    """Snapshot for ``wire_snapshot()``: mean payload copies per decoded
    frame plus the default ring's occupancy."""
    with _RX_LOCK:
        frames = _RX_FRAMES
        copies = _RX_COPIES
    ring = _DEFAULT_RING.stats()
    return {
        "frames": frames,
        "copies": copies,
        "copies_per_frame": (copies / frames) if frames else 0.0,
        "ring_occupancy": ring["occupancy"],
    }


def reset_rx_stats() -> None:
    """Test/bench hook: zero the process-wide tally."""
    global _RX_FRAMES, _RX_COPIES
    with _RX_LOCK:
        _RX_FRAMES = 0
        _RX_COPIES = 0


# errnos some platforms use to refuse sendmsg on connected TCP sockets.
_SENDMSG_UNSUPPORTED = {
    getattr(errno, "ENOTSUP", None),
    getattr(errno, "EOPNOTSUPP", None),
    getattr(errno, "ENOSYS", None),
} - {None}


def sendall_segments(
    sock: socket.socket, segments: Sequence[Buffer]
) -> None:
    """Send every segment, in order, without concatenating them.

    Uses ``socket.sendmsg`` (scatter-gather, one syscall for header +
    payload + trailers) and completes partial sends by advancing
    memoryviews — fully-sent segments are dropped, a partially-sent
    head is sliced, never copied.  Where ``sendmsg`` is missing or the
    platform refuses it, falls back to per-segment ``sendall``, which
    preserves byte order and blocking/timeout semantics exactly.
    """
    segs: List[memoryview] = [
        memoryview(s).cast("B") for s in segments if len(s)
    ]
    if not segs:
        return
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None:
        for seg in segs:
            sock.sendall(seg)
        return
    while segs:
        try:
            sent = sendmsg(segs)
        except OSError as exc:
            if exc.errno in _SENDMSG_UNSUPPORTED:
                for seg in segs:
                    sock.sendall(seg)
                return
            raise
        while sent > 0 and segs:
            head = segs[0]
            if sent >= len(head):
                sent -= len(head)
                segs.pop(0)
            else:
                segs[0] = head[sent:]
                sent = 0
