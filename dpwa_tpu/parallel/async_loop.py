"""Barrier-free async gossip rounds (docs/async.md).

:class:`AsyncExchangeEngine` decouples **publish** from **merge**: the
lock-step round loop (publish → fetch → guard → trust → merge, one
partner per round, the round gated on that partner's stream) becomes a
free-running loop in which partner frames stream on background slots,
land in a bounded per-peer pending queue, and merge **whenever ready**.
A trickling straggler's fetch simply stays in flight across rounds while
every healthy peer keeps exchanging at full rate — the round wall never
tracks the slowest peer.

The price of barrier-freedom is staleness, and the engine makes it a
first-class, bounded quantity:

- **Staleness damping** — a frame whose publish clock lags the local
  clock by ``L`` merges at ``alpha * staleness_damping**L``, composing
  multiplicatively with the trust damping already applied through
  ``interpolation._clamped`` (the trust scale rides the transport's
  ``_pending_trust_scale`` hook; the staleness factor scales the final
  alpha — same channel, one multiplication).
- **Bounded-staleness drop** — ``lag > max_staleness`` drops the frame
  as the soft ``stale`` outcome (weight like ``slow``): lag is load
  evidence, so it degrades the peer in the scoreboard but can never
  quarantine it.
- **Deduplication** — the transport-level publish-clock guard
  (``TcpTransport._async_guard``, armed by this engine) rejects a
  publish clock that already merged, so a frame delivered both through
  a prefetch slot and the async queue can never merge twice.

Determinism contract (dpwalint enforces the ``det-*`` rules on this
module): every scheduling decision — queue admission, the drop rule,
drain order, fold grouping — is a pure function of publish clocks and
the registered ``async_drain_draw`` threefry stream (tag 33).  Wall
time feeds telemetry spans ONLY, and always through the injected
``now`` callable (the flowctl ``vclock`` seam), so a soak driven under a
:class:`~dpwa_tpu.flowctl.vclock.VirtualClock` with a scripted arrival
plan is bit-identical across reruns, spans included.

Composition with the existing planes:

- dense frames pending together fold through the device merge engine's
  batched ``fold`` dispatch (one kernel for the run — bit-identical to
  sequential merges, the ``lax.scan`` contract);
- shard frames merge only their ``[lo, hi)`` slice (the transport's
  ``_pending_shard`` double-buffer), bit-exact per slice;
- every frame still runs the full consume leg — decode, zero-energy
  guard, trust screen, scoreboard, estimator — charged to the consuming
  round's step, exactly like the prefetch pipeline.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dpwa_tpu.flowctl.vclock import monotonic_now
from dpwa_tpu.health.detector import Outcome
from dpwa_tpu.parallel.schedules import async_drain_draw

__all__ = ["AsyncExchangeEngine", "register_metrics"]

# Staleness histogram: one bucket per lag 0..max_staleness plus one
# overflow bucket counting bounded-staleness drops.
_OVERFLOW = "overflow"


class AsyncExchangeEngine:
    """Barrier-free round loop over a :class:`TcpTransport`.

    One engine wraps one transport.  The training thread drives
    :meth:`exchange` (host replica) or :meth:`exchange_on_device`
    (device-resident replica) once per local step; fetch slots run on
    daemon threads and never gate a round.

    ``now`` is the telemetry time source.  Default resolution order:
    an explicit argument, then the transport's flowctl estimator's
    ``now`` seam (so one VirtualClock injection governs the whole
    flowctl + async stack), then the production monotonic clock.
    """

    def __init__(self, transport, now: Optional[Callable[[], float]] = None):
        self.t = transport
        cfg = transport.config.protocol.async_rounds
        self.cfg = cfg
        self.me = transport.me
        self.seed = transport.schedule.seed
        if now is None:
            est = getattr(transport, "_estimator", None)
            now = est.now if est is not None else monotonic_now
        self.now: Callable[[], float] = now
        # Arm the transport's publish-clock dedup guard: from here on a
        # publish clock merges at most once per peer, whichever path
        # (prefetch slot, async queue, plain fetch) delivered it.
        transport._async_guard = {}
        transport.async_engine = self
        # Pay the drain-draw's first-call jit compile now, off the round
        # clock (the warm_control_draws rationale, scoped to one draw).
        float(async_drain_draw(self.seed, 0, self.me))
        # -- cross-thread state (slot threads append, training drains) --
        self._lock = threading.Lock()
        # (peer, raw9, launch_step, t_launch, t_land) in arrival order.
        self._arrivals: List[tuple] = []
        self._inflight: Dict[int, dict] = {}
        # -- training-thread state --------------------------------------
        # peer -> deque of (clock, raw9, wire_span_s, t_land) admitted
        # frames, newest clocks kept (queue_depth admission).
        self._pending: Dict[int, deque] = {}
        self._round_stale: List[int] = []
        # -- tallies (under _lock: snapshot runs on healthz threads) ----
        self._rounds = 0
        self._merges = 0
        self._stale_drops = 0
        self._dup_drops = 0
        self._shed = 0
        self._fold_dispatches = 0
        self._fold_frames = 0
        self._pending_wait_s = 0.0
        self._hist: Dict[object, int] = {
            **{lag: 0 for lag in range(int(cfg.max_staleness) + 1)},
            _OVERFLOW: 0,
        }
        self._peer: Dict[int, dict] = {}
        if getattr(transport, "metrics_registry", None) is not None:
            register_metrics(transport.metrics_registry, self)

    # ------------------------------------------------------------------
    # Frame intake
    # ------------------------------------------------------------------

    def _peer_stats(self, peer: int) -> dict:
        s = self._peer.get(peer)
        if s is None:
            s = self._peer[peer] = {
                "merges": 0, "stale": 0, "shed": 0, "last_lag": None,
                "lag_sum": 0, "fails": 0,
            }
        return s

    def _launch(self, peer: int, step: int) -> None:
        """Start a background wire fetch to ``peer`` if none is already
        in flight.  The slot thread only moves bytes (the transport's
        wire/consume split); judgement happens at drain time on the
        training thread."""
        with self._lock:
            if peer in self._inflight:
                return
            slot = {"peer": peer, "step": int(step), "t0": self.now()}
            self._inflight[peer] = slot

        def _run():
            raw = self.t._wire_fetch(peer, step=step)
            t1 = self.now()
            with self._lock:
                self._arrivals.append((peer, raw, step, slot["t0"], t1))
                self._inflight.pop(peer, None)

        th = threading.Thread(
            target=_run, daemon=True,
            name=f"dpwa-async:{self.t.port}",
        )
        slot["thread"] = th
        th.start()

    def offer(self, peer: int, raw: tuple, step: int = 0,
              span_s: float = 0.0) -> None:
        """Hand the engine an already-fetched raw 9-tuple.

        The scripted-arrival entry point: soak tests and harnesses
        deliver frames here under a VirtualClock instead of running live
        fetch slots, which is what makes the full soak bit-identical
        across reruns."""
        t1 = self.now()
        with self._lock:
            self._arrivals.append((peer, raw, int(step), t1 - span_s, t1))

    def _collect(self, step: int) -> List[tuple]:
        """Move completed arrivals into the pending queues.

        Admission is a pure function of publish clocks: failed fetches
        bypass the queue (returned for immediate outcome accounting), a
        clock at or below the peer's last-merged clock is a duplicate
        (counted, recorded ``stale`` at drain), and a full queue sheds
        its OLDEST clock — the frame that would merge at the smallest
        weight anyway.  Returns the list of failure/duplicate arrivals
        to account this round."""
        with self._lock:
            arrivals, self._arrivals = self._arrivals, []
        charge: List[tuple] = []
        guard = self.t._async_guard or {}
        for peer, raw, launch_step, t0, t1 in arrivals:
            got = raw[1]
            if got is None:
                charge.append((peer, raw, t1))
                continue
            clock = float(got[1])
            merged_ck = guard.get(int(raw[0]))
            if merged_ck is not None and clock <= merged_ck:
                with self._lock:
                    self._dup_drops += 1
                charge.append((peer, raw, t1))
                continue
            dq = self._pending.get(peer)
            if dq is None:
                dq = self._pending[peer] = deque()
            dq.append((clock, raw, max(t1 - t0, 0.0), t1))
            if len(dq) > int(self.cfg.queue_depth):
                # Shed the smallest publish clock in the queue.
                oldest = min(range(len(dq)), key=lambda i: (dq[i][0], i))
                del dq[oldest]
                with self._lock:
                    self._shed += 1
                self._peer_stats(peer)["shed"] += 1
        return charge

    # ------------------------------------------------------------------
    # Drain + merge
    # ------------------------------------------------------------------

    def _drain_order(self, clock: float, step: int) -> List[tuple]:
        """Flatten the pending queues into the deterministic drain
        order: lag-ascending (freshest merges first, so the best
        information lands before maximally-damped stragglers), with
        equal-lag ties rotated by the ``async_drain_draw`` stream and
        finally broken by peer index.  Pure function of publish clocks
        and the registered threefry tag — two reruns with the same
        pending sets drain identically."""
        cands: List[tuple] = []
        for peer in sorted(self._pending):
            dq = self._pending[peer]
            while dq:
                ck, raw, span, t_land = dq.popleft()
                lag = max(int(clock) - int(ck), 0)
                draw = async_drain_draw(self.seed, step, peer)
                cands.append((lag, draw, peer, ck, raw, span, t_land))
        cands.sort(key=lambda c: (c[0], c[1], c[2], -c[3]))
        return cands

    def _charge_failures(self, charge: List[tuple], step: int) -> None:
        """Record failed/duplicate arrivals against the consuming round:
        failures run the ordinary consume leg (scoreboard + estimator
        accounting); duplicates record the soft ``stale`` outcome
        directly (the dedup guard would classify them anyway, but a
        second consume would re-run the guard/trust screens on bytes
        that already merged)."""
        sb = self.t.scoreboard
        for peer, raw, _t1 in charge:
            if raw[1] is None:
                self.t._consume_fetch(raw, step)
                self._peer_stats(int(raw[0]))["fails"] += 1
            elif sb is not None:
                sb.record(
                    int(raw[0]), Outcome.STALE,
                    latency_s=float(raw[3]), nbytes=int(raw[4]),
                    round=step,
                )

    def _drop_stale(self, peer: int, raw: tuple, lag: int,
                    step: int) -> None:
        """The bounded-staleness drop rule: record the soft ``stale``
        outcome (degrade, never quarantine) and count the overflow
        bucket; the frame's bytes are never screened or merged."""
        with self._lock:
            self._stale_drops += 1
            self._hist[_OVERFLOW] += 1
        st = self._peer_stats(peer)
        st["stale"] += 1
        st["last_lag"] = int(lag)
        self._round_stale.append(peer)
        if self.t.scoreboard is not None:
            self.t.scoreboard.record(
                peer, Outcome.STALE,
                latency_s=float(raw[3]), nbytes=int(raw[4]), round=step,
            )

    def _consume(self, raw: tuple, clock: float, loss: float, step: int,
                 lag: int):
        """Run the transport's consume leg on one pending frame and
        weigh it, composing the staleness damping into alpha.  Returns
        ``(remote_vec, damped_alpha)`` or ``None`` when the frame failed
        a screen (guard/trust/dedup — outcome already recorded)."""
        got = self.t._consume_fetch(raw, step)
        if got is None:
            return None
        remote_vec, alpha = self.t._weigh_remote(got, clock, loss)
        damped = float(alpha) * float(self.cfg.staleness_damping) ** int(lag)
        return remote_vec, damped

    def _note_merge(self, peer: int, lag: int, t_land: float) -> None:
        wait = max(self.now() - t_land, 0.0)
        with self._lock:
            self._merges += 1
            self._hist[int(lag)] = self._hist.get(int(lag), 0) + 1
            self._pending_wait_s += wait
        st = self._peer_stats(peer)
        st["merges"] += 1
        st["last_lag"] = int(lag)
        st["lag_sum"] += int(lag)

    def exchange(
        self, vec: np.ndarray, clock: float, loss: float, step: int
    ) -> Tuple[np.ndarray, List[Tuple[int, float, int]]]:
        """One barrier-free round on a HOST replica.

        Publish, collect completed arrivals, launch this step's schedule
        partner fetch (if idle), then merge every pending frame that
        survives the drop rule — in the deterministic drain order, each
        through the full consume leg, dense or sparse or shard alike
        (shard frames lerp only their slice via ``_merge_remote``).
        Never blocks on an in-flight stream.

        Returns ``(merged_vec, merges)`` with ``merges`` the drain-
        ordered list of ``(peer, damped_alpha, lag)`` actually applied.
        """
        try:
            self.t.publish(vec, clock, loss)
            with self._lock:
                self._rounds += 1
            charge = self._collect(clock)
            sched, partner, remapped = self.t._resolve_partner(step)
            self.t.last_round = {
                "step": step, "sched_partner": sched, "partner": partner,
                "remapped": remapped, "outcome": None,
            }
            if partner != self.me and self.t.schedule.participates(
                step, self.me
            ):
                self._launch(partner, step)
            self._charge_failures(charge, step)
            merges: List[Tuple[int, float, int]] = []
            out = np.asarray(vec, dtype=np.float32)
            for lag, _draw, peer, _ck, raw, _span, t_land in (
                self._drain_order(clock, step)
            ):
                if lag > int(self.cfg.max_staleness):
                    self._drop_stale(peer, raw, lag, step)
                    continue
                res = self._consume(raw, clock, loss, step, lag)
                if peer == partner:
                    self.t.last_round["outcome"] = (
                        self.t.last_fetch.get("outcome")
                    )
                if res is None:
                    self._peer_stats(peer)["fails"] += 1
                    continue
                remote_vec, damped = res
                out = self.t._merge_remote(out, remote_vec, damped)
                self._note_merge(peer, lag, t_land)
                merges.append((peer, damped, lag))
            return out, merges
        finally:
            self.t._membership_end_round(step)

    def exchange_on_device(
        self, vec_dev, clock: float, loss: float, step: int
    ):
        """One barrier-free round on a DEVICE-RESIDENT replica.

        Same intake/drop/drain discipline as :meth:`exchange`; accepted
        frames become ``(kind, payload, peer, alpha)`` device frames and
        — with ``async_rounds.fold`` on — consecutive dense frames in
        the drain order batch through the merge engine's single
        ``fold`` dispatch (bit-identical to sequential merges).  Sparse
        frames stay sparse across the seam (``_sparse_consume``), so
        shard slices splice in-kernel with no host densify.

        Returns ``(merged_device_vec, merges)``."""
        from dpwa_tpu.device import DeviceReplica, default_engine

        eng = default_engine()
        t = self.t
        rep = t._dev_replica
        if rep is None or rep.dev is not vec_dev:
            rep = DeviceReplica(vec_dev)
            t._dev_replica = rep
        try:
            t.publish(rep.host(), clock, loss)
            with self._lock:
                self._rounds += 1
            charge = self._collect(clock)
            sched, partner, remapped = t._resolve_partner(step)
            t.last_round = {
                "step": step, "sched_partner": sched, "partner": partner,
                "remapped": remapped, "outcome": None,
            }
            if partner != self.me and t.schedule.participates(
                step, self.me
            ):
                self._launch(partner, step)
            self._charge_failures(charge, step)
            frames: List[tuple] = []
            merges: List[Tuple[int, float, int]] = []
            t._sparse_consume = True
            try:
                for lag, _draw, peer, _ck, raw, _span, t_land in (
                    self._drain_order(clock, step)
                ):
                    if lag > int(self.cfg.max_staleness):
                        self._drop_stale(peer, raw, lag, step)
                        continue
                    res = self._consume(raw, clock, loss, step, lag)
                    if res is None:
                        self._peer_stats(peer)["fails"] += 1
                        continue
                    remote_vec, damped = res
                    frames.append(
                        t._classify_device_frame(remote_vec, peer, damped)
                    )
                    self._note_merge(peer, lag, t_land)
                    merges.append((peer, damped, lag))
            finally:
                t._sparse_consume = False
            merged = t._apply_device_frames(
                eng, rep.dev, frames, fold=bool(self.cfg.fold)
            )
            if frames and self.cfg.fold:
                # Fold accounting: runs of >=2 consecutive dense frames
                # went through a single batched dispatch.
                runs: List[int] = []
                n = 0
                for f in frames:
                    if f[0] == "dense":
                        n += 1
                    elif n:
                        runs.append(n)
                        n = 0
                if n:
                    runs.append(n)
                with self._lock:
                    self._fold_dispatches += sum(
                        1 for r in runs if r >= 2
                    )
                    self._fold_frames += sum(r for r in runs if r >= 2)
            eng.note_round()
            if merged is not rep.dev:
                rep.swap(merged)
            return merged, merges
        finally:
            t._membership_end_round(step)

    # ------------------------------------------------------------------
    # Plane integration
    # ------------------------------------------------------------------

    def pop_round_stale(self) -> List[int]:
        """Drain the peers dropped stale this round (incident plane)."""
        out, self._round_stale = self._round_stale, []
        return out

    def pending_depth(self, peer: int) -> int:
        dq = self._pending.get(peer)
        return len(dq) if dq is not None else 0

    def join_inflight(self, timeout_s: float = 5.0) -> None:
        """Block until in-flight fetch slots land (tests/bench teardown
        — never called on the round path)."""
        with self._lock:
            slots = [self._inflight[p] for p in sorted(self._inflight)]
        for slot in slots:
            th = slot.get("thread")
            if th is not None:
                th.join(timeout_s)

    def snapshot(self) -> dict:
        """JSON-ready async-plane state: the ``async`` sub-document in
        ``health_snapshot`` (schema ``_HEALTH_GROUPS["async"]``)."""
        with self._lock:
            hist = [
                self._hist.get(lag, 0)
                for lag in range(int(self.cfg.max_staleness) + 1)
            ] + [self._hist.get(_OVERFLOW, 0)]
            out = {
                "rounds": self._rounds,
                "merges": self._merges,
                "stale_drops": self._stale_drops,
                "dup_drops": self._dup_drops,
                "shed": self._shed,
                "fold_dispatches": self._fold_dispatches,
                "fold_frames": self._fold_frames,
                "pending_wait_s": round(self._pending_wait_s, 6),
                "max_staleness": int(self.cfg.max_staleness),
                "staleness_damping": float(self.cfg.staleness_damping),
                "queue_depth": int(self.cfg.queue_depth),
                "staleness_hist": hist,
                "inflight": sorted(self._inflight),
            }
        peers = {}
        for p in sorted(self._peer):
            st = self._peer[p]
            n = st["merges"]
            peers[p] = {
                "merges": n,
                "stale": st["stale"],
                "shed": st["shed"],
                "fails": st["fails"],
                "pending": self.pending_depth(p),
                "last_lag": st["last_lag"],
                "mean_lag": round(st["lag_sum"] / n, 3) if n else None,
            }
        out["peers"] = peers
        return out


def register_metrics(registry, engine: "AsyncExchangeEngine") -> None:
    """Expose the async round plane on a MetricsRegistry
    (``dpwa_async_*`` families, the flowctl estimator pattern)."""
    from dpwa_tpu.obs.prometheus import Family

    def collect():
        snap = engine.snapshot()
        merges = Family(
            "dpwa_async_merges_total", "counter",
            "Frames merged by the barrier-free async round loop",
        )
        stale = Family(
            "dpwa_async_stale_drops_total", "counter",
            "Frames dropped by the bounded-staleness rule",
        )
        lag = Family(
            "dpwa_async_peer_last_lag", "gauge",
            "Publish-clock lag of the last frame seen per peer",
        )
        pend = Family(
            "dpwa_async_pending_frames", "gauge",
            "Frames currently queued per peer",
        )
        hist = Family(
            "dpwa_async_staleness_merges", "counter",
            "Merged frames by publish-clock lag (overflow = dropped)",
        )
        for p, info in sorted((snap.get("peers") or {}).items()):
            labels = {"peer": p}
            merges.sample(info.get("merges"), labels)
            stale.sample(info.get("stale"), labels)
            if info.get("last_lag") is not None:
                lag.sample(info.get("last_lag"), labels)
            pend.sample(info.get("pending"), labels)
        buckets = snap.get("staleness_hist") or []
        for i, n in enumerate(buckets):
            label = str(i) if i < len(buckets) - 1 else "overflow"
            hist.sample(n, {"lag": label})
        return [
            merges, stale, lag, pend, hist,
            Family(
                "dpwa_async_rounds_total", "counter",
                "Barrier-free rounds driven",
            ).sample(snap.get("rounds")),
            Family(
                "dpwa_async_fold_frames_total", "counter",
                "Dense frames batched through fold dispatches",
            ).sample(snap.get("fold_frames")),
            Family(
                "dpwa_async_pending_wait_seconds_total", "counter",
                "Cumulative arrival-to-merge wait across merged frames",
            ).sample(snap.get("pending_wait_s")),
        ]

    registry.register(collect)
