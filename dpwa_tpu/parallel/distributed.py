"""Multi-host (DCN) support: ``jax.distributed`` + hierarchical gossip.

The reference scales across machines with one TCP process per node
(SURVEY.md §3.4); the TPU-native equivalent is a multi-host JAX program:
every host runs THIS same SPMD code, ``jax.distributed`` stitches their
chips into one global device list, and the ``peers`` mesh axis spans all of
them.  ``ppermute`` pairs that stay inside a host ride ICI; pairs that cross
hosts ride DCN — which is why config 4 (BASELINE.json:10) uses the
hierarchical schedule: dense intra-host slots, sparse inter-host slots.

``mesh_utils.create_device_mesh`` keeps each host's chips contiguous along
the axis, so ``group_size = chips-per-host`` aligns the schedule's groups
with the physical ICI domains.

Single-host usage is unchanged — these helpers are no-ops there (the
framework runs identically on an emulated CPU mesh; see tests)."""

from __future__ import annotations

import os
from typing import Optional

import jax

from dpwa_tpu.config import DpwaConfig
from dpwa_tpu.parallel.ici import IciTransport
from dpwa_tpu.parallel.mesh import PEER_AXIS, make_mesh


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up ``jax.distributed``.

    With no arguments, relies on the environment (TPU pod metadata or
    ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``),
    which is how TPU VMs launch.  Call once per host before any backend
    use."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    jax.distributed.initialize(**kwargs)


def hierarchical_config_for_hosts(
    config: DpwaConfig, chips_per_host: Optional[int] = None
) -> DpwaConfig:
    """Rewrite ``config`` so the hierarchical schedule's groups equal the
    physical hosts (intra-group = ICI, inter-group = DCN)."""
    import dataclasses

    chips = chips_per_host or jax.local_device_count()
    if config.n_peers % chips != 0:
        raise ValueError(
            f"{config.n_peers} peers not divisible by {chips} chips/host"
        )
    proto = dataclasses.replace(
        config.protocol, schedule="hierarchical", group_size=chips
    )
    return dataclasses.replace(config, protocol=proto)


class DcnHierarchicalTransport(IciTransport):
    """Gossip transport for multi-host meshes (config 4).

    Identical execution path to :class:`IciTransport` — the hierarchy lives
    in the *schedule*: intra-group pairings permute within a host's
    contiguous chip block (ICI), the sparse inter-group slot permutes
    across blocks (DCN).  This class only enforces that alignment."""

    def __init__(self, config: DpwaConfig, mesh=None, axis_name: str = PEER_AXIS):
        if config.protocol.schedule != "hierarchical":
            config = hierarchical_config_for_hosts(config)
        super().__init__(config, mesh=mesh, axis_name=axis_name)
