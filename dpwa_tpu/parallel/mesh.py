"""Device-mesh construction from the reference-style YAML config.

The contract (BASELINE.json:5, SURVEY.md §2 "Distributed communication
backend"): the YAML ``nodes:`` list that names TCP peers in the reference is
reinterpreted as a **device-mesh axis of the same length**.  One config file
drives either transport; the ICI transport ignores per-node host/port.

Multi-host: initialize ``jax.distributed`` before calling :func:`make_mesh`
and the global device list spans hosts; ``mesh_utils.create_device_mesh``
orders devices so that contiguous index ranges are intra-host — which is what
makes the hierarchical schedule's intra-group slots ride ICI and only the
inter-group slots cross DCN (SURVEY.md §5 "Distributed communication
backend").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dpwa_tpu.config import DpwaConfig

PEER_AXIS = "peers"


def make_mesh(
    config: DpwaConfig,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = PEER_AXIS,
) -> Mesh:
    """A 1-D mesh whose axis length equals ``len(config.nodes)``."""
    n = config.n_peers
    if devices is None:
        if len(jax.devices()) >= n:
            devices = mesh_utils.create_device_mesh(
                (n,), devices=jax.devices()[:n]
            )
        else:
            raise RuntimeError(
                f"config names {n} peers but only {len(jax.devices())} JAX "
                f"devices are visible; set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n} for "
                f"CPU emulation or use the TCP transport"
            )
    else:
        devices = np.asarray(devices)
    return Mesh(np.asarray(devices).reshape(n), (axis_name,))


def peer_sharding(mesh: Mesh, axis_name: str = PEER_AXIS) -> NamedSharding:
    """Sharding that splits a leading peer-stacked axis across the mesh."""
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
