"""TCP gossip transport — the reference-equivalent CPU path.

Reproduces the reference's transport semantics (SURVEY.md §2 "TCP transport",
§3.2/§3.3 call stacks; reference file ``dpwa/conn.py`` — mount empty,
reconstructed): every worker process runs an **Rx thread** that serves the
node's most recently *published* flattened parameter vector (plus clock/loss
metadata) to any peer that connects; the training thread, once per step,
publishes its own vector, picks a partner, connects, fetches the partner's
blob with a timeout, and merges on the CPU.  A fetch that times out is simply
skipped — training continues (the reference's implicit elasticity,
SURVEY.md §5 "Failure detection").

Differences from the reference, on purpose:

- **No pickle.**  The wire format is a fixed ``struct`` header + raw
  little-endian float bytes — deserializing untrusted peers with pickle is an
  RCE; a framed binary format is also faster.
- **Deterministic rendezvous.**  Peer selection delegates to the same
  :mod:`~dpwa_tpu.parallel.schedules` pool the ICI transport compiles in, and
  participation uses the identical threefry draw — so with a lock-step driver
  the TCP and ICI paths produce bit-comparable merges (SURVEY.md §4 parity).
  Set ``schedule: random`` + ``fetch_probability < 1`` and run free-running
  processes to recover the reference's fully asynchronous behavior.

This path exists for capability parity (true multi-process elasticity on
non-TPU hosts) and as the baseline the ICI path is benchmarked against
(BASELINE.json:5 — ≥50× averaging throughput target).
"""

from __future__ import annotations

import socket
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dpwa_tpu import native
from dpwa_tpu.config import (
    DEFAULT_MIN_WIRE_MB_PER_S,
    DpwaConfig,
    FlowctlConfig,
)
# flowctl imports config + detector only — no cycle with this module.
from dpwa_tpu.flowctl import AdmissionController, DeadlineEstimator
# detector/scoreboard import config + schedules only — no cycle; chaos
# (which imports THIS module) is loaded lazily inside TcpTransport.
from dpwa_tpu.health.detector import Outcome
from dpwa_tpu.health.scoreboard import PeerState, Scoreboard
from dpwa_tpu.interpolation import PeerMeta, make_interpolation
from dpwa_tpu.parallel.schedules import Schedule, build_schedule

# Every magic, struct layout, payload code, and size clamp on the wire
# comes from the protocol_constants registry (with its back-compat
# ledger); dpwalint's wire-protocol checker rejects inline literals.
# The old underscored names are kept as module-level aliases because
# chaos/recovery/test code imports them from here.
from dpwa_tpu.parallel import protocol_constants as _pc
# Zero-copy data movement: the shared recv_into loop, the receive-buffer
# ring every fetch leases from, and scatter-gather sends
# (docs/transport.md "The zero-copy landing zone").
from dpwa_tpu.parallel import ingest as _ingest

# Gossip blob wire: request is the 5-byte magic; response is
# BLOB_HDR (magic version dtype clock loss nbytes) + nbytes of payload.
_REQ = _pc.BLOB_REQ
_MAGIC = _pc.BLOB_MAGIC
_HDR = _pc.BLOB_HDR
_DTYPES = {
    _pc.PAYLOAD_F32: np.dtype("<f4"),
    _pc.PAYLOAD_F64: np.dtype("<f8"),
    _pc.PAYLOAD_U16: np.dtype("<u2"),
}
try:  # bf16 wire code (protocol.wire_dtype: bf16) — ml_dtypes ships w/ jax
    import ml_dtypes

    _DTYPES[_pc.PAYLOAD_BF16] = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes is a jax dependency
    ml_dtypes = None
_DTYPE_CODES = {v: k for k, v in _DTYPES.items()}
# Codec payloads (int8-chunked, top-k delta) are NOT flat numpy dtypes —
# see the notes on PAYLOAD_INT8_CHUNKED / PAYLOAD_TOPK_DELTA in
# protocol_constants.py for their body layouts and decode ownership.
_INT8_CHUNKED = _pc.PAYLOAD_INT8_CHUNKED
_TOPK_DELTA = _pc.PAYLOAD_TOPK_DELTA
_SHARD = _pc.PAYLOAD_SHARD

# Outcomes the self-tuning wire counts as wire-bound evidence
# regardless of measured spans: the link (or the peer behind it) could
# not deliver a timely frame, which is exactly what escalating
# compression relieves.  Hard-failure outcomes (refused/corrupt/
# poisoned/untrusted) stay OUT — fewer bytes do not fix a dead or
# byzantine peer, and the scoreboard owns those.
_TUNE_SOFT_OUTCOMES = frozenset(
    (Outcome.BUSY, Outcome.SLOW, Outcome.STALE, Outcome.TIMEOUT)
)
_PAYLOAD_CODES = _pc.CODEC_PAYLOAD_CODES
_MAX_BLOB = _pc.MAX_BLOB_BYTES

# Probe-before-commit bound for the payload ring lease: advertisements
# above the threshold read a probe's worth of real bytes before the
# full-size buffer is allocated, so a peer that lies about nbytes and
# hangs up costs a 64 KiB lease, not a multi-GB upfront allocation
# (the old grow-by-chunk loop had the same received-bytes-proportional
# property; _MAX_BLOB alone is a 16 GiB bound).
_PROBE_THRESHOLD = 1 << 20
_PROBE_BYTES = 1 << 16

# STATE transfer wire (crash recovery, dpwa_tpu/recovery/): a restarted
# worker bootstraps a donor's full serialized train state over the same
# one-shot socket discipline as the gossip fetch — request, one framed
# response, close.  Layout + resumability notes: protocol_constants.py
# (STATE_HDR_FMT, BACK_COMPAT["state_one_chunk_per_connection"]).
_STATE_REQ = _pc.STATE_REQ
_STATE_REQ_BODY = _pc.STATE_REQ_BODY
_STATE_MAGIC = _pc.STATE_MAGIC
_STATE_HDR = _pc.STATE_HDR
_MAX_STATE_CHUNK = _pc.MAX_STATE_CHUNK_BYTES

# RELAY probe wire (epidemic membership, dpwa_tpu/membership/): before a
# node promotes a suspect to quarantined on its own evidence alone, it
# asks K drawn healthy peers to header-probe the suspect FOR it — an
# asymmetric fault (my link to the suspect is down, yours is not) then
# yields "alive" votes that avert a false quarantine.  The response's
# ``outcome`` byte indexes _RELAY_OUTCOMES — the relay's CLASSIFIED
# result of its own probe_header_classified against the target.
_RELAY_REQ = _pc.RELAY_REQ
_RELAY_BODY = _pc.RELAY_BODY
_RELAY_MAGIC = _pc.RELAY_MAGIC
_RELAY_HDR = _pc.RELAY_HDR
# The wire contract is the NAME tuple in protocol_constants; this maps
# each code onto the health-detector Outcome enum and must stay aligned
# (asserted below — drift would misclassify relay votes).
_RELAY_OUTCOMES = (
    Outcome.SUCCESS,
    Outcome.TIMEOUT,
    Outcome.REFUSED,
    Outcome.SHORT_READ,
    Outcome.CORRUPT,
    Outcome.BUSY,
)
assert tuple(_RELAY_OUTCOMES) == _pc.RELAY_OUTCOME_NAMES
_MAX_RELAY_TIMEOUT_MS = _pc.MAX_RELAY_TIMEOUT_MS

# BUSY shed frame (flowctl admission, dpwa_tpu/flowctl/): when the Rx
# server refuses work — connection cap, token bucket, in-flight-bytes
# ceiling — it answers this tiny frame instead of silently dropping.
# Why it is deliberately SHORTER than the blob header:
# BACK_COMPAT["busy_nack_short_frame"] in protocol_constants.py.
_BUSY_MAGIC = _pc.BUSY_MAGIC
_BUSY_HDR = _pc.BUSY_HDR


def _busy_frame(retry_hint_ms: int = 0) -> bytes:
    """The DPWB shed reply: explicit 'loaded, come back later'."""
    return _BUSY_HDR.pack(
        _BUSY_MAGIC, 1, min(max(int(retry_hint_ms), 0), 0xFFFF)
    )
# Default deadline floor for the payload read (bytes/s): the fetch
# budget grows at this rate per byte RECEIVED, so a healthy peer
# streaming a large replica is never killed by a fixed timeout_ms sized
# for the rendezvous (100 MB at 500 ms would otherwise fail FOREVER,
# silently disabling gossip), while a trickling peer — orders of
# magnitude below any real fabric — still gets dropped promptly.
# Derived from the config default (one source of truth); configurable
# per deployment via ``protocol.min_wire_mb_per_s`` (slow-WAN fabrics
# must lower it).
_MIN_WIRE_BANDWIDTH = DEFAULT_MIN_WIRE_MB_PER_S * 1e6


def _recv_exact(
    sock: socket.socket,
    n: int,
    deadline: Optional[float] = None,
    per_byte_s: float = 0.0,
    progress: Optional[list] = None,
    out: Optional[bytearray] = None,
) -> memoryview:
    """Read exactly ``n`` bytes (thin wrapper over
    :func:`dpwa_tpu.parallel.ingest.recv_exact_into` — the one buffered
    read loop both the gossip fetch and the state transfer share).

    With ``deadline`` (a ``time.monotonic`` instant) the WHOLE read must
    finish by that wall-clock point: the socket timeout is re-derived from
    the remaining budget before every ``recv``.  A plain ``settimeout``
    restarts on each successful recv, so a peer trickling one byte at a
    time could pin the caller indefinitely — precisely the slow peer the
    skip semantics exist for.

    ``per_byte_s`` grows the deadline with bytes ACTUALLY RECEIVED (not
    the advertised size): a healthy stream earns budget as it flows,
    while a peer that advertised a huge payload and then stalls is still
    dropped at the base deadline — trusting the advertisement up front
    would let a malicious 16 GiB header pin the fetch for minutes.

    ``progress`` (a single-cell ``[int]`` list) accumulates the bytes
    received across a SEQUENCE of reads, surviving the timeout this
    function raises — the caller's classifier uses it to tell a peer
    that streamed something and lapsed (``slow``) from one that never
    answered at all (``timeout``).

    ``out`` is an optional destination buffer (bytearray or writable
    memoryview, at least ``n`` bytes); the bytes land there via
    ``recv_into`` and the returned memoryview aliases it — the zero-copy
    ingest path (a fresh bytearray is allocated when omitted).  Returns
    a memoryview, which compares equal to ``bytes`` by content; callers
    needing an owning copy take ``bytes(view)`` explicitly."""
    return _ingest.recv_exact_into(sock, n, deadline, per_byte_s, progress, out)


def _frame_segments(
    vec: np.ndarray,
    clock: float,
    loss: float,
    code: Optional[int] = None,
    digest: Optional[bytes] = None,
    obs: Optional[bytes] = None,
) -> Tuple[bytes, ...]:
    """The wire frame as ordered segments ``(header, payload[, digest]
    [, obs])`` — the one definition of the wire format, shared by the
    Python and native Rx servers.  Serve paths send the tuple via
    scatter-gather (:func:`ingest.sendall_segments`) so the header is
    never concatenated onto a multi-MB payload; :func:`_frame` joins it
    for consumers that need one contiguous byte string.

    ``code`` overrides the dtype byte for structured payloads
    (``_INT8_CHUNKED``: ``vec`` is the already-encoded uint8 buffer).

    ``digest`` (a serialized membership digest) rides as an OPTIONAL
    trailing section AFTER the nbytes payload: the header's ``nbytes``
    still counts only the vector, so a pre-membership fetcher reads
    exactly header + payload and never sees the trailer, while a
    digest-aware fetcher attempts a tolerant trailing read — version-
    gated wire compatibility in both directions (docs/membership.md).

    ``obs`` (a serialized ``DPWT`` observability section: trace id +
    replica sketch, dpwa_tpu/obs/wire.py) rides the same way, AFTER the
    digest when both are present.  Ordering matters for back-compat:
    a digest-aware pre-obs fetcher reads the digest it wants, then its
    next read fails the DPWM magic check on the DPWT header and stops
    harmlessly; obs-aware fetchers dispatch trailers by magic
    (:func:`_read_trailers`) and handle every presence combination."""
    vec = np.ascontiguousarray(vec)
    if code is None:
        # Exact-dtype lookup first (covers bf16, whose custom numpy dtype
        # has no byte-order variants), then the byte-order-normalized
        # form, then an f32 fallback.
        code = _DTYPE_CODES.get(vec.dtype)
        if code is None:
            try:
                code = _DTYPE_CODES.get(
                    np.dtype(vec.dtype.newbyteorder("<"))
                )
            except (TypeError, ValueError):  # pragma: no cover
                code = None
        if code is None:
            vec = vec.astype("<f4")
            code = _DTYPE_CODES[np.dtype("<f4")]
    # The one deliberate copy on the publish path: the frame must
    # snapshot the replica — the training thread mutates ``vec`` right
    # after publish, and serving a live view would tear frames mid-send.
    data = vec.tobytes()  # dpwalint: ignore[zerocopy-tobytes] -- publish-time snapshot; serving a view of the live replica would tear frames
    header = _HDR.pack(_MAGIC, 1, code, float(clock), float(loss), len(data))
    if digest or obs:
        segs = [header, data]
        if digest:
            segs.append(digest)
        if obs:
            segs.append(obs)
        return tuple(segs)
    return (header, data)


def _frame(
    vec: np.ndarray,
    clock: float,
    loss: float,
    code: Optional[int] = None,
    digest: Optional[bytes] = None,
    obs: Optional[bytes] = None,
) -> bytes:
    """:func:`_frame_segments` joined into one contiguous byte string —
    for the native server's ``publish_framed``, the chaos mutators, and
    golden-frame tests."""
    return b"".join(_frame_segments(vec, clock, loss, code, digest, obs))


class PeerServer:
    """The Rx thread: serves this node's latest published blob.

    Mirrors the reference's always-on listener (SURVEY.md §3.3): the training
    thread and the Rx thread share only the publish buffer, guarded by a
    lock."""

    # Optional hook consulted by the relay-probe handler: a callable
    # (target_index) -> bool that returns True when this node's OWN link
    # to the target is blocked (the chaos harness wires it so injected
    # partitions constrain relays exactly like real ones).
    relay_guard = None

    # Optional serve-span hook (obs.trace): a callable
    # (trace_id, nbytes, dur_s) invoked after each served blob, wired by
    # the transport to Tracer.note_serve so the serving side of an
    # exchange lands in the cross-peer round trace.  The trace id is
    # stored WITH the payload under the publish lock, so a served frame
    # and the id reported for it can never come from different rounds.
    obs_serve_hook = None

    def __init__(
        self,
        host: str,
        port: int,
        flowctl: Optional[FlowctlConfig] = None,
    ):
        self._lock = threading.Lock()
        # Pre-framed (header, payload[, digest][, obs]) segments; served
        # via scatter-gather so publish never joins them into one blob.
        self._segments: Optional[Tuple[bytes, ...]] = None
        self._payload_nbytes = 0
        self._payload_trace_id: Optional[str] = None
        self._state: Optional[bytes] = None  # serialized bootstrap state
        self._state_gen = 0
        # Serving-side flow control (dpwa_tpu/flowctl/): connection cap,
        # per-remote token pacing, in-flight-bytes ceiling, slow-loris
        # eviction.  Defaults apply when no config is passed; admission
        # is skipped entirely when the block is disabled.
        self.flowctl = flowctl if flowctl is not None else FlowctlConfig()
        self.admission = (
            AdmissionController(self.flowctl)
            if self.flowctl.enabled
            else None
        )
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]  # resolves port=0 to real port
        self._sock.listen(16)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name=f"dpwa-rx:{self.port}", daemon=True
        )
        self._thread.start()

    def publish(
        self,
        vec: np.ndarray,
        clock: float,
        loss: float,
        code: Optional[int] = None,
        digest: Optional[bytes] = None,
        obs: Optional[bytes] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        segments = _frame_segments(vec, clock, loss, code, digest, obs)
        with self._lock:
            self._segments = segments
            self._payload_nbytes = sum(len(s) for s in segments)
            self._payload_trace_id = trace_id

    @property
    def _payload(self) -> Optional[bytes]:
        """The published frame as one contiguous byte string — the
        pre-segment representation, kept for the chaos harness and
        tests.  Lock-free: a single attribute read of the segments tuple
        is atomic, and the tuple itself is immutable."""
        segs = self._segments
        return b"".join(segs) if segs is not None else None

    def publish_state(self, blob: bytes) -> None:
        """Expose a serialized train state for peer-assisted bootstrap.

        ``blob`` is whatever :mod:`dpwa_tpu.recovery.state_transfer`
        packed; the server is agnostic — it chunks bytes.  Each publish
        bumps the generation, so an in-flight transfer against the old
        blob restarts instead of splicing."""
        with self._lock:
            # dpwalint: ignore[zerocopy-tobytes] -- publish-time snapshot: served views must outlive the caller's buffer
            self._state = bytes(blob)
            self._state_gen = (self._state_gen + 1) & 0xFFFFFFFF

    def _serve(self) -> None:
        try:
            # close() may already have closed the listener before this
            # thread got scheduled; EBADF here is a clean shutdown, not an
            # error to surface.
            self._sock.settimeout(0.2)
        except OSError:
            return
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            host = addr[0] if addr else ""
            if self.admission is not None:
                ok, retry_ms = self.admission.admit(host)
                if not ok:
                    # Shed EXPLICITLY: the tiny DPWB frame tells a
                    # flowctl-aware fetcher "loaded, retry later" (low-
                    # weight busy outcome); an old fetcher sees EOF short
                    # of a full header and classifies its existing reset
                    # path.  Either way the accept loop stays free.
                    self._shed(conn, retry_ms)
                    continue
            worker = threading.Thread(
                target=self._conn_worker,
                args=(conn, host),
                name=f"dpwa-rx-conn:{self.port}",
                daemon=True,
            )
            worker.start()

    def _shed(self, conn: socket.socket, retry_ms: int) -> None:
        """Best-effort busy reply + close (never blocks the accept loop)."""
        try:
            conn.settimeout(0.5)
            conn.sendall(_busy_frame(retry_ms))
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _conn_worker(self, conn: socket.socket, host: str) -> None:
        """One admitted connection, on its own thread: under admission
        the handler count is bounded by ``max_connections``, so thread-
        per-connection cannot run away — and a relay probe serving
        synchronously no longer pins every other fetcher behind it."""
        try:
            # Handler budget derived from the flowctl block (one source
            # of truth with the request-read eviction deadline) instead
            # of the old hard-coded 5.0 s.
            conn.settimeout(self.flowctl.request_timeout_ms / 1000.0)
            self._handle(conn)
        except OSError:
            pass
        finally:
            if self.admission is not None:
                self.admission.release(host)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, conn: socket.socket) -> None:
        """Serve one accepted connection.  Split out of the accept loop
        so the chaos harness (health/chaos.py) can wrap per-connection
        behavior without duplicating the listener."""
        fc = self.flowctl
        deadline = per_byte = None
        if fc.enabled:
            # Slow-loris discipline on the REQUEST read: cumulative
            # deadline, extended per byte received at the minimum ingest
            # rate — a client trickling its request is evicted, not
            # waited on (the same _recv_exact mechanics the fetch side
            # uses against trickling servers).
            deadline = time.monotonic() + fc.request_timeout_ms / 1000.0
            per_byte = (
                1.0 / fc.min_ingest_bytes_per_s
                if fc.min_ingest_bytes_per_s > 0
                else 0.0
            )
        body = None
        try:
            req = _recv_exact(conn, len(_REQ), deadline, per_byte or 0.0)
            if req == _STATE_REQ:
                body = _recv_exact(
                    conn, _STATE_REQ_BODY.size, deadline, per_byte or 0.0
                )
        except socket.timeout:
            if self.admission is not None:
                self.admission.note_eviction()
            return
        if req == _STATE_REQ:
            offset, max_chunk = _STATE_REQ_BODY.unpack(body)
            self._handle_state(conn, offset, max_chunk)
            return
        if req == _RELAY_REQ:
            self._handle_relay(conn)
            return
        if req != _REQ:
            return
        self._serve_blob(conn)

    def _serve_blob(self, conn: socket.socket) -> None:
        """Send the published frame under the in-flight-bytes ceiling.

        Scatter-gather: the (header, payload, trailers) segments go out
        via one ``sendmsg`` instead of being concatenated first — the
        serve path never allocates payload-sized scratch."""
        with self._lock:
            segments = self._segments
            nbytes = self._payload_nbytes
            trace_id = self._payload_trace_id
        if segments is None:
            return
        adm = self.admission
        if adm is not None and not adm.reserve_bytes(nbytes):
            # Ceiling crossed: shed this send explicitly rather than
            # queue unbounded payload bytes behind slow readers.
            try:
                conn.sendall(_busy_frame(self.flowctl.busy_retry_ms))
            except OSError:
                pass
            return
        hook = self.obs_serve_hook
        t0 = time.monotonic() if hook is not None else 0.0
        try:
            _ingest.sendall_segments(conn, segments)
        finally:
            if adm is not None:
                adm.release_bytes(nbytes)
            if hook is not None and trace_id is not None:
                try:
                    hook(trace_id, nbytes, time.monotonic() - t0)
                except Exception:
                    pass  # observability must never break a serve

    def _handle_relay(self, conn: socket.socket) -> None:
        """Serve one relayed header probe: probe the requested target
        ourselves and report the CLASSIFIED outcome plus the target's
        publish clock.  The probe runs on this Rx thread with a clamped
        budget — relays are drawn from healthy peers and one header
        probe is the cheapest thing on this wire, so the serving stall
        is bounded and rare."""
        body = _recv_exact(conn, _RELAY_BODY.size)
        target, port, timeout_ms, hostlen = _RELAY_BODY.unpack(body)
        host = (
            str(_recv_exact(conn, hostlen), "ascii", "replace")
            if hostlen
            else "127.0.0.1"
        )
        timeout_ms = min(max(int(timeout_ms), 1), _MAX_RELAY_TIMEOUT_MS)
        guard = self.relay_guard
        if guard is not None and guard(int(target)):
            outcome, clock = Outcome.REFUSED, None
        else:
            outcome, clock = probe_header_classified(host, port, timeout_ms)
        conn.sendall(
            _RELAY_HDR.pack(
                _RELAY_MAGIC,
                1,
                _RELAY_OUTCOMES.index(outcome),
                float(clock) if clock is not None else -1.0,
            )
        )

    def _handle_state(
        self, conn: socket.socket, offset: int, max_chunk: int
    ) -> None:
        """Serve one STATE chunk at ``offset``.  No published state is a
        well-formed empty transfer (total = 0): the client reads it as
        'this donor has nothing for you' and tries the next candidate —
        distinct from a protocol failure, which would accrue suspicion
        against an innocent peer."""
        with self._lock:
            blob = self._state if self._state is not None else b""
            gen = self._state_gen
        total = len(blob)
        off = min(max(offset, 0), total)
        n = min(max(max_chunk, 0), total - off, _MAX_STATE_CHUNK)
        # A VIEW of the published blob, not a slice copy: ``blob`` is an
        # immutable bytes object and a re-publish replaces the object,
        # so the view stays valid for the duration of the send.
        chunk = memoryview(blob)[off : off + n]
        header = _STATE_HDR.pack(
            _STATE_MAGIC, 1, gen, total, off, len(chunk), zlib.crc32(chunk)
        )
        _ingest.sendall_segments(conn, (header, chunk))

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


class NativePeerServer:
    """Rx server backed by the C++ serve loop (native/rx_server.cpp).

    Same protocol and publish semantics as :class:`PeerServer`; the serve
    thread is native, so fetches from peers cost this process zero GIL
    time — under free-running training the Python Rx thread otherwise
    competes with fwd/bwd for the interpreter."""

    def __init__(self, host: str, port: int):
        from dpwa_tpu import native

        self._srv = native.NativeRxServer(host, port)
        self.port = self._srv.port

    def publish(
        self,
        vec: np.ndarray,
        clock: float,
        loss: float,
        code: Optional[int] = None,
        digest: Optional[bytes] = None,
        obs: Optional[bytes] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        # The native loop serves the framed bytes verbatim, so the
        # digest/obs trailers ride along without the C++ side knowing.
        # trace_id is accepted-and-ignored: serve-side spans need the
        # Python server's hook (the transport forces it when obs.trace).
        self._srv.publish_framed(_frame(vec, clock, loss, code, digest, obs))

    def publish_state(self, blob: bytes) -> None:
        raise RuntimeError(
            "the native Rx server only speaks the blob protocol; STATE "
            "serving needs the Python server (TcpTransport selects it "
            "automatically when recovery.enabled)"
        )

    def close(self) -> None:
        self._srv.close()


def make_peer_server(
    host: str, port: int, flowctl: Optional[FlowctlConfig] = None
):
    """Native Rx server when the toolchain allows, Python thread otherwise.

    ``DPWA_NATIVE_RX=0`` forces the Python server (debugging / parity
    tests).  ``flowctl`` configures the Python server's admission plane;
    the native C++ loop speaks only the blob protocol and ignores it
    (``TcpTransport`` forces the Python server when ``flowctl.enabled``
    so admission is actually in force)."""
    import os

    if os.environ.get("DPWA_NATIVE_RX", "1") != "0":
        try:
            return NativePeerServer(host, port)
        except (RuntimeError, OSError):
            pass  # no toolchain / bind raced: identical Python fallback
    return PeerServer(host, port, flowctl=flowctl)


def _recv_trailing(
    sock: socket.socket, n: int, deadline: float
) -> Optional[memoryview]:
    """Best-effort exact read for an OPTIONAL trailing section.

    Returns None — never raises — on timeout/EOF/reset: a peer that
    closed right after its payload simply has no trailer, which is the
    normal pre-membership wire and must not look like a failure."""
    try:
        return _recv_exact(sock, n, deadline)
    except (socket.timeout, ConnectionError, OSError):
        return None


def _read_digest_trailer(
    sock: socket.socket, budget_s: float = 0.25
) -> Optional[bytes]:
    """Read the optional membership-digest trailer after a payload.

    Two-phase tolerant read (fixed digest header, then the entry block
    the header's count implies); ANY malformation — missing bytes, bad
    magic, absurd count — yields None rather than an error, because an
    old-format peer legitimately serves no trailer.  The budget is small
    and fixed: the digest is ~11 B/peer and the peer has already proven
    responsive by streaming the whole payload."""
    from dpwa_tpu.membership.digest import (
        HEADER_SIZE,
        header_entries_nbytes,
    )

    deadline = time.monotonic() + budget_s
    head = _recv_trailing(sock, HEADER_SIZE, deadline)
    if head is None:
        return None
    nbytes = header_entries_nbytes(head)
    if nbytes is None:
        return None
    body = _recv_trailing(sock, nbytes, deadline)
    if body is None:
        return None
    # join, not +: _recv_trailing hands back memoryviews now, and the
    # digest contract returns owning bytes (tiny — ~11 B/peer).
    return b"".join((head, body))


def _read_trailers(
    sock: socket.socket,
    want_digest: bool,
    want_obs: bool,
    budget_s: float = 0.25,
) -> Tuple[Optional[bytes], Optional[bytes]]:
    """Magic-dispatched tolerant read of ALL optional trailing sections.

    A served frame may carry, after the payload: a membership digest
    (``DPWM``) and/or an observability section (``DPWT``), in that
    order.  Reading them naively in sequence breaks when the local node
    wants only one of them — e.g. membership off + obs on against a peer
    serving both would consume the digest header while looking for the
    obs magic and lose the section boundary.  So: read a 4-byte magic
    tolerantly, dispatch on it, repeat; stop on anything unrecognized.
    Sections the caller doesn't want are still consumed (the socket is
    about to close — the bytes are free) but returned as None.

    Returns ``(digest_bytes, obs_bytes)``; each None when absent,
    malformed, or unwanted.  Never raises."""
    from dpwa_tpu.membership.digest import (
        DIGEST_MAGIC,
        HEADER_SIZE,
        header_entries_nbytes,
    )
    from dpwa_tpu.obs.wire import (
        OBS_HEADER_SIZE,
        OBS_MAGIC,
        header_sketch_count,
        values_size,
    )

    deadline = time.monotonic() + budget_s
    digest = obs = None
    # Bounded dispatch: one section per known magic, tiny loop cap so a
    # hostile peer streaming valid-looking sections can't pin us here.
    for _ in range(4):
        magic = _recv_trailing(sock, 4, deadline)
        if magic is None:
            break
        if magic == DIGEST_MAGIC and digest is None:
            rest = _recv_trailing(sock, HEADER_SIZE - 4, deadline)
            if rest is None:
                break
            head = b"".join((magic, rest))
            nbytes = header_entries_nbytes(head)
            if nbytes is None:
                break
            body = _recv_trailing(sock, nbytes, deadline)
            if body is None:
                break
            digest = b"".join((head, body))
        elif magic == OBS_MAGIC and obs is None:
            rest = _recv_trailing(sock, OBS_HEADER_SIZE - 4, deadline)
            if rest is None:
                break
            head = b"".join((magic, rest))
            n = header_sketch_count(head)
            if n is None:
                break
            body = _recv_trailing(sock, values_size(n), deadline)
            if body is None:
                break
            obs = b"".join((head, body))
        else:
            break
    return (digest if want_digest else None, obs if want_obs else None)


def fetch_blob_full(
    host: str,
    port: int,
    timeout_ms: int,
    min_bandwidth_bps: float = _MIN_WIRE_BANDWIDTH,
    want_digest: bool = False,
    sock_box: Optional[list] = None,
    want_obs: bool = False,
    lease_box: Optional[list] = None,
) -> Tuple[
    Optional[Tuple[np.ndarray, float, float]], str, float, int,
    Optional[bytes], Optional[bytes],
]:
    """:func:`fetch_blob` plus the classified outcome the health
    subsystem feeds on, plus the optional trailing sections.

    Returns ``(result, outcome, latency_s, payload_bytes_received,
    digest, obs)`` where ``result`` is ``(vec, clock, loss)`` or None,
    ``digest`` is the raw membership-digest trailer bytes and ``obs``
    the raw DPWT observability trailer bytes (each only attempted when
    ``want_digest`` / ``want_obs`` and the payload fetch succeeded; None
    whenever the peer served no valid section) and ``outcome``
    is one of :class:`dpwa_tpu.health.detector.Outcome`:

    - ``refused`` — the connect itself failed (peer process gone);
    - ``timeout`` — the cumulative deadline expired with NOTHING received
      (connect, request, or a header that never started);
    - ``slow`` — the cumulative deadline expired with bytes already
      flowing: the peer is alive and serving, just not fast enough for
      the budget (low detector weight — soft-degrades, never
      quarantines);
    - ``busy`` — the peer answered the tiny ``DPWB`` shed frame: loaded
      but honest (same low weight as ``slow``);
    - ``short_read`` — the peer closed or reset mid-frame;
    - ``corrupt`` — bad magic/version/dtype, oversize advertisement, or
      an int8 payload that failed to decode;
    - ``success`` — a full, valid frame.

    ``sock_box`` (a plain list) receives the connected socket as soon as
    it exists: a hedging caller running this fetch on a thread closes it
    to cancel the losing leg promptly instead of waiting out its
    deadline.

    ``lease_box`` (a plain list) opts into explicit receive-buffer
    ownership: the payload's ring :class:`~dpwa_tpu.parallel.ingest
    .Lease` is appended on success and the CALLER must ``release()`` it
    once every view of the decoded vector is dead — the allocation-flat
    steady state (the bench and the tracemalloc tier-1 test drive this).
    Without it, leases whose decode produced escaping views (dense /
    top-k / shard) are detached — correct but unpooled — and fully
    consumed payloads (int8) are released here.

    ``timeout_ms`` is a CUMULATIVE wall-clock budget enforced via a
    monotonic deadline threaded through :func:`_recv_exact` — not a
    per-recv timer a trickling peer could keep resetting.  It covers
    connect + request + header outright; the payload read then earns
    ``1 / min_bandwidth_bps`` extra seconds per byte received (default:
    the module floor derived from ``DEFAULT_MIN_WIRE_MB_PER_S``; the
    transport passes ``protocol.min_wire_mb_per_s``), so the budget
    scales with the replica actually flowing instead of rejecting every
    blob larger than bandwidth × timeout_ms — and a peer that merely
    ADVERTISES a huge payload earns nothing."""
    t0 = time.monotonic()
    deadline = t0 + timeout_ms / 1000.0
    nbytes_rx = 0
    # Total bytes received across header + payload, surviving a raised
    # timeout: >0 at deadline lapse means the peer was STREAMING, which
    # classifies as ``slow`` (soft evidence) rather than ``timeout``.
    rx = [0]
    # The payload's ring lease, once taken: every non-success exit must
    # release it back to the ring (the except arms below do).
    lease = None
    try:
        sock = socket.create_connection(
            (host, port), timeout=timeout_ms / 1000.0
        )
    except socket.timeout:
        return None, Outcome.TIMEOUT, time.monotonic() - t0, 0, None, None
    except (ConnectionError, OSError):
        # Refused, unreachable, reset during handshake: no peer process
        # is answering on that port.
        return None, Outcome.REFUSED, time.monotonic() - t0, 0, None, None
    if sock_box is not None:
        sock_box.append(sock)
    try:
        with sock:
            # The request send draws from the SAME cumulative budget as
            # the reads: create_connection leaves only the connect
            # timeout on the socket, which restarts the clock — a peer
            # that accepts but never reads (full Rx backlog) would get a
            # fresh window for sendall on top of a spent deadline.
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout(
                    "cumulative fetch deadline exceeded before request"
                )
            sock.settimeout(remaining)
            sock.sendall(_REQ)
            # Magic peek: 4 bytes decide DPWB (busy shed) vs DPWA (blob
            # header).  An old server never sends DPWB, so the peek is
            # just the header's first read split in two — both halves
            # land in ONE scratch buffer so the header is never
            # reassembled by concatenation.
            hdr_buf = bytearray(max(_HDR.size, _BUSY_HDR.size))
            peek = _recv_exact(sock, 4, deadline, progress=rx, out=hdr_buf)
            if peek == _BUSY_MAGIC:
                _recv_exact(
                    sock, _BUSY_HDR.size - 4, deadline, progress=rx,
                    out=memoryview(hdr_buf)[4:],
                )
                _m, bversion, _retry_ms = _BUSY_HDR.unpack_from(hdr_buf, 0)
                if bversion != 1:
                    return (
                        None, Outcome.CORRUPT, time.monotonic() - t0, 0,
                        None, None,
                    )
                return None, Outcome.BUSY, time.monotonic() - t0, 0, None, None
            _recv_exact(
                sock, _HDR.size - 4, deadline, progress=rx,
                out=memoryview(hdr_buf)[4:],
            )
            magic, version, code, clock, loss, nbytes = _HDR.unpack_from(
                hdr_buf, 0
            )
            if magic != _MAGIC or version != 1 or (
                code not in _DTYPES and code not in _PAYLOAD_CODES
            ):
                return (
                    None, Outcome.CORRUPT, time.monotonic() - t0, 0, None,
                    None,
                )
            if nbytes > _MAX_BLOB:
                return (
                    None, Outcome.CORRUPT, time.monotonic() - t0, 0, None,
                    None,
                )
            # Payload lands straight in a ring buffer via recv_into —
            # no chunk-grow bytearray, no final bytes() copy.  For a
            # large advertisement the full-size lease is deferred behind
            # a small probe read: the old grow-by-chunk loop only ever
            # allocated in proportion to bytes actually RECEIVED, so a
            # peer that advertises gigabytes and hangs up must not cost
            # a huge upfront allocation here either.
            per_byte = 1.0 / min_bandwidth_bps
            pre = 0
            if nbytes > _PROBE_THRESHOLD:
                lease = _ingest.default_ring().lease(_PROBE_BYTES)
                _recv_exact(
                    sock, _PROBE_BYTES, deadline, per_byte,
                    progress=rx, out=lease.view,
                )
                try:
                    full = _ingest.default_ring().lease(nbytes)
                except (MemoryError, OverflowError):
                    # Advertised size within _MAX_BLOB but beyond this
                    # host: a frame this process can never hold is
                    # malformed from its point of view.
                    lease.release()
                    lease = None
                    return (
                        None, Outcome.CORRUPT,
                        time.monotonic() - t0, rx[0], None, None,
                    )
                full.view[:_PROBE_BYTES] = lease.view
                lease.release()
                lease = full
                pre = _PROBE_BYTES
            else:
                lease = _ingest.default_ring().lease(nbytes)
            # The probe already earned its per-byte budget: shift the
            # deadline so the cumulative contract spans both reads.
            data = _recv_exact(
                sock, nbytes - pre, deadline + pre * per_byte,
                per_byte, progress=rx, out=lease.view[pre:],
            )
            data = lease.view
            nbytes_rx = len(data)
            # Payload-sized copies this decode performs (0 = the decoded
            # vector is a view into the ring buffer); feeds the
            # copies_per_frame health column.
            copies = 0
            escapes = True
            if code == _TOPK_DELTA:
                # Sparse top-k frame: validated and decoded here (the
                # full malformed-input taxonomy — truncated index list,
                # k > n, unsorted/duplicate indices, lying value-block
                # length — classifies as CORRUPT, never crashes), but
                # NOT densified: only the transport holds the local
                # replica the indices splice into, so the TopkPayload
                # object rides the vector slot up to TcpTransport.fetch.
                from dpwa_tpu.ops.quantize import decode_topk_payload

                try:
                    vec = decode_topk_payload(
                        np.frombuffer(data, dtype=np.uint8)
                    )
                except ValueError:
                    lease.release()
                    return (
                        None, Outcome.CORRUPT,
                        time.monotonic() - t0, nbytes_rx, None, None,
                    )
                # f32 value blocks decode as views into the buffer; an
                # int8 block materializes fresh f32 values (one copy).
                copies = 0 if vec.value_dtype == "f32" else 1
            elif code == _SHARD:
                # Sharded frame: one contiguous slice of the replica in
                # any inner encoding.  Decoded and validated here (lying
                # k, out-of-range shard_idx, truncated preamble, inner
                # bodies that fail their own codec — all CORRUPT, never
                # a crash) but NOT densified: like top-k, only the
                # transport holds the replica the slice merges into, so
                # the ShardPayload object rides the vector slot.
                from dpwa_tpu.ops.shard import decode_shard_payload

                try:
                    vec = decode_shard_payload(
                        np.frombuffer(data, dtype=np.uint8)
                    )
                except ValueError:
                    lease.release()
                    return (
                        None, Outcome.CORRUPT,
                        time.monotonic() - t0, nbytes_rx, None, None,
                    )
                # Dense-f32 inner slices (and top-k f32 value blocks)
                # stay views; bf16/int8 inners materialize f32.
                if vec.inner_code == _pc.PAYLOAD_F32:
                    copies = 0
                elif vec.inner_code == _TOPK_DELTA:
                    copies = 0 if vec.inner.value_dtype == "f32" else 1
                else:
                    copies = 1
            elif code == _INT8_CHUNKED:
                # Receiver-side dequantize: the wire moved 1 byte/elem
                # (+ scales); the merge math runs on the f32 decode.
                from dpwa_tpu.ops.quantize import decode_int8_payload

                try:
                    vec = decode_int8_payload(
                        np.frombuffer(data, dtype=np.uint8)
                    )
                except ValueError:
                    # malformed payload == skipped fetch
                    lease.release()
                    return (
                        None, Outcome.CORRUPT,
                        time.monotonic() - t0, nbytes_rx, None, None,
                    )
                # Dequantize materialized a fresh f32 vector: the wire
                # bytes are fully consumed, nothing views the buffer.
                copies = 1
                escapes = False
            else:
                try:
                    # A VIEW over the ring buffer, not .copy(): the
                    # lease below keeps the bytes alive for exactly as
                    # long as the vector does.
                    vec = np.frombuffer(data, dtype=_DTYPES[code])
                except ValueError:
                    # Payload length not a multiple of the advertised
                    # dtype's itemsize: malformed frame.
                    lease.release()
                    return (
                        None, Outcome.CORRUPT,
                        time.monotonic() - t0, nbytes_rx, None, None,
                    )
                # f32 merges straight off the view; bf16/f64/u16 pay
                # their one upcast copy downstream in _weigh_remote.
                copies = 0 if _DTYPES[code] == np.dtype("<f4") else 1
            # Optional trailing sections (epidemic-membership digest,
            # DPWT observability): attempted only after a fully valid
            # payload (a frame that failed above carries no trustworthy
            # trailer), tolerant of their absence, dispatched by magic
            # so every presence combination parses.
            if want_digest or want_obs:
                digest, obs = _read_trailers(sock, want_digest, want_obs)
            else:
                digest = obs = None
            # Buffer ownership handoff (docs/transport.md): the caller
            # takes the lease explicitly (lease_box), or the views keep
            # the escaped buffer alive, or — payload fully consumed —
            # the buffer goes straight back to the ring.  Dense frames
            # escape as ONE ndarray whose .base chain owns every derived
            # view, so their lease is *recycled* (pooled again when the
            # vector dies) instead of detached — otherwise every frame
            # in the small-frame regime (LoRA adapters) costs a fresh
            # allocation and the ring's hit rate pins at zero.  Top-k /
            # shard payload objects stay detached: their member views
            # can be extracted and outlive the payload wrapper.
            if lease_box is not None:
                lease_box.append(lease)
            elif not escapes:
                lease.release()
            elif code in (_TOPK_DELTA, _SHARD):
                lease.detach()
            else:
                lease.recycle(vec)
            lease = None
            _ingest.note_rx_frame(copies)
            return (
                (vec, clock, loss), Outcome.SUCCESS,
                time.monotonic() - t0, nbytes_rx, digest, obs,
            )
    except socket.timeout:
        # Bytes flowed and the budget still lapsed: a live-but-slow peer
        # (trickle, overload) — soft evidence, not a death mark.
        if lease is not None:
            lease.release()
        outcome = Outcome.SLOW if rx[0] > 0 else Outcome.TIMEOUT
        return None, outcome, time.monotonic() - t0, nbytes_rx, None, None
    except (ConnectionError, OSError):
        # Accepted, then closed/reset mid-frame: the peer process is
        # alive enough to accept but served a broken stream.
        if lease is not None:
            lease.release()
        return (
            None, Outcome.SHORT_READ, time.monotonic() - t0, nbytes_rx, None,
            None,
        )


def fetch_blob_ex(
    host: str,
    port: int,
    timeout_ms: int,
    min_bandwidth_bps: float = _MIN_WIRE_BANDWIDTH,
) -> Tuple[
    Optional[Tuple[np.ndarray, float, float]], str, float, int
]:
    """:func:`fetch_blob_full` without the trailing sections — the
    4-tuple ``(result, outcome, latency_s, nbytes_rx)`` shape the
    health subsystem and existing callers consume."""
    return fetch_blob_full(host, port, timeout_ms, min_bandwidth_bps)[:4]


def fetch_blob(
    host: str,
    port: int,
    timeout_ms: int,
    min_bandwidth_bps: float = _MIN_WIRE_BANDWIDTH,
) -> Optional[Tuple[np.ndarray, float, float]]:
    """Connect to a peer's Rx thread and pull its latest blob.

    Returns None on timeout / refused connection / malformed reply — the
    caller skips the merge and keeps training, like the reference.  Thin
    wrapper over :func:`fetch_blob_ex`, which additionally classifies
    the failure for the health subsystem; see it for deadline
    semantics."""
    return fetch_blob_ex(host, port, timeout_ms, min_bandwidth_bps)[0]


def fetch_state_chunk(
    host: str,
    port: int,
    offset: int,
    max_chunk: int,
    timeout_ms: int,
    min_bandwidth_bps: float = _MIN_WIRE_BANDWIDTH,
    out: Optional[memoryview] = None,
) -> Tuple[Optional[Tuple[memoryview, int, int]], str, float, int]:
    """Fetch one STATE chunk: ``(result, outcome, latency_s, nbytes_rx)``
    where ``result`` is ``(chunk_view, total_len, generation)`` or None.

    Same cumulative-deadline discipline as :func:`fetch_blob_ex`: the
    budget covers connect + request + header outright and the chunk read
    earns per-byte extension.  A CRC mismatch or malformed header is
    ``corrupt``; the caller (:func:`fetch_state`) decides whether to
    resume, restart, or give up.

    ``out`` (a writable memoryview) receives the chunk bytes in place —
    :func:`fetch_state` passes a window of its preassembled blob so
    chunks land at their final offset with no accumulation copy.  A
    server-advertised ``chunk_len`` that would overflow ``out`` is
    ``corrupt`` (the blob shrank or the donor is lying).  The returned
    chunk is a memoryview either way; it compares equal to ``bytes``."""
    t0 = time.monotonic()
    deadline = t0 + timeout_ms / 1000.0
    nbytes_rx = 0
    try:
        sock = socket.create_connection(
            (host, port), timeout=timeout_ms / 1000.0
        )
    except socket.timeout:
        return None, Outcome.TIMEOUT, time.monotonic() - t0, 0
    except (ConnectionError, OSError):
        return None, Outcome.REFUSED, time.monotonic() - t0, 0
    try:
        with sock:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout(
                    "cumulative state-fetch deadline exceeded before request"
                )
            sock.settimeout(remaining)
            sock.sendall(
                _STATE_REQ + _STATE_REQ_BODY.pack(offset, max_chunk)
            )
            raw = _recv_exact(sock, _STATE_HDR.size, deadline)
            magic, version, gen, total, off, chunk_len, crc = (
                _STATE_HDR.unpack(raw)
            )
            if (
                magic != _STATE_MAGIC
                or version != 1
                or total > _MAX_BLOB
                or chunk_len > max(total - off, 0)
                or (out is not None and chunk_len > len(out))
            ):
                return None, Outcome.CORRUPT, time.monotonic() - t0, 0
            data = _recv_exact(
                sock, chunk_len, deadline, 1.0 / min_bandwidth_bps,
                out=out,
            )
            nbytes_rx = len(data)
            if zlib.crc32(data) != crc or off != min(max(offset, 0), total):
                # A clamped offset means the blob shrank under us (the
                # donor re-published): same remedy as a bad chunk —
                # the transfer-level loop restarts.
                return None, Outcome.CORRUPT, time.monotonic() - t0, nbytes_rx
            return (
                (data, total, gen), Outcome.SUCCESS,
                time.monotonic() - t0, nbytes_rx,
            )
    except socket.timeout:
        return None, Outcome.TIMEOUT, time.monotonic() - t0, nbytes_rx
    except (ConnectionError, OSError):
        return None, Outcome.SHORT_READ, time.monotonic() - t0, nbytes_rx


def fetch_state(
    host: str,
    port: int,
    timeout_ms: int,
    chunk_bytes: int = 1 << 20,
    max_retries: int = 8,
    min_bandwidth_bps: float = _MIN_WIRE_BANDWIDTH,
) -> Tuple[Optional[bytes], str, float, int]:
    """Full resumable STATE transfer from a donor peer.

    Loops :func:`fetch_state_chunk` from offset 0, each chunk on a fresh
    one-shot connection (a short read or timeout resumes at the last
    acknowledged offset — bytes already banked are never refetched);
    ``max_retries`` bounds the total number of failed chunk attempts
    across the transfer.  A generation change or corrupt chunk restarts
    the transfer from zero (also charged as a retry).  Returns
    ``(blob | None, outcome, latency_s, nbytes_received)`` — an empty
    blob (donor has no published state) comes back as ``(b"", success)``
    for the caller to interpret; ``outcome`` on failure is the LAST
    chunk's classification."""
    t0 = time.monotonic()
    # Chunks land DIRECTLY at their final offset: the first successful
    # chunk learns ``total`` and sizes the blob once; every later chunk
    # recv_into's a window of it — no chunk-grow accumulation buffer,
    # no per-chunk splice copy (the tcp.py:1021 twin of the old
    # _recv_n loop, now shared via ingest.recv_exact_into).
    blob: Optional[bytearray] = None
    filled = 0
    total: Optional[int] = None
    gen: Optional[int] = None
    retries = 0
    nbytes_rx = 0
    while True:
        window = (
            memoryview(blob)[filled:] if blob is not None else None
        )
        got, outcome, _lat, nrx = fetch_state_chunk(
            host, port, filled, chunk_bytes, timeout_ms,
            min_bandwidth_bps, out=window,
        )
        nbytes_rx += nrx
        if got is None:
            # A refused connect means the donor process is gone — no
            # point burning the remaining retries against it.
            if outcome == Outcome.REFUSED or retries >= max_retries:
                return None, outcome, time.monotonic() - t0, nbytes_rx
            retries += 1
            if outcome == Outcome.CORRUPT:
                blob, filled = None, 0
                total = gen = None
            continue
        data, tot, g = got
        if gen is not None and (g != gen or tot != total):
            # Donor re-published mid-transfer: splicing chunks from two
            # different blobs would hand the bootstrap a frankenstate.
            if retries >= max_retries:
                return None, Outcome.CORRUPT, time.monotonic() - t0, nbytes_rx
            retries += 1
            blob, filled = None, 0
            total = gen = None
            continue
        gen, total = g, tot
        if blob is None:
            # First chunk of a (re)started transfer: size the blob from
            # the donor's advertisement and bank what just arrived.
            blob = bytearray(total)
            blob[: len(data)] = data
            filled = len(data)
        else:
            # ``data`` IS blob[filled:filled+len] (recv_into'd there).
            filled += len(data)
        if filled >= total:
            # bytes() here is the public immutable-contract copy, not a
            # frame-path one — bootstrap runs once per restart.
            return (
                bytes(memoryview(blob)[:total]), Outcome.SUCCESS,  # dpwalint: ignore[zerocopy-tobytes] -- one-shot bootstrap transfer returns owning bytes by contract
                time.monotonic() - t0, nbytes_rx,
            )
        if not len(data):
            # Zero-byte chunk while bytes remain: malformed server.
            if retries >= max_retries:
                return None, Outcome.CORRUPT, time.monotonic() - t0, nbytes_rx
            retries += 1


def probe_header_classified(
    host: str, port: int, timeout_ms: int = 100
) -> Tuple[str, Optional[float]]:
    """Header-only liveness probe with the CLASSIFIED outcome.

    Same wire exchange as :func:`probe_header` but the failure mode is
    reported as a :class:`~dpwa_tpu.health.detector.Outcome` string —
    the membership layer treats "nothing listening" (``refused``) very
    differently from "listening but serving garbage" (``corrupt``), and
    relays forward exactly this classification to the asking node."""
    deadline = time.monotonic() + timeout_ms / 1000.0
    try:
        sock = socket.create_connection(
            (host, port), timeout=timeout_ms / 1000.0
        )
    except socket.timeout:
        return Outcome.TIMEOUT, None
    except (ConnectionError, OSError):
        return Outcome.REFUSED, None
    try:
        with sock:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return Outcome.TIMEOUT, None
            sock.settimeout(remaining)
            sock.sendall(_REQ)
            hdr_buf = bytearray(max(_HDR.size, _BUSY_HDR.size))
            peek = _recv_exact(sock, 4, deadline, out=hdr_buf)
            if peek == _BUSY_MAGIC:
                # A shedding server answers probes with DPWB too: the
                # peer is ALIVE but loaded — the caller records the
                # low-weight busy outcome, never a hard failure.
                _recv_exact(
                    sock, _BUSY_HDR.size - 4, deadline,
                    out=memoryview(hdr_buf)[4:],
                )
                _m, bversion, _retry = _BUSY_HDR.unpack_from(hdr_buf, 0)
                if bversion != 1:
                    return Outcome.CORRUPT, None
                return Outcome.BUSY, None
            _recv_exact(
                sock, _HDR.size - 4, deadline, out=memoryview(hdr_buf)[4:]
            )
            magic, version, code, clock, _loss, nbytes = _HDR.unpack_from(
                hdr_buf, 0
            )
            if (
                magic != _MAGIC
                or version != 1
                or (code not in _DTYPES and code not in _PAYLOAD_CODES)
                or nbytes > _MAX_BLOB
            ):
                return Outcome.CORRUPT, None
            return Outcome.SUCCESS, float(clock)
    except socket.timeout:
        return Outcome.TIMEOUT, None
    except (ConnectionError, OSError):
        return Outcome.SHORT_READ, None


def probe_header_ex(
    host: str, port: int, timeout_ms: int = 100
) -> Tuple[bool, Optional[float]]:
    """:func:`probe_header` plus the probed frame's publish clock.

    The clock rides the header for free, and re-admission wants it: a
    readmitted peer whose clock is far AHEAD of ours means we are the
    stale replica (we were partitioned while it kept training) — the
    freshness check behind ``recovery.max_clock_lag``.  Thin wrapper
    over :func:`probe_header_classified`, which keeps the failure
    taxonomy."""
    outcome, clock = probe_header_classified(host, port, timeout_ms)
    return outcome == Outcome.SUCCESS, clock


def relay_probe(
    relay_host: str,
    relay_port: int,
    target_index: int,
    target_host: str,
    target_port: int,
    probe_timeout_ms: int,
    timeout_ms: int,
) -> Tuple[str, Optional[str], Optional[float]]:
    """Ask a relay peer to header-probe ``target`` on our behalf.

    The SWIM indirect-probe leg: returns ``(relay_outcome,
    probe_outcome, clock)`` where ``relay_outcome`` classifies OUR
    connection to the relay (it feeds the relay's own health record),
    ``probe_outcome`` is the relay's classified
    :func:`probe_header_classified` result against the target (None
    whenever the relay leg itself failed), and ``clock`` is the
    target's publish clock as the relay saw it (None when unknown).

    ``timeout_ms`` must comfortably exceed ``probe_timeout_ms``: the
    relay performs its probe synchronously before answering."""
    deadline = time.monotonic() + timeout_ms / 1000.0
    try:
        sock = socket.create_connection(
            (relay_host, relay_port), timeout=timeout_ms / 1000.0
        )
    except socket.timeout:
        return Outcome.TIMEOUT, None, None
    except (ConnectionError, OSError):
        return Outcome.REFUSED, None, None
    try:
        with sock:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return Outcome.TIMEOUT, None, None
            sock.settimeout(remaining)
            host_b = target_host.encode("ascii", "replace")[:255]
            sock.sendall(
                _RELAY_REQ
                + _RELAY_BODY.pack(
                    target_index & 0xFFFF,
                    target_port & 0xFFFF,
                    int(probe_timeout_ms) & 0xFFFFFFFF,
                    len(host_b),
                )
                + host_b
            )
            raw = _recv_exact(sock, _RELAY_HDR.size, deadline)
            magic, version, code, clock = _RELAY_HDR.unpack(raw)
            if (
                magic != _RELAY_MAGIC
                or version != 1
                or code >= len(_RELAY_OUTCOMES)
            ):
                return Outcome.CORRUPT, None, None
            return (
                Outcome.SUCCESS,
                _RELAY_OUTCOMES[code],
                float(clock) if clock >= 0 else None,
            )
    except socket.timeout:
        return Outcome.TIMEOUT, None, None
    except (ConnectionError, OSError):
        return Outcome.SHORT_READ, None, None


def probe_header(host: str, port: int, timeout_ms: int = 100) -> bool:
    """Cheap liveness probe: connect, request, validate the HEADER only.

    The re-admission check for a quarantined peer — it answers "is a
    live dpwa Rx serving a well-formed frame there?" without pulling the
    payload (a full replica would cost the quarantined-peer path the
    very bandwidth quarantine exists to save).  The connection is
    abandoned after the header; the Rx side's sendall into a closed
    socket is its normal ``OSError -> close`` path."""
    return probe_header_ex(host, port, timeout_ms)[0]


def _host_merge(
    vec: np.ndarray, remote_vec: np.ndarray, alpha: float
) -> np.ndarray:
    """Host-side ``(1-α)·vec + α·remote`` — native single-pass axpy on
    the f32 fast path (numpy takes three passes + temps)."""
    if vec.dtype == np.float32 and remote_vec.dtype == np.float32:
        return native.merge_out(
            np.ascontiguousarray(vec),
            np.ascontiguousarray(remote_vec),
            alpha,
        )
    return (
        (1.0 - alpha) * vec.astype(np.float32)
        + alpha * remote_vec.astype(np.float32)
    ).astype(vec.dtype)


class _OverlappedExchange:
    """In-flight overlapped gossip round: the fetch runs on a daemon
    thread while the owner computes its local step.

    ``finish(pre_vec, update)`` joins the fetch and returns
    ``(merged_plus_update, alpha, partner)`` where
    ``merged_plus_update = (1-α)·pre + α·remote + update`` — identical
    algebra to the SPMD ``overlap=True`` step (merge the PRE-update
    replicas, land the local update on the merged result).  A skipped
    round (self-pair, masked, fetch timeout) returns
    ``pre_vec + update`` with α = 0."""

    def __init__(
        self, transport: "TcpTransport", clock, loss, step,
        expected_nbytes: int = 0,
    ):
        self._t = transport
        self._clock, self._loss = clock, loss
        self._step = step
        # Gossip replicas are symmetric: the partner's payload is the
        # same size (in WIRE bytes) as what we just published.  Sizes
        # the join backstop the same way fetch_blob's deadline scales.
        self._expected_nbytes = expected_nbytes
        self.sched_partner, self.partner, self.remapped = (
            transport._resolve_partner(step)
        )
        # Participation is gated on the ORIGINAL schedule pairing (same
        # threefry draw as the ICI path); a health remap changes only
        # WHO gets fetched, never WHETHER this round merges.
        self._participates = (
            self.partner != transport.me
            and transport.schedule.participates(step, transport.me)
        )
        # dpwalint: double_buffered(_got) -- handoff by join ordering: the fetch thread is the only writer, and finish() joins it before reading
        self._got = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if not self._participates:
            return

        def _fetch():
            self._got = self._t.fetch(self.partner, step=self._step)

        self._thread = threading.Thread(target=_fetch, daemon=True)
        self._thread.start()

    def finish(
        self, pre_vec: np.ndarray, update: Optional[np.ndarray] = None
    ) -> Tuple[np.ndarray, float, int]:
        if self._thread is not None:
            # The fetch itself is bounded by timeout_ms plus the
            # per-byte-received extension (fetch_blob's scaled deadline);
            # the join backstop must allow the same worst case — a fixed
            # 2.5 s join would abandon large-replica fetches the deadline
            # deliberately tolerates, silently skipping every merge.
            # ``_expected_nbytes`` is the WIRE size of the partner frame
            # (int8/bf16-aware: the deadline earns budget only for bytes
            # actually on the wire, so sizing from the f32 replica would
            # inflate the backstop 4x under int8), and timeout_ms appears
            # exactly once: the deadline already folds it in, so the
            # slack term is a fixed 1 s for thread scheduling, not a
            # second copy of the timeout.  A timed-out join skips the
            # round like any other failed fetch.  With flowctl enabled
            # the fetch may run TWO sequential budgets (primary deadline
            # up to flowctl.max_ms, then a hedge leg with its own), so
            # the backstop doubles the larger of the two ceilings.
            fc = self._t.config.flowctl
            base_s = self._t.config.protocol.timeout_ms / 1000.0
            if fc.enabled:
                base_s = 2.0 * max(base_s, fc.max_ms / 1000.0)
            self._thread.join(
                timeout=1.0
                + base_s
                + self._expected_nbytes
                / (self._t.config.protocol.min_wire_mb_per_s * 1e6)
            )
        got = self._got if self._thread is not None else None
        # The overlapped path never runs _round, so the membership round
        # boundary lands here — after the fetch (and its digest merge)
        # has been joined.
        self._t._membership_end_round(self._step)
        if got is None:
            merged, alpha = pre_vec, 0.0
        else:
            remote_vec, alpha = self._t._weigh_remote(
                got, self._clock, self._loss
            )
            merged = self._t._merge_remote(pre_vec, remote_vec, alpha)
        if update is not None:
            merged = merged + update
        return merged, alpha, self.partner


# Device-side merging lives in dpwa_tpu/device/ (docs/device.md): the
# single-slot jitted lerp that used to sit here (_LERP_CACHE) became the
# engine's keyed LRU jit cache, and the per-frame jnp.asarray upload
# became the zero-copy handoff.  The import stays deferred to the device
# substrates so this module remains importable (and its CPU exchange
# usable) without touching a JAX backend — bench.py's TCP leg runs it in
# a backend-pinned subprocess for exactly that reason.
def _merge_engine():
    from dpwa_tpu.device import default_engine

    return default_engine()


class TcpTransport:
    """Per-process gossip transport with the reference's update semantics.

    One instance per worker process; ``name`` selects this node's entry in
    the shared YAML ``nodes:`` list (exactly the reference's CLI contract,
    SURVEY.md §3.1)."""

    def __init__(self, config: DpwaConfig, name: str):
        self.config = config
        self.me = config.node_index(name)
        # Hierarchical gossip (docs/hierarchy.md): a ``topology:`` block
        # swaps in the two-level island×wide-area pool — intra-island
        # slots everyone works, wide-area slots only the elected island
        # leaders work (non-leaders self-pair, and a self-pair never
        # fetches).  No block -> the flat pool, bit-identical to before
        # the topology grammar existed.  Deferred import: hier pulls in
        # the election machinery only topology users need.
        self.topology = None
        if config.topology.enabled:
            from dpwa_tpu.hier.schedule import build_hier_schedule
            from dpwa_tpu.hier.topology import Topology

            self.topology = Topology.from_config(config)
            self.schedule: Schedule = build_hier_schedule(config)
        else:
            self.schedule = build_schedule(config)
        # Content-trust plane (dpwa_tpu/trust/): screens every decoded
        # REMOTE payload and damps/rejects the merge.  Deferred import —
        # trust pulls in the screening jit machinery this module must
        # not require at import time.
        self.trust = None
        if config.trust.enabled:
            from dpwa_tpu.trust.manager import TrustManager

            self.trust = TrustManager(
                len(config.nodes), self.me, config.trust
            )
        # The CURRENT exchange's trust damping, read by the interpolation
        # through a zero-arg callable: fetch() writes it (fetch thread in
        # the overlapped path), _weigh_remote reads it AFTER the fetch is
        # joined, so the handoff is ordered.  1.0 (fully trusted) is a
        # bit-exact no-op on alpha.
        # dpwalint: double_buffered(_pending_trust_scale) -- written by the fetch leg before finish() joins it; _weigh_remote reads strictly after the join
        self._pending_trust_scale = 1.0
        # Local replica view for screening + the zero-energy guard:
        # stashed by publish() (publish always precedes fetch in a round).
        # dpwalint: double_buffered(_local_vec) -- swap-on-publish: _publish rebinds a fresh array, readers see the old or new ref, never a torn write; straddling prefetches re-screen via _last_clock
        self._local_vec: Optional[np.ndarray] = None
        # dpwalint: double_buffered(_local_norm) -- rebound alongside _local_vec under the same swap-on-publish discipline
        self._local_norm: Optional[float] = None
        self.interp = make_interpolation(
            config.interpolation,
            max_abs_loss=(
                config.recovery.rescue_bound() if config.recovery.enabled else None
            ),
            trust_scale=(
                self._trust_alpha_scale if self.trust is not None else None
            ),
        )
        self._wire_bf16 = config.protocol.wire_dtype == "bf16"
        self._wire_int8 = config.protocol.wire_dtype == "int8"
        if self._wire_bf16 and ml_dtypes is None:  # pragma: no cover
            raise RuntimeError("wire_dtype bf16 requires ml_dtypes")
        # Top-k delta codec (protocol.wire_codec: topk): the published
        # frame carries only the k largest-|residual| coordinates; the
        # encoder's error-feedback base guarantees dropped coordinates
        # accumulate and ship later.  Takes precedence over wire_dtype
        # for the gossip frame (the value-block precision is
        # protocol.topk_values); STATE/relay verbs are unaffected.
        self._wire_topk = config.protocol.wire_codec == "topk"
        self._topk_encoder = None
        if self._wire_topk:
            from dpwa_tpu.ops.quantize import TopkEncoder

            self._topk_encoder = TopkEncoder(
                config.protocol.topk_fraction,
                config.protocol.topk_values,
            )
        # Sharded gossip (shard.k > 1, docs/wire.md): each publish ships
        # ONE contiguous shard of the replica — the one the per-epoch
        # shard_draw permutation assigns to the publish clock — wrapped
        # in the code-6 preamble around the inner wire_dtype/wire_codec
        # encoding, and the merge touches only that slice.  k == 1 (or
        # an absent shard: block) keeps every branch below untaken and
        # the frames byte-identical to a pre-shard build.
        self._shard_k = config.shard.k
        self._shard_on = config.shard.k > 1
        # Top-k-within-shard keeps one error-feedback encoder PER shard:
        # the base tracks "what the ring was told about this slice", and
        # slices ship on independent cadences, so a shared base would
        # smear one shard's residuals into another's selection.
        self._shard_topk_encoders: Dict[int, object] = {}
        # Per-epoch shard-visit permutation memo (one threefry draw per
        # k rounds instead of per publish): (epoch, perm ndarray).
        self._shard_perm: Optional[Tuple[int, np.ndarray]] = None
        # The CURRENT fetch's shard slice bounds, consumed by
        # _merge_remote so every merge substrate lerps ONLY [lo, hi)
        # and copies the other k-1 slices bit-exactly ((1-a)x + ax is
        # NOT x in f32).  None for dense/topk/full-vector fetches.
        # dpwalint: double_buffered(_pending_shard) -- written by the fetch leg alongside _pending_trust_scale before finish() joins it; the merge reads strictly after the join
        self._pending_shard: Optional[Tuple[int, int]] = None
        # Device merge mode (docs/device.md): exchange_on_device flips
        # _sparse_consume around its _round so _consume_fetch keeps
        # sparse frames SPARSE — no host densify; the fused scatter /
        # dynamic-slice kernels splice on the device instead.  The
        # pending support rides next to _pending_shard under the same
        # double-buffer discipline.
        # dpwalint: double_buffered(_pending_topk) -- written by the fetch leg alongside _pending_shard before finish() joins it; the device merge reads strictly after the join
        self._pending_topk: Optional[Tuple[np.ndarray, np.ndarray]] = None
        # dpwalint: double_buffered(_sparse_consume) -- flipped by the round driver strictly before the fetch starts and restored strictly after finish() joins it; the fetch leg only reads inside that window
        self._sparse_consume = False
        # Device-resident replica handle, cached across rounds so the
        # host mirror (lazy readback) survives between exchanges.
        self._dev_replica = None
        # Per-shard wire accounting under _stats_lock: frames and bytes
        # per shard index, behind wire_snapshot()["shard"] and the
        # health_report --wire coverage columns.
        self._shard_tally: Dict[int, Dict[str, int]] = {}
        # Per-publish wire accounting: actual on-wire payload bytes vs
        # the dense f32 size, behind the ``compression_ratio`` health
        # column and bench.py's codec sweep.  Guarded by _stats_lock:
        # the training thread tallies while the healthz / metrics-scrape
        # threads read multi-key snapshots (unlocked, a scrape could see
        # frames from one publish and bytes from another — or hit a dict
        # mutated mid-iteration).
        self._stats_lock = threading.Lock()
        self._wire_tally = {"frames": 0, "wire_bytes": 0, "dense_bytes": 0}
        # Double-buffered prefetch pipeline (protocol.overlap_prefetch):
        # round t+1's partner fetch streams on a background slot while
        # round t's decode -> trust-screen -> merge runs.  One slot:
        # {step, sched, partner, remapped, expected_nbytes, thread, box,
        #  t_end} — thread is None when the slot round does not
        # participate (self-pair / masked).
        self._prefetch_on = config.protocol.overlap_prefetch
        self._prefetch_slot: Optional[dict] = None
        self._pipe_last_entry: Optional[float] = None
        self._overlap = {
            "rounds": 0, "prefetched": 0, "straddled": 0,
            "fetch_s": 0.0, "join_wait_s": 0.0,
            "inflight_s": 0.0, "round_s": 0.0,
        }
        # Observability plane (dpwa_tpu/obs/, docs/observability.md):
        # round tracer, replica-sketch board, /metrics registry.  All
        # None when the obs: block is off — the hot path then takes no
        # obs branches, adds no timing calls, and publishes frames
        # bit-identical to an obs-free build.
        obs_cfg = config.obs
        self.tracer = None
        if obs_cfg.trace:
            from dpwa_tpu.obs.trace import Tracer

            self.tracer = Tracer(
                self.me,
                every=obs_cfg.trace_every,
                path=obs_cfg.trace_path,
                max_records=obs_cfg.trace_max_records,
            )
        self.sketchboard = None
        if obs_cfg.sketch:
            from dpwa_tpu.obs.sketch import SketchBoard

            self.sketchboard = SketchBoard(self.me, k=obs_cfg.sketch_k)
        # Published DPWT sections and fetch-side trailer reads gate on
        # either facility (the trace id is free once the section exists).
        self._obs_wire = obs_cfg.trace or obs_cfg.sketch
        self._trace_id: Optional[str] = None
        self._obs_trailer_cache: Optional[Tuple[int, bytes]] = None
        self.metrics_registry = None
        if obs_cfg.metrics:
            from dpwa_tpu.obs.prometheus import MetricsRegistry

            self.metrics_registry = MetricsRegistry()
        # Incident plane + black-box flight recorder (docs/incidents.md):
        # online detectors over the signals the other planes already
        # produce, correlated into open→update→resolved incidents, plus
        # a bounded last-N-rounds ring dumped on crash/incident/demand.
        # Both None when off — the round boundary then takes no extra
        # branches and no timing calls (zero-cost-when-disabled).
        self.incidents = None
        if obs_cfg.incidents:
            from dpwa_tpu.obs.incidents import IncidentPlane

            self.incidents = IncidentPlane(
                self.me, len(config.nodes), obs_cfg,
                topology=self.topology,
            )
        self.flight = None
        if obs_cfg.recorder:
            from dpwa_tpu.obs.recorder import FlightRecorder

            self.flight = FlightRecorder(
                self.me,
                rounds=obs_cfg.recorder_rounds,
                path=obs_cfg.recorder_path,
            )
            self.flight.arm_crash_dump()
        # Event interception: when the incident plane (or recorder) is
        # armed the round hook drains membership/trust events so
        # detectors see them the round they happen; adapters keep seeing
        # every event through pop_*_events reading these buffers.
        self._membership_event_buf: list = []
        self._trust_event_buf: list = []
        self._obs_round_entry_t: Optional[float] = None
        spec = config.nodes[self.me]
        # Fetcher-side flow control: the per-peer latency estimator that
        # derives adaptive cumulative deadlines and hedge launch points.
        # None when the flowctl block is disabled — every fetch then
        # runs on the static protocol.timeout_ms exactly as before.
        self._estimator: Optional[DeadlineEstimator] = (
            DeadlineEstimator(
                config.flowctl, timeout_ms=config.protocol.timeout_ms
            )
            if config.flowctl.enabled
            else None
        )
        # Kept when chaos is on so the FETCHING side can honor injected
        # partitions (the serving side cannot know who is connecting).
        self._chaos_engine = None
        if config.chaos.enabled:
            # Chaos wraps the Rx server (fault injection needs
            # per-connection control of the serve path); the import is
            # deferred because health.chaos imports this module.  Both
            # Rx servers inject: the threaded wrapper rewrites frames in
            # its serve loop, the reactor subclass rewrites them at
            # _serve_blob time — same pure mutation functions, so the
            # served bytes are identical (tests/test_fleet.py pins it).
            from dpwa_tpu.health.chaos import (
                ChaosEngine,
                ChaosPeerServer,
                ChaosReactorPeerServer,
            )

            self._chaos_engine = ChaosEngine(config.chaos, self.me)
            if config.protocol.rx_server == "reactor":
                self.server = ChaosReactorPeerServer(
                    spec.host, spec.port, self._chaos_engine,
                    flowctl=config.flowctl,
                )
            else:
                self.server = ChaosPeerServer(
                    spec.host, spec.port, self._chaos_engine,
                    flowctl=config.flowctl,
                )
        elif config.protocol.rx_server == "reactor":
            # Single-threaded event-loop Rx (docs/transport.md): same
            # wire bytes and admission semantics as PeerServer, with
            # the connection cap lifted to flowctl.reactor_max_
            # connections.  Deferred import: reactor.py imports this
            # module for the frame builders.
            from dpwa_tpu.parallel.reactor import ReactorPeerServer

            self.server = ReactorPeerServer(
                spec.host, spec.port, flowctl=config.flowctl
            )
        elif (
            config.recovery.enabled
            or config.flowctl.enabled
            or config.obs.trace
            or (config.health.enabled and config.membership.enabled)
        ):
            # STATE serving (peer-assisted bootstrap), the RELAY probe
            # verb (indirect membership probing), and flowctl admission
            # (DPWB shedding, token pacing, loris eviction) live in the
            # Python Rx server only — the native C++ loop speaks just
            # the blob protocol.  Same forcing rationale as chaos.
            self.server = PeerServer(
                spec.host, spec.port, flowctl=config.flowctl
            )
        else:
            self.server = make_peer_server(
                spec.host, spec.port, flowctl=config.flowctl
            )
        if self.tracer is not None and hasattr(
            self.server, "obs_serve_hook"
        ):
            # Serve-side spans: only the Python Rx servers (threaded
            # PeerServer and the reactor — both expose the hook attr)
            # can time their sends (obs.trace forces them above).
            # Under chaos the serve path bypasses _serve_blob and the
            # wrapper has no hook, so chaos runs trace the fetcher
            # side only.
            self.server.obs_serve_hook = self.tracer.note_serve
        self._ports = {
            i: (n.host, n.port) for i, n in enumerate(config.nodes)
        }
        # Peer-health control plane: every fetch outcome feeds the
        # scoreboard; quarantined partners are remapped in
        # _resolve_partner.  health.enabled=False restores the seed's
        # raw skip-on-timeout behavior exactly.
        self.scoreboard: Optional[Scoreboard] = (
            Scoreboard(
                len(config.nodes), self.me, config.health,
                seed=self.schedule.seed,
            )
            if config.health.enabled
            else None
        )
        # Epidemic membership rides on the scoreboard: digests merge
        # into the same per-peer records the fetch outcomes feed.
        self.membership = None
        if self.scoreboard is not None and config.membership.enabled:
            from dpwa_tpu.membership.manager import MembershipManager

            leader_board = None
            if self.topology is not None:
                # The board's seed must be the topology's leader_seed —
                # the SAME draw build_hier_schedule compiled the term-0
                # wide-area slots from — so digest-adopted successions
                # and the static pool agree on who term 0's leaders are.
                from dpwa_tpu.hier.leader import LeaderBoard

                leader_board = LeaderBoard(
                    self.topology, seed=config.topology.leader_seed
                )
            self.membership = MembershipManager(
                len(config.nodes), self.me, self.scoreboard,
                config.membership, seed=self.schedule.seed,
                topology=self.topology, leader_board=leader_board,
            )
            # Churn hardening: when the manager evicts a dead peer it
            # prunes the scoreboard itself; the trust EWMAs/windows and
            # the flowctl deadline windows are pruned through these
            # listeners so no plane holds O(everyone-ever-seen) state.
            if self.trust is not None:
                self.membership.add_evict_listener(self.trust.evict_peer)
            if self._estimator is not None:
                self.membership.add_evict_listener(
                    self._estimator.evict_peer
                )
            if self.membership.partial is not None:
                # Bounded partial views (membership.view): the LRU
                # state cap must never silently drop a collapsed-trust
                # verdict, and trust snapshots switch to tracked-map
                # iteration (len(peers) == N no longer holds).
                if self.trust is not None:
                    self.membership.add_cap_protector(
                        self.trust.is_collapsed
                    )
                    self.trust.enable_capped_snapshots()
        # dpwalint: double_buffered(_last_digest_nbytes) -- a single int rebound whole by the publish path; the healthz snapshot reads the old or new value, never a torn write (stale-but-consistent telemetry)
        self._last_digest_nbytes = 0
        if self.trust is not None and self.scoreboard is not None:
            # Collapsed trust feeds the scoreboard as ``untrusted``
            # probes — the quarantine path for a persistently-suspect
            # peer no single rejection condemns.
            self.trust.attach_scoreboard(self.scoreboard)
        # Self-tuning wire (docs/tune.md): the per-link degradation
        # controller that walks the frozen codec ladder — escalating
        # compression on wire-bound links, backing off when the sketch
        # plane shows convergence stalling, and shedding FIDELITY (not
        # rounds) at a DEGRADED partner.  None when tune.enabled is off:
        # every publish then takes the original static-codec branches
        # and the frames stay byte-identical.
        self._tuner = None
        # Per-(link, shard) top-k error-feedback encoders and the last
        # effective rung served per link — a rung change drops the
        # accumulated residual base (ops/quantize.TopkEncoder.retune),
        # because it was measured against what the OLD codec told the
        # ring.  Training-thread-only state, like _topk_encoder.
        self._tune_topk_encoders: Dict[Tuple[int, int], object] = {}
        self._tune_last_rung: Dict[int, int] = {}
        self._tune_plan_cache: Optional[tuple] = None
        if config.tune.enabled:
            from dpwa_tpu.tune import LinkTuner, start_rung_for

            self._tuner = LinkTuner(config.tune, seed=self.schedule.seed)
            # Anchor at the static YAML rung: a link that never shows
            # evidence publishes exactly what the config asked for.
            self._tuner.set_start_rung(start_rung_for(
                "topk" if self._wire_topk else "dense",
                config.protocol.wire_dtype,
                config.protocol.topk_fraction,
            ))
            if self.membership is not None:
                # Same churn-hardening contract as trust/flowctl: an
                # evicted peer's ladder state dies with it; a rejoiner
                # re-enters at the static start rung.
                self.membership.add_evict_listener(self._tuner.evict_peer)
        self.healthz = None
        if config.health.enabled and config.health.healthz_port is not None:
            from dpwa_tpu.health.endpoint import HealthzServer

            extra_routes: dict = {}
            if self.incidents is not None:
                extra_routes["/incidents"] = self.incidents.snapshot
            if self.flight is not None:
                extra_routes["/flightdump"] = self._flight_dump_route
            self.healthz = HealthzServer(
                self.health_snapshot, spec.host, config.health.healthz_port,
                metrics_fn=(
                    self.metrics_registry.render
                    if self.metrics_registry is not None
                    else None
                ),
                extra_routes=extra_routes or None,
            )
        # Bookkeeping for metrics/adapters: last fetch outcome and the
        # last round's partner resolution (schedule vs. health remap).
        # dpwalint: double_buffered(last_fetch) -- rebound as one fresh dict per fetch; readers take the whole ref (stale-but-consistent telemetry)
        self.last_fetch: dict = {}
        self.last_round: dict = {}
        # Recovery bookkeeping: the clock we last published (for the
        # re-admission freshness check) and a pending re-sync advice
        # record the adapter pops when a readmitted peer's clock shows
        # WE are the stale replica.  _last_clock is guarded by
        # _clock_lock: the training thread writes it in _publish while a
        # prefetch/overlap daemon leg may concurrently read it through
        # _link_blocked (chaos partitions are keyed on the publish
        # clock).
        self._clock_lock = threading.Lock()
        self._last_clock = 0.0
        self.resync_advice: Optional[dict] = None
        # Barrier-free async round plane (docs/async.md): when
        # protocol.async_rounds is enabled an AsyncExchangeEngine wraps
        # this transport — publish decoupled from merge, frames queueing
        # per peer and merging staleness-damped whenever ready — and
        # arms _async_guard, the per-peer highest-merged-publish-clock
        # map that _consume_fetch uses to drop duplicate deliveries (a
        # frame both prefetched and queued async must merge exactly
        # once).  Both stay None when the block is off: the lock-step
        # paths then take no async branches and produce byte-identical
        # frames and merges.
        # dpwalint: double_buffered(_async_guard) -- written only by the training thread inside _consume_fetch; async fetch slots read a consistent snapshot at admission time (a miss is re-screened at consume)
        self.async_engine = None
        self._async_guard: Optional[Dict[int, float]] = None
        if config.protocol.async_rounds.enabled:
            # Deferred import: async_loop imports schedules/detector and
            # is wired onto this transport, not the other way around.
            from dpwa_tpu.parallel.async_loop import AsyncExchangeEngine

            AsyncExchangeEngine(self)
        if self._chaos_engine is not None:
            # Compile-once discipline for the control plane: the threefry
            # draws (fallback/relay/heal/...) jit on first call, and left
            # lazy that compile fires at the first FAILURE — stalling only
            # the replicas having the incident.  Under chaos the injected
            # windows are keyed on each process's own publish clock, so a
            # seconds-long stall on half the ring desynchronizes the very
            # faults being injected; warm the draws off the step clock.
            # Without chaos the stall is a one-time latency blip and lazy
            # compile wins: a restarted worker must reach its bootstrap
            # probes before the survivors move on, not sit in jit.
            from dpwa_tpu.parallel.schedules import warm_control_draws

            warm_control_draws(self.schedule.seed, self.me)
        if self.metrics_registry is not None:
            # Last: collectors read plane snapshots, so every plane must
            # exist before its collector registers.
            self._register_metrics(self.metrics_registry)

    @property
    def port(self) -> int:
        return self.server.port

    def set_peer_port(self, index: int, port: int) -> None:
        """Tests use OS-assigned ports (port 0); let the driver rewire."""
        host, _ = self._ports[index]
        self._ports[index] = (host, port)

    def publish(self, vec: np.ndarray, clock: float, loss: float) -> None:
        tr = self.tracer
        if tr is None:
            self._publish(vec, clock, loss)
            return
        t0 = time.monotonic()
        try:
            self._publish(vec, clock, loss)
        finally:
            tr.mark("publish", time.monotonic() - t0)
            tr.set(trace_id=self._trace_id)

    def _make_obs_trailer(self, vec: np.ndarray, clock: float) -> bytes:
        """The DPWT section for this publish: trace id + (optionally)
        the replica sketch.  The norm estimate is the sketch's own L2
        norm — unbiased for the replica norm under Rademacher signs, so
        it costs no extra pass over the parameters."""
        from dpwa_tpu.obs.wire import encode_obs

        seq = int(clock) & 0xFFFFFFFF
        self._trace_id = f"{self.me}:{seq}"
        # One trailer per publish clock: the round protocol republishes
        # the same replica under the same clock (driver publish, then
        # the publish inside ``_round``), and seq granularity is the
        # estimator's contract anyway — so a same-seq republish reuses
        # the encoded section instead of paying a second sketch pass on
        # the exchange hot path.
        cached = self._obs_trailer_cache
        if cached is not None and cached[0] == seq:
            return cached[1]
        sketch = None
        norm = 0.0
        board = self.sketchboard
        if (
            board is not None
            and vec.dtype == np.float32
            and int(clock) % self.config.obs.sketch_every == 0
        ):
            from dpwa_tpu.obs.sketch import replica_sketch

            sketch = replica_sketch(vec, self.schedule.seed, board.k)
            norm = float(np.dot(sketch, sketch)) ** 0.5
            board.note_local(seq, sketch)
        blob = encode_obs(self.me, seq, norm, sketch)
        self._obs_trailer_cache = (seq, blob)
        return blob

    def _publish(self, vec: np.ndarray, clock: float, loss: float) -> None:
        # Compressed wire: only the PUBLISHED (served) copy is compressed
        # — bf16 halves the wire bytes, int8 quarters them; the local
        # replica stays f32 (mirrors the ICI transport, which compresses
        # the shipped copy before the collective).  int8 is quantized
        # with stochastic rounding keyed on (seed, clock, me) and
        # dequantized by the FETCHING side (ops/quantize.py).
        with self._clock_lock:
            self._last_clock = float(clock)
        f32_vec = None  # contiguous-f32 view of vec, stashed below
        if (
            self.trust is not None
            or self._wire_topk
            or self._shard_on
            # The tuner can move any link onto a top-k rung at runtime,
            # and a fetched top-k frame can only densify against the
            # stashed replica — so the self-tuning wire always stashes.
            or self._tuner is not None
            or (
                self.config.recovery.enabled
                and self.config.recovery.min_param_norm_ratio > 0.0
            )
        ) and vec.dtype in (np.float32, np.float64):
            # Stash the f32 replica this round merges against: trust
            # screening and the zero-energy guard both compare the
            # incoming payload to what we just published — and a top-k
            # or shard frame can only densify against it.
            self._local_vec = np.ascontiguousarray(vec, dtype=np.float32)
            f32_vec = self._local_vec
            self._local_norm = float(
                np.linalg.norm(self._local_vec.astype(np.float64))
            )
        # Epidemic piggyback: the current membership digest rides every
        # published frame as the optional trailer (_frame docstring).
        digest = (
            self.membership.encode(int(clock))
            if self.membership is not None
            else None
        )
        if digest is not None:
            # Partial-view observability: the actual digest bytes this
            # frame carries (O(digest_sample) under membership.view).
            self._last_digest_nbytes = len(digest)
        # Observability piggyback: trace id + replica sketch ride AFTER
        # the digest (ordering is the back-compat contract — see _frame).
        # When trust/topk/guard already stashed a contiguous-f32 copy of
        # this vec, sketch THAT — it saves a second full-replica pass
        # (and a device transfer when vec is a jax array).
        obs = (
            self._make_obs_trailer(
                vec if f32_vec is None else f32_vec, clock
            )
            if self._obs_wire
            else None
        )
        tid = self._trace_id if obs is not None else None
        # Self-tuning wire: one controller decision per publish clock
        # for the scheduled partner's link; None when the tuner is off
        # (the static branches below then run untouched).
        tune_sel = (
            self._tune_plan(int(clock))
            if self._tuner is not None and vec.dtype == np.float32
            else None
        )
        if self._shard_on and vec.dtype == np.float32:
            # Sharded wire (code 6): the obs trailer above was built
            # from the FULL replica — the sketch plane's rel_rms stays
            # full-vector so convergence accounting is honest even
            # though the frame below carries one slice.
            self._publish_shard(vec, f32_vec, clock, loss, digest, obs,
                                tid, tune_sel)
            return
        if tune_sel is not None:
            self._publish_tuned(
                vec, f32_vec, clock, loss, digest, obs, tid, tune_sel
            )
            return
        if self._wire_topk and vec.dtype == np.float32:
            payload = self._topk_encoder.encode(
                np.ascontiguousarray(vec, dtype=np.float32).reshape(-1),
                self.schedule.seed, clock, self.me,
            )
            self._note_published(int(payload.size), int(vec.size) * 4)
            self.server.publish(
                payload, clock, loss, code=_TOPK_DELTA, digest=digest,
                obs=obs, trace_id=tid,
            )
            return
        if self._wire_int8 and vec.dtype == np.float32:
            from dpwa_tpu.ops.quantize import encode_int8_payload

            payload = encode_int8_payload(
                vec, self.schedule.seed, clock, self.me
            )
            self._note_published(int(payload.size), int(vec.size) * 4)
            self.server.publish(
                payload, clock, loss, code=_INT8_CHUNKED, digest=digest,
                obs=obs, trace_id=tid,
            )
            return
        if self._wire_bf16 and vec.dtype == np.float32:
            vec = vec.astype(_DTYPES[3])
        self._note_published(int(vec.nbytes), int(vec.size) * 4)
        self.server.publish(vec, clock, loss, digest=digest, obs=obs,
                            trace_id=tid)

    def _tune_plan(self, step: int):
        """One ladder decision per publish clock: resolve the scheduled
        partner (the link this frame is FOR under pairwise gossip),
        overlay the DEGRADED fidelity shed, and return ``(link, rung)``
        — or None when this clock pairs the node with itself.

        Memoized per clock like the obs trailer: the round protocol
        republishes the same replica under the same clock (driver
        publish, then the publish inside ``_round``), and the dwell
        clock must advance once per ROUND, not once per frame."""
        cached = self._tune_plan_cache
        if cached is not None and cached[0] == step:
            return cached[1]
        link = self.schedule.partner(step, self.me)
        sel = None
        if link != self.me:
            sb = self.scoreboard
            degraded = bool(
                sb is not None and sb.is_degraded(link, step)
            )
            rung = self._tuner.plan(link, step, degraded=degraded)
            eff = self._tuner.effective_rung(link)
            last = self._tune_last_rung.get(link)
            if last is not None and last != eff:
                # Rung change: drop the error-feedback base of every
                # top-k encoder serving this link — the accumulated
                # residual was measured against what the OLD codec told
                # the ring, and replaying it through the new one would
                # double-ship (or re-ship stale) coordinates.
                for key, enc in self._tune_topk_encoders.items():
                    if key[0] != link:
                        continue
                    if rung.codec == "topk":
                        enc.retune(rung.topk_fraction)
                    else:
                        enc.reset()
            self._tune_last_rung[link] = eff
            sel = (link, rung)
        self._tune_plan_cache = (step, sel)
        return sel

    def _tune_topk_encoder(self, link: int, fraction: float, shard: int):
        """The (link, shard) error-feedback encoder at ``fraction``,
        created on first use and retuned (fraction swap + base reset)
        when the ladder moved it to a different top-k rung."""
        key = (link, shard)
        enc = self._tune_topk_encoders.get(key)
        if enc is None:
            from dpwa_tpu.ops.quantize import TopkEncoder

            enc = TopkEncoder(
                fraction, self.config.protocol.topk_values
            )
            self._tune_topk_encoders[key] = enc
        elif enc.fraction != fraction:
            enc.retune(fraction)
        return enc

    def _publish_tuned(
        self, vec: np.ndarray, f32_vec: Optional[np.ndarray],
        clock: float, loss: float, digest, obs, tid, sel,
    ) -> None:
        """Publish one frame at the link's current ladder rung.  Frames
        stay self-describing (code byte), so the fetching side decodes
        whatever rung this side chose without negotiation."""
        link, rung = sel
        flat = (
            f32_vec
            if f32_vec is not None
            else np.ascontiguousarray(vec, dtype=np.float32)
        ).reshape(-1)
        if rung.codec == "topk":
            enc = self._tune_topk_encoder(link, rung.topk_fraction, -1)
            payload = enc.encode(
                flat, self.schedule.seed, clock, self.me
            )
            self._note_published(int(payload.size), int(flat.size) * 4)
            self.server.publish(
                payload, clock, loss, code=_TOPK_DELTA, digest=digest,
                obs=obs, trace_id=tid,
            )
            return
        if rung.dtype == "int8":
            from dpwa_tpu.ops.quantize import encode_int8_payload

            payload = encode_int8_payload(
                flat, self.schedule.seed, clock, self.me
            )
            self._note_published(int(payload.size), int(flat.size) * 4)
            self.server.publish(
                payload, clock, loss, code=_INT8_CHUNKED, digest=digest,
                obs=obs, trace_id=tid,
            )
            return
        out = flat.astype(_DTYPES[3]) if rung.dtype == "bf16" else flat
        self._note_published(int(out.nbytes), int(flat.size) * 4)
        self.server.publish(out, clock, loss, digest=digest, obs=obs,
                            trace_id=tid)

    def _observed_wire_rung(self, sp, vec, nbytes: int) -> int:
        """Ladder rung the partner encoded its last frame at, for
        mirroring.  Sparse payloads are explicit about their codec;
        dense frames are classified by the wire-bytes-per-element ratio
        (the code byte is consumed inside fetch_blob_full, and
        f32/bf16/int8 sit well apart at ~4/2/1 bytes per element).
        Shard frames mirror the INNER codec — shard width is never on
        the ladder."""
        from dpwa_tpu.ops.shard import ShardPayload
        from dpwa_tpu.tune import start_rung_for

        if sp is not None:
            if isinstance(sp, ShardPayload):
                inner = sp.inner
                if not isinstance(inner, np.ndarray):
                    lo, hi = sp.bounds
                    frac = float(inner.values.size) / max(1, hi - lo)
                    return start_rung_for("topk", "f32", frac)
                return {0: 0, 3: 1, 4: 2}.get(sp.inner_code, 0)
            frac = float(sp.values.size) / max(1, int(sp.n))
            return start_rung_for("topk", "f32", frac)
        n = max(1, int(getattr(vec, "size", 1)))
        ratio = float(nbytes) / n
        if ratio < 1.5:
            return 2
        if ratio < 3.0:
            return 1
        return 0

    def _shard_index(self, step: int, k: int) -> int:
        """This publish clock's shard under the per-epoch permutation
        (schedules.shard_draw semantics), with the epoch's permutation
        memoized — one threefry draw per k rounds, not per publish."""
        from dpwa_tpu.parallel.schedules import shard_permutation

        epoch, pos = divmod(int(step), k)
        memo = self._shard_perm
        if memo is None or memo[0] != epoch:
            memo = (epoch, shard_permutation(self.schedule.seed, epoch, k))
            self._shard_perm = memo
        return int(memo[1][pos])

    def _publish_shard(
        self, vec: np.ndarray, f32_vec: Optional[np.ndarray],
        clock: float, loss: float, digest, obs, tid,
        tune_sel=None,
    ) -> None:
        """Serve this round's shard: slice -> inner wire_dtype /
        wire_codec encoding -> SHARD_HDR preamble -> code-6 frame.  The
        codecs compose per slice: top-k selects within the shard (one
        error-feedback encoder per shard), the int8 scale tables restart
        at the slice boundary because chunking is per-payload.  Shard k
        itself is never tuned (both ends must agree on the round-robin
        permutation); with the tuner on, the ladder rung selects the
        INNER codec of the slice instead."""
        from dpwa_tpu.ops import shard as _shard_ops

        flat = (
            f32_vec
            if f32_vec is not None
            else np.ascontiguousarray(vec, dtype=np.float32)
        ).reshape(-1)
        k = self._shard_k
        idx = self._shard_index(int(clock), k)
        lo, hi = _shard_ops.shard_bounds(flat.size, k, idx)
        sl = np.ascontiguousarray(flat[lo:hi])
        if tune_sel is not None:
            link, rung = tune_sel
            if rung.codec == "topk":
                enc = self._tune_topk_encoder(
                    link, rung.topk_fraction, idx
                )
                inner = enc.encode(
                    sl, self.schedule.seed, clock, self.me
                )
                inner_code = _TOPK_DELTA
            elif rung.dtype == "int8":
                from dpwa_tpu.ops.quantize import encode_int8_payload

                inner = encode_int8_payload(
                    sl, self.schedule.seed, clock, self.me
                )
                inner_code = _INT8_CHUNKED
            elif rung.dtype == "bf16":
                inner = sl.astype(_DTYPES[3]).view(np.uint8)
                inner_code = _pc.PAYLOAD_BF16
            else:
                arr = (
                    sl if sl.dtype == np.dtype("<f4")
                    else sl.astype("<f4")
                )
                inner = arr.view(np.uint8)
                inner_code = _pc.PAYLOAD_F32
        elif self._wire_topk:
            enc = self._shard_topk_encoders.get(idx)
            if enc is None:
                from dpwa_tpu.ops.quantize import TopkEncoder

                enc = TopkEncoder(
                    self.config.protocol.topk_fraction,
                    self.config.protocol.topk_values,
                )
                self._shard_topk_encoders[idx] = enc
            inner = enc.encode(sl, self.schedule.seed, clock, self.me)
            inner_code = _TOPK_DELTA
        elif self._wire_int8:
            from dpwa_tpu.ops.quantize import encode_int8_payload

            inner = encode_int8_payload(
                sl, self.schedule.seed, clock, self.me
            )
            inner_code = _INT8_CHUNKED
        elif self._wire_bf16:
            # astype is the required downcast; the uint8 reinterpret is
            # a free view (the old frombuffer(tobytes()) round-trip
            # copied the slice twice).
            inner = sl.astype(_DTYPES[3]).view(np.uint8)
            inner_code = _pc.PAYLOAD_BF16
        else:
            arr = sl if sl.dtype == np.dtype("<f4") else sl.astype("<f4")
            inner = arr.view(np.uint8)
            inner_code = _pc.PAYLOAD_F32
        payload = _shard_ops.encode_shard_payload(
            inner, flat.size, k, idx, inner_code
        )
        self._note_published(
            int(payload.size), int(flat.size) * 4, shard=idx
        )
        self.server.publish(
            payload, clock, loss, code=_SHARD, digest=digest, obs=obs,
            trace_id=tid,
        )

    def _note_published(
        self, wire_bytes: int, dense_bytes: int,
        shard: Optional[int] = None,
    ) -> None:
        with self._stats_lock:
            t = self._wire_tally
            t["frames"] += 1
            t["wire_bytes"] += wire_bytes
            t["dense_bytes"] += dense_bytes
            if shard is not None:
                st = self._shard_tally.get(shard)
                if st is None:
                    st = self._shard_tally[shard] = {
                        "frames": 0, "wire_bytes": 0,
                    }
                st["frames"] += 1
                st["wire_bytes"] += wire_bytes

    # dpwalint: thread_root(overlap-fetch)
    def fetch(
        self,
        peer_index: int,
        timeout_ms: Optional[int] = None,
        step: Optional[int] = None,
    ) -> Optional[Tuple[np.ndarray, float, float]]:
        return self._consume_fetch(
            self._wire_fetch(peer_index, timeout_ms, step), step
        )

    def _wire_fetch(
        self,
        peer_index: int,
        timeout_ms: Optional[int] = None,
        step: Optional[int] = None,
    ) -> tuple:
        """The WIRE leg of a fetch — connect, stream, frame-validate —
        with none of the consuming-side semantics (densify, guard, trust,
        scoreboard, estimator).  Split from :meth:`_consume_fetch` so the
        prefetch pipeline can stream round t+1's bytes on a background
        thread while round t is still screening: only byte movement may
        run ahead; every judgement about a payload happens at consume
        time against the replica it would actually merge into.

        Returns the 9-tuple ``(winner_peer, got, outcome, latency_s,
        nbytes, digest, obs, hedged, hedge_winner)``."""
        if timeout_ms is None:
            timeout_ms = self.config.protocol.timeout_ms
        if self._link_blocked(peer_index):
            # Injected partition, fetcher side: the chaos harness blocks
            # this directed link, so no socket is even opened — the
            # round records a refused fetch, exactly what a firewalled
            # link produces.
            return (
                peer_index, None, Outcome.REFUSED, 0.0, 0, None, None,
                False, None,
            )
        if self._estimator is not None:
            # Flowctl path: the estimator's adaptive cumulative deadline
            # (falling back to timeout_ms while cold) plus at most one
            # hedged retry to the schedule's fallback partner once the
            # quantile budget lapses.  The winner slot may come back as
            # the FALLBACK peer — everything recorded by the consume
            # half (trust, guard, scoreboard, estimator) is then charged
            # to the peer whose payload actually merges; the losing leg
            # was already recorded inside _hedged_fetch.
            return self._hedged_fetch(peer_index, step, timeout_ms)
        host, port = self._ports[peer_index]
        got, outcome, latency_s, nbytes, digest, obs = fetch_blob_full(
            host, port, timeout_ms,
            min_bandwidth_bps=(
                self.config.protocol.min_wire_mb_per_s * 1e6
            ),
            want_digest=self.membership is not None,
            want_obs=self._obs_wire,
        )
        return (
            peer_index, got, outcome, latency_s, nbytes, digest, obs,
            False, None,
        )

    def _consume_fetch(
        self, raw: tuple, step: Optional[int]
    ) -> Optional[Tuple[np.ndarray, float, float]]:
        """The CONSUME leg: densify a sparse frame against the CURRENT
        local replica, then guard/trust/scoreboard/estimator — all
        charged to the consuming round's ``step``.  Under the prefetch
        pipeline the wire leg may have run a full round earlier; this is
        the publish-clock guard in structural form — a prefetched payload
        that straddled a local publish is screened against the replica
        that exists NOW, never against the one that existed at launch."""
        (
            peer_index, got, outcome, latency_s, nbytes, digest, obs,
            hedged, hedge_winner,
        ) = raw
        est = self._estimator
        tr = self.tracer
        timing = tr is not None and tr.active
        if timing:
            # The wire span is the leg's own streaming duration — under
            # prefetch it ran on a background slot a round earlier; the
            # blocking cost the caller actually paid is the join_wait
            # span marked by _prefetch_take.
            tr.mark("wire", latency_s)
        if obs is not None and (timing or self.sketchboard is not None):
            from dpwa_tpu.obs.wire import decode_obs

            frame = decode_obs(obs)
            if frame is not None:
                if timing:
                    tr.set(remote_trace_id=frame.trace_id)
                if self.sketchboard is not None and frame.sketch is not None:
                    self.sketchboard.note_remote(
                        frame.origin, frame.seq, frame.sketch, round=step
                    )
        if (
            self._async_guard is not None
            and got is not None
            and float(got[1])
            <= self._async_guard.get(peer_index, float("-inf"))
        ):
            # Async publish-clock dedup: this peer's publish clock (or a
            # newer one) already merged through SOME path — an async
            # queue drain, a prefetch slot, a hedge leg.  Whichever leg
            # re-delivered it, merging twice would double-count the
            # frame, so it is dropped here as the soft ``stale``
            # outcome before any decode/guard/trust work is spent.
            got = None
            outcome = Outcome.STALE
        codec = None
        wire_sp = None        # decoded sparse payload (rung mirroring)
        sparse_guard = None   # (values, local_selected) for the guard
        sparse_trust = None   # (indices, values) for trust screening
        trust_codec = None    # baseline family key (inner codec for shard)
        trust_shard = None    # shard index for per-(codec, shard) windows
        trust_local = None    # slice-local vectors for shard screening
        trust_remote = None
        # Double-buffered shard bounds: None for every dense/top-k frame
        # so the merge substrates fall through to the full-vector lerp;
        # a successfully decoded shard frame below overwrites it with
        # its [lo, hi) before finish() joins the round.
        self._pending_shard = None
        self._pending_topk = None
        if got is not None and not isinstance(got[0], np.ndarray):
            t_stage = time.monotonic() if timing else 0.0
            # Sparse frame: fetch_blob_full returns the decoded payload
            # object (TopkPayload or ShardPayload) in the vector slot;
            # only this side holds the replica it splices into.  No
            # stashed local replica (or a size mismatch after a reshard)
            # means the frame cannot be interpreted — classified
            # corrupt, never merged.
            from dpwa_tpu.ops.shard import ShardPayload

            sp = got[0]
            wire_sp = sp
            lv = self._local_vec
            if isinstance(sp, ShardPayload):
                if lv is None or int(lv.size) != int(sp.d):
                    got = None
                    outcome = Outcome.CORRUPT
                else:
                    lo, hi = sp.bounds
                    local_slice = np.ascontiguousarray(lv[lo:hi])
                    est_slice = sp.slice_estimate(local_slice)
                    inner = sp.inner
                    if not isinstance(inner, np.ndarray):
                        # top-k within the shard: guard/trust judge the
                        # SUPPORT, indices relative to the slice.
                        trust_codec = "topk"
                        local_sel = local_slice[
                            inner.indices.astype(np.intp)
                        ]
                        sparse_guard = (inner.values, local_sel)
                        sparse_trust = (inner.indices, inner.values)
                    else:
                        trust_codec = {
                            0: "f32", 3: "bf16", 4: "int8",
                        }.get(sp.inner_code, "dense")
                        # Zero-energy screening on the slice actually
                        # shipped — the densified remote shares k−1
                        # slices with the local replica, which would
                        # mask a silenced shard.
                        sparse_guard = (est_slice, local_slice)
                    codec = f"shard+{trust_codec}"
                    trust_shard = sp.shard_idx
                    # Trust compares slice against slice: cosine/norm on
                    # the densified FULL vector would sit near +1 by
                    # construction (k−1 shared slices) and dilute the
                    # byzantine signal k-fold.
                    trust_local = local_slice
                    trust_remote = est_slice
                    if self._sparse_consume:
                        # Device merge: ship the m-sized slice estimate
                        # straight to the dynamic-slice kernel — the
                        # full-vector densified copy never exists.
                        got = (est_slice, got[1], got[2])
                    else:
                        remote = lv.astype(np.float32, copy=True)
                        remote[lo:hi] = est_slice
                        got = (remote, got[1], got[2])
                    self._pending_shard = (lo, hi)
            elif lv is None or int(lv.size) != int(sp.n):
                got = None
                outcome = Outcome.CORRUPT
            else:
                codec = "topk"
                local_sel = lv[sp.indices.astype(np.intp)]
                if self._sparse_consume:
                    # Device merge: keep the support sparse for the
                    # scatter-lerp kernel.  Trust still screens the
                    # frame on its SUPPORT via payload_stats_sparse —
                    # the dense remote argument is only a shape check
                    # there, so the local replica stands in for the
                    # densified estimate bit-identically.  The guard
                    # judges the shipped values (sparse_guard) rather
                    # than a densified vector it would have to build.
                    got = (sp.values, got[1], got[2])
                    self._pending_topk = (sp.indices, sp.values)
                    trust_remote = lv
                else:
                    got = (sp.densify(lv), got[1], got[2])
                sparse_guard = (sp.values, local_sel)
                sparse_trust = (sp.indices, sp.values)
            if timing:
                tr.mark("decode", time.monotonic() - t_stage)
        if (
            self._tuner is not None
            and got is not None
            and peer_index != self.me
        ):
            # Rung mirroring: the frame just decoded tells us what rung
            # the partner encoded this link at — floor our own effective
            # rung with it so a one-sided throttle (where only the
            # partner's fetches observe slowness) still slims BOTH
            # directions of the pair.
            self._tuner.note_partner_rung(
                peer_index,
                self._observed_wire_rung(wire_sp, got[0], int(nbytes)),
            )
        reason = None
        if got is not None and self.config.recovery.enabled:
            # Divergence/poison guard: a frame can be perfectly formed
            # and still carry a sick replica (NaNs, exploded norm, an
            # insane advertised loss).  Reject BEFORE the merge and feed
            # the detector — a diverged peer is as unfit a partner as a
            # dead one.
            from dpwa_tpu.recovery.guard import validate_payload

            t_stage = time.monotonic() if timing else 0.0
            reason = validate_payload(
                got[0], got[2], self.config.recovery,
                local_norm=self._local_norm,
                sparse=sparse_guard,
            )
            if timing:
                tr.mark("guard", time.monotonic() - t_stage)
            if reason is not None:
                got = None
                outcome = Outcome.POISONED
        trust_info = None
        self._pending_trust_scale = 1.0
        if (
            got is not None
            and self.trust is not None
            and self._local_vec is not None
        ):
            # Trust screening runs on the DECODED f32 vector (the int8
            # wire path dequantized inside fetch_blob_full, bf16 casts
            # in payload_stats) — the payload is judged on what would
            # actually merge.  A top-k frame is judged on its SUPPORT
            # (payload_stats_sparse) under its own per-codec baselines.
            # A rejection is the ``untrusted`` outcome:
            # recorded below exactly like ``poisoned``, and — also like
            # poisoned — never gated behind indirect probing, since a
            # byzantine peer answers header probes perfectly.
            t_stage = time.monotonic() if timing else 0.0
            verdict, scale, tstats = self.trust.screen(
                peer_index,
                trust_remote if trust_remote is not None else got[0],
                got[1],
                trust_local if trust_local is not None else self._local_vec,
                round=step,
                codec=trust_codec or codec or "dense",
                sparse=sparse_trust,
                shard=trust_shard,
            )
            if timing:
                tr.mark("trust", time.monotonic() - t_stage)
            from dpwa_tpu.trust.manager import REJECTED

            trust_info = dict(
                tstats, verdict=verdict, alpha_scale=round(scale, 4)
            )
            if verdict == REJECTED:
                got = None
                outcome = Outcome.UNTRUSTED
            else:
                self._pending_trust_scale = scale
        self.last_fetch = {
            "peer": peer_index, "outcome": outcome,
            "latency_s": latency_s, "nbytes": nbytes,
        }
        if codec is not None:
            self.last_fetch["codec"] = codec
        if hedged:
            self.last_fetch["hedged"] = True
            self.last_fetch["hedge_winner"] = hedge_winner
        if reason is not None:
            self.last_fetch["poison_reason"] = reason
        if trust_info is not None:
            self.last_fetch["trust"] = trust_info
        if self.membership is not None and digest is not None:
            self.membership.merge(digest, round=step)
        if (
            self.membership is not None
            and self.scoreboard is not None
            and step is not None
            and outcome
            in (
                Outcome.TIMEOUT,
                Outcome.REFUSED,
                Outcome.SHORT_READ,
                Outcome.CORRUPT,
            )
            and self.config.membership.indirect_probes > 0
            and self.scoreboard.would_quarantine(peer_index, outcome)
        ):
            # SWIM indirect probing: this failure WOULD cross the
            # quarantine threshold on our evidence alone — before the
            # record below promotes the peer, ask drawn healthy relays
            # to probe it for us.  A single vouch decays our suspicion
            # (an asymmetric-link false positive); when every relay
            # agrees the peer is gone, nothing is fed and the record
            # promotes on the ordinary single-failure weight.  POISONED
            # is deliberately not gated: a diverged peer answers header
            # probes perfectly and every relay would vouch for it.
            self._indirect_probe(peer_index, step)
        if self.scoreboard is not None:
            self.scoreboard.record(
                peer_index, outcome,
                latency_s=latency_s, nbytes=nbytes, round=step,
            )
        if est is not None:
            # The estimator feeds on the FINAL classified outcome (after
            # guard/trust screening): a poisoned success must not teach
            # the deadline that the peer is healthy-fast.
            est.observe(
                peer_index, outcome, latency_s=latency_s, nbytes=nbytes
            )
        if self._async_guard is not None and got is not None:
            # Latch the merged publish clock AFTER every screen passed:
            # a guarded/untrusted frame never merged, so a later clean
            # re-delivery of the same clock must still be admissible.
            ck = float(got[1])
            if ck > self._async_guard.get(peer_index, float("-inf")):
                self._async_guard[peer_index] = ck
        return got

    def _fetch_leg(
        self, peer: int, deadline_ms: float, box: list, sock_box: list
    ) -> None:
        """One fetch leg of a (possibly hedged) flowctl fetch, run on a
        thread: appends the full 6-tuple to ``box``; ``sock_box`` lets
        the racing side cancel this leg by closing its socket."""
        host, port = self._ports[peer]
        box.append(
            fetch_blob_full(
                host, port, int(deadline_ms),
                min_bandwidth_bps=(
                    self.config.protocol.min_wire_mb_per_s * 1e6
                ),
                want_digest=self.membership is not None,
                sock_box=sock_box,
                want_obs=self._obs_wire,
            )
        )

    def _view_candidates(self) -> Optional[List[int]]:
        """The active partial view when ``membership.view`` is on, else
        None (draws range over all of ``nodes:`` — the legacy path)."""
        if self.membership is None:
            return None
        return self.membership.partner_candidates()

    def _remap_mask(self, candidates: Optional[List[int]], step: int):
        """Fallback-eligibility mask for ``remap_partner``: the full
        O(N) healthy mask on the legacy path, or an O(active) map over
        the view candidates."""
        if candidates is not None:
            return self.scoreboard.healthy_map(candidates, step)
        return self.scoreboard.healthy_mask(step)

    def _hedge_fallback(self, peer: int, step: int) -> Optional[int]:
        """The deterministic hedge target: the schedule's fallback draw
        over currently-healthy peers (the SAME draw a quarantine remap
        would make this round), or None when no distinct healthy
        candidate exists."""
        n = len(self.config.nodes)
        candidates = self._view_candidates()
        if self.scoreboard is not None:
            mask = self._remap_mask(candidates, step)
        else:
            mask = [True] * n
        fallback = self.schedule.remap_partner(
            step, self.me, peer, mask, candidates
        )
        if (
            fallback == self.me
            or fallback == peer
            or self._link_blocked(fallback)
        ):
            return None
        return int(fallback)

    @staticmethod
    def _close_leg(sock_box: list) -> None:
        for s in sock_box:
            try:
                s.close()
            except OSError:
                pass

    @staticmethod
    def _leg_result(box: list, elapsed: float) -> tuple:
        """A leg's 6-tuple result; a leg that died without reporting
        (should not happen — fetch_blob_full classifies every failure)
        degrades to a short_read instead of crashing the round."""
        if box:
            return box[0]
        return None, Outcome.SHORT_READ, elapsed, 0, None, None

    def _record_loser(
        self,
        peer: int,
        result: Optional[tuple],
        cancelled: bool,
        latency_s: float,
        step: Optional[int],
    ) -> None:
        """Feed the LOSING leg of a hedge race to the scoreboard and
        estimator.  A leg we cancelled by closing its socket surfaces a
        short_read/timeout ARTIFACT of our own close — recording that
        hard evidence would walk an honest slow peer into quarantine, so
        a cancelled leg records the low-weight ``slow`` outcome instead.
        A leg that genuinely finished records its real outcome."""
        if cancelled or result is None:
            outcome, lat, nbytes = Outcome.SLOW, latency_s, 0
        else:
            _got, outcome, lat, nbytes, _digest, _obs = result
        if self.scoreboard is not None:
            self.scoreboard.record(
                peer, outcome, latency_s=lat, nbytes=nbytes, round=step
            )
        if self._estimator is not None:
            self._estimator.observe(
                peer, outcome, latency_s=lat, nbytes=nbytes
            )

    def _hedged_fetch(
        self, peer: int, step: Optional[int], timeout_ms: float
    ) -> tuple:
        """Adaptive-deadline fetch with a single hedged retry.

        Runs the primary fetch under the estimator's cumulative deadline
        for ``peer`` (``timeout_ms`` while the estimator is cold); if the
        un-margined quantile budget lapses with the primary still in
        flight and a healthy fallback partner exists, launches ONE hedge
        leg and returns the first success (closing the loser's socket
        promptly).  Returns ``(winner_peer, got, outcome, latency_s,
        nbytes, digest, obs, hedged, hedge_winner)`` — the winner's outcome
        flows through fetch()'s normal screening tail; only the LOSER is
        recorded here."""
        est = self._estimator
        r = int(step) if step is not None else 0
        deadline_ms = (
            est.deadline_ms(peer) if est.warm(peer) else float(timeout_ms)
        )
        t0 = time.monotonic()
        p_box: list = []
        p_sock: list = []
        p_thread = threading.Thread(
            target=self._fetch_leg,
            args=(peer, deadline_ms, p_box, p_sock),
            daemon=True,
        )
        p_thread.start()
        launch_ms = (
            est.hedge_launch_ms(peer) if self.config.flowctl.hedge else None
        )
        fallback = None
        if launch_ms is not None:
            p_thread.join(launch_ms / 1000.0)
            if p_thread.is_alive():
                fallback = self._hedge_fallback(peer, r)
        if fallback is None:
            # No hedge: cold estimator, fast primary, or no healthy
            # fallback.  The leg's own cumulative deadline bounds the
            # join (budget extends only while bytes actually flow).
            p_thread.join()
            got, outcome, latency_s, nbytes, digest, obs = self._leg_result(
                p_box, time.monotonic() - t0
            )
            return (
                peer, got, outcome, latency_s, nbytes, digest, obs,
                False, None,
            )
        est.note_hedge(peer)
        f_box: list = []
        f_sock: list = []
        f_thread = threading.Thread(
            target=self._fetch_leg,
            args=(fallback, est.deadline_ms(fallback), f_box, f_sock),
            daemon=True,
        )
        f_thread.start()
        # Race: first SUCCESS wins; ties (both done) prefer the
        # scheduled primary.  Both legs self-terminate on their own
        # cumulative deadlines, so the poll loop is bounded.
        while True:
            p_done = not p_thread.is_alive()
            f_done = not f_thread.is_alive()
            if p_done and p_box and p_box[0][1] == Outcome.SUCCESS:
                break
            if f_done and f_box and f_box[0][1] == Outcome.SUCCESS:
                break
            if p_done and f_done:
                break
            time.sleep(0.002)
        p_done = not p_thread.is_alive()
        f_done = not f_thread.is_alive()
        p_ok = p_done and p_box and p_box[0][1] == Outcome.SUCCESS
        primary_wins = p_ok or (p_done and f_done and not (
            f_box and f_box[0][1] == Outcome.SUCCESS
        ))
        elapsed = time.monotonic() - t0
        if primary_wins:
            # Cancel the hedge leg.  A leg that never got a fair budget
            # (cancelled mid-flight) is not evidence against the
            # fallback peer — only a genuinely finished leg records.
            self._close_leg(f_sock)
            f_thread.join(0.5)
            if f_done and f_box:
                self._record_loser(
                    fallback, f_box[0], cancelled=False,
                    latency_s=f_box[0][2], step=step,
                )
            got, outcome, latency_s, nbytes, digest, obs = self._leg_result(
                p_box, elapsed
            )
            return (
                peer, got, outcome, latency_s, nbytes, digest, obs,
                True, peer,
            )
        # Fallback wins (or both failed — prefer the fallback's result
        # only on success; otherwise report the primary's real failure).
        if f_done and f_box and f_box[0][1] == Outcome.SUCCESS:
            est.note_hedge_win(peer)
            self._close_leg(p_sock)
            p_thread.join(0.5)
            self._record_loser(
                peer,
                p_box[0] if p_box else None,
                cancelled=not (p_done and p_box),
                latency_s=elapsed,
                step=step,
            )
            got, outcome, latency_s, nbytes, digest, obs = f_box[0]
            return (
                fallback, got, outcome, latency_s, nbytes, digest, obs,
                True, fallback,
            )
        # Both legs finished without a success: record the fallback's
        # genuine failure here, report the primary's through the tail.
        if f_box:
            self._record_loser(
                fallback, f_box[0], cancelled=False,
                latency_s=f_box[0][2], step=step,
            )
        got, outcome, latency_s, nbytes, digest, obs = self._leg_result(
            p_box, elapsed
        )
        return peer, got, outcome, latency_s, nbytes, digest, obs, True, None

    def _link_blocked(self, peer_index: int) -> bool:
        """Fetcher-side view of an injected partition (False without
        chaos).  Keyed on the last PUBLISHED clock — publish always
        precedes fetch in a round, so both endpoints and any relay
        agree on the same round key."""
        if self._chaos_engine is None:
            return False
        with self._clock_lock:
            clock = self._last_clock
        return self._chaos_engine.link_blocked(
            int(clock), self.me, peer_index
        )

    def _indirect_probe(self, suspect: int, step: int) -> None:
        """Ask K deterministically-drawn healthy peers to header-probe
        ``suspect`` on our behalf (the RELAY verb), and feed the
        scoreboard AT MOST one summarized outcome for the suspect.

        The relay set is drawn with :func:`~dpwa_tpu.parallel.schedules.
        relay_draw` — counter-based threefry keyed on (seed, step, me,
        slot), no wall clock — so replays pick identical relays.  Each
        relay's OWN reachability outcome feeds its record too: a relay
        that cannot be reached is itself evidence."""
        from dpwa_tpu.parallel.schedules import relay_draw

        sb = self.scoreboard
        view = self._view_candidates()
        universe = (
            view if view is not None else range(len(self.config.nodes))
        )
        candidates = [
            p
            for p in universe
            if p != self.me
            and p != suspect
            and sb.state(p) == PeerState.HEALTHY
        ]
        if not candidates:
            return
        k = min(int(self.config.membership.indirect_probes), len(candidates))
        s_host, s_port = self._ports[suspect]
        vouched = False
        for slot in range(k):
            idx = int(
                relay_draw(
                    self.schedule.seed, step, self.me, slot, len(candidates)
                )
            )
            relay = candidates.pop(idx)
            if self._link_blocked(relay):
                relay_outcome, probe_outcome = Outcome.REFUSED, None
            else:
                r_host, r_port = self._ports[relay]
                relay_outcome, probe_outcome, _clock = relay_probe(
                    r_host, r_port, suspect, s_host, s_port,
                    self.config.health.probe_timeout_ms,
                    self.config.membership.relay_timeout_ms,
                )
            sb.record_probe(relay, relay_outcome, round=step)
            if probe_outcome == Outcome.SUCCESS:
                vouched = True
        if vouched:
            sb.record_probe(suspect, Outcome.SUCCESS, round=step)

    def _resolve_partner(self, step: int) -> Tuple[int, int, bool]:
        """Health-aware partner resolution: ``(scheduled, actual,
        remapped)`` for this round.

        If the scheduled partner is quarantined and its backoff has
        elapsed, spend a cheap header-only probe first (probes ride the
        pairing rounds that would have fetched from it anyway, so the
        probe budget is self-rationing).  If it is (still) quarantined
        after that, remap to a threefry-drawn healthy fallback
        (:meth:`Schedule.remap_partner`) — replicas sharing the same
        scoreboard view make the identical draw, and with health
        disabled this degrades to the plain schedule partner."""
        sched = self.schedule.partner(step, self.me)
        partner, remapped = sched, False
        sb = self.scoreboard
        if sb is not None and sched != self.me:
            if sb.probe_due(sched, step):
                if self._link_blocked(sched):
                    outcome, remote_clock = Outcome.REFUSED, None
                else:
                    host, port = self._ports[sched]
                    outcome, remote_clock = probe_header_classified(
                        host, port, self.config.health.probe_timeout_ms
                    )
                sb.record_probe(sched, outcome, round=step)
                ok = outcome == Outcome.SUCCESS
                with self._clock_lock:
                    local_clock = self._last_clock
                if (
                    ok
                    and remote_clock is not None
                    and self.config.recovery.enabled
                    and remote_clock - local_clock
                    > self.config.recovery.max_clock_lag
                ):
                    # Re-admission freshness check: the peer came back
                    # with a clock far AHEAD of ours — we are the stale
                    # one (partitioned while the ring kept training).
                    # Interpolation alone digs out slowly; advise the
                    # adapter to re-sync (it bootstraps if auto_resync).
                    self.resync_advice = {
                        "peer": sched,
                        "remote_clock": float(remote_clock),
                        "local_clock": float(local_clock),
                        "step": int(step),
                    }
            if sb.is_quarantined(sched, step):
                view = self._view_candidates()
                partner = self.schedule.remap_partner(
                    step, self.me, sched, self._remap_mask(view, step),
                    view,
                )
                remapped = True
            elif (
                self.config.flowctl.enabled
                and self.config.flowctl.degrade_shed_fraction > 0.0
                # With the self-tuning wire running, a DEGRADED partner
                # sheds FIDELITY at publish (the ladder overlay) instead
                # of rounds — the round-drop remap below is bypassed so
                # the honest-peer round rate never dips under load.
                and self._tuner is None
                and sb.is_degraded(sched, step)
            ):
                # Scoreboard soft-degrade: a DEGRADED partner (load, not
                # death) keeps a deterministic fraction of its scheduled
                # pairings — full shedding would starve it of the very
                # successes that drain its suspicion — and the rest remap
                # to a healthy fallback.  The draw is threefry-keyed on
                # (seed, step, me): bit-identical across reruns.
                from dpwa_tpu.parallel.schedules import degrade_shed_draw

                if (
                    degrade_shed_draw(self.schedule.seed, step, self.me)
                    < self.config.flowctl.degrade_shed_fraction
                ):
                    view = self._view_candidates()
                    partner = self.schedule.remap_partner(
                        step, self.me, sched,
                        self._remap_mask(view, step), view,
                    )
                    remapped = True
        return sched, partner, remapped

    def publish_state(self, blob: bytes) -> None:
        """Expose this worker's serialized train state for peers to
        bootstrap from (zero shared-disk recovery)."""
        self.server.publish_state(blob)

    def fetch_state(
        self, peer_index: int, timeout_ms: Optional[int] = None
    ) -> Tuple[Optional[bytes], str, float, int]:
        """Pull a donor's full serialized state (chunked, CRC-checked,
        resumable — :func:`fetch_state`), sized by the ``recovery:``
        config block."""
        if self._link_blocked(peer_index):
            return None, Outcome.REFUSED, 0.0, 0
        host, port = self._ports[peer_index]
        rec = self.config.recovery
        if timeout_ms is None:
            timeout_ms = rec.bootstrap_timeout_ms
        return fetch_state(
            host, port, timeout_ms,
            chunk_bytes=rec.state_chunk_bytes,
            max_retries=rec.max_resume_retries,
            min_bandwidth_bps=self.config.protocol.min_wire_mb_per_s * 1e6,
        )

    def pop_resync_advice(self) -> Optional[dict]:
        """Consume the pending re-admission freshness advice, if any."""
        advice, self.resync_advice = self.resync_advice, None
        return advice

    # dpwalint: thread_root(healthz)
    def health_snapshot(self) -> dict:
        """JSON-ready per-peer health state (scoreboard + detector
        EWMAs, plus per-peer trust columns and a top-level ``trust``
        view when the trust plane is on); the payload behind metrics'
        ``health`` records and the optional /healthz endpoint."""
        if self.scoreboard is None:
            snap = {"me": self.me, "round": 0, "peers": {}}
        else:
            snap = self.scoreboard.snapshot()
        if self.trust is not None:
            tsnap = self.trust.snapshot()
            for p, info in tsnap["peers"].items():
                snap["peers"].setdefault(p, {}).update(info)
            snap["trust"] = tsnap
        if self._estimator is not None:
            fsnap = self._estimator.snapshot()
            admission = getattr(self.server, "admission", None)
            if admission is not None:
                fsnap["admission"] = admission.snapshot()
            for p, info in fsnap["peers"].items():
                snap["peers"].setdefault(p, {}).update(
                    {
                        "deadline_ms": info["deadline_ms"],
                        "hedges": info["hedges"],
                        "hedge_wins": info["hedge_wins"],
                        "busy": info["busy"],
                        "slow": info["slow"],
                    }
                )
            snap["flowctl"] = fsnap
        reactor_snap = getattr(self.server, "reactor_snapshot", None)
        if reactor_snap is not None:
            # Present exactly when the reactor serves this node, so
            # threaded runs keep their health records byte-identical.
            snap["reactor"] = reactor_snap()
        from dpwa_tpu.device import device_snapshot as _device_snapshot

        if (
            self._wire_topk or self._prefetch_on or self._shard_on
            or _device_snapshot()["device_rounds"] > 0
            or (
                self.membership is not None
                and self.membership.partial is not None
            )
        ):
            # Gated on the new planes being ON (or the device merge
            # engine having served a round): a dense sequential host
            # run keeps its health records byte-identical to PR 5.
            snap["wire"] = self.wire_snapshot()
        if self.tracer is not None or self.sketchboard is not None:
            snap["obs"] = self.obs_snapshot()
        if self.incidents is not None:
            snap["incidents"] = self.incidents.snapshot()
        if self.async_engine is not None:
            # Present exactly when the barrier-free round loop drives
            # this transport (protocol.async_rounds), so lock-step runs
            # keep their health records byte-identical.
            snap["async"] = self.async_engine.snapshot()
        if self._tuner is not None:
            # Present exactly when the self-tuning wire is on, so
            # static-wire runs keep their health records byte-identical.
            snap["tune"] = self._tuner.snapshot()
        return snap

    # dpwalint: thread_root(healthz)
    def obs_snapshot(self) -> dict:
        """JSON-ready observability sub-document (healthz ``obs`` key,
        metrics' ``disagreement_*`` columns): the sketch-based ring
        convergence estimate and the tracer's per-stage summary."""
        out: dict = {}
        if self.sketchboard is not None:
            out["convergence"] = self.sketchboard.snapshot()
        if self.tracer is not None:
            out["trace"] = self.tracer.stage_summary()
        return out

    # dpwalint: thread_root(healthz)
    def wire_snapshot(self) -> dict:
        """JSON-ready wire-plane state: which codec is publishing, the
        actual on-wire vs dense f32 byte tallies behind the
        ``compression_ratio`` column, and — under the prefetch pipeline
        — the overlap accounting (``occupancy`` = fetch in-flight time
        over entry-to-entry round wall; ``hidden_frac`` = the fraction
        of fetch wall-time the caller never waited on)."""
        with self._stats_lock:
            t = dict(self._wire_tally)
            shard_tally = {
                i: dict(st) for i, st in self._shard_tally.items()
            }
        codec = "topk" if self._wire_topk else self.config.protocol.wire_dtype
        if self._shard_on:
            codec = f"shard+{codec}"
        zc = _ingest.rx_stats()
        out = {
            "codec": codec,
            "frames": t["frames"],
            "wire_bytes": t["wire_bytes"],
            "dense_bytes": t["dense_bytes"],
            "compression_ratio": (
                round(t["dense_bytes"] / t["wire_bytes"], 4)
                if t["wire_bytes"]
                else 0.0
            ),
            # Zero-copy hot-path accounting (process-wide: the receive
            # ring and the copy tally are shared across transports, like
            # the frame path itself): payload-sized copies per decoded
            # frame (0.0 = views straight out of the ring) and the
            # fraction of ring bytes currently leased out.
            "copies_per_frame": round(zc["copies_per_frame"], 4),
            "ring_occupancy": round(zc["ring_occupancy"], 4),
        }
        # Device-plane accounting (process-wide, like the receive ring):
        # the merge engine's jit cache and dispatch tallies, plus the
        # zero-copy fraction of host→device crossings.  All zeros until
        # a device exchange runs; never imports a JAX backend.
        from dpwa_tpu.device import device_snapshot

        dv = device_snapshot()
        out["device"] = {
            "device_rounds": dv["device_rounds"],
            "jit_cache_hits": dv["jit_cache_hits"],
            "jit_cache_misses": dv["jit_cache_misses"],
            "device_dispatches_per_round": dv[
                "device_dispatches_per_round"
            ],
            "h2d_zero_copy_frac": round(dv["h2d_zero_copy_frac"], 4),
            "fold_frames": dv["fold_frames"],
        }
        if self._wire_topk:
            out["topk_fraction"] = self.config.protocol.topk_fraction
            out["topk_values"] = self.config.protocol.topk_values
        if self._shard_on:
            k = self._shard_k
            # coverage = distinct shards this node has actually served /
            # k — the round-robin invariant says it reaches 1.0 within
            # the first k publishes and stays there.
            out["shard"] = {
                "k": k,
                "frames_per_shard": [
                    shard_tally.get(i, {}).get("frames", 0)
                    for i in range(k)
                ],
                "wire_bytes_per_shard": [
                    shard_tally.get(i, {}).get("wire_bytes", 0)
                    for i in range(k)
                ],
                "coverage": round(len(shard_tally) / k, 4),
            }
        if self.membership is not None and self.membership.partial is not None:
            # Partial-view accounting (membership.view): view sizes,
            # residency, evictions by cause, and the actual digest bytes
            # the last published frame carried — the O(sample) numbers
            # the fleet bench gate watches.  Schema-frozen as the
            # ``view_*`` group (tools/schema_check.py); present exactly
            # when the view plane is on.
            vs = dict(self.membership.view_snapshot().get("view") or {})
            vs["view_digest_bytes"] = self._last_digest_nbytes
            out["view"] = vs
        if self._prefetch_on:
            with self._stats_lock:
                o = dict(self._overlap)
            out["overlap"] = {
                "rounds": o["rounds"],
                "prefetched": o["prefetched"],
                "straddled": o["straddled"],
                "fetch_s": round(o["fetch_s"], 6),
                "join_wait_s": round(o["join_wait_s"], 6),
                "occupancy": (
                    round(o["inflight_s"] / o["round_s"], 4)
                    if o["round_s"] > 0
                    else 0.0
                ),
                "hidden_frac": (
                    round(max(1.0 - o["join_wait_s"] / o["fetch_s"], 0.0), 4)
                    if o["fetch_s"] > 0
                    else 0.0
                ),
            }
        return out

    def _register_metrics(self, registry) -> None:
        """Wire every enabled plane's collectors into the /metrics
        registry (called once, at the end of __init__).  Collectors read
        the planes' existing snapshots at scrape time — nothing here
        touches the exchange hot path."""
        from dpwa_tpu.obs.prometheus import Family

        registry.gauge_fn(
            "dpwa_me", "This node's ring index.", lambda: self.me
        )
        if self.scoreboard is not None:
            from dpwa_tpu.health.scoreboard import (
                register_metrics as _reg_health,
            )

            _reg_health(registry, self.scoreboard)
        if self.membership is not None:
            from dpwa_tpu.membership.manager import (
                register_metrics as _reg_member,
            )

            _reg_member(registry, self.membership)
        if self.trust is not None:
            from dpwa_tpu.trust.manager import (
                register_metrics as _reg_trust,
            )

            _reg_trust(registry, self.trust)
        if self._estimator is not None:
            from dpwa_tpu.flowctl.estimator import (
                register_metrics as _reg_est,
            )

            _reg_est(registry, self._estimator)
        admission = getattr(self.server, "admission", None)
        if admission is not None:
            from dpwa_tpu.flowctl.admission import (
                register_metrics as _reg_adm,
            )

            _reg_adm(registry, admission)
        if hasattr(self.server, "reactor_snapshot"):
            from dpwa_tpu.parallel.reactor import (
                register_metrics as _reg_reactor,
            )

            _reg_reactor(registry, self.server)

        def _wire():
            snap = self.wire_snapshot()
            fams = [
                Family(
                    "dpwa_wire_bytes_total",
                    "counter",
                    "Payload bytes published to the wire.",
                ).sample(snap["wire_bytes"]),
                Family(
                    "dpwa_wire_frames_total",
                    "counter",
                    "Frames published to the wire.",
                ).sample(snap["frames"]),
                Family(
                    "dpwa_wire_compression_ratio",
                    "gauge",
                    "Dense f32 bytes over on-wire bytes.",
                ).sample(snap["compression_ratio"]),
            ]
            ov = snap.get("overlap")
            if ov is not None:
                fams.append(
                    Family(
                        "dpwa_overlap_occupancy",
                        "gauge",
                        "Fetch in-flight time over round wall time.",
                    ).sample(ov["occupancy"])
                )
                fams.append(
                    Family(
                        "dpwa_overlap_hidden_frac",
                        "gauge",
                        "Fraction of fetch wall-time hidden from the "
                        "caller.",
                    ).sample(ov["hidden_frac"])
                )
            return fams

        registry.register(_wire)
        if self.sketchboard is not None:

            def _sketch():
                snap = self.sketchboard.snapshot()
                return [
                    Family(
                        "dpwa_disagreement_rms",
                        "gauge",
                        "Sketch-estimated RMS replica disagreement "
                        "across peers seen.",
                    ).sample(snap["rms"]),
                    Family(
                        "dpwa_disagreement_rel",
                        "gauge",
                        "RMS disagreement relative to the local "
                        "replica norm estimate.",
                    ).sample(snap["rel_rms"]),
                    Family(
                        "dpwa_sketch_peers",
                        "gauge",
                        "Peers with a current sketch on the board.",
                    ).sample(snap["peers_seen"]),
                ]

            registry.register(_sketch)
        if self.tracer is not None:

            def _trace():
                summary = self.tracer.stage_summary()
                total = Family(
                    "dpwa_trace_stage_seconds_total",
                    "counter",
                    "Cumulative seconds spent per exchange stage.",
                )
                med = Family(
                    "dpwa_trace_stage_median_ms",
                    "gauge",
                    "Median stage duration over the recent window.",
                )
                for stage, info in summary.items():
                    total.sample(info["total_s"], {"stage": stage})
                    med.sample(info["median_ms"], {"stage": stage})
                return [total, med]

            registry.register(_trace)
        if self.incidents is not None:
            from dpwa_tpu.obs.incidents import (
                register_metrics as _reg_inc,
            )

            _reg_inc(registry, self.incidents)
        if self._tuner is not None:
            from dpwa_tpu.tune import register_metrics as _reg_tune

            _reg_tune(registry, self._tuner)

    def _trust_alpha_scale(self) -> float:
        """The CURRENT exchange's trust damping (interpolation hook)."""
        return self._pending_trust_scale

    def _wire_nbytes(self, vec: np.ndarray) -> int:
        """Bytes the published frame's PAYLOAD occupies on the wire —
        what a symmetric partner fetch will actually stream, used to
        size the overlapped-join backstop.  Mirrors :meth:`publish`'s
        encoding choice exactly."""
        n = int(vec.size)
        if self._tuner is not None and vec.dtype == np.float32:
            # Self-tuning wire: the partner's rung can sit anywhere on
            # the ladder by the time it fetches — size the backstop for
            # the f32 floor, the ladder's largest frame (a conservative
            # bound is the contract here).
            if self._shard_on:
                m = -(-n // self._shard_k)
                return _pc.SHARD_HDR.size + 4 * m
            return 4 * n
        if self._shard_on and vec.dtype == np.float32:
            # Sharded frame: SHARD_HDR preamble + the inner encoding
            # over the LONGEST slice (ceil(n/k)) — a conservative upper
            # bound is fine for a join backstop.
            m = -(-n // self._shard_k)
            if self._wire_topk:
                from dpwa_tpu.ops.quantize import topk_k, topk_nbytes

                inner = topk_nbytes(
                    m,
                    topk_k(m, self.config.protocol.topk_fraction),
                    self.config.protocol.topk_values,
                )
            elif self._wire_int8:
                from dpwa_tpu.ops.quantize import _n_chunks

                inner = 8 + 4 * _n_chunks(m) + m
            elif self._wire_bf16:
                inner = 2 * m
            else:
                inner = 4 * m
            return _pc.SHARD_HDR.size + inner
        if self._wire_topk and vec.dtype == np.float32:
            from dpwa_tpu.ops.quantize import topk_k, topk_nbytes

            return topk_nbytes(
                n,
                topk_k(n, self.config.protocol.topk_fraction),
                self.config.protocol.topk_values,
            )
        if self._wire_int8 and vec.dtype == np.float32:
            from dpwa_tpu.ops.quantize import _n_chunks

            return 8 + 4 * _n_chunks(n) + n  # u64 n | f32 scales | int8 q
        if self._wire_bf16 and vec.dtype == np.float32:
            return 2 * n
        return int(vec.nbytes)

    def _weigh_remote(
        self, got: Tuple[np.ndarray, float, float], clock: float, loss: float
    ) -> Tuple[np.ndarray, float]:
        """Fetched blob -> (f32-ready remote vector, interpolation α):
        the metadata weighing + bf16-wire upcast shared by every merge
        substrate (host, device-resident, overlapped)."""
        remote_vec, remote_clock, remote_loss = got
        local = PeerMeta(np.float32(clock), np.float32(loss))
        remote = PeerMeta(np.float32(remote_clock), np.float32(remote_loss))
        alpha = float(self.interp(local, remote))
        if self.membership is not None:
            # Degraded-mode damping: inside a below-quorum component the
            # merge pull is optionally scaled down (1.0 by default — a
            # bit-exact no-op) so a small island doesn't overcommit to
            # its own consensus before the heal.
            alpha *= self.membership.alpha_scale()
        if (
            not self._sparse_consume
            and ml_dtypes is not None
            and remote_vec.dtype == _DTYPES[3]
        ):
            # bf16 off the wire: upcast once, merge in f32 (same math as
            # the ICI transport's bf16-wire merge).  The device engine
            # skips this copy — its bf16 kernel bitcasts and upcasts
            # in-graph, so the raw u16 wire view crosses the seam as-is.
            remote_vec = remote_vec.astype(np.float32)
        return remote_vec, alpha

    def _merge_remote(
        self, vec: np.ndarray, remote_vec: np.ndarray, alpha: float
    ) -> np.ndarray:
        """The merge shared by every host-side substrate: full-vector
        lerp normally; when the consume leg stashed shard bounds, lerp
        ONLY the ``[lo, hi)`` slice and copy the rest bit-exactly.  An
        f32 ``(1-α)·x + α·x`` is NOT exactly ``x``, so lerping the
        densified full vector would silently perturb the k−1 slices the
        frame never shipped."""
        bounds = self._pending_shard
        if bounds is None:
            return _host_merge(vec, remote_vec, alpha)
        lo, hi = bounds
        merged = np.array(vec, dtype=np.float32, copy=True)
        merged[lo:hi] = _host_merge(
            np.ascontiguousarray(merged[lo:hi]),
            np.ascontiguousarray(remote_vec[lo:hi]),
            alpha,
        )
        return merged

    def _round(
        self, vec: np.ndarray, clock: float, loss: float, step: int
    ) -> Tuple[Optional[np.ndarray], float, int]:
        """The round protocol shared by every merge substrate: publish,
        pick partner, participation gate, fetch, interpolation weight,
        bf16-wire upcast.  Returns (remote_f32_vector | None, alpha,
        partner); None means the round was skipped (self-pair, masked, or
        fetch timeout) and the caller keeps its vector untouched."""
        try:
            self.publish(vec, clock, loss)
            tr = self.tracer
            timing = tr is not None and tr.active
            t0 = time.monotonic() if timing else 0.0
            sched, partner, remapped = self._resolve_partner(step)
            if timing:
                tr.mark("partner_resolve", time.monotonic() - t0)
            self.last_round = {
                "step": step, "sched_partner": sched, "partner": partner,
                "remapped": remapped, "outcome": None,
            }
            # Participation stays keyed on the ORIGINAL pairing (identical
            # threefry draw to the ICI path); remap changes only the fetch
            # target.  A remap to self (no healthy candidate) skips.
            if partner == self.me or not self.schedule.participates(
                step, self.me
            ):
                return None, 0.0, partner
            got = self.fetch(partner, step=step)
            self.last_round["outcome"] = self.last_fetch.get("outcome")
            if "codec" in self.last_fetch:
                self.last_round["codec"] = self.last_fetch["codec"]
            if "trust" in self.last_fetch:
                self.last_round["trust"] = self.last_fetch["trust"]
            if self.last_fetch.get("hedged"):
                self.last_round["hedged"] = True
                self.last_round["hedge_winner"] = self.last_fetch.get(
                    "hedge_winner"
                )
            if got is None:
                # dead/slow peer: skip, keep training
                return None, 0.0, partner
            remote_vec, alpha = self._weigh_remote(got, clock, loss)
            return remote_vec, alpha, partner
        finally:
            # Membership round boundary runs on EVERY exit path —
            # component/quorum state must advance even on skipped rounds
            # (a partitioned node skips every round, and that is exactly
            # when it must notice it is partitioned).
            self._membership_end_round(step)

    def _membership_end_round(self, step: int) -> None:
        if self.membership is not None:
            self.membership.end_round(step)
        if self.incidents is not None or self.flight is not None:
            self._obs_round_end(step)
        elif self._tuner is not None:
            # Tuner without the incident plane: feed the controller its
            # round evidence on the same every-exit-path boundary, but
            # WITHOUT draining membership/trust events (that drain is
            # the incident plane's contract — pop_*_events would lose
            # the buffered copies otherwise).
            self._tune_round_end(step)

    def _obs_round_end(self, step: int) -> None:
        """Incident-plane + flight-recorder round boundary — runs right
        after the membership boundary on EVERY exit path of every
        exchange substrate.  Gathers this round's evidence from state
        the round already produced (``last_round``/``last_fetch``, the
        scoreboard, the membership view, the sketch board) — no extra
        wire traffic, no device syncs."""
        now = time.monotonic()
        wall = None
        if self._obs_round_entry_t is not None:
            # Entry-to-entry wall: compute + exchange, the quantity the
            # SLO-burn detector baselines.
            wall = now - self._obs_round_entry_t
        self._obs_round_entry_t = now
        lr = self.last_round
        this_round = lr.get("step") == step
        peer = lr.get("partner") if this_round else None
        outcome = lr.get("outcome") if this_round else None
        lf = self.last_fetch if this_round else {}
        # Drain membership/trust events HERE so detectors see them the
        # round they happen; adapters still receive every event through
        # the pop_*_events buffers (one drain later at worst).
        events: list = []
        if self.membership is not None:
            evs = self.membership.pop_events()
            events.extend(evs)
            self._membership_event_buf.extend(evs)
        if self.trust is not None:
            evs = self.trust.pop_events()
            events.extend(evs)
            self._trust_event_buf.extend(evs)
        board = (
            self.scoreboard.snapshot()
            if self.scoreboard is not None
            else None
        )
        partition_state = component = None
        if self.membership is not None:
            view = self.membership.view_snapshot()
            partition_state = view.get("partition_state")
            component = view.get("component")
        rel = None
        if self.sketchboard is not None:
            _, rel = self.sketchboard.disagreement()
        if self._tuner is not None and peer is not None and peer != self.me:
            self._tuner.observe(
                peer,
                wall_s=wall,
                wire_s=lf.get("latency_s"),
                soft=outcome in _TUNE_SOFT_OUTCOMES,
                rel=rel,
            )
        stale_peers: Sequence[int] = ()
        if self.async_engine is not None:
            # Peers whose frames the bounded-staleness rule dropped this
            # round — the staleness_storm detector's evidence stream.
            stale_peers = self.async_engine.pop_round_stale()
        fired: list = []
        opened = False
        if self.incidents is not None:
            res = self.incidents.observe_round(
                step,
                outcome=outcome,
                peer=peer,
                board=board,
                events=events,
                rel_rms=rel,
                wall_s=wall,
                partition_state=partition_state,
                component=component,
                stale_peers=stale_peers,
            )
            fired = res["alerts"]
            opened = res["opened"]
        if self.flight is not None:
            self.flight.note_round(
                step,
                partner=peer,
                sched_partner=lr.get("sched_partner") if this_round else None,
                remapped=lr.get("remapped") if this_round else None,
                outcome=outcome,
                codec=lr.get("codec") if this_round else None,
                trust=lr.get("trust") if this_round else None,
                latency_s=lf.get("latency_s"),
                nbytes=lf.get("nbytes"),
                rel_rms=rel,
                wall_s=round(wall, 6) if wall is not None else None,
                partition_state=partition_state,
                events=[e.get("event") for e in events] or None,
                alerts=fired or None,
            )
            if opened:
                # Incident open is a dump trigger: preserve the run-up
                # before the ring scrolls past it.
                self.flight.dump("incident", step)

    def _tune_round_end(self, step: int) -> None:
        """Controller-only round boundary (incident plane off): the
        same entry-to-entry wall + last-fetch spans the obs boundary
        gathers, quantized inside LinkTuner.observe before any decision
        can branch on them."""
        now = time.monotonic()
        wall = None
        if self._obs_round_entry_t is not None:
            wall = now - self._obs_round_entry_t
        self._obs_round_entry_t = now
        lr = self.last_round
        this_round = lr.get("step") == step
        peer = lr.get("partner") if this_round else None
        if peer is None or peer == self.me:
            return
        lf = self.last_fetch if this_round else {}
        outcome = lr.get("outcome") if this_round else None
        rel = None
        if self.sketchboard is not None:
            _, rel = self.sketchboard.disagreement()
        self._tuner.observe(
            peer,
            wall_s=wall,
            wire_s=lf.get("latency_s"),
            soft=outcome in _TUNE_SOFT_OUTCOMES,
            rel=rel,
        )

    def pop_tune_decisions(self) -> list:
        """Drain the controller's buffered ladder decisions (the JSONL
        ``tune`` record kind); [] when the tuner is off."""
        if self._tuner is None:
            return []
        return self._tuner.pop_decisions()

    def _flight_dump_route(self) -> dict:
        """``/flightdump`` healthz route: dump the ring on demand."""
        path = (
            self.flight.dump("endpoint")
            if self.flight is not None
            else None
        )
        out: dict = {"dumped": path is not None}
        if path is not None:
            out["path"] = path
        return out

    def pop_membership_events(self) -> list:
        """Drain membership events (refutations, component changes,
        partition entered/healed) for the metrics JSONL."""
        if self.membership is None:
            return []
        if self.incidents is not None or self.flight is not None:
            out = self._membership_event_buf
            self._membership_event_buf = []
            out.extend(self.membership.pop_events())
            return out
        return self.membership.pop_events()

    def pop_heal_advice(self) -> Optional[dict]:
        """Consume the pending heal-reconciliation advice, if any."""
        if self.membership is None:
            return None
        return self.membership.pop_heal_advice()

    def pop_trust_events(self) -> list:
        """Drain trust events (collapse, recovery, clock resets) for the
        metrics JSONL."""
        if self.trust is None:
            return []
        if self.incidents is not None or self.flight is not None:
            out = self._trust_event_buf
            self._trust_event_buf = []
            out.extend(self.trust.pop_events())
            return out
        return self.trust.pop_events()

    def set_trust_leaves(self, sizes) -> None:
        """Adopt the adapter pytree's leaf sizes so the per-leaf max-abs
        screening statistic follows real parameter boundaries instead of
        fixed segments (adapters call this once at construction)."""
        if self.trust is not None:
            self.trust.set_leaf_sizes(sizes)

    def exchange(
        self, vec: np.ndarray, clock: float, loss: float, step: int
    ) -> Tuple[np.ndarray, float, int]:
        """One full gossip round: publish, pick partner, fetch, merge.

        Returns (merged_vector, alpha_applied, partner).  alpha == 0.0 means
        the round was skipped (self-pair, masked, or fetch timeout).

        With ``protocol.overlap_prefetch`` the wire leg of the NEXT
        round's fetch is launched before this round returns, so the
        caller's compute between exchanges hides the partner stream
        (:meth:`_exchange_pipelined`); the sequential path below is the
        bit-identity reference the pipeline is tested against.

        With ``protocol.async_rounds`` the round goes barrier-free
        through the :class:`~dpwa_tpu.parallel.async_loop
        .AsyncExchangeEngine` instead — publish decoupled from merge,
        pending frames draining staleness-damped — and the returned
        alpha is the damped alpha applied to THIS round's schedule
        partner (0.0 when its frame is still in flight)."""
        if self.async_engine is not None:
            return self._exchange_async(vec, clock, loss, step)
        if self._prefetch_on:
            return self._exchange_pipelined(vec, clock, loss, step)
        tr = self.tracer
        rt = tr is not None and tr.begin_round(step)
        try:
            remote_vec, alpha, partner = self._round(vec, clock, loss, step)
            if remote_vec is None:
                return vec, alpha, partner
            t0 = time.monotonic() if rt else 0.0
            merged = self._merge_remote(vec, remote_vec, alpha)
            if rt:
                tr.mark("merge", time.monotonic() - t0)
                tr.set(alpha=float(alpha))
            return merged, alpha, partner
        finally:
            if rt:
                self._trace_finish(tr)

    def _exchange_pipelined(
        self, vec: np.ndarray, clock: float, loss: float, step: int
    ) -> Tuple[np.ndarray, float, int]:
        """One gossip round through the double-buffered prefetch slot.

        Steady state per round ``t``: publish x_t, JOIN the slot that has
        been streaming partner(t)'s frame since round t−1 (the caller's
        compute between exchanges is what the stream hid under), LAUNCH
        round t+1's wire fetch on a fresh background slot, then decode →
        guard → trust-screen → merge round t's payload.  Everything
        judgemental runs at consume time against the replica published
        THIS round — the publish-clock guard: a payload whose fetch
        straddled our publish is screened against the current local
        view, never the one that existed at launch (``straddled`` counts
        those rounds).  Failure semantics (busy, slow, hedge losers,
        chaos partitions) are charged to the consuming round's step, and
        a partition that opened after launch still refuses the payload
        at consume (:meth:`_prefetch_take`)."""
        t_entry = time.monotonic()
        with self._stats_lock:
            o = self._overlap
            if self._pipe_last_entry is not None:
                # Entry-to-entry wall clock — the denominator of the
                # overlap-occupancy column (compute + exchange, everything).
                o["round_s"] += t_entry - self._pipe_last_entry
            self._pipe_last_entry = t_entry
            o["rounds"] += 1
        tr = self.tracer
        rt = tr is not None and tr.begin_round(step)
        try:
            self.publish(vec, clock, loss)
            raw, sched, partner, remapped = self._prefetch_take(step)
            self.last_round = {
                "step": step, "sched_partner": sched, "partner": partner,
                "remapped": remapped, "outcome": None,
            }
            # Launch round t+1's wire leg BEFORE consuming round t: the
            # stream overlaps this round's decode/screen/merge and the
            # caller's next compute interval.
            self._prefetch_launch(step + 1, self._wire_nbytes(vec))
            if raw is None:
                return vec, 0.0, partner
            got = self._consume_fetch(raw, step)
            self.last_round["outcome"] = self.last_fetch.get("outcome")
            if "codec" in self.last_fetch:
                self.last_round["codec"] = self.last_fetch["codec"]
            if "trust" in self.last_fetch:
                self.last_round["trust"] = self.last_fetch["trust"]
            if self.last_fetch.get("hedged"):
                self.last_round["hedged"] = True
                self.last_round["hedge_winner"] = self.last_fetch.get(
                    "hedge_winner"
                )
            if got is None:
                return vec, 0.0, partner
            remote_vec, alpha = self._weigh_remote(got, clock, loss)
            t_m = time.monotonic() if rt else 0.0
            merged = self._merge_remote(vec, remote_vec, alpha)
            if rt:
                tr.mark("merge", time.monotonic() - t_m)
                tr.set(alpha=float(alpha))
            return merged, alpha, partner
        finally:
            self._membership_end_round(step)
            if rt:
                self._trace_finish(tr)

    def _exchange_async(
        self, vec: np.ndarray, clock: float, loss: float, step: int
    ) -> Tuple[np.ndarray, float, int]:
        """Adapt the async engine's ``(vec, merges)`` round to the
        lock-step ``(vec, alpha, partner)`` contract: the reported alpha
        is the staleness-damped alpha of this round's resolved partner
        when its frame merged, else the LAST merge applied (pending
        frames from other peers fold in the same round).  Callers treat
        ``alpha != 0.0`` as "the replica moved", so it must be non-zero
        whenever ANY frame merged — 0.0 only for a genuinely empty
        round, exactly what a skipped lock-step round reports."""
        merged, merges = self.async_engine.exchange(vec, clock, loss, step)
        partner = self.last_round.get("partner", self.me)
        alpha = merges[-1][1] if merges else 0.0
        for peer, damped, _lag in merges:
            if peer == partner:
                alpha = damped
        return merged, alpha, partner

    def _trace_finish(self, tr) -> None:
        """Close the active round trace with the round's resolution
        fields (from ``last_round``/``last_fetch``) plus the current
        sketch-based disagreement estimate when the board is on."""
        lr = self.last_round
        fields = {
            "partner": lr.get("partner"),
            "sched_partner": lr.get("sched_partner"),
            "remapped": lr.get("remapped"),
            "outcome": lr.get("outcome"),
            "codec": lr.get("codec"),
        }
        if lr.get("outcome") is not None:
            fields["nbytes"] = self.last_fetch.get("nbytes")
        if lr.get("hedged"):
            fields["hedged"] = True
        if self.sketchboard is not None:
            rms, rel = self.sketchboard.disagreement()
            fields["disagreement_rms"] = rms
            fields["disagreement_rel"] = rel
        tr.end_round(**fields)

    def _prefetch_launch(self, step: int, expected_nbytes: int) -> None:
        """Arm the slot for round ``step``: resolve its partner NOW (the
        scoreboard view is one round younger than a sequential resolve
        would see — acceptable prefetch skew, the pipeline is config-
        gated) and start the wire leg on a daemon thread.  A slot whose
        round does not participate (self-pair / masked) is armed with no
        thread so the take side still returns its partner resolution."""
        tr = self.tracer
        timing = tr is not None and tr.active
        t0 = time.monotonic() if timing else 0.0
        sched, partner, remapped = self._resolve_partner(step)
        if timing:
            tr.mark("partner_resolve", time.monotonic() - t0)
        slot = {
            "step": step, "sched": sched, "partner": partner,
            "remapped": remapped, "expected_nbytes": int(expected_nbytes),
            "thread": None, "box": [], "t_start": 0.0, "t_end": [0.0],
        }
        if partner != self.me and self.schedule.participates(step, self.me):
            box, t_end = slot["box"], slot["t_end"]

            def _run():
                box.append(self._wire_fetch(partner, step=step))
                t_end[0] = time.monotonic()

            slot["t_start"] = time.monotonic()
            th = threading.Thread(
                target=_run, daemon=True,
                name=f"dpwa-prefetch:{self.port}",
            )
            slot["thread"] = th
            th.start()
        self._prefetch_slot = slot

    def _prefetch_take(self, step: int) -> tuple:
        """Claim the slot for round ``step``: ``(raw_9tuple | None,
        sched, partner, remapped)``.

        A cold pipeline (first round) or a step discontinuity resolves
        and fetches synchronously — correctness never depends on the
        slot being warm.  The join backstop mirrors the overlapped
        exchange's: the wire leg's own cumulative deadline (doubled
        under flowctl for a hedge's two sequential budgets) plus the
        per-byte allowance for the expected frame, so a healthy large
        stream is never abandoned while a hung leg cannot wedge the
        round — a lapsed join skips the merge like any failed fetch."""
        slot, self._prefetch_slot = self._prefetch_slot, None
        tr = self.tracer
        timing = tr is not None and tr.active
        if slot is None or slot["step"] != step:
            sched, partner, remapped = self._resolve_partner(step)
            if partner == self.me or not self.schedule.participates(
                step, self.me
            ):
                return None, sched, partner, remapped
            t0 = time.monotonic()
            raw = self._wire_fetch(partner, step=step)
            dt = time.monotonic() - t0
            # A synchronous fill is all join-wait: nothing was hidden.
            with self._stats_lock:
                o = self._overlap
                o["fetch_s"] += dt
                o["join_wait_s"] += dt
                o["inflight_s"] += dt
            if timing:
                tr.mark("join_wait", dt)
                tr.set(prefetched=False)
            return raw, sched, partner, remapped
        sched, partner, remapped = (
            slot["sched"], slot["partner"], slot["remapped"]
        )
        th = slot["thread"]
        if th is None:
            return None, sched, partner, remapped
        with self._stats_lock:
            self._overlap["prefetched"] += 1
        if timing:
            tr.set(prefetched=True, straddled=slot["t_end"][0] == 0.0)
        if slot["t_end"][0] == 0.0:
            # Still streaming as this round's publish landed: the
            # payload straddled a local publish and the consume-time
            # screen (not any launch-time state) is what judges it.
            with self._stats_lock:
                self._overlap["straddled"] += 1
        fc = self.config.flowctl
        base_s = self.config.protocol.timeout_ms / 1000.0
        if fc.enabled:
            base_s = 2.0 * max(base_s, fc.max_ms / 1000.0)
        t_join = time.monotonic()
        th.join(
            1.0
            + base_s
            + slot["expected_nbytes"]
            / (self.config.protocol.min_wire_mb_per_s * 1e6)
        )
        join_dt = time.monotonic() - t_join
        if timing:
            tr.mark("join_wait", join_dt)
        t_end = slot["t_end"][0] or time.monotonic()
        span = max(t_end - slot["t_start"], 0.0)
        with self._stats_lock:
            o = self._overlap
            o["join_wait_s"] += join_dt
            o["fetch_s"] += span
            o["inflight_s"] += span
        if not slot["box"]:
            # Join backstop lapsed: the daemon leg keeps running but
            # this round moves on without a merge.
            return None, sched, partner, remapped
        raw = slot["box"][0]
        if self._link_blocked(partner):
            # A chaos partition keyed on the CURRENT publish clock —
            # the consuming round's — refuses the payload even though
            # the launch-time check (one clock earlier) let the wire
            # leg run: partition semantics charge the consuming round.
            raw = (partner, None, Outcome.REFUSED, 0.0, 0, None, None,
                   False, None)
        return raw, sched, partner, remapped

    def exchange_overlapped_start(
        self, vec: np.ndarray, clock: float, loss: float, step: int
    ) -> "_OverlappedExchange":
        """Begin a gossip round that OVERLAPS the partner fetch with the
        caller's compute — the TCP twin of the SPMD paths'
        ``overlap=True`` (publish the PRE-step replica, never gate the
        exchange wire time on this step's fwd/bwd).

        Publishes ``vec`` (the pre-step replica), resolves
        partner/participation, and starts the fetch on a daemon thread;
        the caller runs its local step, then calls
        :meth:`_OverlappedExchange.finish` with its pre-step vector and
        the step's update to get ``merge(pre, remote) + update`` — the
        exact ``overlap=True`` algebra of
        :func:`dpwa_tpu.train.make_gossip_train_step`."""
        self.publish(vec, clock, loss)
        ex = _OverlappedExchange(
            self, clock, loss, step, expected_nbytes=self._wire_nbytes(vec)
        )
        ex.start()
        return ex

    def exchange_on_device(
        self, vec_dev, clock: float, loss: float, step: int
    ):
        """:meth:`exchange` with a DEVICE-RESIDENT replica (VERDICT r3 #6).

        ``vec_dev`` is a flat f32 JAX array living on an accelerator (or
        the forced-CPU backend standing in for one): the local replica
        never exists as host state — TCP is only the wire.  Per round:
        download once to publish (the wire needs host bytes; on real
        hardware this is the device→NIC staging copy), fetch the
        partner's bytes, upload them, and merge ON DEVICE with a jitted
        lerp.  Returns ``(merged_device_vec, alpha, partner)`` with the
        result still on the device; alpha == 0.0 means the round was
        skipped and ``vec_dev`` is returned untouched (no copies).

        This is the reference's free-running async semantics executed on
        the rebuild's actual data plane — each OS process free-runs its
        own device-resident replica — where the lock-step SPMD paths
        emulate it with masked merges.

        The data plane is the device merge engine (docs/device.md): the
        publish-side readback is LAZY (a skipped round republishes from
        the cached host mirror for free), the consume leg keeps sparse
        frames sparse (``_sparse_consume``), and every codec family
        merges through one fused kernel — scatter-lerp for top-k,
        dynamic-slice lerp for shards (the slice-only invariant is
        structural, no host round-trip), in-kernel bitcast+upcast for
        bf16 wires.

        With ``protocol.async_rounds`` the round goes barrier-free
        through the async engine's device drain instead — same
        ``(merged, alpha, partner)`` adaptation as :meth:`exchange`."""
        if self.async_engine is not None:
            merged, merges = self.async_engine.exchange_on_device(
                vec_dev, clock, loss, step
            )
            partner = self.last_round.get("partner", self.me)
            alpha = merges[-1][1] if merges else 0.0
            for peer, damped, _lag in merges:
                if peer == partner:
                    alpha = damped
            return merged, alpha, partner
        from dpwa_tpu.device import DeviceReplica, default_engine

        eng = default_engine()
        rep = self._dev_replica
        if rep is None or rep.dev is not vec_dev:
            # A replica the engine didn't produce (first round, or the
            # caller trained on a fresh array): adopt it; its mirror is
            # read back once below and cached until the next merge.
            rep = DeviceReplica(vec_dev)
            self._dev_replica = rep
        host_vec = rep.host()
        self._sparse_consume = True
        try:
            remote_vec, alpha, partner = self._round(
                host_vec, clock, loss, step
            )
        finally:
            self._sparse_consume = False
        eng.note_round()
        if remote_vec is None:
            return rep.dev, alpha, partner
        if self._pending_topk is not None:
            idx, vals = self._pending_topk
            merged = eng.merge_topk(rep.dev, idx, vals, alpha)
        elif self._pending_shard is not None:
            # remote_vec IS the m-sized slice estimate (the consume leg
            # never densified); the kernel lerps [lo, lo+m) in-graph and
            # rides the other k−1 slices through bit-identically.
            lo, _hi = self._pending_shard
            merged = eng.merge_shard(rep.dev, lo, remote_vec, alpha)
        elif ml_dtypes is not None and remote_vec.dtype == _DTYPES[3]:
            merged = eng.merge_bf16(rep.dev, remote_vec, alpha)
        else:
            if remote_vec.dtype != np.float32:
                remote_vec = remote_vec.astype(np.float32)
            merged = eng.merge_dense(rep.dev, remote_vec, alpha)
        rep.swap(merged)
        return merged, alpha, partner

    def exchange_on_device_fold(
        self, vec_dev, clock: float, loss: float, step: int,
        peers: Sequence[int],
    ):
        """Fan-in round: fetch a frame from EACH listed peer and fold
        every accepted one into the device replica, batching runs of
        consecutive dense frames into single ``fold`` dispatches.

        Where :meth:`exchange_on_device` is the schedule-driven pairwise
        round (one partner, one frame), this is the explicit fan-in the
        batched-fold kernel exists for: hedged/prefetch legs or an
        experiment harness that drains several ready peers at once.
        Each frame still runs the full consume leg — decode, guard,
        trust screen, scoreboard — exactly as a pairwise round would,
        and the result is bit-identical to applying the accepted frames
        as sequential :meth:`exchange_on_device` merges in arrival
        order (the fold kernel's ``lax.scan`` contract).  Sparse and
        bf16 frames break a dense run and dispatch their own fused
        kernel, preserving arrival order.

        Returns ``(merged_device_vec, merges)`` where ``merges`` is the
        arrival-ordered list of ``(peer, alpha)`` actually applied."""
        from dpwa_tpu.device import DeviceReplica, default_engine

        eng = default_engine()
        rep = self._dev_replica
        if rep is None or rep.dev is not vec_dev:
            rep = DeviceReplica(vec_dev)
            self._dev_replica = rep
        self.publish(rep.host(), clock, loss)
        frames = []  # (kind, payload, peer, alpha) in arrival order
        self._sparse_consume = True
        try:
            for peer in peers:
                if peer == self.me:
                    continue
                got = self.fetch(peer, step=step)
                if got is None:
                    continue
                remote_vec, alpha = self._weigh_remote(got, clock, loss)
                frames.append(
                    self._classify_device_frame(remote_vec, peer, alpha)
                )
        finally:
            self._sparse_consume = False
            self._membership_end_round(step)
        merges = [(peer, alpha) for _, _, peer, alpha in frames]
        merged = self._apply_device_frames(eng, rep.dev, frames)
        eng.note_round()
        if merged is not rep.dev:
            rep.swap(merged)
        return merged, merges

    def _classify_device_frame(
        self, remote_vec, peer: int, alpha: float
    ) -> tuple:
        """Map one sparse-mode consumed frame to its device-merge
        descriptor ``(kind, payload, peer, alpha)``, reading the
        double-buffered pending support ``_consume_fetch`` just set —
        must therefore run before the next consume, like the merge
        substrates themselves."""
        if self._pending_topk is not None:
            return ("topk", self._pending_topk, peer, alpha)
        if self._pending_shard is not None:
            # remote_vec IS the m-sized slice estimate (sparse consume
            # never densified); the kernel lerps [lo, lo+m) in-graph.
            return (
                "shard", (self._pending_shard[0], remote_vec), peer, alpha,
            )
        if ml_dtypes is not None and remote_vec.dtype == _DTYPES[3]:
            return ("bf16", remote_vec, peer, alpha)
        if remote_vec.dtype != np.float32:
            remote_vec = remote_vec.astype(np.float32)
        return ("dense", remote_vec, peer, alpha)

    def _apply_device_frames(
        self, eng, start_dev, frames: Sequence[tuple], fold: bool = True,
    ):
        """Apply device-frame descriptors in order onto ``start_dev``.

        Runs of consecutive dense frames batch into single ``fold``
        dispatches — bit-identical to applying them as sequential
        merges (the fold kernel's ``lax.scan`` contract); sparse and
        bf16 frames break a run and dispatch their own fused kernel,
        preserving order.  ``fold=False`` dispatches one kernel per
        frame (``async_rounds.fold`` off).  Shared by the fan-in fold
        round and the async engine's device drain; returns the merged
        device array (the caller swaps the replica)."""
        merged = start_dev
        run_r: list = []
        run_a: list = []

        def _flush_dense():
            nonlocal merged
            if not run_r:
                return
            if len(run_r) == 1 or not fold:
                for r, a in zip(run_r, run_a):
                    merged = eng.merge_dense(merged, r, a)
            else:
                merged = eng.fold(merged, list(run_r), list(run_a))
            run_r.clear()
            run_a.clear()

        for kind, payload, _peer, alpha in frames:
            if kind == "dense":
                run_r.append(payload)
                run_a.append(alpha)
                continue
            _flush_dense()
            if kind == "topk":
                idx, vals = payload
                merged = eng.merge_topk(merged, idx, vals, alpha)
            elif kind == "shard":
                lo, est_slice = payload
                merged = eng.merge_shard(merged, lo, est_slice, alpha)
            else:
                merged = eng.merge_bf16(merged, payload, alpha)
        _flush_dense()
        return merged

    def close(self) -> None:
        if self.flight is not None:
            # Clean-close dump, then drop the crash hooks — atexit must
            # not overwrite this dump with a shorter post-close ring.
            self.flight.dump("close")
            self.flight.disarm()
        if self.incidents is not None:
            self.incidents.close()
        if self.healthz is not None:
            self.healthz.close()
        if self.tracer is not None:
            self.tracer.close()
        self.server.close()
