"""ICI gossip transport: ``ppermute`` + fused merge inside ``shard_map``.

This replaces the reference's hot path end to end (SURVEY.md §3.2): where the
reference flattens params to numpy, pickles them through a TCP socket to a
peer's Rx thread, and merges on the CPU (reference ``dpwa/conn.py`` +
``dpwa/adapters/pytorch.py`` — mount empty), here every replica lives in HBM
as the per-device shard of a peer-stacked pytree and one jitted SPMD program
does, per step:

1. select the pairing in effect (``lax.switch`` over a small pool of static
   involutions — compiled once, step-indexed on device),
2. exchange parameters AND (clock, loss) metadata with the partner via
   ``lax.ppermute`` over ICI,
3. compute α from both sides' metadata (interpolation strategy) and the
   per-pair participation draw (emulating the reference's probabilistic
   fetch; SURVEY.md §7 design stance),
4. merge ``x ← (1−α)·x + α·x_peer`` — fused by XLA into the same program.

No host round-trips, no serialization, no copies: the "wire format" is the
collective itself.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from dpwa_tpu.utils.compat import shard_map

from dpwa_tpu.config import DpwaConfig
from dpwa_tpu.interpolation import Interpolation, PeerMeta, make_interpolation
from dpwa_tpu.parallel import schedules
from dpwa_tpu.parallel.mesh import PEER_AXIS, make_mesh
from dpwa_tpu.parallel.schedules import Schedule, participation_draw

PyTree = Any


class ExchangeInfo(NamedTuple):
    """Per-peer diagnostics from one gossip round (stacked over peers)."""

    partner: jnp.ndarray  # int32[n] — pairing in effect this step
    alpha: jnp.ndarray  # float32[n] — merge coefficient actually applied
    participated: jnp.ndarray  # bool[n]


def _perm_pairs(perm) -> Tuple[Tuple[int, int], ...]:
    """ppermute (source, dest) pairs so device i receives from perm[i].

    Valid for pairwise involutions AND one-sided pull maps: ``ppermute``
    only requires each *destination* to appear once; a popular source may
    feed several pullers."""
    return tuple((int(perm[i]), int(i)) for i in range(len(perm)))


def gossip_exchange_local(
    params: PyTree,
    meta: PeerMeta,
    step: jnp.ndarray,
    *,
    schedule: Schedule,
    interp: Interpolation,
    axis_name: str = PEER_AXIS,
):
    """The per-device gossip body. Call INSIDE shard_map/pjit over
    ``axis_name``; ``params`` leaves and ``meta`` scalars are this device's
    local (unstacked) values.

    Returns (merged_params, (partner, alpha, participated)) for this device.
    """
    me = lax.axis_index(axis_name)
    pool = jnp.asarray(schedule.pool)  # [K, n] baked-in constant
    branch = schedule.branch_traced(step)
    partner = pool[branch, me]

    def make_branch(perm):
        pairs = _perm_pairs(perm)

        def apply(operand):
            return jax.tree.map(
                lambda v: lax.ppermute(v, axis_name, perm=pairs), operand
            )

        return apply

    # Compressed wire: only the SHIPPED copy is compressed — bf16 halves
    # the ICI/DCN bytes; int8 quarters them for real (the collective
    # moves the ``(int8 q, f32 scales)`` encoding, NOT a dequantized f32
    # copy — the receiver decodes after the ppermute); the local replica
    # and the merge math stay f32 (the partner's contribution arrives
    # rounded, scaled by α).  Stochastic rounding keeps the quantizer
    # unbiased (ops/quantize.py).
    decode_remote = None
    if schedule.wire_dtype == "bf16":
        wire_params = jax.tree.map(
            lambda v: v.astype(jnp.bfloat16)
            if v.dtype == jnp.float32
            else v,
            params,
        )
    elif schedule.wire_dtype == "int8":
        from dpwa_tpu.ops import quantize as qz

        # Each device quantizes ITS OWN copy (sender-keyed, per-leaf) —
        # the stacked twin derives the same (step, sender, leaf) keys and
        # dequantize commutes with its gather elementwise, so the two
        # transports stay bit-identical.
        leaves, treedef = jax.tree.flatten(params)
        enc = [
            qz.quantize(v, qz.wire_key(schedule.seed, step, me, leaf=i))
            if v.dtype == jnp.float32
            else v
            for i, v in enumerate(leaves)
        ]
        # (q, scales) tuples become subtrees: ppermute moves the int8
        # codes and the tiny f32 scale vectors as separate leaves.
        wire_params = jax.tree.unflatten(treedef, enc)

        def decode_remote(remote_tree):
            flat = jax.tree.leaves(remote_tree)
            out, j = [], 0
            for v in leaves:
                if v.dtype == jnp.float32:
                    q, s = flat[j], flat[j + 1]
                    j += 2
                    out.append(qz.dequantize(q, s, v.shape))
                else:
                    out.append(flat[j])
                    j += 1
            return jax.tree.unflatten(treedef, out)

    else:
        wire_params = params
    remote_params, remote_meta = lax.switch(
        branch,
        [make_branch(p) for p in schedule.pool],
        (wire_params, meta),
    )
    if decode_remote is not None:
        remote_params = decode_remote(remote_params)

    # Pull mode: the pull is one-sided, so the puller draws alone (the
    # reference's per-process fetch decision); pairwise: both members of a
    # pair share one draw keyed on min(i, partner).
    pair_id = me if schedule.mode == "pull" else jnp.minimum(me, partner)
    if schedule.fetch_probability >= 1.0:
        drawn = jnp.bool_(True)
    else:
        drawn = participation_draw(
            schedule.seed, step, pair_id, schedule.fetch_probability
        )
    if schedule.drop_probability > 0.0:
        # Fault injection: masked merge (α=0) is the SPMD form of the
        # reference's timed-out fetch (SURVEY.md §5).
        drawn = jnp.logical_and(
            drawn,
            jnp.logical_not(
                schedules.fault_draw(
                    schedule.seed, step, pair_id, schedule.drop_probability
                )
            ),
        )
    participated = jnp.logical_and(drawn, partner != me)
    alpha = jnp.where(participated, interp(meta, remote_meta), 0.0)
    alpha = alpha.astype(jnp.float32)

    def merge(x, y):
        a = alpha.astype(jnp.promote_types(x.dtype, jnp.float32))
        return ((1.0 - a) * x.astype(a.dtype) + a * y.astype(a.dtype)).astype(
            x.dtype
        )

    merged = jax.tree.map(merge, params, remote_params)
    return merged, (partner, alpha, participated)


class IciTransport:
    """On-device gossip over a ``peers`` mesh axis.

    Drop-in peer of :class:`dpwa_tpu.parallel.tcp.TcpTransport` behind the
    same exchange semantics (SURVEY.md §7 transports plugin interface), but
    SPMD: one process owns all replicas as a peer-stacked, peer-sharded
    pytree and :meth:`exchange` advances every replica's gossip round in a
    single XLA program.
    """

    def __init__(
        self,
        config: DpwaConfig,
        mesh: Optional[Mesh] = None,
        axis_name: str = PEER_AXIS,
    ):
        self.config = config
        self.schedule = schedules.build_schedule(config)
        self.interp = make_interpolation(
            config.interpolation,
            max_abs_loss=(
                config.recovery.rescue_bound() if config.recovery.enabled else None
            ),
        )
        self.axis_name = axis_name
        self.mesh = mesh if mesh is not None else make_mesh(config, axis_name=axis_name)
        (axis_size,) = (self.mesh.shape[axis_name],)
        if axis_size != config.n_peers:
            raise ValueError(
                f"mesh axis {axis_name!r} has size {axis_size} but config "
                f"names {config.n_peers} peers"
            )
        # XLA:CPU's in-process collectives rendezvous on a shared thread
        # pool; on thread-starved hosts, letting many in-flight steps queue
        # up deadlocks the pool (threads blocked in step k+j's rendezvous
        # starve the laggards of step k, which aborts after 40s).  Bounding
        # run-ahead to one step on CPU meshes removes the hazard; real TPU
        # meshes keep fully async dispatch.
        self._block_per_call = all(
            d.platform == "cpu" for d in self.mesh.devices.flat
        )
        self._exchange = self._build_exchange()

    def _build_exchange(self):
        schedule, interp, axis = self.schedule, self.interp, self.axis_name

        def body(params, meta, step):
            # shard_map hands us a leading peer axis of local size 1;
            # strip it so interpolation sees true scalars, then restore.
            params1 = jax.tree.map(lambda v: v[0], params)
            meta1 = jax.tree.map(lambda v: v[0], meta)
            merged, (partner, alpha, part) = gossip_exchange_local(
                params1,
                meta1,
                step,
                schedule=schedule,
                interp=interp,
                axis_name=axis,
            )
            merged = jax.tree.map(lambda v: v[None], merged)
            return merged, (
                partner[None],
                alpha[None],
                part[None],
            )

        mapped = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(self.axis_name), P(self.axis_name), P()),
            out_specs=(
                P(self.axis_name),
                (P(self.axis_name), P(self.axis_name), P(self.axis_name)),
            ),
        )

        @jax.jit
        def exchange(params, meta, step):
            merged, (partner, alpha, part) = mapped(params, meta, step)
            return merged, ExchangeInfo(partner, alpha, part)

        return exchange

    def exchange(
        self, params: PyTree, meta: PeerMeta, step
    ) -> Tuple[PyTree, ExchangeInfo]:
        """One gossip round over every replica.

        Args:
          params: pytree whose leaves are peer-stacked ``[n_peers, ...]``
            arrays (ideally already sharded with :func:`peer_sharding`).
          meta: :class:`PeerMeta` of ``[n_peers]`` float32 arrays.
          step: int — selects the pairing and the participation draw.
        """
        out = self._exchange(params, meta, jnp.asarray(step, jnp.int32))
        if self._block_per_call:
            jax.block_until_ready(out)
        return out
