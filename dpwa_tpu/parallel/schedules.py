"""Gossip pairing schedules.

The reference's ``RumorProtocol`` decides, each step, whether to exchange and
with whom: a random peer pulled with some probability (SURVEY.md §2/§3.2 —
reference had this in/near ``dpwa/conn.py``; mount empty).  In the SPMD
re-design that per-process random choice becomes a **deterministic per-step
pairing permutation** shared by all devices, with the probabilistic part
emulated by a per-pair participation mask (α forced to 0 when a pair "would
not have gossiped" — SURVEY.md §7 design stance).

Every pairing is an **involution** (perm[perm[i]] == i): ``ppermute`` is
one-directional, and a pairwise average needs both members to receive each
other, so schedules emit perfect matchings (odd one out pairs with itself and
is masked).  SURVEY.md §7 hard part #2.

Compile-once design: a schedule materializes a small **pool** of static
pairings at init (ring: 2; random: ``pool_size`` matchings; hierarchical: its
period).  The jitted exchange selects a pool entry with ``lax.switch`` indexed
by a traced function of ``step`` — no per-step recompilation, no host
round-trip in the hot loop.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dpwa_tpu.config import DpwaConfig
# Every control-tag literal comes from the central registry; dpwalint's
# determinism checker rejects raw tag ints in the draw calls below.
from dpwa_tpu.utils import tags as _tags


def _pair_key(seed, step, pair_id, tag: int):
    return jax.random.fold_in(
        jax.random.fold_in(
            jax.random.fold_in(
                jax.random.key(seed), jnp.asarray(step, jnp.int32)
            ),
            jnp.asarray(pair_id, jnp.int32),
        ),
        tag,
    )


def participation_draw(seed, step, pair_id, fetch_probability):
    """One Bernoulli per (step, pair), shared by both members of the pair.

    Defined once in terms of ``jax.random`` (counter-based threefry) so the
    host-side TCP transport and the in-jit ICI transport draw **identical**
    streams from the same (seed, step, pair) — this is what makes the
    TCP-vs-ICI parity test (SURVEY.md §4) bit-comparable.  All of ``step`` and
    ``pair_id`` may be traced.
    """
    return jax.random.uniform(_pair_key(seed, step, pair_id, _tags.TAG_PARTICIPATION)) < fetch_probability


def fault_draw(seed, step, pair_id, drop_probability):
    """Fault-injection draw: True means this pair's exchange is DROPPED.

    The gossip failure model (SURVEY.md §5 "Failure detection"): a failed
    fetch is skipped and training continues.  A nonzero
    ``protocol.drop_probability`` injects such failures at a configured rate
    — an independent threefry stream (tag 1) from the participation draw, so
    the two knobs compose without correlation.  Same stream on the host (TCP
    path times out naturally, but injection lets tests force it) and in-jit
    (masked merge, α=0)."""
    return jax.random.uniform(_pair_key(seed, step, pair_id, _tags.TAG_FAULT)) < drop_probability


def fallback_draw(seed, step, me, n_candidates: int):
    """Index of the fallback partner a peer reroutes to when its scheduled
    partner is quarantined (tag 3 — independent of the participation,
    fault, and pool streams).

    Keyed on ``(seed, step, me)`` only: every lock-step replica holding
    the same healthy-peer view draws the same fallback, so the
    health-aware remap (:meth:`Schedule.remap_partner`) preserves
    bit-identical behavior across replicas — the same property the
    participation draw guarantees."""
    return jax.random.randint(
        _pair_key(seed, step, me, _tags.TAG_FALLBACK), (), 0, n_candidates
    )


def backoff_jitter_draw(seed, peer, streak, jitter_rounds: int) -> int:
    """Deterministic quarantine-backoff jitter in ``[0, jitter_rounds]``
    (tag 4), keyed on ``(seed, peer, consecutive-quarantine count)``.

    Jitter de-synchronizes probe storms (many fetchers re-probing a
    recovered peer on the same round) without sacrificing run-to-run
    reproducibility — the chaos acceptance test replays byte-identical
    quarantine windows under a fixed seed."""
    if jitter_rounds <= 0:
        return 0
    return int(
        jax.random.randint(
            _pair_key(seed, peer, streak, _tags.TAG_BACKOFF_JITTER), (), 0, jitter_rounds + 1
        )
    )


def donor_draw(seed, step, me, n_candidates: int):
    """Index of the bootstrap donor a restarted peer fetches state from
    when several healthy candidates exist (tag 5 — independent of every
    other control stream).

    Keyed on ``(seed, step, me)`` like :func:`fallback_draw`: a rejoiner
    restarted at the same (seed, step) always elects the same donor, so
    the crash→restart→bootstrap acceptance path replays bit-identically
    and load spreads across donors instead of always hammering the
    lowest-indexed healthy peer."""
    return jax.random.randint(
        _pair_key(seed, step, me, _tags.TAG_DONOR), (), 0, n_candidates
    )


def relay_draw(seed, step, me, probe_slot: int, n_candidates: int):
    """Index of the ``probe_slot``-th relay a suspecting peer asks to
    header-probe a suspect before quarantining it (tag 6 — independent
    of every other control stream; ``probe_slot`` is folded in so the K
    indirect probes of one round draw distinct streams).

    Keyed on ``(seed, step, me, probe_slot)``: replays of a seed pick
    the identical relay set, so indirect-probe outcomes — and therefore
    quarantine decisions — stay bit-identical across runs."""
    return jax.random.randint(
        jax.random.fold_in(_pair_key(seed, step, me, _tags.TAG_RELAY_PROBE), probe_slot),
        (), 0, n_candidates,
    )


def degrade_shed_draw(seed, step, me):
    """Uniform [0, 1) deciding whether THIS round's exchange with a
    soft-DEGRADED scheduled partner is shed to a fallback (tag 8).

    Compared against ``flowctl.degrade_shed_fraction``: below it, the
    round remaps away from the overloaded peer; at or above it, the
    fetch proceeds under the peer's (short) adaptive deadline so recovery
    evidence keeps flowing.  Keyed on ``(seed, step, me)`` like
    :func:`fallback_draw`, so shed decisions replay bit-identically."""
    return float(jax.random.uniform(_pair_key(seed, step, me, _tags.TAG_DEGRADE_SHED)))


def heal_draw(seed, step, me, n_candidates: int):
    """Index of the reconciliation donor drawn from a returning
    partition component at heal time (tag 7).

    Keyed on ``(seed, step, me)`` like :func:`donor_draw`: every member
    of the staying component reconciles against a deterministically
    drawn member of the returning one, spreading the anti-entropy fetch
    load while keeping heal events replayable."""
    return jax.random.randint(
        _pair_key(seed, step, me, _tags.TAG_HEAL_DONOR), (), 0, n_candidates
    )


@jax.jit
def _uniform_draw(seed, step, pair_id, tag):
    # Jitted once for the whole uniform-draw family (tag is traced): the
    # fleet orchestrator pays one leave + one join draw per node per
    # round, where the eager fold_in dispatch chain dominates the round.
    return jax.random.uniform(_pair_key(seed, step, pair_id, tag))


def churn_leave_draw(seed, round_, peer):
    """Uniform [0, 1) deciding whether ``peer`` LEAVES the fleet at
    ``round_`` (tag 10 — the fleet orchestrator's continuous-departure
    stream, compared against ``ChurnSchedule.leave_probability``).

    Keyed on ``(seed, round, peer)`` like :func:`chaos_draw`, so a churn
    episode replays bit-identically under a fixed seed — the property the
    8-peer mini-churn acceptance test asserts across reruns."""
    return float(_uniform_draw(seed, round_, peer, _tags.TAG_CHURN_LEAVE))


def churn_join_draw(seed, round_, peer):
    """Uniform [0, 1) deciding whether a departed ``peer`` REJOINS at
    ``round_`` (tag 11 — independent of the leave stream, so arrival and
    departure rates tune without correlation)."""
    return float(_uniform_draw(seed, round_, peer, _tags.TAG_CHURN_JOIN))


def churn_cohort_draw(seed, round_, n_max: int):
    """Size of an autoscale-style cohort arrival at ``round_`` in
    ``[0, n_max]`` (tag 12, peer key 0 — one draw per round, like the
    partition-split draw).  0 means no cohort lands this round; the
    orchestrator admits the ``n`` lowest-indexed departed peers at once,
    the membership-merge burst a real autoscaler produces."""
    if n_max <= 0:
        return 0
    return int(
        jax.random.randint(
            _pair_key(seed, round_, 0, _tags.TAG_CHURN_COHORT), (), 0, n_max + 1
        )
    )


def churn_restart_draw(seed, round_, n_candidates: int):
    """Index of the live peer rolling-restarted at ``round_`` (tag 13,
    peer key 0 — one draw per restart event, over the live-peer list in
    index order).  Drawn, not round-robin, so restart order decorrelates
    from ring position while staying replayable."""
    return int(
        jax.random.randint(
            _pair_key(seed, round_, 0, _tags.TAG_CHURN_RESTART),
            (), 0, n_candidates,
        )
    )


def leader_draw(seed, term, island, n_candidates: int):
    """Index of the island member elected leader for ``term`` (tag 14).

    Keyed on (seed, term, island) — every replica that knows the same
    candidate list elects the same leader with no coordination round,
    which is the whole point: succession after a leader death is just a
    term bump plus a re-draw over the surviving members, computed
    independently and identically everywhere (dpwa_tpu/hier/leader.py).
    The draw indexes the SORTED surviving-member list, so determinism
    only needs agreement on who is alive, which membership already
    disseminates."""
    return int(
        jax.random.randint(
            _pair_key(seed, term, island, _tags.TAG_LEADER),
            (), 0, n_candidates,
        )
    )


def island_churn_draw(seed, round_, island):
    """Uniform [0,1) deciding whether ``island`` churns as a unit at
    ``round_`` (tag 15) — the fleet orchestrator's whole-island
    join/leave stream, independent of the per-peer churn draws so
    island-granular chaos does not skew individual-peer churn."""
    return float(
        jax.random.uniform(_pair_key(seed, round_, island, _tags.TAG_ISLAND_CHURN))
    )


def shard_permutation(seed, epoch, k: int) -> np.ndarray:
    """The shard-visit order for one k-round epoch (tag 32 — the first
    draw in the second control block, ``tags.CONTROL_TAG_BASE_2``).

    A fresh permutation of ``range(k)`` per epoch, keyed on
    ``(seed, epoch)`` only: every peer holding the seed computes the
    same order, so a pair exchanges the SAME slice in both directions
    each round with no negotiation, and coverage is balanced by
    construction — each shard is visited exactly once per ``k``
    consecutive rounds.  A permutation rather than ``step % k`` so the
    visit order varies across epochs (a fixed order would give shard 0
    systematically fresher averages than shard k−1 at any stopping
    point)."""
    return np.asarray(
        jax.random.permutation(_pair_key(seed, epoch, 0, _tags.TAG_SHARD), k)
    )


def shard_draw(seed, step, k: int) -> int:
    """Shard index published at ``step`` under a k-way partition.

    Pure function of ``(seed, step, k)``: ``step`` is bucketed into
    epochs of ``k`` rounds and indexes that epoch's
    :func:`shard_permutation`.  The TCP transport keys this on its
    publish clock; hot-path callers should cache the per-epoch
    permutation (one draw per k rounds) rather than re-drawing here
    every round."""
    k = int(k)
    if k <= 1:
        return 0
    epoch, pos = divmod(int(step), k)
    return int(shard_permutation(seed, epoch, k)[pos])


def async_drain_draw(seed, step, peer) -> float:
    """Uniform [0,1) tie-break for the async drain order (tag 33).

    When several peers hold pending frames at the SAME publish clock,
    the :class:`~dpwa_tpu.parallel.async_loop.AsyncExchangeEngine`
    drains them sorted by ``(lag, draw, peer)`` — this draw rotates the
    equal-lag order across steps so no peer's frame is systematically
    merged last (the clock-major sort already fixes cross-lag order).
    Pure function of ``(seed, step, peer)``: a rerun of the same soak
    drains identically regardless of arrival-thread timing."""
    return float(
        jax.random.uniform(_pair_key(seed, step, peer, _tags.TAG_ASYNC_DRAIN))
    )


def data_shuffle_draw(seed, epoch, me, n_samples: int) -> np.ndarray:
    """Node ``me``'s data-shard permutation for one training epoch
    (tag 36 — the training-harness data-order stream).

    Pure function of ``(seed, epoch, me)``: the harness's per-node batch
    sequence is fully determined by the config seed, so a seeded rerun
    replays byte-identical loss curves, and a crashed node restarting
    from a checkpoint's ``(epoch, cursor)`` resumes the EXACT stream it
    left — no RNG state rides the checkpoint.  A stream independent of
    every control draw: data order must not correlate with partner
    choice or fault injection."""
    return np.asarray(
        jax.random.permutation(
            _pair_key(seed, epoch, me, _tags.TAG_DATA_SHUFFLE), n_samples
        )
    )


def tune_jitter_draw(seed, clock, link, jitter_rounds: int) -> int:
    """Dwell-jitter offset in ``[0, jitter_rounds]`` for one link's
    escalation decision (tag 37 — the self-tuning-wire stream).

    When a link's observation window says "wire-bound" the controller
    does not escalate the instant the dwell expires: it adds this drawn
    offset so that many links shaped by the same event do not all step
    their codec on the same round (the backoff_jitter_draw argument,
    applied to the ladder).  Keyed on ``(seed, publish clock, link)``
    like :func:`shard_draw`, so a seeded rerun replays the identical
    escalation rounds and both ends of a link agree without
    negotiation."""
    if jitter_rounds <= 0:
        return 0
    return int(
        jax.random.randint(
            _pair_key(seed, clock, link, _tags.TAG_TUNE_JITTER),
            (), 0, jitter_rounds + 1,
        )
    )


@functools.partial(jax.jit, static_argnums=(3,))
def _view_perm(seed, clock, me, n_candidates: int):
    # Jitted: this is the one control draw on the per-frame publish path
    # (every other draw fires on failures or round boundaries), so the
    # eager fold_in dispatch cost would be paid once per published frame.
    return jax.random.permutation(
        _pair_key(seed, clock, me, _tags.TAG_VIEW_SAMPLE), n_candidates
    )


def view_sample_draw(seed, clock, me, n_candidates: int) -> np.ndarray:
    """Permutation of the tracked-peer candidate list for one digest
    frame (tag 34 — the partial-view sample stream).

    Keyed on ``(seed, publish clock, me)``: a node's frame at a given
    clock always samples the same peers, so seeded reruns publish
    byte-identical digests and any two receivers of the frame saw the
    same subset.  Callers index the first ``digest_sample`` entries of
    this permutation into the canonically-sorted candidate list —
    truncation happens in the caller, so ``sample >= n_candidates``
    degenerates to the full list and the identity guarantee holds."""
    return np.asarray(_view_perm(seed, clock, me, n_candidates))


def passive_shuffle_draw(seed, round_, me, n_candidates: int):
    """Index of the passive-view candidate promoted (or displaced) on a
    shuffle or failure-replacement event (tag 35 — independent of the
    digest-sample stream, so truncation cannot skew replacement).

    Keyed on ``(seed, round, me)``: replicas replaying a seed promote
    identical replacements, which keeps the 4096-peer soak bit-identical
    across reruns."""
    return jax.random.randint(
        _pair_key(seed, round_, me, _tags.TAG_PASSIVE_SHUFFLE),
        (), 0, n_candidates,
    )


_CONTROL_DRAWS_WARM = False


def warm_control_draws(seed: int = 0, me: int = 0) -> None:
    """Pay every control-plane draw's first-call jit compile up front.

    Each draw family above is a distinct jitted computation whose first
    invocation compiles (~1s apiece on CPU).  Left lazy, that cost lands
    at the first *failure* — only on the replicas that experience one —
    which stalls their step clock mid-incident, skews every round-keyed
    decision (chaos windows, relay vouching, backoff expiry) ring-wide,
    and is exactly the wall-clock sensitivity the control plane is
    designed not to have.  Calling this at transport init moves every
    compile off the training clock; repeat calls are near-free (the jit
    cache is the real latch, the module flag just skips the dispatch).
    """
    global _CONTROL_DRAWS_WARM
    if _CONTROL_DRAWS_WARM:
        return
    bool(participation_draw(seed, 0, 0, 0.5))
    bool(fault_draw(seed, 0, 0, 0.5))
    int(fallback_draw(seed, 0, me, 2))
    backoff_jitter_draw(seed, me, 1, 1)
    int(donor_draw(seed, 0, me, 2))
    int(relay_draw(seed, 0, me, 0, 2))
    int(heal_draw(seed, 0, me, 2))
    float(degrade_shed_draw(seed, 0, me))
    float(chaos_draw(seed, 0, me, _tags.CHAOS_KIND_DROP))
    float(churn_leave_draw(seed, 0, me))
    float(churn_join_draw(seed, 0, me))
    churn_cohort_draw(seed, 0, 1)
    churn_restart_draw(seed, 0, 2)
    leader_draw(seed, 0, 0, 2)
    island_churn_draw(seed, 0, 0)
    shard_draw(seed, 0, 2)
    float(async_drain_draw(seed, 0, me))
    view_sample_draw(seed, 0, me, 2)
    int(passive_shuffle_draw(seed, 0, me, 2))
    tune_jitter_draw(seed, 0, me, 1)
    _CONTROL_DRAWS_WARM = True


# Chaos fault-kind tags start far clear of the control-plane tags so
# new control draws can claim the 10..15 range without colliding with
# fault kinds.  The allocation map lives in dpwa_tpu/utils/tags.py;
# re-exported here because chaos/test code historically imports it from
# the schedules module.
CHAOS_TAG_BASE = _tags.CHAOS_TAG_BASE


def chaos_draw(seed, step, peer, kind: int):
    """Uniform [0, 1) draw on the chaos-harness fault stream.

    One independent threefry stream per ``(peer, fault kind)`` — kinds
    index from :data:`CHAOS_TAG_BASE` — keyed on the gossip round, so
    injected faults are schedule-locked: a given (seed, round, peer)
    always injects the same fault, in tests and in a ``chaos:``-config
    soak alike (the same design as :func:`fault_draw`)."""
    return float(
        jax.random.uniform(_pair_key(seed, step, peer, _tags.CHAOS_TAG_BASE + kind))
    )


def pool_branch_draw(seed, step, pool_size: int, periodic: bool):
    """Pool index in effect at ``step`` — traced or host, same stream.

    Deterministic schedules (ring phases, the hierarchical period) cycle:
    ``step % pool_size`` — the period IS the design.  The ``random``
    schedule must not: cycling a pool of K matchings gives the pairing
    sequence period K, a correlation artifact the reference (fresh draws
    every step) does not have.  Its pool entry is therefore drawn i.i.d.
    per step from an independent threefry stream (tag 2) shared by the
    host (TCP) and in-jit (ICI/stacked) paths, so lock-step parity holds
    while the pairing sequence is aperiodic."""
    step = jnp.asarray(step, jnp.int32)
    if periodic or pool_size <= 1:
        return jnp.mod(step, pool_size)
    return jax.random.randint(_pair_key(seed, step, 0, _tags.TAG_POOL_BRANCH), (), 0, pool_size)


def is_involution(perm: np.ndarray) -> bool:
    """True iff perm is a valid pairing: perm[perm[i]] == i for all i."""
    idx = np.arange(len(perm))
    return bool(np.all(perm[perm] == idx))


def _ring_even(n: int) -> np.ndarray:
    """Pair (0,1),(2,3),...  Last element self-pairs when n is odd."""
    perm = np.arange(n)
    for i in range(0, n - 1, 2):
        perm[i], perm[i + 1] = i + 1, i
    return perm


def _ring_odd(n: int) -> np.ndarray:
    """Pair (1,2),(3,4),... and close the ring with (n-1, 0) when n is even.

    n == 2 keeps the single pair active in both phases — a 2-node ring has
    only one edge, and idling it every other step would halve the exchange
    rate for no reason."""
    if n == 2:
        return np.array([1, 0])
    perm = np.arange(n)
    for i in range(1, n - 1, 2):
        perm[i], perm[i + 1] = i + 1, i
    if n % 2 == 0:
        perm[n - 1], perm[0] = 0, n - 1
    return perm


def _random_matching(n: int, rng: np.random.Generator) -> np.ndarray:
    """A uniform random perfect matching (odd one out self-pairs)."""
    order = rng.permutation(n)
    perm = np.arange(n)
    for i in range(0, n - 1, 2):
        a, b = order[i], order[i + 1]
        perm[a], perm[b] = b, a
    return perm


def _ring_pull(n: int, phase: int) -> np.ndarray:
    """Directed ring pull map: peer i pulls from its ±1 neighbor."""
    return (np.arange(n) + (1 if phase % 2 == 0 else -1)) % n


def _exponential_pool(n: int) -> np.ndarray:
    """Hypercube (recursive-doubling) pool: slot k pairs ``i ↔ i XOR 2^k``.

    The fastest-mixing pairing sequence there is: with α = 0.5 and full
    participation, one pass over the log2(n) slots IS an exact all-reduce
    — every replica equals the global mean after log2(n) pairwise merges
    (each slot averages across one hypercube dimension; property-tested).
    Under probabilistic participation it degrades gracefully to gossip
    with an O(log n) mixing time, vs O(n²) for the ring.  XOR pairings
    are involutions by construction.  Requires n a power of two."""
    if n < 2 or n & (n - 1) != 0:
        # n == 1 would pass the bit test (1 & 0 == 0) but has zero hypercube
        # dimensions — reject it with the same clear message instead of
        # letting np.stack([]) raise something opaque.
        raise ValueError(
            f"exponential schedule needs a power-of-two peer count >= 2, got {n}"
        )
    bits = n.bit_length() - 1
    idx = np.arange(n)
    return np.stack([idx ^ (1 << k) for k in range(bits)])


def _random_pull(n: int, rng: np.random.Generator) -> np.ndarray:
    """Random pull map: every peer pulls a distinct source != itself.

    Sattolo's algorithm — a uniform random *cyclic* permutation, so
    ``src[i]`` is uniform over the other peers (the reference's per-process
    random pick) while sources stay distinct.  The distinctness matters on
    the fabric: ``lax.ppermute`` carries one send per source per round, so
    a popular source cannot multicast; a derangement is the reference's
    iid pull conditioned on collision-freeness (same marginals).  True
    collisions still occur on the TCP transport under free-running
    processes, where the Rx thread naturally serves any number of
    fetchers."""
    src = np.arange(n)
    for i in range(n - 1, 0, -1):
        j = rng.integers(0, i)
        src[i], src[j] = src[j], src[i]
    return src


def _hierarchical_pull_pool(
    n: int, group_size: int, inter_period: int
) -> np.ndarray:
    """Pull-mode two-level pool: directed intra-group ring rotations, with
    every ``inter_period``-th slot pulling from the same index in the next
    group (groups in a directed ring)."""
    if n % group_size != 0:
        raise ValueError(f"n_peers {n} not divisible by group_size {group_size}")
    if inter_period < 1:
        raise ValueError(f"inter_period must be >= 1, got {inter_period}")
    n_groups = n // group_size
    if inter_period == 1 and group_size > 1 and n_groups > 1:
        # Same disconnection as the pairwise pool: an all-inter pool never
        # mixes across intra-group indices.
        raise ValueError(
            "hierarchical schedule with inter_period=1 has no intra-group "
            "slots, so the gossip graph is disconnected for group_size >= 2; "
            "use inter_period >= 2"
        )
    pool = []
    for slot in range(inter_period):
        if slot == inter_period - 1 and n_groups > 1:
            src = np.arange(n)
            for g in range(n_groups):
                pg = (g + 1) % n_groups
                src[g * group_size : (g + 1) * group_size] = (
                    np.arange(group_size) + pg * group_size
                )
            pool.append(src)
        else:
            base = _ring_pull(group_size, slot)
            pool.append(
                np.concatenate([base + g * group_size for g in range(n_groups)])
            )
    return np.stack(pool)


def _group_round_robin(n_groups: int) -> list[np.ndarray]:
    """Round-robin tournament (circle method) over groups.

    Returns a list of group-level perfect matchings (involutions over
    ``range(n_groups)``) that together visit **every unordered group pair**:
    ``n_groups - 1`` rounds for even counts, ``n_groups`` rounds for odd
    (one group sits out per round, left as a masked self-pair).  Standard
    circle method: pin item 0, rotate the rest one position per round, pair
    position ``i`` with position ``m-1-i``."""
    if n_groups == 1:
        return [np.array([0])]
    m = n_groups if n_groups % 2 == 0 else n_groups + 1  # m-1 = bye dummy
    arr = list(range(m))
    rounds = []
    for _ in range(m - 1):
        gperm = np.arange(n_groups)
        for i in range(m // 2):
            a, b = arr[i], arr[m - 1 - i]
            if a < n_groups and b < n_groups:  # skip the odd-count dummy
                gperm[a], gperm[b] = b, a
        rounds.append(gperm)
        arr = [arr[0], arr[-1]] + arr[1:-1]
    return rounds


def _hierarchical_pool(
    n: int, group_size: int, inter_period: int
) -> np.ndarray:
    """Two-level pool: intra-group ring pairings, with every
    ``inter_period``-th slot exchanging across groups instead.

    The inter slots cycle through a **round-robin tournament over groups**
    (:func:`_group_round_robin`): block ``b`` of the pool ends with peer
    ``i`` of group ``g`` paired with peer ``i`` of round ``b``'s partner
    group, so over one pool period every group meets every other group —
    the gossip graph is connected for any ``n_groups`` (a single rotating
    ring phase is NOT enough: with a fixed ``_ring_even(n_groups)`` inter
    pairing, 4 groups split into two components {0↔1, 2↔3} forever).
    Pool length = ``inter_period × n_rounds``.

    Intra slots alternate the two ring phases on a *global* intra-slot
    counter — per-block parity would pin ``inter_period == 2`` pools to
    the even phase only, disconnecting groups of size ≥ 4 internally.
    This is the intra-host-ICI / inter-host-DCN split of BASELINE.json:10
    (config 4, hierarchical averaging).
    """
    if n % group_size != 0:
        raise ValueError(f"n_peers {n} not divisible by group_size {group_size}")
    if inter_period < 1:
        raise ValueError(f"inter_period must be >= 1, got {inter_period}")
    n_groups = n // group_size
    if inter_period == 1 and group_size > 1 and n_groups > 1:
        # With inter_period=1 every slot is the index-preserving cross-group
        # pairing: peers at different intra-group indices would never
        # exchange — a permanently disconnected gossip graph.
        raise ValueError(
            "hierarchical schedule with inter_period=1 has no intra-group "
            "slots, so the gossip graph is disconnected for group_size >= 2; "
            "use inter_period >= 2"
        )
    rounds = _group_round_robin(n_groups) if n_groups > 1 else [None]
    n_blocks = len(rounds)
    # Guarantee both intra ring phases appear in the pool (needed to connect
    # groups of size > 2) even when there is only one intra slot per block.
    if group_size > 2 and n_blocks * (inter_period - 1) < 2:
        rounds = rounds * 2
        n_blocks *= 2
    pool = []
    intra_count = 0
    for block in range(n_blocks):
        for slot in range(inter_period):
            if slot == inter_period - 1 and n_groups > 1:
                # Inter-group slot: this block's tournament-round pairing.
                gperm = rounds[block]
                perm = np.arange(n)
                for g in range(n_groups):
                    pg = gperm[g]
                    perm[g * group_size : (g + 1) * group_size] = (
                        np.arange(group_size) + pg * group_size
                    )
                pool.append(perm)
            else:
                # Intra-group slot: ring phase alternates globally.
                base = (
                    _ring_even if intra_count % 2 == 0 else _ring_odd
                )(group_size)
                intra_count += 1
                perm = np.concatenate(
                    [base + g * group_size for g in range(n_groups)]
                )
                pool.append(perm)
    return np.stack(pool)


@dataclasses.dataclass(frozen=True)
class Schedule:
    """A compiled-pool gossip schedule.

    Attributes:
      pool: [K, n] int32 — K static involution pairings.
      n_peers: mesh-axis size (length of the YAML ``nodes:`` list).
      fetch_probability: per-step chance that a pair actually exchanges;
        emulates the reference's probabilistic pull (masked, not skipped).
      seed: RNG seed for the participation draws (and the random pool).
    """

    pool: np.ndarray
    n_peers: int
    fetch_probability: float
    seed: int
    name: str
    drop_probability: float = 0.0
    mode: str = "pairwise"  # pairwise (involutions) | pull (one-sided maps)
    wire_dtype: str = "f32"  # precision of the shipped replica (f32 | bf16)
    # Optional [period] map from step-in-period to pool row.  The
    # hierarchical schedule's cycle repeats the two intra ring phases many
    # times (period = inter_period × n_tournament_rounds slots, but only
    # n_rounds + 2 DISTINCT pairings) — deduping keeps the jit path's
    # lax.switch at one branch per distinct pairing instead of one per
    # slot, bounding compile time as group count grows.  None ⇒ identity.
    branch_map: Optional[np.ndarray] = None

    @property
    def pool_size(self) -> int:
        return len(self.pool)

    @property
    def period(self) -> int:
        """Length of the schedule's repeating cycle in steps (for periodic
        schedules; the random schedule draws i.i.d. and has no cycle)."""
        return len(self.branch_map) if self.branch_map is not None else len(self.pool)

    @property
    def periodic(self) -> bool:
        """Whether pool selection cycles (ring/hierarchical) or is drawn
        per step (random — see :func:`pool_branch_draw`)."""
        return self.name != "random"

    def branch_traced(self, step):
        """Pool index at ``step`` as a traced int32 (the jit-path form)."""
        idx = pool_branch_draw(self.seed, step, self.period, self.periodic)
        if self.branch_map is not None:
            idx = jnp.asarray(self.branch_map, jnp.int32)[idx]
        return idx

    def branch(self, step: int) -> int:
        """Host-side pool index for ``step`` — same stream as the jit path."""
        if self.periodic or self.pool_size <= 1:
            idx = int(step) % self.period
            return int(self.branch_map[idx]) if self.branch_map is not None else idx
        return int(self.branch_traced(step))

    def pair_id(self, i: int, partner: int):
        """The RNG key a peer's participation/fault draws are folded on.

        Pairwise mode: ``min(i, partner)`` — both members of a pair share
        one draw, so the exchange is all-or-nothing.  Pull mode: ``i`` —
        the pull is one-sided, so the puller draws alone (the reference's
        per-process independent fetch decision, SURVEY.md §3.2)."""
        return i if self.mode == "pull" else min(i, partner)

    def pairing(self, step: int) -> np.ndarray:
        """The pairing permutation (pairwise) or pull map (pull) in effect
        at ``step`` (host-side view, used by the TCP transport and tests)."""
        return self.pool[self.branch(step)]

    def partner(self, step: int, i: int) -> int:
        return int(self.pairing(step)[i])

    def remap_partner(
        self, step: int, i: int, partner: int, healthy_mask,
        candidates=None,
    ) -> int:
        """Health-aware fallback: the peer ``i`` fetches at ``step`` when
        its scheduled ``partner`` is quarantined.

        Candidates are every peer that is healthy per ``healthy_mask``
        (indexable by peer id), excluding ``i`` itself and the sick
        ``partner``; the pick is a :func:`fallback_draw` over the
        candidate list in index order.  Deterministic: replicas that
        agree on the healthy set agree on the remap — and a remapped
        round is a one-sided pull (the fallback peer's Rx server serves
        any fetcher; it does not reciprocate), which pairwise averaging
        tolerates the same way the reference's random pulls do.

        No healthy candidate ⇒ returns ``i`` (self-pair, i.e. the round
        is skipped — the all-peers-dead posture is solo training).

        ``candidates`` (optional, sorted peer ids) restricts the draw to
        a partial view's active peers instead of all of ``range(n)`` —
        with ``candidates=None`` (or a view spanning the whole ring) the
        candidate list, and therefore the draw, is identical to the
        legacy global path."""
        universe = (
            range(self.n_peers) if candidates is None else candidates
        )
        candidates = [
            p
            for p in universe
            if p != i and p != partner and healthy_mask[p]
        ]
        if not candidates:
            return i
        idx = int(fallback_draw(self.seed, step, i, len(candidates)))
        return candidates[idx]

    def participates(self, step: int, i: int) -> bool:
        """Host-side participation draw — the same threefry stream the jit
        path uses (see :func:`participation_draw`)."""
        p = self.partner(step, i)
        if p == i:
            return False
        pair_id = self.pair_id(i, p)
        ok = self.fetch_probability >= 1.0 or bool(
            participation_draw(
                self.seed, step, pair_id, self.fetch_probability
            )
        )
        if ok and self.drop_probability > 0.0:
            ok = not bool(
                fault_draw(self.seed, step, pair_id, self.drop_probability)
            )
        return ok


def build_schedule(config: DpwaConfig) -> Schedule:
    """Materialize the pairing/pull pool described by ``config.protocol``."""
    proto = config.protocol
    n = config.n_peers
    pull = proto.mode == "pull"
    if n == 1:
        pool = np.zeros((1, 1), dtype=np.int64)
    elif pull:
        # One-sided pull maps: arbitrary src[i], no involution constraint
        # (the reference's RumorProtocol behavior — each process
        # independently pulls a peer; SURVEY.md §3.2).
        if proto.schedule == "ring":
            pool = np.stack([_ring_pull(n, 0), _ring_pull(n, 1)])
        elif proto.schedule == "random":
            rng = np.random.default_rng(proto.seed)
            pool = np.stack(
                [_random_pull(n, rng)
                 for _ in range(proto.resolved_pool_size(n))]
            )
        elif proto.schedule == "hierarchical":
            group = proto.group_size or _auto_group_size(n)
            pool = _hierarchical_pull_pool(n, group, proto.inter_period)
        elif proto.schedule == "exponential":
            # XOR pairings are their own pull maps (involutions with no
            # fixed points) — identical pool in both modes; only the
            # participation-draw keying differs.
            pool = _exponential_pool(n)
        else:  # pragma: no cover - config validates earlier
            raise ValueError(proto.schedule)
    elif proto.schedule == "ring":
        pool = np.stack([_ring_even(n), _ring_odd(n)])
    elif proto.schedule == "random":
        rng = np.random.default_rng(proto.seed)
        pool = np.stack(
            [_random_matching(n, rng)
             for _ in range(proto.resolved_pool_size(n))]
        )
    elif proto.schedule == "hierarchical":
        group = proto.group_size or _auto_group_size(n)
        pool = _hierarchical_pool(n, group, proto.inter_period)
    elif proto.schedule == "exponential":
        pool = _exponential_pool(n)
    else:  # pragma: no cover - config validates earlier
        raise ValueError(proto.schedule)
    pool = pool.astype(np.int32)
    branch_map = None
    if not pull and proto.schedule == "hierarchical" and len(pool) > 1:
        # Dedupe repeated slots (the intra ring phases recur every block):
        # pool keeps only distinct pairings, branch_map restores the cycle.
        pool, inverse = np.unique(pool, axis=0, return_inverse=True)
        branch_map = inverse.astype(np.int32).reshape(-1)
    for k, perm in enumerate(pool):
        if pull:
            # Pull maps must be permutations (ppermute: unique sources AND
            # destinations) with no self-pulls beyond the n == 1 corner.
            if sorted(perm) != list(range(n)):
                raise AssertionError(f"pull map not a permutation at slot {k}")
            if n > 1 and np.any(perm == np.arange(n)):
                raise AssertionError(f"pull map has self-pull at slot {k}")
        elif not is_involution(perm):
            raise AssertionError(f"schedule produced non-involution at slot {k}")
    return Schedule(
        pool=pool,
        n_peers=n,
        fetch_probability=proto.fetch_probability,
        seed=proto.seed,
        name=proto.schedule,
        drop_probability=proto.drop_probability,
        mode=proto.mode,
        wire_dtype=proto.wire_dtype,
        branch_map=branch_map,
    )


def _auto_group_size(n: int) -> int:
    """Default hierarchical group: 4 peers per group when divisible (one
    v4 host's worth of chips), else the largest divisor ≤ sqrt-ish."""
    for g in (4, 8, 2):
        if n % g == 0 and n // g > 1:
            return g
    return n
