"""Single-device gossip over a stacked virtual-peer axis.

The SPMD transport (:mod:`dpwa_tpu.parallel.ici`) needs one device per peer.
This module provides the same gossip semantics on ONE device — every replica
lives in a ``[n_peers, ...]``-stacked pytree and the exchange is a batched
gather-merge instead of a ``ppermute`` — so a single TPU chip can train and
benchmark an N-peer gossip run (SURVEY.md §7: the dev/bench box has exactly
one chip; the driver's real meshes come later).

Semantics parity is exact, not approximate: the pairing pool, the per-pair
participation/fault draws (same counter-based threefry streams), the
interpolation α from exchanged (clock, loss) metadata, and the masked merge
all reproduce :func:`dpwa_tpu.parallel.ici.gossip_exchange_local` bit for
bit — ``tests/test_stacked.py`` asserts it against the multi-device path on
a forced-CPU mesh.  One jitted program still advances every replica's round;
there is simply no collective in it, only a leading-axis gather.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from dpwa_tpu.config import DpwaConfig
from dpwa_tpu.interpolation import PeerMeta, make_interpolation
from dpwa_tpu.parallel import schedules
from dpwa_tpu.parallel.ici import ExchangeInfo
from dpwa_tpu.parallel.schedules import participation_draw
from dpwa_tpu.utils.pytree import combine as pytree_combine
from dpwa_tpu.utils.pytree import partition as pytree_partition

PyTree = Any


def stacked_gossip_exchange(
    params: PyTree,
    meta: PeerMeta,
    step: jnp.ndarray,
    *,
    schedule: schedules.Schedule,
    interp,
) -> Tuple[PyTree, ExchangeInfo]:
    """One gossip round over a ``[n, ...]``-stacked pytree, single device.

    The batched twin of
    :func:`dpwa_tpu.parallel.ici.gossip_exchange_local`: identical pool
    selection (:meth:`Schedule.branch_traced` — cyclic for ring/
    hierarchical, per-step threefry draw for random), identical per-pair
    threefry draws, identical α math — the partner's replica arrives by
    leading-axis gather (``x[partner]``, fused by XLA into the merge)
    instead of ``ppermute``.
    """
    n = schedule.n_peers
    me = jnp.arange(n)
    pool = jnp.asarray(schedule.pool)  # [K, n] baked-in constant
    branch = schedule.branch_traced(step)
    partner = pool[branch]  # [n]

    remote_meta = jax.tree.map(lambda v: v[partner], meta)
    # Pull mode: one-sided, puller draws alone; pairwise: shared pair draw.
    pair_id = me if schedule.mode == "pull" else jnp.minimum(me, partner)
    if schedule.fetch_probability >= 1.0:
        drawn = jnp.ones(n, jnp.bool_)
    else:
        drawn = jax.vmap(
            lambda pid: participation_draw(
                schedule.seed, step, pid, schedule.fetch_probability
            )
        )(pair_id)
    if schedule.drop_probability > 0.0:
        drawn = jnp.logical_and(
            drawn,
            jnp.logical_not(
                jax.vmap(
                    lambda pid: schedules.fault_draw(
                        schedule.seed, step, pid, schedule.drop_probability
                    )
                )(pair_id)
            ),
        )
    participated = jnp.logical_and(drawn, partner != me)
    alpha = jax.vmap(interp)(meta, remote_meta)
    alpha = jnp.where(participated, alpha, 0.0).astype(jnp.float32)

    if schedule.wire_dtype == "int8":
        from dpwa_tpu.ops.quantize import fake_quant_tree

        # Emulate the wire per SENDER: row s of every stacked leaf is
        # quantized with sender s's key (vmap over the peer axis), then
        # gathered by the receiver — the same (step, sender, leaf) key
        # derivation as the ICI transport, so the merges stay
        # bit-identical across the two.
        wire_params = jax.vmap(
            lambda row, s: fake_quant_tree(row, schedule.seed, step, s)
        )(params, me)
    else:
        wire_params = params

    def merge(x, xw):
        a = alpha.reshape((n,) + (1,) * (x.ndim - 1)).astype(
            jnp.promote_types(x.dtype, jnp.float32)
        )
        y = xw[partner]
        if schedule.wire_dtype == "bf16" and x.dtype == jnp.float32:
            # Emulate the wire: the partner's contribution is what would
            # have arrived over the fabric — bf16-rounded.  Keeps the
            # stacked path bit-matched to the ICI transport's merges.
            y = y.astype(jnp.bfloat16)
        return ((1.0 - a) * x.astype(a.dtype) + a * y.astype(a.dtype)).astype(
            x.dtype
        )

    merged = jax.tree.map(merge, params, wire_params)
    return merged, ExchangeInfo(partner, alpha, participated)


class StackedTransport:
    """Virtual-peer gossip on a single device.

    Drop-in peer of :class:`dpwa_tpu.parallel.ici.IciTransport` behind the
    same ``exchange(params, meta, step)`` surface, for hosts with fewer
    devices than peers.  The YAML config is the same one that drives the
    ICI and TCP transports (BASELINE.json:5 contract) — ``nodes:`` length
    sets the stacked-axis size; host/port entries are ignored.
    """

    def __init__(self, config: DpwaConfig):
        self.config = config
        self.schedule = schedules.build_schedule(config)
        self.interp = make_interpolation(
            config.interpolation,
            max_abs_loss=(
                config.recovery.rescue_bound() if config.recovery.enabled else None
            ),
        )
        schedule, interp = self.schedule, self.interp

        @jax.jit
        def exchange(params, meta, step):
            return stacked_gossip_exchange(
                params, meta, step, schedule=schedule, interp=interp
            )

        self._exchange = exchange

    def exchange(
        self, params: PyTree, meta: PeerMeta, step
    ) -> Tuple[PyTree, ExchangeInfo]:
        """One gossip round over every stacked replica.

        Args:
          params: pytree whose leaves are ``[n_peers, ...]`` arrays.
          meta: :class:`PeerMeta` of ``[n_peers]`` float32 arrays.
          step: int — selects the pairing and the participation draw.
        """
        return self._exchange(params, meta, jnp.asarray(step, jnp.int32))


class StackedTrainState(NamedTuple):
    """Stacked training state; every leaf's leading axis is n_peers.

    ``loss`` is each peer's most recent training loss — what the
    reference's Rx thread serves alongside the published vector; overlapped
    exchanges ship it as the metadata (see
    :class:`dpwa_tpu.train.GossipTrainState`)."""

    params: PyTree
    opt_state: PyTree
    clock: jnp.ndarray  # float32[n]
    step: jnp.ndarray  # int32 scalar
    model_state: PyTree = None
    loss: jnp.ndarray = None  # float32[n] — last step's per-peer loss


def init_stacked_state(
    stacked_params: PyTree,
    optimizer: optax.GradientTransformation,
    transport: StackedTransport,
    stacked_model_state: PyTree = None,
) -> StackedTrainState:
    n = transport.config.n_peers
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stacked_params)}
    if leading != {n}:
        raise ValueError(
            f"stacked params must have leading peer axis {n}, got {leading}"
        )
    # Own copies: the train step DONATES the state, so the state must not
    # alias arrays the caller still holds.
    own = lambda t: jax.tree.map(lambda v: jnp.array(v, copy=True), t)
    params = own(stacked_params)
    return StackedTrainState(
        params=params,
        opt_state=jax.vmap(optimizer.init)(params),
        clock=jnp.zeros(n, jnp.float32),
        step=jnp.int32(0),
        model_state=own(stacked_model_state)
        if stacked_model_state is not None
        else None,
        loss=jnp.zeros(n, jnp.float32),
    )


def make_stacked_train_step(
    loss_fn,
    optimizer: optax.GradientTransformation,
    transport: StackedTransport,
    exchange_filter: Optional[Callable[[str], bool]] = None,
    with_state: bool = False,
    overlap: bool = False,
):
    """Jitted ``train_step(state, batch) -> (state, losses, info)`` on one
    device: vmapped per-peer forward/backward/optimizer followed by the
    stacked gossip exchange, all in one XLA program — the single-chip twin
    of :func:`dpwa_tpu.train.make_gossip_train_step`.

    ``batch`` is peer-stacked ``(x[n, b, ...], y[n, b])``; with
    ``with_state=True``, ``loss_fn(params, model_state, batch) ->
    (loss, new_model_state)`` as in
    :func:`dpwa_tpu.train.make_gossip_train_step_with_state`.

    The state is **donated**: each call consumes its input state's buffers
    and the caller must use the returned one (``state, … = step(state, …)``
    — the standard loop).  Without donation every in-flight step holds a
    full fresh copy of params + optimizer state, and a deep async dispatch
    queue (hundreds of steps) can swamp the HBM allocator.

    ``overlap=True`` exchanges the PRE-update replicas (with the previous
    step's losses as metadata) and applies the local updates to the merged
    result, exactly as :func:`dpwa_tpu.train.make_gossip_train_step`
    documents.  On one chip the gain is small (~1 % — a single core has
    no second engine to hide the gather behind); the mode exists here for
    layout parity with the ICI path, where the dependency-free collective
    genuinely overlaps compute.
    """
    grad_fn = jax.value_and_grad(loss_fn, has_aux=with_state)
    schedule, interp = transport.schedule, transport.interp

    def check_state(state):
        # Same misuse guards as the SPMD twin (dpwa_tpu/train.py): silently
        # frozen BatchNorm stats are worse than an error.
        if not with_state and state.model_state is not None:
            raise ValueError(
                "state carries model_state but this step was built with "
                "with_state=False, which would never update it; pass "
                "with_state=True"
            )
        if with_state and state.model_state is None:
            raise ValueError(
                "step built with with_state=True but state.model_state is "
                "None; pass stacked_model_state to init_stacked_state"
            )

    def per_peer(params, opt_state, model_state, batch):
        if with_state:
            (loss, new_model_state), grads = grad_fn(
                params, model_state, batch
            )
        else:
            loss, grads = grad_fn(params, batch)
            new_model_state = ()
        updates, opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, updates, opt_state, new_model_state, loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _step(state: StackedTrainState, batch):
        model_state = state.model_state if with_state else ()
        params, updates, opt_state, new_model_state, losses = jax.vmap(
            per_peer
        )(state.params, state.opt_state, model_state, batch)
        clock = state.clock + 1.0
        # Overlap mode exchanges the pre-update replicas (state.params)
        # with the PREVIOUS step's losses — every exchanged operand is
        # ready at step entry, so the exchange's HBM reads never wait on
        # this step's fwd/bwd/optimizer; the local updates (and the
        # model-state delta) land on the merged result afterwards.
        if overlap:
            prev_loss = (
                state.loss
                if state.loss is not None
                else jnp.zeros_like(clock)
            )
            meta = PeerMeta(clock, prev_loss)
            exchange_params, exchange_state = state.params, model_state
        else:
            meta = PeerMeta(clock, losses.astype(jnp.float32))
            exchange_params, exchange_state = params, new_model_state
        if exchange_filter is not None:
            selected, _ = pytree_partition(exchange_params, exchange_filter)
            (merged_sel, merged_state), info = stacked_gossip_exchange(
                (selected, exchange_state), meta, state.step,
                schedule=schedule, interp=interp,
            )
            if overlap:
                sel_updates, _ = pytree_partition(updates, exchange_filter)
                merged_sel = optax.apply_updates(merged_sel, sel_updates)
            _, rest = pytree_partition(params, exchange_filter)
            merged = pytree_combine(merged_sel, rest)
        else:
            (merged, merged_state), info = stacked_gossip_exchange(
                (exchange_params, exchange_state), meta, state.step,
                schedule=schedule, interp=interp,
            )
            if overlap:
                merged = optax.apply_updates(merged, updates)
        if overlap:
            merged_state = jax.tree.map(
                lambda m, new, old: m + (new - old),
                merged_state, new_model_state, model_state,
            )
        new_state = StackedTrainState(
            params=merged,
            opt_state=opt_state,
            clock=clock,
            step=state.step + 1,
            model_state=merged_state if with_state else state.model_state,
            loss=losses,
        )
        return new_state, losses, info

    def train_step(state: StackedTrainState, batch):
        check_state(state)
        return _step(state, batch)

    return train_step
