"""Per-link wire-degradation controller (docs/tune.md).

The obs planes already measure exactly what the static wire knobs trade
off — per-stage spans (wire vs merge), busy/slow/stale outcome rates,
and the sketch plane's ring-disagreement ``rel_rms`` — but nothing
closed the loop: codec, top-k fraction, and value precision were
hand-tuned YAML shared by every link.  The :class:`LinkTuner` closes it
in the DeadlineEstimator mold: per tracked link it keeps a small bounded
observation window and walks a FROZEN escalation ladder:

- **escalate** one rung (coarser codec, fewer bytes) when the window
  shows wire-bound rounds — the quantized wire-span fraction of the
  round wall at/above ``wire_bound_frac``, with busy/slow/stale
  outcomes counting as wire-bound evidence;
- **back off** one rung when the sketch plane shows convergence
  stalling (fractional ``rel_rms`` improvement across the window below
  ``stall_eps``) AND the window shows wire headroom (not majority
  wire-bound) — compression is starving the gossip average and the
  link can afford finer frames; without the headroom gate a stall on a
  congested link would walk it back into codecs that only time out;
- **shed** ``shed_rungs`` extra rungs while the scheduled partner is
  scoreboard-DEGRADED: the robustness core — a loaded peer gets fewer
  bytes at lower fidelity, NOT dropped rounds (the
  ``degrade_shed_fraction`` remap is bypassed while the tuner runs) and
  never trust/quarantine evidence;
- **mirror** the partner's rung, read off the self-describing frames it
  serves: the effective rung is floored one rung below the rung the
  partner last encoded at (the slack keeps two mirrors from ratcheting
  each other up forever).  Evidence is fetch-side but the lever is
  publish-side, so
  a one-sided throttle (only one end's egress shaped) would otherwise
  never heal — the shaped end's own fetches stay fast and it keeps
  serving fat frames the other side can never land; the partner's
  escalations, visible in the frames themselves, are the missing
  backchannel.

Hysteresis makes a flapping link settle instead of thrash: a rung is
held for ``min_dwell_rounds`` plus a threefry-drawn jitter (tag 37 —
desynchronizes fleet-wide escalations) before the next escalation, and
a back-off starts a ``cooldown_rounds`` window during which the link may
not re-escalate.  Sheds are overlays: they do not advance the dwell
clock or touch the base rung, so a DEGRADED window ends with the link
exactly where it was.

Determinism: every decision is a pure function of QUANTIZED
observations (span fractions bucketed to ``quant`` levels, ``rel_rms``
rounded to fixed precision, outcome booleans) plus the registered
threefry jitter stream — the controller itself never reads a clock.
Wall-derived spans arrive as arguments, exactly like the
DeadlineEstimator's latencies, so a scripted observation feed replays
its decision log bit-identically (tests/test_tune.py).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List, NamedTuple, Optional

from dpwa_tpu.config import TuneConfig


class Rung(NamedTuple):
    """One frozen ladder entry: how the wire encodes at this fidelity."""

    codec: str              # dense | topk
    dtype: str              # f32 | bf16 | int8 (dense) / value block (topk)
    topk_fraction: Optional[float]  # None for dense rungs


# The frozen escalation ladder, finest (most bytes, exact) to coarsest.
# Rung 0 is the floor: "never underperforms static f32" holds because a
# back-off can always reach the reference codec.  Top-k rungs all ship
# int8 value blocks — by the time a link is deep enough in the ladder to
# shed coordinates, exact values for the survivors are not the
# bottleneck.  Shard k is NOT on the ladder: both ends of every link
# must agree on the shard permutation epoch, and a per-link k would
# break the round-robin coverage invariant — when sharding is on, the
# ladder selects the INNER codec of each shard frame instead.
LADDER: tuple = (
    Rung("dense", "f32", None),
    Rung("dense", "bf16", None),
    Rung("dense", "int8", None),
    Rung("topk", "int8", 0.10),
    Rung("topk", "int8", 0.03),
    Rung("topk", "int8", 0.01),
)


def rung_label(rung: int) -> str:
    """Human/metric label for a ladder rung ("f32", "topk0.03", ...)."""
    r = LADDER[max(0, min(int(rung), len(LADDER) - 1))]
    if r.codec == "topk":
        return f"topk{r.topk_fraction:g}"
    return r.dtype


def start_rung_for(
    wire_codec: str, wire_dtype: str, topk_fraction: float
) -> int:
    """The ladder rung matching the static wire config — the controller
    starts every link exactly where the YAML put it ("static config as
    configured"), so a link that never shows evidence never moves."""
    if wire_codec == "topk":
        best, best_d = 3, float("inf")
        for i, r in enumerate(LADDER):
            if r.codec != "topk":
                continue
            d = abs(r.topk_fraction - float(topk_fraction))
            if d < best_d:
                best, best_d = i, d
        return best
    if wire_dtype == "int8":
        return 2
    if wire_dtype == "bf16":
        return 1
    return 0


class _LinkState:
    __slots__ = (
        "rung", "mirror", "dwell", "cooldown", "jitter", "shed_active",
        "window", "rel_window", "escalations", "backoffs", "sheds",
    )

    def __init__(self, rung: int, window: int):
        self.rung = int(rung)
        # Partner's rung as read off the frames it serves us (frames
        # are self-describing, so the pair needs no control channel).
        # Floors the effective rung: "if you are shedding fidelity on
        # this link, so am I."  Without it a one-sided throttle never
        # heals — the shaped peer's own fetches stay fast, so it keeps
        # serving fat frames the other side can never land.
        self.mirror = 0
        self.dwell = 0
        self.cooldown = 0
        self.jitter = 0
        self.shed_active = False
        # Per-round wire-bound booleans (already quantized upstream).
        self.window: Deque[bool] = deque(maxlen=window)
        # Quantized rel_rms samples for the stall trend.
        self.rel_window: Deque[float] = deque(maxlen=window)
        self.escalations = 0
        self.backoffs = 0
        self.sheds = 0


class LinkTuner:
    """Frozen-ladder wire controller, one state machine per link."""

    def __init__(self, config: Optional[TuneConfig] = None, seed: int = 0):
        self.config = config if config is not None else TuneConfig()
        self.seed = int(seed)
        self.start_rung = 0
        self._lock = threading.Lock()
        self._links: Dict[int, _LinkState] = {}
        self._decisions: List[dict] = []
        # Invariant counter, not a feature: a rung change that happened
        # before the dwell clock allowed it.  Asserted == 0 by the
        # health_report digest and tests — if it ever moves, the
        # hysteresis contract is broken.
        self._dwell_violations = 0

    def set_start_rung(self, rung: int) -> None:
        """Anchor new links at the static config's rung (clamped)."""
        self.start_rung = max(0, min(int(rung), len(LADDER) - 1))

    def _state(self, link: int) -> _LinkState:
        st = self._links.get(link)
        if st is None:
            st = self._links[link] = _LinkState(
                self.start_rung, self.config.window
            )
        return st

    # ------------------------------------------------------------------
    # Ingestion (the _obs_round_end feed)
    # ------------------------------------------------------------------

    def observe(
        self,
        link: int,
        wall_s: Optional[float] = None,
        wire_s: Optional[float] = None,
        soft: bool = False,
        rel: Optional[float] = None,
    ) -> None:
        """Feed one finished round on ``link``.

        ``wall_s``/``wire_s`` are the round's entry-to-entry wall and
        the fetch's wire span — quantized HERE into ``quant`` buckets
        before anything downstream can branch on them, so two runs whose
        raw timings differ inside a bucket make identical decisions.
        ``soft`` marks a busy/slow/stale/timeout outcome (wire-bound
        evidence regardless of spans); ``rel`` is the sketch plane's
        current ring-disagreement estimate."""
        q = self.config.quant
        wire_bound = bool(soft)
        if not wire_bound and wall_s is not None and wire_s is not None:
            if wall_s > 0 and wire_s >= 0:
                bucket = min(q, int((float(wire_s) / float(wall_s)) * q))
                wire_bound = (bucket / q) >= self.config.wire_bound_frac
        with self._lock:
            st = self._state(link)
            st.window.append(wire_bound)
            if rel is not None and rel >= 0:
                # 1e-4 buckets: fine enough for the stall trend, coarse
                # enough that float noise cannot flip a decision.
                st.rel_window.append(round(float(rel), 4))

    def note_partner_rung(self, link: int, rung: int) -> None:
        """Record the rung the partner encoded its last frame at (read
        off the frame's code byte on the consume path).  Tracks the
        partner both up AND down — the partner's own hysteresis is the
        damping, so no extra state is kept here."""
        with self._lock:
            st = self._state(link)
            st.mirror = max(0, min(int(rung), len(LADDER) - 1))

    def evict_peer(self, link: int) -> None:
        """Drop the link's controller state (membership eviction): a
        rejoiner re-enters the ladder at the static start rung."""
        with self._lock:
            self._links.pop(link, None)

    def tracked_peers(self) -> list:
        with self._lock:
            return sorted(self._links)

    # ------------------------------------------------------------------
    # The per-round decision (publish path)
    # ------------------------------------------------------------------

    def _stalling(self, st: _LinkState) -> bool:
        rels = list(st.rel_window)
        if len(rels) < self.config.window:
            return False
        half = len(rels) // 2
        old = sum(rels[:half]) / half
        new = sum(rels[half:]) / (len(rels) - half)
        if old <= 0:
            return False
        return (old - new) / old < self.config.stall_eps

    def plan(self, link: int, clock: int, degraded: bool = False) -> Rung:
        """Advance ``link``'s state machine one round and return the
        EFFECTIVE rung for the frame published at ``clock``.

        Called once per publish for the scheduled partner.  The base
        rung walks the ladder under hysteresis; ``degraded`` overlays
        ``shed_rungs`` extra rungs (clamped to the ladder top) without
        touching the base state — fidelity shed, never a dropped round.
        """
        cfg = self.config
        with self._lock:
            st = self._state(link)
            st.dwell += 1
            if st.cooldown > 0:
                st.cooldown -= 1
            prev = st.rung
            action = None
            reason = None
            if (
                st.rung > 0
                and st.dwell >= cfg.min_dwell_rounds
                and len(st.window) >= cfg.window
                # Back-off needs wire headroom: while the window is
                # still majority wire-bound, a finer codec can only
                # turn a landing frame back into a timeout — the stall
                # is congestion, not compression starvation.
                and sum(st.window) < cfg.escalate_frac * len(st.window)
                and self._stalling(st)
            ):
                st.rung -= 1
                st.backoffs += 1
                action, reason = "backoff", "stall"
                if st.dwell < cfg.min_dwell_rounds:
                    self._dwell_violations += 1
                st.dwell = 0
                st.cooldown = cfg.cooldown_rounds
                st.rel_window.clear()
                st.window.clear()
            elif (
                st.rung < len(LADDER) - 1
                and st.cooldown == 0
                and len(st.window) >= cfg.window
                and sum(st.window) >= cfg.escalate_frac * len(st.window)
                and st.dwell >= cfg.min_dwell_rounds + st.jitter
            ):
                st.rung += 1
                st.escalations += 1
                action, reason = "escalate", "wire_bound"
                if st.dwell < cfg.min_dwell_rounds:
                    self._dwell_violations += 1
                st.dwell = 0
                st.window.clear()
                # Draw the NEXT escalation's extra dwell now, keyed on
                # the clock the decision landed at — both ends of the
                # link (and any rerun) draw the same offset.
                from dpwa_tpu.parallel import schedules

                st.jitter = schedules.tune_jitter_draw(
                    self.seed, int(clock), int(link), cfg.jitter_rounds
                )
            shed = bool(degraded) and cfg.shed_rungs > 0
            if shed != st.shed_active:
                st.shed_active = shed
                if shed:
                    st.sheds += 1
                self._decisions.append(self._record(
                    link, clock, "shed_on" if shed else "shed_off",
                    st, prev, "degraded",
                ))
            if action is not None:
                self._decisions.append(
                    self._record(link, clock, action, st, prev, reason)
                )
            return LADDER[self._eff(st)]

    def _eff(self, st: _LinkState) -> int:
        """Effective rung: own ladder walk, floored by ONE RUNG BELOW
        the partner's mirrored rung, plus the DEGRADED shed overlay
        (clamped).  The -1 breaks the mirror ratchet: frames carry the
        partner's EFFECTIVE rung — which includes its mirror of us —
        so flooring at the mirror itself would make the pair's rungs
        monotone non-decreasing (each side re-serving the other's
        reflection forever, back-offs never propagating).  With the
        slack, the pair's fixed point is max(own_A, own_B): mirrors
        decay one rung per exchange once real evidence recedes."""
        eff = max(st.rung, st.mirror - 1)
        if st.shed_active:
            eff = min(len(LADDER) - 1, eff + self.config.shed_rungs)
        return eff

    def effective_rung(self, link: int) -> int:
        with self._lock:
            st = self._links.get(link)
            if st is None:
                return self.start_rung
            return self._eff(st)

    def _record(
        self, link, clock, action, st: _LinkState, prev: int, reason
    ) -> dict:
        eff = self._eff(st)
        return {
            "link": int(link),
            "round": int(clock),
            "action": action,
            "rung": int(eff),
            "prev_rung": int(prev),
            "codec": rung_label(eff),
            "reason": reason,
            "dwell": int(st.dwell),
        }

    def pop_decisions(self) -> List[dict]:
        """Drain buffered decision records (the JSONL ``tune`` kind)."""
        with self._lock:
            out, self._decisions = self._decisions, []
            return out

    # ------------------------------------------------------------------
    # Observability
    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready: per-link rung/codec + ladder traffic counters."""
        with self._lock:
            links = {}
            esc = back = sheds = 0
            for link in sorted(self._links):
                st = self._links[link]
                eff = self._eff(st)
                links[link] = {
                    "rung": st.rung,
                    "mirror": st.mirror,
                    "effective_rung": eff,
                    "codec": rung_label(eff),
                    "dwell": st.dwell,
                    "cooldown": st.cooldown,
                    "shed_active": st.shed_active,
                    "escalations": st.escalations,
                    "backoffs": st.backoffs,
                    "sheds": st.sheds,
                }
                esc += st.escalations
                back += st.backoffs
                sheds += st.sheds
            return {
                "start_rung": self.start_rung,
                "ladder": len(LADDER),
                "escalations": esc,
                "backoffs": back,
                "sheds": sheds,
                "dwell_violations": self._dwell_violations,
                "links": links,
            }


def register_metrics(registry, tuner: "LinkTuner") -> None:
    """Expose the ladder state on a MetricsRegistry (dpwa_tune_*)."""
    from dpwa_tpu.obs.prometheus import Family

    def collect():
        snap = tuner.snapshot()
        rung = Family(
            "dpwa_tune_rung", "gauge",
            "Effective ladder rung per link (0 = f32 floor)",
        )
        shed = Family(
            "dpwa_tune_shed_active", "gauge",
            "1 while the link sheds fidelity under a DEGRADED partner",
        )
        for link, info in sorted((snap.get("links") or {}).items()):
            labels = {"link": link, "codec": info.get("codec")}
            rung.sample(info.get("effective_rung"), labels)
            shed.sample(1 if info.get("shed_active") else 0, {"link": link})
        return [
            rung,
            shed,
            Family(
                "dpwa_tune_escalations_total", "counter",
                "Ladder escalations (coarser codec) across links",
            ).sample(snap.get("escalations")),
            Family(
                "dpwa_tune_backoffs_total", "counter",
                "Ladder back-offs (finer codec) across links",
            ).sample(snap.get("backoffs")),
            Family(
                "dpwa_tune_sheds_total", "counter",
                "DEGRADED fidelity-shed windows entered",
            ).sample(snap.get("sheds")),
            Family(
                "dpwa_tune_dwell_violations_total", "counter",
                "Rung changes inside the dwell window (invariant: 0)",
            ).sample(snap.get("dwell_violations")),
        ]

    registry.register(collect)
