"""Self-tuning wire: the per-link degradation controller (docs/tune.md)."""

from dpwa_tpu.tune.controller import (  # noqa: F401
    LADDER,
    LinkTuner,
    Rung,
    register_metrics,
    rung_label,
    start_rung_for,
)
