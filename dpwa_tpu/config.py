"""Reference-compatible YAML configuration.

The reference (zenghanfu/dpwa) is driven by a YAML file whose ``nodes:`` list
enumerates the peer topology as ``{name, host, port}`` entries, plus protocol
knobs (fetch probability, socket timeout) and an interpolation spec
(SURVEY.md §2 "Config system"; reference file ``dpwa/config.py`` — mount empty,
reconstructed per SURVEY.md §0).  Contract preserved here (BASELINE.json:5):
**the same YAML file drives either transport** — the TCP transport uses
``host``/``port`` per node, while the ICI transport reinterprets the length of
``nodes:`` as the size of a device-mesh axis and ignores host/port.

Schema::

    nodes:
      - {name: node0, host: 127.0.0.1, port: 45000}
      - {name: node1, host: 127.0.0.1, port: 45001}
    protocol:
      schedule: ring            # ring | random | hierarchical | exponential
      mode: pairwise            # pairwise (mutual merge) | pull (one-sided)
      fetch_probability: 1.0    # per-step chance a pair actually exchanges
      timeout_ms: 500           # TCP transport only: fetch budget
                                #   (connect+header; payload earns
                                #   1s per min_wire_mb_per_s received)
      min_wire_mb_per_s: 10.0       # TCP only: slowest peer rate treated
                                #   as alive (deadline floor)
      seed: 0                   # schedule / participation RNG seed
      pool_size: null           # random schedule: # static pairings compiled
                                #   (default auto = clamp(2n, 16, 128))
      group_size: 0             # hierarchical: peers per host group (0 = auto)
      inter_period: 4           # hierarchical: cross-group exchange cadence
      drop_probability: 0.0     # fault injection: drop pairs at this rate
      wire_dtype: f32           # f32 | bf16 | int8 (shipped replica compressed)
    interpolation:
      type: constant            # constant | clock | loss
      factor: 0.5               # constant alpha (0.5 == (local+remote)/2)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import yaml


@dataclasses.dataclass(frozen=True)
class NodeSpec:
    """One ``nodes:`` entry: a peer's identity and (TCP-only) address."""

    name: str
    host: str = "127.0.0.1"
    port: int = 0


# One source of truth for the TCP liveness floor (MEGABYTES/s):
# ProtocolConfig's default and parallel/tcp.py's module default both
# derive from this, so the "same" default cannot drift between the
# config path and direct fetch_blob() calls.
DEFAULT_MIN_WIRE_MB_PER_S = 10.0


@dataclasses.dataclass(frozen=True)
class ProtocolConfig:
    schedule: str = "ring"
    mode: str = "pairwise"  # pairwise (mutual merge) | pull (one-sided)
    fetch_probability: float = 1.0
    timeout_ms: int = 500
    # TCP transport: the slowest transfer rate still treated as a live
    # peer, in MEGABYTES per second (the name says mb_per_s, not mbps,
    # deliberately — a megabit reading would be off by 8×).  The fetch
    # deadline is timeout_ms (connect + header) plus 1 / this rate per
    # payload byte RECEIVED, so large replicas are never rejected by a
    # fixed budget while trickling peers still die promptly.
    # Deployments on genuinely slow fabrics (WAN links below 10 MB/s)
    # with large models must lower this, or every large fetch is
    # abandoned and gossip silently degrades to solo training.
    min_wire_mb_per_s: float = DEFAULT_MIN_WIRE_MB_PER_S
    seed: int = 0
    # Random schedule: number of static matchings compiled into the
    # lax.switch pool.  None = auto-scale with the peer count,
    # clamp(2n, 16, 128): artifacts/pool_truncation.json shows mixing
    # time reaches the fresh-draw rate by K=16 but pair COVERAGE at
    # n=64/K=16 is only 23 % (3/4 of pairs could never meet), while the
    # switch's compile cost stays flat to K=128.  Explicit values are
    # honored unchanged (the TCP/host path pays no compile cost and can
    # go higher freely).
    pool_size: int | None = None
    group_size: int = 0
    inter_period: int = 4
    drop_probability: float = 0.0  # fault injection: drop pairs at this rate
    # Wire precision of the SHIPPED replica: "f32" (exact, the reference's
    # format) or "bf16" — halves exchange traffic (ICI/DCN bytes, TCP wire
    # bytes); the local replica and the merge arithmetic stay f32, only
    # the partner's contribution is rounded.  Pairwise-averaging tolerates
    # this well: quantization error enters scaled by alpha and is averaged
    # away across rounds.
    wire_dtype: str = "f32"

    def __post_init__(self) -> None:
        if not 0.0 <= self.fetch_probability <= 1.0:
            raise ValueError(
                f"fetch_probability must be in [0, 1], got {self.fetch_probability}"
            )
        if not 0.0 <= self.drop_probability <= 1.0:
            raise ValueError(
                f"drop_probability must be in [0, 1], got {self.drop_probability}"
            )
        if self.schedule not in (
            "ring", "random", "hierarchical", "exponential"
        ):
            raise ValueError(f"unknown schedule {self.schedule!r}")
        if self.mode not in ("pairwise", "pull"):
            raise ValueError(f"unknown protocol mode {self.mode!r}")
        if self.wire_dtype not in ("f32", "bf16", "int8"):
            raise ValueError(f"unknown wire_dtype {self.wire_dtype!r}")
        if self.min_wire_mb_per_s <= 0:
            raise ValueError(
                f"min_wire_mb_per_s must be > 0, got {self.min_wire_mb_per_s}"
            )
        if self.pool_size is not None and self.pool_size < 1:
            raise ValueError(
                f"pool_size must be >= 1 (or null for auto), "
                f"got {self.pool_size}"
            )

    def resolved_pool_size(self, n_peers: int) -> int:
        """The random-schedule pool size in effect for ``n_peers``."""
        if self.pool_size is not None:
            return self.pool_size
        return max(16, min(128, 2 * n_peers))


@dataclasses.dataclass(frozen=True)
class InterpolationConfig:
    type: str = "constant"
    factor: float = 0.5

    def __post_init__(self) -> None:
        if self.type not in ("constant", "clock", "loss"):
            raise ValueError(f"unknown interpolation type {self.type!r}")
        if not 0.0 <= self.factor <= 1.0:
            raise ValueError(f"factor must be in [0, 1], got {self.factor}")


@dataclasses.dataclass(frozen=True)
class DpwaConfig:
    nodes: tuple[NodeSpec, ...]
    protocol: ProtocolConfig = ProtocolConfig()
    interpolation: InterpolationConfig = InterpolationConfig()

    @property
    def n_peers(self) -> int:
        """Length of ``nodes:`` — the size of the gossip mesh axis."""
        return len(self.nodes)

    @property
    def node_names(self) -> tuple[str, ...]:
        return tuple(n.name for n in self.nodes)

    def node_index(self, name: str) -> int:
        """Position of ``name`` in ``nodes:`` — this process/device's peer id."""
        try:
            return self.node_names.index(name)
        except ValueError:
            raise KeyError(
                f"node {name!r} not in config (have {self.node_names})"
            ) from None

    def node(self, name: str) -> NodeSpec:
        return self.nodes[self.node_index(name)]


def _build_nodes(raw: Sequence[Any]) -> tuple[NodeSpec, ...]:
    nodes = []
    for i, entry in enumerate(raw):
        if isinstance(entry, str):
            # Shorthand: a bare name (ICI transport needs no address).
            nodes.append(NodeSpec(name=entry))
        elif isinstance(entry, Mapping):
            nodes.append(
                NodeSpec(
                    name=str(entry.get("name", f"node{i}")),
                    host=str(entry.get("host", "127.0.0.1")),
                    port=int(entry.get("port", 0)),
                )
            )
        else:
            raise TypeError(f"bad nodes[{i}] entry: {entry!r}")
    names = [n.name for n in nodes]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate node names in config: {names}")
    if not nodes:
        raise ValueError("config must list at least one node")
    return tuple(nodes)


def config_from_dict(raw: Mapping[str, Any]) -> DpwaConfig:
    """Build a :class:`DpwaConfig` from a parsed-YAML mapping."""
    if "nodes" not in raw:
        raise ValueError("config is missing the required 'nodes:' list")
    proto = dict(raw.get("protocol") or {})
    interp = dict(raw.get("interpolation") or {})
    return DpwaConfig(
        nodes=_build_nodes(raw["nodes"]),
        protocol=ProtocolConfig(**proto),
        interpolation=InterpolationConfig(**interp),
    )


def load_config(path: str) -> DpwaConfig:
    """Load the reference-style YAML config file."""
    with open(path, "r", encoding="utf-8") as f:
        raw = yaml.safe_load(f)
    if not isinstance(raw, Mapping):
        raise ValueError(f"config file {path} did not parse to a mapping")
    return config_from_dict(raw)


def make_local_config(
    n_peers: int,
    *,
    schedule: str = "ring",
    fetch_probability: float = 1.0,
    interpolation: str = "constant",
    factor: float = 0.5,
    seed: int = 0,
    base_port: int = 45000,
    **protocol_kwargs: Any,
) -> DpwaConfig:
    """Programmatic config for tests/benchmarks: n local peers on 127.0.0.1."""
    return DpwaConfig(
        nodes=tuple(
            NodeSpec(name=f"node{i}", host="127.0.0.1", port=base_port + i)
            for i in range(n_peers)
        ),
        protocol=ProtocolConfig(
            schedule=schedule,
            fetch_probability=fetch_probability,
            seed=seed,
            **protocol_kwargs,
        ),
        interpolation=InterpolationConfig(type=interpolation, factor=factor),
    )
