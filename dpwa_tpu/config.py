"""Reference-compatible YAML configuration.

The reference (zenghanfu/dpwa) is driven by a YAML file whose ``nodes:`` list
enumerates the peer topology as ``{name, host, port}`` entries, plus protocol
knobs (fetch probability, socket timeout) and an interpolation spec
(SURVEY.md §2 "Config system"; reference file ``dpwa/config.py`` — mount empty,
reconstructed per SURVEY.md §0).  Contract preserved here (BASELINE.json:5):
**the same YAML file drives either transport** — the TCP transport uses
``host``/``port`` per node, while the ICI transport reinterprets the length of
``nodes:`` as the size of a device-mesh axis and ignores host/port.

Schema::

    nodes:
      - {name: node0, host: 127.0.0.1, port: 45000}
      - {name: node1, host: 127.0.0.1, port: 45001}
    protocol:
      schedule: ring            # ring | random | hierarchical | exponential
      mode: pairwise            # pairwise (mutual merge) | pull (one-sided)
      fetch_probability: 1.0    # per-step chance a pair actually exchanges
      timeout_ms: 500           # TCP transport only: fetch budget
                                #   (connect+header; payload earns
                                #   1s per min_wire_mb_per_s received)
      min_wire_mb_per_s: 10.0       # TCP only: slowest peer rate treated
                                #   as alive (deadline floor)
      seed: 0                   # schedule / participation RNG seed
      pool_size: null           # random schedule: # static pairings compiled
                                #   (default auto = clamp(2n, 16, 128))
      group_size: 0             # hierarchical: peers per host group (0 = auto)
      inter_period: 4           # hierarchical: cross-group exchange cadence
      drop_probability: 0.0     # fault injection: drop pairs at this rate
      wire_dtype: f32           # f32 | bf16 | int8 (shipped replica compressed)
      wire_codec: dense         # dense | topk (TCP only: topk ships only the
                                #   k largest-magnitude changed coordinates
                                #   against an error-feedback residual; see
                                #   docs/wire.md)
      topk_fraction: 0.05       # topk codec: k = round(fraction * n),
                                #   clamped to [1, n]
      topk_values: int8         # topk value block: int8 (chunk-scaled SR,
                                #   ~5 B/coord) | f32 (exact, 8 B/coord)
      overlap_prefetch: false   # TCP only: double-buffered pipeline — round
                                #   t+1's partner fetch streams while round
                                #   t's decode/screen/merge runs; payloads
                                #   that straddle a local publish re-screen
      rx_server: threaded       # threaded (thread-per-connection Rx) |
                                #   reactor (single-threaded selectors
                                #   event loop, docs/transport.md; wire
                                #   behavior identical, chaos still
                                #   forces the threaded server)
      async_rounds:             # barrier-free async gossip (TCP only,
                                #   docs/async.md); absent/off keeps the
                                #   lock-step round loop byte-identical
        enabled: false          # decouple publish from merge: frames land
                                #   in per-peer queues and merge when ready
        max_staleness: 4        # largest publish-clock lag still merged;
                                #   beyond it the frame drops as the soft
                                #   ``stale`` outcome (degrade, never
                                #   quarantine)
        staleness_damping: 0.5  # per-lag alpha decay: a frame lagging L
                                #   clocks merges at alpha * damping**L,
                                #   composing with trust damping
        queue_depth: 4          # bounded per-peer pending queue (newest
                                #   frames win admission)
        fold: true              # batch pending dense frames through one
                                #   exchange_on_device_fold dispatch
    shard:                      # sharded gossip (TCP only, docs/wire.md)
      k: 1                      # contiguous shards per replica; each round
                                #   ships ONE shard (k× fewer wire bytes,
                                #   full coverage every k rounds), merged
                                #   slice-wise.  1 = off: frames stay
                                #   byte-identical to a pre-shard build
    interpolation:
      type: constant            # constant | clock | loss
      factor: 0.5               # constant alpha (0.5 == (local+remote)/2)
    health:                     # peer-health control plane (TCP transport)
      enabled: true             # failure detection + quarantine/remap
      suspicion_threshold: 2.0  # quarantine when suspicion crosses this
      ewma_alpha: 0.2           # latency/throughput EWMA smoothing
      success_decay: 0.25       # suspicion multiplier per good fetch
      quarantine_base_rounds: 4 # first quarantine length (doubles per
                                #   consecutive failed probe, clamped)
      quarantine_max_rounds: 64
      jitter_rounds: 2          # deterministic backoff jitter in [0, j]
      probe_timeout_ms: 100     # header-only re-admission probe budget
      healthz_port: null        # JSON /healthz endpoint (null = off,
                                #   0 = OS-assigned port)
    chaos:                      # deterministic fault injection harness
      enabled: false            # forces the Python Rx server when on
      seed: 0
      drop_probability: 0.0     # close the connection before serving
      delay_probability: 0.0    # sleep delay_ms before serving
      delay_ms: 50.0
      throttle_probability: 0.0 # serve at throttle_bytes_per_s
      throttle_bytes_per_s: 1e6
      truncate_probability: 0.0 # cut the frame mid-payload
      corrupt_probability: 0.0  # flip the frame's magic bytes
      down_windows: []          # [{peer, start, stop}]: hard-down rounds
      partition_windows: []     # [{group: [peers], start, stop}]: block all
                                #   links between group and its complement
      link_windows: []          # [{src, dst, start, stop}]: block one
                                #   DIRECTED link (asymmetric faults)
      partition_probability: 0.0  # drawn partitions: each block of
                                #   partition_len_rounds splits the ring
                                #   into two drawn groups at this rate
      partition_len_rounds: 8
      byzantine_peers: []       # peers eligible for byzantine injection
                                #   ([] = all peers)
      byzantine_start_round: 0  # rounds before this serve honestly
      byzantine_sign_probability: 0.0   # serve the sign-flipped replica
      byzantine_scale_probability: 0.0  # serve a scaled replica (finite,
                                #   below recovery.max_param_norm)
      byzantine_scale_factor: 100.0
      byzantine_replay_probability: 0.0 # re-serve an old own snapshot
      byzantine_replay_age: 8   # how many rounds stale the replay is
      byzantine_zero_probability: 0.0   # serve an all-zero replica
      trickle_windows: []       # [{peer, start, stop}]: serve at
                                #   trickle_bytes_per_s (straggler shaping)
      trickle_bytes_per_s: 2048.0
      stall_probability: 0.0    # jittered mid-payload serving stall
      stall_ms_max: 200.0       # drawn stall length in [0, stall_ms_max]
      accept_delay_windows: []  # [{peer, start, stop}]: sleep before
                                #   reading the request (accept-path lag)
      accept_delay_ms: 100.0
      bandwidth_windows: []     # [{peer, start, stop}]: link-quality
                                #   flapping — time slices into blocks of
                                #   bandwidth_block_rounds rounds; each
                                #   block draws shaped-or-not (chaos kind
                                #   13) and, when shaped, a serving rate
                                #   in [bandwidth_bps_min, bps_max] (kind
                                #   14); composes with trickle windows by
                                #   taking the slower rate
      bandwidth_flap_probability: 1.0  # per-block chance the link flaps
      bandwidth_block_rounds: 4 # rounds per flap block (square-wave width)
      bandwidth_bps_min: 4096.0 # drawn shaped-rate range (bytes/s)
      bandwidth_bps_max: 65536.0
    recovery:                   # crash recovery & divergence guard
      enabled: true             # peer bootstrap serving + payload guard
      max_param_norm: 1.0e12    # reject/roll back when ||vec||_2 exceeds
      max_loss: 1.0e9           # reject/roll back when |loss| exceeds
      rescue_loss: null         # finite local loss beyond THIS bound gets
                                #   the interpolation alpha=1 rescue
                                #   (null = 16 * max_loss; must be >=
                                #   max_loss so a normal training spike
                                #   near the guard bound never triggers
                                #   wholesale replica adoption)
      min_param_norm_ratio: 1.0e-4  # reject a remote whose norm is below
                                #   this fraction of the local norm
                                #   (zero-energy payload; 0 = off)
      snapshot_every: 1         # push a last-good ring snapshot every k
                                #   healthy steps
      snapshot_ring: 4          # in-memory last-good snapshots kept
      state_chunk_bytes: 1048576  # STATE transfer chunk size (CRC per chunk)
      bootstrap_timeout_ms: 10000 # per-chunk fetch budget during bootstrap
      max_resume_retries: 8     # short-read resume attempts per bootstrap
      max_clock_lag: 64.0       # re-admission freshness: advise re-sync
                                #   when a readmitted peer's clock leads
                                #   ours by more than this
      auto_resync: false        # adapter re-bootstraps itself when a
                                #   re-admission freshness check trips
    membership:                 # epidemic membership & partition tolerance
      enabled: true             # piggyback a membership digest on every
                                #   gossip frame (needs health.enabled)
      indirect_probes: 2        # K relay probes before suspect->quarantine
      relay_timeout_ms: 250     # budget per relay probe round-trip
      dead_after_quarantines: 3 # declare a peer dead after this many
                                #   consecutive failed re-admissions
      dead_gossip_rounds: 16    # disseminate a dead claim this many
                                #   rounds, then EVICT the peer's
                                #   per-peer state (scoreboard, trust,
                                #   flowctl) and drop it from the digest
                                #   until it refutes (0 = never evict)
      quorum_fraction: 0.5      # degraded mode when the connected
                                #   component falls below this fraction
      degraded_alpha_scale: 1.0 # damp interpolation alpha while degraded
                                #   (1.0 = off)
      heal_reconcile: true      # anti-entropy state merge on partition heal
      reconcile_min_fraction: 0.3  # reconcile only when the returning
                                #   component is at least this fraction
      max_heal_weight: 0.75     # clamp on the returning side's merge weight
    trust:                      # content-trust plane (docs/trust.md)
      enabled: true             # screen every decoded REMOTE payload
      window: 32                # median/MAD window of accepted exchanges
      min_window: 8             # screening arms once this many accepted
                                #   exchanges exist (cold-start guard)
      mad_multiplier: 8.0       # robust z beyond this -> suspect (damped)
      reject_multiplier: 24.0   # robust z beyond this -> rejected
      damping: 1.0              # alpha *= trust ** damping for suspects
      ewma_half_life: 4.0       # clean exchanges to halve trust deficit
      suspect_decay: 0.7        # trust *= this per suspect verdict
      reject_decay: 0.25        # trust *= this per rejected verdict
      quarantine_trust: 0.15    # below this, feed 'untrusted' probes to
                                #   the scoreboard until quarantine
      cosine_floor: -0.5        # hard bound: reject anti-aligned payloads
      norm_ratio_max: 64.0      # hard bound: reject scale blow-ups
      replay_slack: 0.5         # clock may run backward by this much
                                #   before a payload counts as a replay
      amnesty_gap: 4            # a peer unscreened for amnesty_gap *
                                #   (n_peers - 1) rounds is re-acquainted
      amnesty_rounds: 8         # ...leniently for this many rounds
                                #   (rejects downgrade to damped suspects)
    flowctl:                    # flow control plane (docs/flowctl.md)
      enabled: true             # adaptive deadlines + serving admission
                                #   (forces the Python Rx server)
      quantile: 0.95            # per-peer latency quantile the budget
                                #   tracks (also the hedge launch point)
      margin: 1.5               # deadline = quantile latency * margin
      min_ms: 50.0              # adaptive-deadline clamp (floor)
      max_ms: 5000.0            # adaptive-deadline clamp (ceiling)
      window: 32                # success-latency samples kept per peer
      warmup: 5                 # cold below this many samples: fall back
                                #   to protocol.timeout_ms, never hedge
      hedge: true               # one hedged retry to the schedule's
                                #   fallback partner once the p95 lapses
      degrade_shed_fraction: 0.5  # fraction of rounds deterministically
                                #   remapped away from a DEGRADED partner
      max_connections: 32       # serving: global concurrent-conn cap
                                #   (threaded Rx: bounds worker threads)
      reactor_max_connections: 1024  # serving cap under rx_server:
                                #   reactor — a connection there costs a
                                #   registered socket, not a thread
      token_rate: 100.0         # serving: requests/s refill per remote
      token_burst: 200.0        # serving: token bucket depth per remote
      max_inflight_bytes: 268435456  # serving: payload bytes in flight
      min_ingest_bytes_per_s: 4096.0 # slow-loris eviction floor on
                                #   request reads
      request_timeout_ms: 5000  # per-connection handler budget (was the
                                #   hard-coded 5 s accept-path timeout)
      busy_retry_ms: 50         # retry hint carried in the DPWB reply
    obs:                        # observability plane (docs/observability.md)
      trace: true               # per-stage round spans + cross-peer trace
                                #   IDs piggybacked on frames (forces the
                                #   Python Rx server for serve-side spans)
      trace_every: 1            # sample 1-in-N rounds for tracing
      trace_path: trace.jsonl   # trace JSONL stream (null = in-memory only)
      trace_max_records: 4096   # in-memory trace ring (tests/adapters)
      sketch: true              # piggyback a replica sketch per frame for
                                #   the ring-disagreement estimate
      sketch_k: 64              # sketch width (floats on the wire)
      sketch_every: 1           # refresh the local sketch 1-in-N publishes
      metrics: true             # Prometheus /metrics on the healthz port
      log_max_bytes: 0          # rotate metrics/health JSONL at this size
                                #   (0 = unbounded)
      log_keep: 1               # rotated generations kept per JSONL file
                                #   (<path>.1 .. <path>.N)
      incidents: true           # online anomaly detectors + incident
                                #   correlator (docs/incidents.md) and the
                                #   /incidents healthz route
      incident_path: null       # alert/incident JSONL stream ("{me}" is
                                #   substituted; null = in-memory only)
      incident_window: 8        # rounds of evidence behind the burst and
                                #   storm detectors
      incident_fail_streak: 2   # consecutive hard fetch failures from one
                                #   peer before a peer_failure alert
      incident_soft_streak: 2   # busy/slow outcomes from one peer inside
                                #   the window before a straggler alert
      incident_trust_burst: 2   # untrusted/poisoned outcomes from one
                                #   peer inside the window before a
                                #   trust_burst alert
      incident_storm_threshold: 3  # quarantine/degrade transitions inside
                                #   the window before a state_storm alert
      incident_stale_storm: 3   # async bounded-staleness drops inside the
                                #   window before a staleness_storm alert
                                #   (docs/async.md)
      incident_stall_window: 8  # rel_rms samples behind the convergence
                                #   stall detector
      incident_stall_min_rel: 0.05  # plateau only counts above this
                                #   rel_rms floor (converged is not stalled)
      incident_stall_improve: 0.01  # required fractional rel_rms
                                #   improvement across the stall window
      incident_slo_factor: 4.0  # round wall beyond this multiple of the
                                #   rolling median starts an SLO burn
      incident_slo_rounds: 5    # consecutive burning rounds before an
                                #   slo_burn alert
      incident_slo_warmup: 16   # wall samples before the SLO baseline arms
      incident_resolve_after: 8 # quiet rounds (no evidence, implicated
                                #   peers healthy) before an incident
                                #   resolves
      recorder: true            # black-box flight recorder: bounded ring
                                #   of per-round records dumped on crash /
                                #   incident open / close / endpoint
      recorder_rounds: 64       # flight-recorder ring depth (rounds)
      recorder_path: flight-{me}.jsonl  # dump path ("{me}" substituted;
                                #   null = dpwa-flight-<me>.jsonl in cwd)
    topology:                   # hierarchical gossip (docs/hierarchy.md);
                                #   absent block = one flat ring,
                                #   bit-identical to pre-hierarchy builds
      islands:                  # partition of nodes: into islands — every
                                #   node in EXACTLY one island; each island
                                #   averages internally (ICI ppermute path)
                                #   and only its elected leader speaks on
                                #   the wide-area ring
        - name: rack0           # island id (defaults island<i>)
          nodes: [node0, node1] # member names from nodes:
        - name: rack1
          nodes: [node2, node3]
      leader_seed: 0            # threefry seed of the leader_draw stream
                                #   (election + failover succession)
      intra_rounds: 1           # intra-island averaging sweeps folded in
                                #   per wide-area round (hypercube phases;
                                #   1 sweep = exact island mean)
    run:                        # training-harness loop (docs/training.md)
      steps: 100                # optimizer steps per node
      batch_size: 32            # per-node minibatch size
      lr: 0.1                   # SGD learning rate
      momentum: 0.0             # SGD momentum (0 = plain SGD)
      loss_every: 1             # emit a loss record every k steps
      checkpoint_every: 0       # save a checkpoint every k steps (0 = off)
      checkpoint_dir: null      # checkpoint directory ("{me}" substituted)
      checkpoint_keep: 3        # newest checkpoints kept per node
      target_loss: 0.0          # time-to-loss threshold the acceptance
                                #   legs measure against (0 = off)
    tune:                       # self-tuning wire (docs/tune.md); absent
                                #   block or enabled: false keeps frames
                                #   byte-identical to a static-config build
      enabled: false            # per-link degradation controller: walks
                                #   the frozen codec ladder (f32 -> bf16 ->
                                #   int8 -> topk 0.1 -> 0.03 -> 0.01) from
                                #   the obs planes' QUANTIZED observations
      window: 8                 # observation rounds per link behind each
                                #   decision
      min_dwell_rounds: 6       # rounds a link holds a rung before it may
                                #   escalate again (hysteresis)
      cooldown_rounds: 12       # rounds after a back-off during which the
                                #   link may not re-escalate
      wire_bound_frac: 0.5      # quantized wire-span fraction of the round
                                #   wall at/above which a round counts as
                                #   wire-bound
      escalate_frac: 0.5        # fraction of the window's rounds that must
                                #   be wire-bound (or busy/slow/stale) to
                                #   escalate one rung
      stall_eps: 0.02           # minimum fractional rel_rms improvement
                                #   across the window; below it the sketch
                                #   plane reads "stalling" -> back off one
                                #   rung
      shed_rungs: 2             # extra rungs shed while the scheduled
                                #   partner is scoreboard-DEGRADED —
                                #   fidelity is shed, the round is NOT
                                #   dropped (replaces the degrade_shed
                                #   remap while enabled)
      quant: 16                 # quantization buckets for observed span
                                #   fractions and trends (decisions never
                                #   branch on raw wall-clock, so seeded
                                #   reruns replay bit-identically)
      jitter_rounds: 2          # threefry dwell jitter (tag 37): drawn
                                #   extra dwell in [0, j] desynchronizes
                                #   fleet-wide escalations
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import yaml


@dataclasses.dataclass(frozen=True)
class NodeSpec:
    """One ``nodes:`` entry: a peer's identity and (TCP-only) address."""

    name: str
    host: str = "127.0.0.1"
    port: int = 0


# One source of truth for the TCP liveness floor (MEGABYTES/s):
# ProtocolConfig's default and parallel/tcp.py's module default both
# derive from this, so the "same" default cannot drift between the
# config path and direct fetch_blob() calls.
DEFAULT_MIN_WIRE_MB_PER_S = 10.0


@dataclasses.dataclass(frozen=True)
class AsyncRoundsConfig:
    """``protocol.async_rounds`` block — barrier-free gossip rounds.

    Off (the default, and the absent-block case) keeps the lock-step
    round loop byte-identical to a pre-async build.  On, the
    :class:`~dpwa_tpu.parallel.async_loop.AsyncExchangeEngine` decouples
    publish from merge: frames stream on background slots, land in a
    bounded per-peer pending queue, and merge whenever ready instead of
    at the round barrier.  Each merge damps its interpolation weight by
    ``staleness_damping ** lag`` (lag = local step − the frame's publish
    clock), and a frame whose lag exceeds ``max_staleness`` is dropped
    as the soft ``stale`` outcome (degrade, never quarantine).  See
    docs/async.md."""

    enabled: bool = False
    # Largest publish-clock lag still merged.  Lag == max_staleness
    # merges (maximally damped); lag > max_staleness drops as ``stale``.
    max_staleness: int = 4
    # Per-lag alpha decay: a frame lagging L clocks merges at
    # alpha * staleness_damping**L, composing multiplicatively with the
    # trust damping already in interpolation._clamped.  1.0 disables
    # damping (bounded-staleness drops still apply).
    staleness_damping: float = 0.5
    # Bounded per-peer pending queue: admission keeps the newest
    # ``queue_depth`` frames per peer (older publish clocks are shed
    # first — they would merge at the smallest weight anyway).
    queue_depth: int = 4
    # Batch consecutive pending dense frames into one
    # exchange_on_device_fold dispatch (device substrate only; the host
    # substrate always folds sequentially, which is bit-identical).
    fold: bool = True

    def __post_init__(self) -> None:
        if self.max_staleness < 1:
            raise ValueError(
                f"async_rounds.max_staleness must be >= 1, "
                f"got {self.max_staleness}"
            )
        if not 0.0 < self.staleness_damping <= 1.0:
            raise ValueError(
                f"async_rounds.staleness_damping must be in (0, 1], "
                f"got {self.staleness_damping}"
            )
        if self.queue_depth < 1:
            raise ValueError(
                f"async_rounds.queue_depth must be >= 1, "
                f"got {self.queue_depth}"
            )


@dataclasses.dataclass(frozen=True)
class ProtocolConfig:
    schedule: str = "ring"
    mode: str = "pairwise"  # pairwise (mutual merge) | pull (one-sided)
    fetch_probability: float = 1.0
    timeout_ms: int = 500
    # TCP transport: the slowest transfer rate still treated as a live
    # peer, in MEGABYTES per second (the name says mb_per_s, not mbps,
    # deliberately — a megabit reading would be off by 8×).  The fetch
    # deadline is timeout_ms (connect + header) plus 1 / this rate per
    # payload byte RECEIVED, so large replicas are never rejected by a
    # fixed budget while trickling peers still die promptly.
    # Deployments on genuinely slow fabrics (WAN links below 10 MB/s)
    # with large models must lower this, or every large fetch is
    # abandoned and gossip silently degrades to solo training.
    min_wire_mb_per_s: float = DEFAULT_MIN_WIRE_MB_PER_S
    seed: int = 0
    # Random schedule: number of static matchings compiled into the
    # lax.switch pool.  None = auto-scale with the peer count,
    # clamp(2n, 16, 128): artifacts/pool_truncation.json shows mixing
    # time reaches the fresh-draw rate by K=16 but pair COVERAGE at
    # n=64/K=16 is only 23 % (3/4 of pairs could never meet), while the
    # switch's compile cost stays flat to K=128.  Explicit values are
    # honored unchanged (the TCP/host path pays no compile cost and can
    # go higher freely).
    pool_size: int | None = None
    group_size: int = 0
    inter_period: int = 4
    drop_probability: float = 0.0  # fault injection: drop pairs at this rate
    # Wire precision of the SHIPPED replica: "f32" (exact, the reference's
    # format) or "bf16" — halves exchange traffic (ICI/DCN bytes, TCP wire
    # bytes); the local replica and the merge arithmetic stay f32, only
    # the partner's contribution is rounded.  Pairwise-averaging tolerates
    # this well: quantization error enters scaled by alpha and is averaged
    # away across rounds.
    wire_dtype: str = "f32"
    # Wire CODEC of the shipped replica (TCP transport only).  "dense"
    # ships every coordinate at wire_dtype precision; "topk" ships only
    # the k = round(topk_fraction * n) largest-magnitude coordinates that
    # changed since the last publish (error-feedback residual scoring, so
    # dropped coordinates accumulate and ship later), as absolute values
    # the receiver splices into its OWN replica.  Orthogonal to
    # wire_dtype: topk_values picks the value-block precision.
    wire_codec: str = "dense"
    topk_fraction: float = 0.05
    topk_values: str = "int8"
    # TCP transport: double-buffered exchange pipeline.  When on, round
    # t+1's partner fetch (deadline-hedged as usual) streams on a
    # background slot while round t's decode -> trust-screen -> merge
    # runs; a prefetched payload that straddles a local publish is
    # re-screened against the fresh replica before merging.  Off by
    # default: the sequential path is the bit-identity reference.
    overlap_prefetch: bool = False
    # Which Rx server serves this node's published frames (TCP
    # transport).  "threaded" is the thread-per-connection PeerServer;
    # "reactor" is the single-threaded selectors event loop
    # (dpwa_tpu/parallel/reactor.py, docs/transport.md) whose admitted
    # connections cost a registered socket instead of a worker thread —
    # the large-N serving path.  Wire behavior is byte-identical.
    # chaos.enabled still forces the threaded chaos wrapper: fault
    # injection needs per-connection control of a blocking serve loop.
    rx_server: str = "threaded"
    # Barrier-free async rounds (docs/async.md): accepts the nested
    # AsyncRoundsConfig or the YAML-block mapping shorthand.  Disabled
    # by default — the lock-step round loop is the bit-identity
    # reference the async engine is tested against.
    async_rounds: "AsyncRoundsConfig | Mapping[str, Any]" = (
        dataclasses.field(default_factory=AsyncRoundsConfig)
    )

    def __post_init__(self) -> None:
        if isinstance(self.async_rounds, Mapping):
            # YAML-block shorthand: coerce in place (frozen dataclass,
            # same discipline as ChaosConfig's window normalization).
            object.__setattr__(
                self, "async_rounds", AsyncRoundsConfig(**self.async_rounds)
            )
        if not 0.0 <= self.fetch_probability <= 1.0:
            raise ValueError(
                f"fetch_probability must be in [0, 1], got {self.fetch_probability}"
            )
        if not 0.0 <= self.drop_probability <= 1.0:
            raise ValueError(
                f"drop_probability must be in [0, 1], got {self.drop_probability}"
            )
        if self.schedule not in (
            "ring", "random", "hierarchical", "exponential"
        ):
            raise ValueError(f"unknown schedule {self.schedule!r}")
        if self.mode not in ("pairwise", "pull"):
            raise ValueError(f"unknown protocol mode {self.mode!r}")
        if self.wire_dtype not in ("f32", "bf16", "int8"):
            raise ValueError(f"unknown wire_dtype {self.wire_dtype!r}")
        if self.wire_codec not in ("dense", "topk"):
            raise ValueError(f"unknown wire_codec {self.wire_codec!r}")
        if not 0.0 < self.topk_fraction <= 1.0:
            raise ValueError(
                f"topk_fraction must be in (0, 1], got {self.topk_fraction}"
            )
        if self.topk_values not in ("int8", "f32"):
            raise ValueError(f"unknown topk_values {self.topk_values!r}")
        if self.min_wire_mb_per_s <= 0:
            raise ValueError(
                f"min_wire_mb_per_s must be > 0, got {self.min_wire_mb_per_s}"
            )
        if self.pool_size is not None and self.pool_size < 1:
            raise ValueError(
                f"pool_size must be >= 1 (or null for auto), "
                f"got {self.pool_size}"
            )
        if self.rx_server not in ("threaded", "reactor"):
            raise ValueError(f"unknown rx_server {self.rx_server!r}")

    def resolved_pool_size(self, n_peers: int) -> int:
        """The random-schedule pool size in effect for ``n_peers``."""
        if self.pool_size is not None:
            return self.pool_size
        return max(16, min(128, 2 * n_peers))


@dataclasses.dataclass(frozen=True)
class ShardConfig:
    """``shard:`` block — exchange 1/k of the replica per round.

    ``k: 1`` (the default) or an absent block keeps sharding OFF and
    every wire frame byte-identical to a pre-shard build.  ``k > 1``
    partitions the flattened replica into k contiguous shards; each
    publish ships the one shard the per-epoch ``shard_draw``
    permutation assigns to that round (every shard once per k rounds),
    and the merge touches only that slice.  Composes with
    ``protocol.wire_dtype`` / ``protocol.wire_codec`` — the inner
    encoding applies to the slice (top-k selects within the shard, int8
    scale tables restart per shard).  TCP transport only; see
    docs/wire.md."""

    k: int = 1

    def __post_init__(self) -> None:
        if self.k < 1:
            raise ValueError(f"shard.k must be >= 1, got {self.k}")

    @property
    def enabled(self) -> bool:
        return self.k > 1


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """``health:`` block — the peer-health control plane's knobs.

    Applies to the TCP transport (the path with per-peer fetches to
    fail); the SPMD transports emulate failures in-graph via
    ``protocol.drop_probability`` and need no detector.  Quarantine
    timing is counted in gossip ROUNDS, never wall time, so health state
    is deterministic for a fixed outcome sequence (see
    :mod:`dpwa_tpu.health.scoreboard`)."""

    enabled: bool = True
    # Quarantine when a peer's suspicion crosses this.  Failure weights
    # (detector.DEFAULT_FAILURE_WEIGHTS) are ~1 per hard failure, so the
    # default 2.0 means two consecutive hard failures.
    suspicion_threshold: float = 2.0
    ewma_alpha: float = 0.2
    success_decay: float = 0.25
    quarantine_base_rounds: int = 4
    quarantine_max_rounds: int = 64
    jitter_rounds: int = 2
    probe_timeout_ms: int = 100
    # None = no endpoint; 0 = OS-assigned port; >0 = fixed port.  The
    # endpoint serves the scoreboard snapshot as JSON over plain HTTP
    # (stdlib-only, dpwa_tpu/health/endpoint.py).
    healthz_port: int | None = None

    def __post_init__(self) -> None:
        if self.suspicion_threshold <= 0:
            raise ValueError(
                f"suspicion_threshold must be > 0, got {self.suspicion_threshold}"
            )
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {self.ewma_alpha}"
            )
        if not 0.0 <= self.success_decay < 1.0:
            raise ValueError(
                f"success_decay must be in [0, 1), got {self.success_decay}"
            )
        if self.quarantine_base_rounds < 1:
            raise ValueError(
                f"quarantine_base_rounds must be >= 1, "
                f"got {self.quarantine_base_rounds}"
            )
        if self.quarantine_max_rounds < self.quarantine_base_rounds:
            raise ValueError(
                "quarantine_max_rounds must be >= quarantine_base_rounds"
            )
        if self.jitter_rounds < 0:
            raise ValueError(
                f"jitter_rounds must be >= 0, got {self.jitter_rounds}"
            )
        if self.probe_timeout_ms < 1:
            raise ValueError(
                f"probe_timeout_ms must be >= 1, got {self.probe_timeout_ms}"
            )
        if self.healthz_port is not None and not 0 <= self.healthz_port < 65536:
            raise ValueError(
                f"healthz_port must be in [0, 65535] or null, "
                f"got {self.healthz_port}"
            )


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """``chaos:`` block — deterministic fault injection for the TCP path.

    Faults are drawn per (seed, round, peer) on independent threefry
    streams (:func:`dpwa_tpu.parallel.schedules.chaos_draw`), so a given
    seed replays the identical fault schedule — the harness doubles as a
    soak tool (``chaos:`` in YAML) and a test fixture
    (:mod:`dpwa_tpu.health.chaos`).  ``down_windows`` hard-kills a peer's
    Rx serving for a round interval ``[start, stop)`` — the
    'process died, later came back' scenario quarantine/re-admission is
    proven against."""

    enabled: bool = False
    seed: int = 0
    drop_probability: float = 0.0
    delay_probability: float = 0.0
    delay_ms: float = 50.0
    throttle_probability: float = 0.0
    throttle_bytes_per_s: float = 1e6
    truncate_probability: float = 0.0
    corrupt_probability: float = 0.0
    down_windows: tuple[tuple[int, int, int], ...] = ()
    # Partition injection: during [start, stop) every link BETWEEN
    # ``group`` and its complement is blocked (both directions); links
    # inside either side stay up.  Entry shape: (group_tuple, start, stop)
    # or the YAML mapping {group: [...], start, stop}.
    partition_windows: tuple[tuple[tuple[int, ...], int, int], ...] = ()
    # Single DIRECTED link block (src cannot reach dst) — the asymmetric
    # fault that makes one node falsely suspect a live peer.  Entry shape:
    # (src, dst, start, stop) or {src, dst, start, stop}.
    link_windows: tuple[tuple[int, int, int, int], ...] = ()
    # Drawn partitions: time is sliced into blocks of partition_len_rounds
    # rounds; each block independently splits the ring at this rate, with
    # per-peer group assignment drawn per block (chaos_draw kinds 5/6).
    partition_probability: float = 0.0
    partition_len_rounds: int = 8
    # Byzantine (content) faults: the served payload is mutated so it
    # stays a VALID wire frame — header, CRC-equivalent structure, and
    # trailer untouched — and only the vector content lies.  Exercises
    # the trust plane end-to-end (dpwa_tpu/trust/, docs/trust.md).
    # ``byzantine_peers`` restricts which peers attack (() = all are
    # eligible); draws stay per (seed, round, peer) threefry streams.
    byzantine_peers: tuple[int, ...] = ()
    byzantine_start_round: int = 0
    byzantine_sign_probability: float = 0.0
    byzantine_scale_probability: float = 0.0
    byzantine_scale_factor: float = 100.0
    byzantine_replay_probability: float = 0.0
    byzantine_replay_age: int = 8
    byzantine_zero_probability: float = 0.0
    # Latency/bandwidth shaping (straggler injection, docs/flowctl.md).
    # ``trickle_windows`` serves a peer's frames at trickle_bytes_per_s
    # during [start, stop) — bytes FLOW but far below any useful rate, the
    # honest-but-overloaded shape the flowctl plane must soft-degrade
    # rather than quarantine.  ``stall_probability`` draws a jittered
    # mid-payload stall up to stall_ms_max; ``accept_delay_windows``
    # sleeps before the request read (accept-path lag).  All draws are
    # per (seed, round, peer) threefry streams like every other fault.
    trickle_windows: tuple[tuple[int, int, int], ...] = ()
    trickle_bytes_per_s: float = 2048.0
    stall_probability: float = 0.0
    stall_ms_max: float = 200.0
    accept_delay_windows: tuple[tuple[int, int, int], ...] = ()
    accept_delay_ms: float = 100.0
    # Link-quality flapping (self-tuning-wire chaos, docs/tune.md).
    # ``bandwidth_windows`` marks [start, stop) round intervals where a
    # peer's serving rate FLAPS: time is sliced into blocks of
    # ``bandwidth_block_rounds`` rounds, each block independently draws
    # whether it is shaped (chaos kind 13, vs bandwidth_flap_probability)
    # and — when shaped — a rate lerped across
    # [bandwidth_bps_min, bandwidth_bps_max] (kind 14).  Shaping composes
    # with trickle windows by taking the slower of the two, so a flapping
    # link looks like a square-wave trickle the tune controller must ride
    # without thrashing its ladder.
    bandwidth_windows: tuple[tuple[int, int, int], ...] = ()
    bandwidth_flap_probability: float = 1.0
    bandwidth_block_rounds: int = 4
    bandwidth_bps_min: float = 4096.0
    bandwidth_bps_max: float = 65536.0

    def __post_init__(self) -> None:
        for name in (
            "drop_probability",
            "delay_probability",
            "throttle_probability",
            "truncate_probability",
            "corrupt_probability",
            "partition_probability",
            "byzantine_sign_probability",
            "byzantine_scale_probability",
            "byzantine_replay_probability",
            "byzantine_zero_probability",
            "stall_probability",
            "bandwidth_flap_probability",
        ):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v}")
        if self.byzantine_scale_factor <= 0:
            raise ValueError(
                f"byzantine_scale_factor must be > 0, "
                f"got {self.byzantine_scale_factor}"
            )
        if self.byzantine_replay_age < 1:
            raise ValueError(
                f"byzantine_replay_age must be >= 1, "
                f"got {self.byzantine_replay_age}"
            )
        if self.byzantine_start_round < 0:
            raise ValueError(
                f"byzantine_start_round must be >= 0, "
                f"got {self.byzantine_start_round}"
            )
        byz = tuple(int(p) for p in self.byzantine_peers)
        if any(p < 0 for p in byz):
            raise ValueError(f"bad byzantine_peers entry in {byz!r}")
        object.__setattr__(self, "byzantine_peers", byz)
        if self.delay_ms < 0:
            raise ValueError(f"delay_ms must be >= 0, got {self.delay_ms}")
        if self.throttle_bytes_per_s <= 0:
            raise ValueError(
                f"throttle_bytes_per_s must be > 0, "
                f"got {self.throttle_bytes_per_s}"
            )
        if self.partition_len_rounds < 1:
            raise ValueError(
                f"partition_len_rounds must be >= 1, "
                f"got {self.partition_len_rounds}"
            )
        if self.trickle_bytes_per_s <= 0:
            raise ValueError(
                f"trickle_bytes_per_s must be > 0, "
                f"got {self.trickle_bytes_per_s}"
            )
        if self.stall_ms_max < 0:
            raise ValueError(
                f"stall_ms_max must be >= 0, got {self.stall_ms_max}"
            )
        if self.accept_delay_ms < 0:
            raise ValueError(
                f"accept_delay_ms must be >= 0, got {self.accept_delay_ms}"
            )
        if self.bandwidth_block_rounds < 1:
            raise ValueError(
                f"bandwidth_block_rounds must be >= 1, "
                f"got {self.bandwidth_block_rounds}"
            )
        if self.bandwidth_bps_min <= 0:
            raise ValueError(
                f"bandwidth_bps_min must be > 0, "
                f"got {self.bandwidth_bps_min}"
            )
        if self.bandwidth_bps_max < self.bandwidth_bps_min:
            raise ValueError(
                f"bandwidth_bps_max must be >= bandwidth_bps_min, "
                f"got {self.bandwidth_bps_max} < {self.bandwidth_bps_min}"
            )
        for field in ("down_windows", "trickle_windows",
                      "accept_delay_windows", "bandwidth_windows"):
            windows = []
            for w in getattr(self, field):
                if isinstance(w, Mapping):
                    w = (w["peer"], w["start"], w["stop"])
                w = tuple(int(x) for x in w)
                if len(w) != 3 or w[0] < 0 or w[1] < 0 or w[2] < w[1]:
                    raise ValueError(f"bad {field} entry {w!r}")
                windows.append(w)
            object.__setattr__(self, field, tuple(windows))
        parts = []
        for w in self.partition_windows:
            if isinstance(w, Mapping):
                w = (w["group"], w["start"], w["stop"])
            group = tuple(sorted(int(p) for p in w[0]))
            start, stop = int(w[1]), int(w[2])
            if not group or min(group) < 0 or start < 0 or stop < start:
                raise ValueError(f"bad partition_windows entry {w!r}")
            parts.append((group, start, stop))
        object.__setattr__(self, "partition_windows", tuple(parts))
        links = []
        for w in self.link_windows:
            if isinstance(w, Mapping):
                w = (w["src"], w["dst"], w["start"], w["stop"])
            w = tuple(int(x) for x in w)
            if len(w) != 4 or min(w) < 0 or w[3] < w[2]:
                raise ValueError(f"bad link_windows entry {w!r}")
            links.append(w)
        object.__setattr__(self, "link_windows", tuple(links))


@dataclasses.dataclass(frozen=True)
class RecoveryConfig:
    """``recovery:`` block — crash recovery & divergence-guard knobs.

    Three concerns share these bounds deliberately (one definition of
    "sane replica" for the whole system):

    * the **remote guard** rejects a fetched payload whose vector is
      non-finite, whose L2 norm exceeds ``max_param_norm``, or whose
      advertised loss exceeds ``max_loss`` (classified as the
      ``poisoned`` detector outcome, never merged);
    * the **local rollback ring** restores the newest last-good snapshot
      when the local replica itself trips the same bounds;
    * the **interpolation rescue** (`interpolation._clamped`) treats a
      finite-but-huge local loss beyond the RESCUE bound as sick
      metadata, granting the full alpha=1 rescue.

    The rescue bound is deliberately NOT ``max_loss`` itself: the guard
    bound gets tuned down to the real loss scale of a workload (so a
    diverged peer's advertised loss is caught early), and a normal
    early-training loss spike can brush right up against it.  Crossing
    the guard bound costs one rejected frame or one ring rollback —
    recoverable either way — but the alpha=1 rescue REPLACES the local
    replica wholesale, which must be reserved for actually-diverged
    state.  ``rescue_loss`` (default ``16 * max_loss``) is that second,
    strictly-larger threshold; see :meth:`rescue_bound`.

    ``enabled`` also turns on STATE serving in the Rx server so a
    restarted peer can bootstrap over the blob wire (this forces the
    Python Rx server, like ``chaos.enabled`` — the native C++ loop only
    speaks the blob protocol)."""

    enabled: bool = True
    max_param_norm: float = 1e12
    max_loss: float = 1e9
    # Interpolation-rescue threshold: a finite LOCAL loss beyond this
    # bound counts as sick metadata deserving the alpha=1 rescue.  None
    # derives 16 * max_loss (see the class docstring for why the rescue
    # must sit well above the guard bound).
    rescue_loss: "float | None" = None
    # Zero-energy floor: reject a remote whose L2 norm falls below this
    # fraction of the LOCAL norm (a half-bootstrapped or byzantine peer
    # serving zeros would otherwise drag honest weights toward zero at
    # alpha-speed).  0 disables; only enforced when the caller knows its
    # own norm, so bare fetches without local context are unaffected.
    min_param_norm_ratio: float = 1e-4
    snapshot_every: int = 1
    snapshot_ring: int = 4
    state_chunk_bytes: int = 1 << 20
    bootstrap_timeout_ms: int = 10000
    max_resume_retries: int = 8
    max_clock_lag: float = 64.0
    auto_resync: bool = False

    def __post_init__(self) -> None:
        if self.max_param_norm <= 0:
            raise ValueError(
                f"max_param_norm must be > 0, got {self.max_param_norm}"
            )
        if self.max_loss <= 0:
            raise ValueError(f"max_loss must be > 0, got {self.max_loss}")
        if self.rescue_loss is not None and self.rescue_loss < self.max_loss:
            raise ValueError(
                f"rescue_loss must be >= max_loss ({self.max_loss}) — a "
                f"rescue below the guard bound would adopt a peer replica "
                f"wholesale on losses the guard still tolerates; got "
                f"{self.rescue_loss}"
            )
        if self.snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {self.snapshot_every}"
            )
        if self.snapshot_ring < 1:
            raise ValueError(
                f"snapshot_ring must be >= 1, got {self.snapshot_ring}"
            )
        if self.state_chunk_bytes < 64:
            raise ValueError(
                f"state_chunk_bytes must be >= 64, got {self.state_chunk_bytes}"
            )
        if self.bootstrap_timeout_ms < 1:
            raise ValueError(
                f"bootstrap_timeout_ms must be >= 1, "
                f"got {self.bootstrap_timeout_ms}"
            )
        if self.max_resume_retries < 0:
            raise ValueError(
                f"max_resume_retries must be >= 0, got {self.max_resume_retries}"
            )
        if self.max_clock_lag <= 0:
            raise ValueError(
                f"max_clock_lag must be > 0, got {self.max_clock_lag}"
            )
        if not 0.0 <= self.min_param_norm_ratio < 1.0:
            raise ValueError(
                f"min_param_norm_ratio must be in [0, 1), "
                f"got {self.min_param_norm_ratio}"
            )

    def rescue_bound(self) -> float:
        """The |loss| threshold for the interpolation alpha=1 rescue.

        ``rescue_loss`` when configured, else ``16 * max_loss`` — always
        at or above the guard's reject bound, so a loss the guard would
        merely reject/roll back never triggers wholesale adoption of a
        peer replica."""
        if self.rescue_loss is not None:
            return float(self.rescue_loss)
        return 16.0 * float(self.max_loss)


@dataclasses.dataclass(frozen=True)
class ViewConfig:
    """``membership.view:`` block — bounded partial views (docs/membership.md).

    Shrinks every control plane's horizon from the full ``nodes:``
    universe to a HyParView-style partial view: the **active** view is
    the peers this node gossips with and probes; the **passive** view is
    a churn-refreshed reservoir that supplies replacements when an
    active peer is evicted.  Digests are truncated to a threefry-drawn
    sample of ``digest_sample`` tracked peers per frame (wire format
    unchanged — receivers already merge arbitrary subsets), and the
    per-peer maps in trust / flowctl / scoreboard / membership are
    LRU-capped at ``state_cap``.

    Identity guarantee: with ``digest_sample >= N``, ``state_cap >= N``
    and ``active_size >= N - 1``, every frame and every plane decision
    is byte-identical to the global-view (``enabled: false``) behavior —
    sampling only ever truncates, never reorders or rewrites."""

    enabled: bool = False
    # Active view size: partner / relay / hedge draws range over (the
    # healthy subset of) these peers instead of all of ``nodes:``.
    active_size: int = 8
    # Passive reservoir size (candidates for promotion on failure).
    passive_size: int = 32
    # Tracked peers sampled into each published digest frame.
    digest_sample: int = 16
    # LRU cap on per-peer map residency across the scoreboard, trust,
    # deadline-estimator, and membership planes.  Evictions flow through
    # the PR 11 evict-listener path (tombstone + prune); QUARANTINED
    # peers with an unexpired streak and collapsed-trust peers are never
    # cap-evicted.
    state_cap: int = 64
    # Shuffle cadence: every this-many rounds one passive slot is
    # refreshed from the recently-seen universe (0 disables shuffling).
    shuffle_every: int = 8

    def __post_init__(self) -> None:
        if self.active_size < 1:
            raise ValueError(
                f"view.active_size must be >= 1, got {self.active_size}"
            )
        if self.passive_size < 0:
            raise ValueError(
                f"view.passive_size must be >= 0, got {self.passive_size}"
            )
        if self.digest_sample < 1:
            raise ValueError(
                f"view.digest_sample must be >= 1, got {self.digest_sample}"
            )
        if self.state_cap < 1:
            raise ValueError(
                f"view.state_cap must be >= 1, got {self.state_cap}"
            )
        if self.state_cap < self.active_size:
            raise ValueError(
                f"view.state_cap ({self.state_cap}) must be >= "
                f"view.active_size ({self.active_size}): the active view "
                f"is always tracked"
            )
        if self.shuffle_every < 0:
            raise ValueError(
                f"view.shuffle_every must be >= 0, got {self.shuffle_every}"
            )


@dataclasses.dataclass(frozen=True)
class MembershipConfig:
    """``membership:`` block — epidemic membership & partition tolerance.

    SWIM-style dissemination over the existing gossip wire: every frame
    carries an optional trailing digest (per-peer state, suspicion,
    incarnation); receivers merge it into their scoreboard so the whole
    ring converges on a shared membership view instead of each node
    rediscovering failures alone.  Needs ``health.enabled`` (the digest
    IS the scoreboard view) and forces the Python Rx server (the relay
    verb and digest trailer live there).  All decisions are keyed on
    gossip rounds and threefry draws — no wall clock — so membership
    event sequences are bit-identical across replays of a seed."""

    enabled: bool = True
    # Indirect probing: before promoting suspect -> quarantined on own
    # evidence, ask K deterministically-drawn healthy peers to
    # header-probe the suspect (0 = promote on own evidence alone).
    indirect_probes: int = 2
    relay_timeout_ms: int = 250
    # A quarantined peer that fails this many consecutive re-admission
    # probes is disseminated as ``dead`` (still probed locally — dead is
    # a gossip label, not a tombstone).
    dead_after_quarantines: int = 3
    # Churn hardening (docs/fleet.md): a peer the combined view holds
    # DEAD for this many further rounds is *evicted* — its scoreboard /
    # trust / flowctl per-peer state is pruned, it leaves the membership
    # digest (bounding digest growth under heavy join/leave), and the
    # partner remap never draws it.  A rejoiner refutes the dead claim
    # with a fresher incarnation and is re-admitted from scratch.
    # 0 disables eviction (legacy unbounded behavior).
    dead_gossip_rounds: int = 16
    # Degraded mode when |connected component| / n_peers falls BELOW
    # this fraction (strictly below: a 2-node ring losing one peer sits
    # exactly at 0.5 and is a peer failure, not a partition).
    quorum_fraction: float = 0.5
    # While degraded, scale interpolation alpha by this factor so a
    # minority island drifts more slowly from the majority (1.0 = off).
    degraded_alpha_scale: float = 1.0
    # Heal reconciliation: on seeing a component return, anti-entropy
    # merge with a drawn donor from the returning side, weighted by its
    # relative size, guarded by validate_payload + RollbackRing.
    heal_reconcile: bool = True
    # Reconcile only when the returning component is at least this
    # fraction of the ring — a single readmitted peer re-syncs itself
    # (recovery.max_clock_lag advice) rather than dragging everyone
    # through a state merge.
    reconcile_min_fraction: float = 0.3
    # Clamp on the returning side's merge weight, so even a majority
    # returning component cannot fully overwrite the local replica.
    max_heal_weight: float = 0.75
    # Bounded partial views (nested ``view:`` block; accepts a plain
    # dict from YAML).  Off by default: the global-view behavior of
    # every pre-view release.
    view: ViewConfig = dataclasses.field(default_factory=ViewConfig)

    def __post_init__(self) -> None:
        if isinstance(self.view, Mapping):
            object.__setattr__(self, "view", ViewConfig(**self.view))
        if self.indirect_probes < 0:
            raise ValueError(
                f"indirect_probes must be >= 0, got {self.indirect_probes}"
            )
        if self.relay_timeout_ms < 1:
            raise ValueError(
                f"relay_timeout_ms must be >= 1, got {self.relay_timeout_ms}"
            )
        if self.dead_after_quarantines < 1:
            raise ValueError(
                f"dead_after_quarantines must be >= 1, "
                f"got {self.dead_after_quarantines}"
            )
        if self.dead_gossip_rounds < 0:
            raise ValueError(
                f"dead_gossip_rounds must be >= 0, "
                f"got {self.dead_gossip_rounds}"
            )
        if not 0.0 <= self.quorum_fraction <= 1.0:
            raise ValueError(
                f"quorum_fraction must be in [0, 1], got {self.quorum_fraction}"
            )
        if not 0.0 < self.degraded_alpha_scale <= 1.0:
            raise ValueError(
                f"degraded_alpha_scale must be in (0, 1], "
                f"got {self.degraded_alpha_scale}"
            )
        if not 0.0 <= self.reconcile_min_fraction <= 1.0:
            raise ValueError(
                f"reconcile_min_fraction must be in [0, 1], "
                f"got {self.reconcile_min_fraction}"
            )
        if not 0.0 < self.max_heal_weight <= 1.0:
            raise ValueError(
                f"max_heal_weight must be in (0, 1], "
                f"got {self.max_heal_weight}"
            )


@dataclasses.dataclass(frozen=True)
class TrustConfig:
    """``trust:`` block — the content-trust plane's knobs (docs/trust.md).

    Screening defaults ON but conservative: classification only arms
    after ``min_window`` accepted exchanges (a cold ring has no baseline
    to deviate from), the MAD multipliers are wide (8σ suspect / 24σ
    reject — honest heterogeneity across data shards sits well inside),
    and a fully-trusted peer's alpha scale snaps to exactly 1.0, so an
    honest ring's trajectory is bit-identical to a trust-disabled run.
    Applies to the TCP transport (the path with per-peer payloads to
    screen); needs ``health.enabled`` for the quarantine feedback."""

    enabled: bool = True
    # Median/MAD window over ACCEPTED exchanges.  Larger = slower to
    # adapt to genuine regime changes, harder to poison; must comfortably
    # exceed min_window.
    window: int = 32
    min_window: int = 8
    # Robust z-score thresholds: [mad_multiplier, reject_multiplier) is
    # the damped band, beyond reject_multiplier the payload never merges.
    mad_multiplier: float = 8.0
    reject_multiplier: float = 24.0
    # Suspect merges at alpha * trust**damping; higher = harsher damping
    # for partially-trusted peers (1.0 = linear in trust).
    damping: float = 1.0
    # Trust EWMA: clean exchanges halve the trust DEFICIT every
    # ewma_half_life exchanges; verdict decays multiply trust down.
    ewma_half_life: float = 4.0
    suspect_decay: float = 0.7
    reject_decay: float = 0.25
    # Below this trust, every screening feeds an ``untrusted`` probe to
    # the scoreboard — a persistently-suspect peer quarantines even if no
    # single payload is outright rejected.
    quarantine_trust: float = 0.15
    # Hard bounds, active once armed, that no drifted baseline excuses:
    # a sign-flip lands at cosine -1; a scale blow-up below the recovery
    # guard's explosion bound still trips the norm ratio.
    cosine_floor: float = -0.5
    norm_ratio_max: float = 64.0
    # Replay detection: a peer's publish clock may run backward by this
    # much (re-serving last round's payload is normal overlap) before the
    # payload counts as a stale replay.
    replay_slack: float = 0.5
    # Re-acquaintance amnesty: a peer unscreened for more than
    # ``amnesty_gap * (n_peers - 1)`` rounds (partition, quarantine,
    # crash-rejoin — its replica has legitimately diverged) gets
    # ``amnesty_rounds`` lenient screenings in which hard rejections
    # downgrade to damped suspects and a stale clock resets the replay
    # base instead of rejecting.  0 on either knob disables amnesty.
    amnesty_gap: int = 4
    amnesty_rounds: int = 8

    def __post_init__(self) -> None:
        if self.window < 2:
            raise ValueError(f"window must be >= 2, got {self.window}")
        if not 1 <= self.min_window <= self.window:
            raise ValueError(
                f"min_window must be in [1, window], got {self.min_window}"
            )
        if self.mad_multiplier <= 0:
            raise ValueError(
                f"mad_multiplier must be > 0, got {self.mad_multiplier}"
            )
        if self.reject_multiplier < self.mad_multiplier:
            raise ValueError(
                "reject_multiplier must be >= mad_multiplier, "
                f"got {self.reject_multiplier} < {self.mad_multiplier}"
            )
        if self.damping <= 0:
            raise ValueError(f"damping must be > 0, got {self.damping}")
        if self.ewma_half_life <= 0:
            raise ValueError(
                f"ewma_half_life must be > 0, got {self.ewma_half_life}"
            )
        for name in ("suspect_decay", "reject_decay"):
            v = getattr(self, name)
            if not 0.0 <= v < 1.0:
                raise ValueError(f"{name} must be in [0, 1), got {v}")
        if not 0.0 < self.quarantine_trust < 1.0:
            raise ValueError(
                f"quarantine_trust must be in (0, 1), "
                f"got {self.quarantine_trust}"
            )
        if not -1.0 <= self.cosine_floor <= 1.0:
            raise ValueError(
                f"cosine_floor must be in [-1, 1], got {self.cosine_floor}"
            )
        if self.norm_ratio_max <= 1.0:
            raise ValueError(
                f"norm_ratio_max must be > 1, got {self.norm_ratio_max}"
            )
        if self.replay_slack < 0:
            raise ValueError(
                f"replay_slack must be >= 0, got {self.replay_slack}"
            )
        for name in ("amnesty_gap", "amnesty_rounds"):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 0:
                raise ValueError(
                    f"{name} must be a non-negative int, got {v!r}"
                )


@dataclasses.dataclass(frozen=True)
class FlowctlConfig:
    """``flowctl:`` block — flow control plane knobs (docs/flowctl.md).

    Fetcher side: every classified fetch outcome feeds a per-peer
    latency/throughput estimator whose quantile sets the next fetch's
    cumulative deadline (clamped to ``[min_ms, max_ms]``; cold peers fall
    back to ``protocol.timeout_ms``), and once the un-margined quantile
    budget lapses a single hedged retry races the schedule's fallback
    partner.  Serving side: admission control in the Python Rx server —
    connection cap, per-remote token bucket, in-flight-bytes ceiling,
    slow-loris eviction — sheds excess load with an explicit ``DPWB``
    busy frame instead of queueing unboundedly.  Busy/slow evidence is
    low-weight (detector outcomes ``busy``/``slow``) and soft-degrades a
    peer (scoreboard ``degraded``, never quarantined on that evidence
    alone).  Like chaos/recovery/membership, enabling this forces the
    Python Rx server — the native C++ loop does not speak DPWB."""

    enabled: bool = True
    # Adaptive deadline: the tracked success-latency quantile, times
    # ``margin``, clamped to [min_ms, max_ms].  The un-margined quantile
    # is the hedge launch point, so the margin IS the hedge's headroom.
    quantile: float = 0.95
    margin: float = 1.5
    min_ms: float = 50.0
    max_ms: float = 5000.0
    # Per-peer success-latency samples kept (ring window); below
    # ``warmup`` samples the estimator is cold: deadlines fall back to
    # protocol.timeout_ms and hedging stays off.
    window: int = 32
    warmup: int = 5
    hedge: bool = True
    # Fraction of scheduled rounds deterministically remapped away from a
    # DEGRADED partner (threefry control draw, tag 8).  The rest still
    # fetch it — under its adaptive (short) budget — so recovery evidence
    # keeps flowing.  0 disables shedding, 1 starves the peer of direct
    # observations (readmission then rides on other peers' digests).
    degrade_shed_fraction: float = 0.5
    # Serving-side admission.
    max_connections: int = 32
    # Connection cap in effect under ``protocol.rx_server: reactor``:
    # the threaded cap bounds worker THREADS, the reactor's bounds
    # registered sockets (a few KB each), so it defaults 32× higher.
    # Every other admission knob is shared between the two servers.
    reactor_max_connections: int = 1024
    token_rate: float = 100.0
    token_burst: float = 200.0
    max_inflight_bytes: int = 1 << 28
    min_ingest_bytes_per_s: float = 4096.0
    # Per-connection handler budget; replaces the hard-coded 5 s
    # conn.settimeout in the accept path, so the request-read eviction
    # deadline and the handler recv timeout agree by construction.
    request_timeout_ms: int = 5000
    busy_retry_ms: int = 50

    def __post_init__(self) -> None:
        if not 0.0 < self.quantile <= 1.0:
            raise ValueError(
                f"quantile must be in (0, 1], got {self.quantile}"
            )
        if self.margin < 1.0:
            raise ValueError(f"margin must be >= 1, got {self.margin}")
        if not 0.0 < self.min_ms <= self.max_ms:
            raise ValueError(
                f"need 0 < min_ms <= max_ms, "
                f"got min_ms={self.min_ms} max_ms={self.max_ms}"
            )
        if self.window < 2:
            raise ValueError(f"window must be >= 2, got {self.window}")
        if not 1 <= self.warmup <= self.window:
            raise ValueError(
                f"warmup must be in [1, window], got {self.warmup}"
            )
        if not 0.0 <= self.degrade_shed_fraction <= 1.0:
            raise ValueError(
                f"degrade_shed_fraction must be in [0, 1], "
                f"got {self.degrade_shed_fraction}"
            )
        if self.max_connections < 1:
            raise ValueError(
                f"max_connections must be >= 1, got {self.max_connections}"
            )
        if self.reactor_max_connections < 1:
            raise ValueError(
                f"reactor_max_connections must be >= 1, "
                f"got {self.reactor_max_connections}"
            )
        if self.token_rate <= 0:
            raise ValueError(
                f"token_rate must be > 0, got {self.token_rate}"
            )
        if self.token_burst < 1:
            raise ValueError(
                f"token_burst must be >= 1, got {self.token_burst}"
            )
        if self.max_inflight_bytes < 1:
            raise ValueError(
                f"max_inflight_bytes must be >= 1, "
                f"got {self.max_inflight_bytes}"
            )
        if self.min_ingest_bytes_per_s <= 0:
            raise ValueError(
                f"min_ingest_bytes_per_s must be > 0, "
                f"got {self.min_ingest_bytes_per_s}"
            )
        if self.request_timeout_ms < 1:
            raise ValueError(
                f"request_timeout_ms must be >= 1, "
                f"got {self.request_timeout_ms}"
            )
        if self.busy_retry_ms < 0:
            raise ValueError(
                f"busy_retry_ms must be >= 0, got {self.busy_retry_ms}"
            )


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """``obs:`` block — observability plane (docs/observability.md).

    Three independently-gated facilities, all default-off because the
    contract is zero-cost-when-disabled: with this block off no trailing
    section is added to gossip frames, no tracing ``perf_counter`` calls
    run, and exchange byte streams are bit-identical to an obs-free
    build.

    - ``trace`` — per-stage round spans written as ``trace`` JSONL
      records, with the round's trace ID piggybacked on served frames
      (``DPWT`` trailing section) so ``tools/trace_report.py`` can join
      fetcher and server spans into one cross-peer timeline.  Forces the
      Python Rx server (like flowctl) so the serve leg can be timed.
    - ``sketch`` — a ``sketch_k``-float threefry-seeded random-projection
      sketch of the local replica piggybacked per frame, giving every
      peer an online ring-disagreement estimate.
    - ``metrics`` — a Prometheus text ``/metrics`` route on the healthz
      port, exposing counters/gauges from every enabled plane.
    - ``incidents`` — online anomaly detectors over the existing
      signals (fetch outcomes, scoreboard transitions, membership and
      trust events, the sketch's rel_rms, round wall time) feeding a
      correlator that folds alerts into open→evolve→resolve
      ``incident`` records (docs/incidents.md), served live at the
      ``/incidents`` healthz route.
    - ``recorder`` — a black-box flight recorder: a bounded in-memory
      ring of the last ``recorder_rounds`` rounds of
      outcomes/verdicts/digests, dumped to a post-mortem JSONL artifact
      on crash (atexit/SIGTERM), on incident open, on close, or via the
      ``/flightdump`` healthz route.

    ``log_max_bytes`` caps any JSONL file the adapter's MetricsLogger
    writes (health/exchange records), rolling through ``log_keep``
    generations (``<path>.1`` .. ``<path>.N``)."""

    trace: bool = False
    trace_every: int = 1
    trace_path: "str | None" = None
    trace_max_records: int = 4096
    sketch: bool = False
    sketch_k: int = 64
    sketch_every: int = 1
    metrics: bool = False
    log_max_bytes: int = 0
    log_keep: int = 1
    incidents: bool = False
    incident_path: "str | None" = None
    incident_window: int = 8
    incident_fail_streak: int = 2
    incident_soft_streak: int = 2
    incident_trust_burst: int = 2
    incident_storm_threshold: int = 3
    # staleness_storm detector (docs/async.md): stale drops within
    # ``incident_window`` rounds before the incident fires — lag
    # evidence is soft, so the bar sits above a lone straggler blip.
    incident_stale_storm: int = 3
    incident_stall_window: int = 8
    incident_stall_min_rel: float = 0.05
    incident_stall_improve: float = 0.01
    incident_slo_factor: float = 4.0
    incident_slo_rounds: int = 5
    incident_slo_warmup: int = 16
    incident_resolve_after: int = 8
    recorder: bool = False
    recorder_rounds: int = 64
    recorder_path: "str | None" = None

    def __post_init__(self) -> None:
        if self.trace_every < 1:
            raise ValueError(
                f"trace_every must be >= 1, got {self.trace_every}"
            )
        if self.trace_max_records < 1:
            raise ValueError(
                f"trace_max_records must be >= 1, "
                f"got {self.trace_max_records}"
            )
        if not 1 <= self.sketch_k <= 4096:
            raise ValueError(
                f"sketch_k must be in [1, 4096], got {self.sketch_k}"
            )
        if self.sketch_every < 1:
            raise ValueError(
                f"sketch_every must be >= 1, got {self.sketch_every}"
            )
        if self.log_max_bytes < 0:
            raise ValueError(
                f"log_max_bytes must be >= 0, got {self.log_max_bytes}"
            )
        if self.log_keep < 1:
            raise ValueError(
                f"log_keep must be >= 1, got {self.log_keep}"
            )
        for name in (
            "incident_window",
            "incident_fail_streak",
            "incident_soft_streak",
            "incident_trust_burst",
            "incident_storm_threshold",
            "incident_stale_storm",
            "incident_stall_window",
            "incident_slo_rounds",
            "incident_slo_warmup",
            "incident_resolve_after",
            "recorder_rounds",
        ):
            v = getattr(self, name)
            if v < 1:
                raise ValueError(f"{name} must be >= 1, got {v}")
        if self.incident_stall_min_rel < 0:
            raise ValueError(
                f"incident_stall_min_rel must be >= 0, "
                f"got {self.incident_stall_min_rel}"
            )
        if not 0.0 <= self.incident_stall_improve < 1.0:
            raise ValueError(
                f"incident_stall_improve must be in [0, 1), "
                f"got {self.incident_stall_improve}"
            )
        if self.incident_slo_factor <= 1.0:
            raise ValueError(
                f"incident_slo_factor must be > 1, "
                f"got {self.incident_slo_factor}"
            )

    @property
    def enabled(self) -> bool:
        """Any facility on (the transport builds obs state iff this)."""
        return (
            self.trace or self.sketch or self.metrics
            or self.incidents or self.recorder
        )


@dataclasses.dataclass(frozen=True)
class InterpolationConfig:
    type: str = "constant"
    factor: float = 0.5

    def __post_init__(self) -> None:
        if self.type not in ("constant", "clock", "loss"):
            raise ValueError(f"unknown interpolation type {self.type!r}")
        if not 0.0 <= self.factor <= 1.0:
            raise ValueError(f"factor must be in [0, 1], got {self.factor}")


@dataclasses.dataclass(frozen=True)
class IslandSpec:
    """One ``topology.islands`` entry: a named subset of ``nodes:``."""

    name: str
    nodes: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Two-level (island × wide-area) gossip topology; docs/hierarchy.md.

    An empty ``islands`` tuple (the default, and the absent-block case)
    means the flat single-ring topology — every pre-hierarchy config
    keeps its exact behavior."""

    islands: tuple[IslandSpec, ...] = ()
    leader_seed: int = 0
    intra_rounds: int = 1

    def __post_init__(self) -> None:
        if self.intra_rounds < 1:
            raise ValueError(
                f"topology.intra_rounds must be >= 1, got {self.intra_rounds}"
            )
        names = [isl.name for isl in self.islands]
        if len(set(names)) != len(names):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"duplicate island names in topology: {dupes}")
        for isl in self.islands:
            if not isl.nodes:
                raise ValueError(
                    f"topology island {isl.name!r} lists no nodes"
                )
            if len(set(isl.nodes)) != len(isl.nodes):
                dupes = sorted(
                    {n for n in isl.nodes if isl.nodes.count(n) > 1}
                )
                raise ValueError(
                    f"topology island {isl.name!r} lists node(s) {dupes}"
                    " more than once"
                )

    @property
    def enabled(self) -> bool:
        """Hierarchical mode on — at least one island is declared."""
        return bool(self.islands)

    def validate_nodes(self, node_names: Sequence[str]) -> None:
        """Cross-check the island partition against the ``nodes:`` list.

        Every error names the offending island and node: islands must
        reference only declared nodes, no node may belong to two
        islands, and — when the block is enabled — every node must be
        covered (a super-peer topology with stragglers outside any
        island has no one to speak for them)."""
        if not self.enabled:
            return
        known = set(node_names)
        owner: dict[str, str] = {}
        for isl in self.islands:
            for node in isl.nodes:
                if node not in known:
                    raise ValueError(
                        f"topology island {isl.name!r} references unknown"
                        f" node {node!r} (declared nodes:"
                        f" {sorted(known)})"
                    )
                if node in owner:
                    raise ValueError(
                        f"node {node!r} appears in both island"
                        f" {owner[node]!r} and island {isl.name!r} — a"
                        " node belongs to exactly one island"
                    )
                owner[node] = isl.name
        uncovered = [n for n in node_names if n not in owner]
        if uncovered:
            raise ValueError(
                f"topology islands do not cover node(s) {uncovered} — every"
                " node must belong to exactly one island"
            )


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """``run:`` block — the training-harness loop (docs/training.md).

    Knobs for :mod:`dpwa_tpu.run`: how many optimizer steps each node
    takes, the SGD hyperparameters, the loss-record cadence, and the
    periodic-checkpoint policy the crash leg restarts from.  The data
    order is NOT configured here: each node's per-epoch shuffle is a
    threefry draw keyed on ``(protocol.seed, epoch, node)``
    (``schedules.data_shuffle_draw``), so a seeded rerun replays the
    exact batch sequence with no stream state to save."""

    steps: int = 100
    batch_size: int = 32
    lr: float = 0.1
    momentum: float = 0.0
    loss_every: int = 1
    checkpoint_every: int = 0
    checkpoint_dir: "str | None" = None
    checkpoint_keep: int = 3
    target_loss: float = 0.0

    def __post_init__(self) -> None:
        if self.steps < 1:
            raise ValueError(f"run.steps must be >= 1, got {self.steps}")
        if self.batch_size < 1:
            raise ValueError(
                f"run.batch_size must be >= 1, got {self.batch_size}"
            )
        if self.lr <= 0:
            raise ValueError(f"run.lr must be > 0, got {self.lr}")
        if not 0.0 <= self.momentum < 1.0:
            raise ValueError(
                f"run.momentum must be in [0, 1), got {self.momentum}"
            )
        if self.loss_every < 1:
            raise ValueError(
                f"run.loss_every must be >= 1, got {self.loss_every}"
            )
        if self.checkpoint_every < 0:
            raise ValueError(
                f"run.checkpoint_every must be >= 0, "
                f"got {self.checkpoint_every}"
            )
        if self.checkpoint_keep < 1:
            raise ValueError(
                f"run.checkpoint_keep must be >= 1, "
                f"got {self.checkpoint_keep}"
            )
        if self.target_loss < 0:
            raise ValueError(
                f"run.target_loss must be >= 0, got {self.target_loss}"
            )


@dataclasses.dataclass(frozen=True)
class TuneConfig:
    """``tune:`` block — the self-tuning wire (docs/tune.md).

    Off (the default, and the absent-block case) the transport publishes
    exactly what the static ``protocol.wire_*`` knobs say — frames stay
    byte-identical to a pre-tune build.  On, a per-link
    :class:`~dpwa_tpu.tune.controller.LinkTuner` (the DeadlineEstimator
    mold) walks each link up and down the frozen codec ladder from the
    observations the obs planes already collect: escalate compression on
    wire-bound links, back off when the sketch plane shows convergence
    stalling, and shed fidelity — never rounds — while the scheduled
    partner is scoreboard-DEGRADED.  Every decision derives from
    QUANTIZED observations plus one registered threefry stream (tag 37,
    dwell jitter), so seeded soaks replay their decision logs
    bit-identically."""

    enabled: bool = False
    # Observation rounds per link behind each decision.
    window: int = 8
    # Hysteresis: a link holds a rung at least this many rounds before
    # it may escalate again, and may not re-escalate for
    # ``cooldown_rounds`` after a back-off — a square-wave (flapping)
    # link settles instead of thrashing the ladder.
    min_dwell_rounds: int = 6
    cooldown_rounds: int = 12
    # A round is "wire-bound" when its quantized wire-span fraction of
    # the round wall is at/above this.
    wire_bound_frac: float = 0.5
    # Escalate one rung when at least this fraction of the window's
    # rounds are wire-bound (busy/slow/stale outcomes count as
    # wire-bound evidence — the link is failing to move bytes in time).
    escalate_frac: float = 0.5
    # Back off one rung when the window's fractional rel_rms improvement
    # falls below this (the sketch plane says compression is starving
    # convergence).  Only meaningful with >= 2 rel samples in-window.
    stall_eps: float = 0.02
    # Extra rungs (clamped to the ladder top) shed while the scheduled
    # partner is DEGRADED — fidelity shed replaces the degrade_shed
    # round-drop remap while the controller is enabled.
    shed_rungs: int = 2
    # Quantization buckets for observed span fractions and rel trends;
    # decisions never branch on raw wall-clock readings.
    quant: int = 16
    # Dwell jitter (threefry tag 37) in [0, jitter_rounds] added to each
    # link's dwell expiry so fleet-wide escalations desynchronize.
    jitter_rounds: int = 2

    def __post_init__(self) -> None:
        if self.window < 2:
            raise ValueError(f"tune.window must be >= 2, got {self.window}")
        if self.min_dwell_rounds < 1:
            raise ValueError(
                f"tune.min_dwell_rounds must be >= 1, "
                f"got {self.min_dwell_rounds}"
            )
        if self.cooldown_rounds < 0:
            raise ValueError(
                f"tune.cooldown_rounds must be >= 0, "
                f"got {self.cooldown_rounds}"
            )
        for name in ("wire_bound_frac", "escalate_frac"):
            v = getattr(self, name)
            if not 0.0 < v <= 1.0:
                raise ValueError(f"tune.{name} must be in (0, 1], got {v}")
        if self.stall_eps < 0:
            raise ValueError(
                f"tune.stall_eps must be >= 0, got {self.stall_eps}"
            )
        if self.shed_rungs < 0:
            raise ValueError(
                f"tune.shed_rungs must be >= 0, got {self.shed_rungs}"
            )
        if self.quant < 2:
            raise ValueError(f"tune.quant must be >= 2, got {self.quant}")
        if self.jitter_rounds < 0:
            raise ValueError(
                f"tune.jitter_rounds must be >= 0, "
                f"got {self.jitter_rounds}"
            )


@dataclasses.dataclass(frozen=True)
class DpwaConfig:
    nodes: tuple[NodeSpec, ...]
    protocol: ProtocolConfig = ProtocolConfig()
    shard: ShardConfig = ShardConfig()
    interpolation: InterpolationConfig = InterpolationConfig()
    health: HealthConfig = HealthConfig()
    chaos: ChaosConfig = ChaosConfig()
    recovery: RecoveryConfig = RecoveryConfig()
    membership: MembershipConfig = MembershipConfig()
    trust: TrustConfig = TrustConfig()
    flowctl: FlowctlConfig = FlowctlConfig()
    obs: ObsConfig = ObsConfig()
    topology: TopologyConfig = TopologyConfig()
    run: RunConfig = RunConfig()
    tune: TuneConfig = TuneConfig()

    def __post_init__(self) -> None:
        # Errors here name the offending island/node (satellite fix):
        # the partition is validated against the ACTUAL nodes: list, not
        # just internally.
        self.topology.validate_nodes(self.node_names)

    @property
    def n_peers(self) -> int:
        """Length of ``nodes:`` — the size of the gossip mesh axis."""
        return len(self.nodes)

    @property
    def node_names(self) -> tuple[str, ...]:
        return tuple(n.name for n in self.nodes)

    def node_index(self, name: str) -> int:
        """Position of ``name`` in ``nodes:`` — this process/device's peer id."""
        try:
            return self.node_names.index(name)
        except ValueError:
            raise KeyError(
                f"node {name!r} not in config (have {self.node_names})"
            ) from None

    def node(self, name: str) -> NodeSpec:
        return self.nodes[self.node_index(name)]


def _build_nodes(raw: Sequence[Any]) -> tuple[NodeSpec, ...]:
    nodes = []
    for i, entry in enumerate(raw):
        if isinstance(entry, str):
            # Shorthand: a bare name (ICI transport needs no address).
            nodes.append(NodeSpec(name=entry))
        elif isinstance(entry, Mapping):
            nodes.append(
                NodeSpec(
                    name=str(entry.get("name", f"node{i}")),
                    host=str(entry.get("host", "127.0.0.1")),
                    port=int(entry.get("port", 0)),
                )
            )
        else:
            raise TypeError(f"bad nodes[{i}] entry: {entry!r}")
    names = [n.name for n in nodes]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate node names in config: {names}")
    if not nodes:
        raise ValueError("config must list at least one node")
    return tuple(nodes)


def _build_islands(raw: Sequence[Any]) -> tuple[IslandSpec, ...]:
    islands = []
    for i, entry in enumerate(raw):
        if isinstance(entry, Mapping):
            islands.append(
                IslandSpec(
                    name=str(entry.get("name", f"island{i}")),
                    nodes=tuple(str(n) for n in (entry.get("nodes") or ())),
                )
            )
        elif isinstance(entry, Sequence) and not isinstance(entry, (str, bytes)):
            # Shorthand: a bare member list gets a positional island name.
            islands.append(
                IslandSpec(
                    name=f"island{i}", nodes=tuple(str(n) for n in entry)
                )
            )
        else:
            raise TypeError(f"bad topology.islands[{i}] entry: {entry!r}")
    return tuple(islands)


def config_from_dict(raw: Mapping[str, Any]) -> DpwaConfig:
    """Build a :class:`DpwaConfig` from a parsed-YAML mapping."""
    if "nodes" not in raw:
        raise ValueError("config is missing the required 'nodes:' list")
    proto = dict(raw.get("protocol") or {})
    shard = dict(raw.get("shard") or {})
    interp = dict(raw.get("interpolation") or {})
    health = dict(raw.get("health") or {})
    chaos = dict(raw.get("chaos") or {})
    recovery = dict(raw.get("recovery") or {})
    membership = dict(raw.get("membership") or {})
    trust = dict(raw.get("trust") or {})
    flowctl = dict(raw.get("flowctl") or {})
    obs = dict(raw.get("obs") or {})
    topology = dict(raw.get("topology") or {})
    run = dict(raw.get("run") or {})
    tune = dict(raw.get("tune") or {})
    if topology.get("islands") is not None:
        topology["islands"] = _build_islands(topology["islands"])
    for key in (
        "down_windows", "partition_windows", "link_windows",
        "byzantine_peers", "trickle_windows", "accept_delay_windows",
        "bandwidth_windows",
    ):
        if chaos.get(key) is not None:
            chaos[key] = tuple(chaos[key])
    return DpwaConfig(
        nodes=_build_nodes(raw["nodes"]),
        protocol=ProtocolConfig(**proto),
        shard=ShardConfig(**shard),
        interpolation=InterpolationConfig(**interp),
        health=HealthConfig(**health),
        chaos=ChaosConfig(**chaos),
        recovery=RecoveryConfig(**recovery),
        membership=MembershipConfig(**membership),
        trust=TrustConfig(**trust),
        flowctl=FlowctlConfig(**flowctl),
        obs=ObsConfig(**obs),
        topology=TopologyConfig(**topology),
        run=RunConfig(**run),
        tune=TuneConfig(**tune),
    )


def load_config(path: str) -> DpwaConfig:
    """Load the reference-style YAML config file."""
    with open(path, "r", encoding="utf-8") as f:
        raw = yaml.safe_load(f)
    if not isinstance(raw, Mapping):
        raise ValueError(f"config file {path} did not parse to a mapping")
    return config_from_dict(raw)


def make_local_config(
    n_peers: int,
    *,
    schedule: str = "ring",
    fetch_probability: float = 1.0,
    interpolation: str = "constant",
    factor: float = 0.5,
    seed: int = 0,
    base_port: int = 45000,
    health: "HealthConfig | Mapping[str, Any] | None" = None,
    chaos: "ChaosConfig | Mapping[str, Any] | None" = None,
    recovery: "RecoveryConfig | Mapping[str, Any] | None" = None,
    membership: "MembershipConfig | Mapping[str, Any] | None" = None,
    trust: "TrustConfig | Mapping[str, Any] | None" = None,
    flowctl: "FlowctlConfig | Mapping[str, Any] | None" = None,
    obs: "ObsConfig | Mapping[str, Any] | None" = None,
    topology: "TopologyConfig | Mapping[str, Any] | None" = None,
    shard: "ShardConfig | Mapping[str, Any] | None" = None,
    run: "RunConfig | Mapping[str, Any] | None" = None,
    tune: "TuneConfig | Mapping[str, Any] | None" = None,
    **protocol_kwargs: Any,
) -> DpwaConfig:
    """Programmatic config for tests/benchmarks: n local peers on 127.0.0.1.

    ``health`` / ``chaos`` / ``recovery`` / ``membership`` / ``trust`` /
    ``flowctl`` / ``obs`` accept a config object or a plain dict (the
    YAML-block shorthand)."""
    if isinstance(health, Mapping):
        health = HealthConfig(**health)
    if isinstance(chaos, Mapping):
        chaos = ChaosConfig(**chaos)
    if isinstance(recovery, Mapping):
        recovery = RecoveryConfig(**recovery)
    if isinstance(membership, Mapping):
        membership = MembershipConfig(**membership)
    if isinstance(trust, Mapping):
        trust = TrustConfig(**trust)
    if isinstance(flowctl, Mapping):
        flowctl = FlowctlConfig(**flowctl)
    if isinstance(obs, Mapping):
        obs = ObsConfig(**obs)
    if isinstance(shard, Mapping):
        shard = ShardConfig(**shard)
    if isinstance(run, Mapping):
        run = RunConfig(**run)
    if isinstance(tune, Mapping):
        tune = TuneConfig(**tune)
    if isinstance(topology, Mapping):
        topology = dict(topology)
        if topology.get("islands") is not None:
            topology["islands"] = _build_islands(topology["islands"])
        topology = TopologyConfig(**topology)
    return DpwaConfig(
        nodes=tuple(
            NodeSpec(name=f"node{i}", host="127.0.0.1", port=base_port + i)
            for i in range(n_peers)
        ),
        protocol=ProtocolConfig(
            schedule=schedule,
            fetch_probability=fetch_probability,
            seed=seed,
            **protocol_kwargs,
        ),
        interpolation=InterpolationConfig(type=interpolation, factor=factor),
        health=health if health is not None else HealthConfig(),
        chaos=chaos if chaos is not None else ChaosConfig(),
        recovery=recovery if recovery is not None else RecoveryConfig(),
        membership=membership if membership is not None else MembershipConfig(),
        trust=trust if trust is not None else TrustConfig(),
        flowctl=flowctl if flowctl is not None else FlowctlConfig(),
        obs=obs if obs is not None else ObsConfig(),
        topology=topology if topology is not None else TopologyConfig(),
        shard=shard if shard is not None else ShardConfig(),
        run=run if run is not None else RunConfig(),
        tune=tune if tune is not None else TuneConfig(),
    )
