"""Merge-coefficient (α) strategies for ``x ← (1−α)·x + α·x_peer``.

The reference ships pluggable interpolation strategies selected from the YAML
config (SURVEY.md §2 "Interpolation strategies"; reference file
``dpwa/interpolation.py`` — mount empty, reconstructed):

- **constant** — fixed α; α = 0.5 is the ``(local+remote)/2`` merge named in
  the north-star (BASELINE.json:5).
- **clock-weighted** — weight by relative training progress: a peer that has
  seen more data is trusted more.
- **loss-weighted** — trust the lower-loss peer more.

All strategies here are pure jittable functions of the local and remote
``(clock, loss)`` metadata pair, so the same code computes α inside the fused
ICI exchange (traced) and on the host for the TCP transport (concrete).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax.numpy as jnp

from dpwa_tpu.config import InterpolationConfig

_EPS = 1e-8


class PeerMeta(NamedTuple):
    """Per-peer scalars that ride along with every exchange.

    ``clock`` counts training progress (steps; the reference exchanged a
    sample/step counter with each payload, SURVEY.md §2).  ``loss`` is the
    most recent training loss passed to ``update(loss)``.
    """

    clock: jnp.ndarray  # float32 scalar
    loss: jnp.ndarray  # float32 scalar

    @staticmethod
    def zeros() -> "PeerMeta":
        return PeerMeta(jnp.float32(0.0), jnp.float32(0.0))


# An interpolation maps (local_meta, remote_meta) -> alpha in [0, 1].
Interpolation = Callable[[PeerMeta, PeerMeta], jnp.ndarray]


def constant(factor: float) -> Interpolation:
    def alpha(local: PeerMeta, remote: PeerMeta) -> jnp.ndarray:
        del local, remote
        return jnp.float32(factor)

    return alpha


def clock_weighted(factor: float = 1.0) -> Interpolation:
    """α = factor · remote_clock / (local_clock + remote_clock).

    A fresh peer (clock 0) contributes nothing; two equally-trained peers
    average symmetrically (α = factor/2)."""

    def alpha(local: PeerMeta, remote: PeerMeta) -> jnp.ndarray:
        total = local.clock + remote.clock
        return jnp.float32(factor) * remote.clock / jnp.maximum(total, _EPS)

    return alpha


def loss_weighted(factor: float = 1.0) -> Interpolation:
    """α = factor · local_loss / (local_loss + remote_loss).

    The higher my loss relative to the peer's, the more of the peer I take;
    a peer whose loss is much lower than mine dominates the merge."""

    def alpha(local: PeerMeta, remote: PeerMeta) -> jnp.ndarray:
        total = local.loss + remote.loss
        return jnp.float32(factor) * local.loss / jnp.maximum(total, _EPS)

    return alpha


def _clamped(
    strategy: Interpolation,
    max_abs_loss: float | None = None,
    trust_scale: Callable[[], float] | None = None,
) -> Interpolation:
    """Restrict α to [0, 1] so the merge is always an interpolation.

    ``loss_weighted`` is unbounded on raw metadata: a negative local loss
    (continuous-density NLL, reward-style objectives) or ``local ≫ remote``
    drives α outside [0, 1], silently turning ``(1−α)x + αy`` into
    extrapolation on every transport.  ``clock_weighted`` is safe only
    because clocks are nonnegative by construction, and any strategy with
    ``factor > 1`` can overshoot — so the clamp is applied uniformly here
    rather than per-strategy.

    A non-finite α (NaN/inf metadata makes the ratio NaN, and
    ``jnp.clip`` propagates NaN) resolves by which side is sick: if the
    LOCAL metadata is sick and the peer's is healthy, α = 1 — adopting
    the healthy peer is exactly the rescue gossip offers a diverged
    replica.  In every other sick case α = 0 (keep the local replica,
    the same keep-training posture as a failed fetch).

    "Sick" means non-finite metadata (NaN/inf clock or loss), and — when
    ``max_abs_loss`` is given (``RecoveryConfig.rescue_bound()``,
    threaded through :func:`make_interpolation`) — also a finite loss
    beyond that bound.  A replica at loss 1e30 has diverged in every
    sense that matters; without the bound it took the ordinary clipped
    path (e.g. ``loss_weighted``'s ratio capped at ``min(factor, 1)``)
    and never got the full α = 1 rescue its state needs.  With no bound
    configured, finite-but-huge keeps the ordinary path — only
    actually-poisoned metadata rescues.

    The bound passed here is deliberately the RESCUE bound, not the
    guard's ``recovery.max_loss`` reject bound: real training runs tune
    ``max_loss`` down to their loss scale so diverged peers are caught
    early, and a normal early-training loss spike can brush against it.
    Tripping the guard costs one rejected frame or one rollback — both
    recoverable — but α = 1 REPLACES the local replica, so it arms only
    ``rescue_bound()`` (default ``16 * max_loss``) past the guard.

    ``trust_scale`` — the content-trust plane's merge damping
    (:meth:`dpwa_tpu.trust.TrustManager.alpha_scale`, threaded by the
    TCP transport as a zero-arg callable so the CURRENT exchange's
    verdict applies).  A fully-trusted peer reports exactly 1.0, a
    bit-exact no-op; a suspect peer's alpha shrinks with its trust, so a
    damped merge is still an interpolation, just a shy one."""

    def alpha(local: PeerMeta, remote: PeerMeta) -> jnp.ndarray:
        a = strategy(local, remote)
        local_ok = jnp.isfinite(local.clock) & jnp.isfinite(local.loss)
        remote_ok = jnp.isfinite(remote.clock) & jnp.isfinite(remote.loss)
        if max_abs_loss is not None:
            bound = jnp.float32(max_abs_loss)
            local_ok = local_ok & (jnp.abs(local.loss) <= bound)
            remote_ok = remote_ok & (jnp.abs(remote.loss) <= bound)
        rescue = jnp.where(~local_ok & remote_ok, 1.0, 0.0)
        a = jnp.where(jnp.isfinite(a) & local_ok, a, rescue)
        # A sick REMOTE never merges: the TCP path's guard already
        # rejects such frames at the (stricter) ``recovery.max_loss``
        # bound, but the ICI/stacked substrates have no per-frame guard
        # — this is their only screen against a diverged neighbor.
        a = jnp.where(remote_ok, a, 0.0)
        a = jnp.clip(a, 0.0, 1.0)
        if trust_scale is not None:
            a = a * jnp.clip(jnp.float32(trust_scale()), 0.0, 1.0)
        return a

    return alpha


def make_interpolation(
    config: InterpolationConfig,
    max_abs_loss: float | None = None,
    trust_scale: Callable[[], float] | None = None,
) -> Interpolation:
    """Factory from the YAML ``interpolation:`` section.

    Every returned strategy is clamped to α ∈ [0, 1] (see ``_clamped``).
    ``max_abs_loss`` — normally ``recovery.rescue_bound()``, passed by
    the transports when recovery is enabled — additionally treats a
    finite-but-huge local loss as sick metadata deserving the full α = 1
    rescue.  ``trust_scale`` — the trust plane's per-exchange merge
    damping, multiplied in after the clamp (see ``_clamped``)."""
    if config.type == "constant":
        return _clamped(constant(config.factor), max_abs_loss, trust_scale)
    if config.type == "clock":
        return _clamped(
            clock_weighted(config.factor), max_abs_loss, trust_scale
        )
    if config.type == "loss":
        return _clamped(
            loss_weighted(config.factor), max_abs_loss, trust_scale
        )
    raise ValueError(f"unknown interpolation type {config.type!r}")
