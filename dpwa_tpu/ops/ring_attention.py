"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support (first-class, per the rebuild mandate; the reference
itself never touches model internals — SURVEY.md §5 "Long-context").  The
sequence is sharded into contiguous blocks over a mesh axis ``sp`` —
orthogonal to the gossip ``peers`` axis, so a 2-D mesh ``(peers, sp)`` runs
gossip-DP across replicas while each replica's long sequences span its
``sp`` sub-mesh.

Algorithm (Liu et al. 2023 ring attention; same math as blockwise/flash):
each device holds Q/K/V for its block; K/V blocks rotate around the ring
with ``lax.ppermute`` while a numerically-stable online softmax accumulates
(running max ``m``, denominator ``l``, weighted sum ``o``).  After
``sp``-many hops every query has attended to every key, with communication
overlapped block-by-block and O(T_local²) peak memory.  Exact — not an
approximation; verified against full attention in tests."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from dpwa_tpu.utils.compat import axis_size


def _block_attn(q, k, v, scale, qpos, kpos, causal):
    """One Q-block × K-block partial attention. Returns (scores_max, exp
    scores @ v, exp scores row-sums).

    Grouped-query attention: ``k``/``v`` may carry fewer heads than ``q``
    (H % KV == 0); they are expanded HERE, per block, so the ring carries
    (and each hop ppermutes) only the small grouped K/V — GQA's whole
    point on a long-context fabric."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        mask = kpos[None, None, None, :] <= qpos[None, None, :, None]
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,T]
    # Guard fully-masked rows (no valid keys in this block yet).
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    o = jnp.einsum("bhts,bshd->bthd", p, v)
    l = jnp.sum(p, axis=-1)  # [B,H,T]
    return m, o, l


def _auto_q_chunk(T: int) -> int:
    """Default query-chunk length: the largest power-of-two divisor of T
    capped at 256, or 0 (no chunking) for short blocks.  Chunking caps the
    per-hop score materialization at ``[B, H, chunk, T]`` instead of
    ``[B, H, T, T]``; 256 keeps the MXU-side matmuls large."""
    if T <= 512:
        return 0
    c = 256
    while c > 1 and T % c:
        c //= 2
    return c if c > 1 else 0


def _merge_partials(m, l, o, m_blk, l_blk, o_blk):
    """Online-softmax combine of two (max, denom, weighted-sum) partials."""
    m_new = jnp.maximum(m, m_blk)
    c_old = jnp.exp(m - m_new)
    c_blk = jnp.exp(m_blk - m_new)
    c_old = jnp.where(jnp.isfinite(c_old), c_old, 0.0)
    c_blk = jnp.where(jnp.isfinite(c_blk), c_blk, 0.0)
    l_new = l * c_old + l_blk * c_blk
    o_new = (
        o * c_old.transpose(0, 2, 1)[..., None]
        + o_blk * c_blk.transpose(0, 2, 1)[..., None]
    )
    return m_new, l_new, o_new


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    causal: bool = True,
    q_chunk: Optional[int] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Call INSIDE shard_map over ``axis_name``.

    Args:
      q, k, v: this device's sequence block, ``[B, T_local, H, D]``;
        device i holds global positions ``[i*T_local, (i+1)*T_local)``.
      q_chunk: query-chunk length for the flash-style inner loop.  None
        picks :func:`_auto_q_chunk`; 0 disables chunking.  With a chunk
        of C the per-hop peak is the ``[B, H, C, T_local]`` score panel —
        never the full ``[B, H, T_local, T_local]`` block — and the hop
        body is rematerialized (``jax.checkpoint``), so the backward pass
        recomputes score panels instead of carrying sp-many of them as
        scan residuals.  Long-context memory is O(T_local) activations.
      impl: "auto" runs every hop through the Pallas flash kernel on TPU
        when shapes allow (:mod:`dpwa_tpu.ops.flash_ring` — VMEM score
        tiles, never HBM panels); "flash" requests the same (on a TPU
        with ineligible shapes it falls back to THIS module's chunked
        einsum hop, never the flash-ring jnp twin, whose per-hop
        [B,H,T,T] panel would be a memory regression at long T; off-TPU
        it forces the twin — the CPU parity tests' hook); "xla" keeps
        the q-chunked einsum hop.  An EXPLICIT ``q_chunk`` pins the
        einsum hop too — it tunes a knob only that path has.
    Returns the local block of the attention output, ``[B, T_local, H, D]``.
    """
    if impl != "xla" and q_chunk is None:
        from dpwa_tpu.ops.flash_ring import (
            flash_ring_supported,
            ring_flash_attention_local,
        )

        on_tpu = jax.default_backend() == "tpu"
        if (on_tpu and flash_ring_supported(q.shape)) or (
            not on_tpu and impl == "flash"
        ):
            # Kernel choice (pallas vs jnp twin) auto-resolves by backend
            # inside flash_ring.
            return ring_flash_attention_local(q, k, v, axis_name, causal)
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    if q_chunk is None:
        q_chunk = _auto_q_chunk(T)
    if q_chunk and T % q_chunk:
        raise ValueError(f"q_chunk {q_chunk} must divide T_local {T}")
    scale = 1.0 / jnp.sqrt(D).astype(jnp.float32)
    q32 = q.astype(jnp.float32)
    qpos = me * T + jnp.arange(T)

    shift = [(j, (j + 1) % n) for j in range(n)]  # rotate kv around the ring

    def hop_attn(k_cur, v_cur, m, l, o, kpos):
        """One hop's partial attention + combine, optionally q-chunked."""
        k32, v32 = k_cur.astype(jnp.float32), v_cur.astype(jnp.float32)
        if not q_chunk:
            m_blk, o_blk, l_blk = _block_attn(
                q32, k32, v32, scale, qpos, kpos, causal
            )
            return _merge_partials(m, l, o, m_blk, l_blk, o_blk)

        nc = T // q_chunk
        # Stack per-chunk slices: scan materializes ONE chunk's score
        # panel at a time (sequential, not vmapped — that is the point).
        qs = q32.reshape(B, nc, q_chunk, H, D).transpose(1, 0, 2, 3, 4)
        qps = qpos.reshape(nc, q_chunk)
        ms = m.reshape(B, H, nc, q_chunk).transpose(2, 0, 1, 3)
        ls = l.reshape(B, H, nc, q_chunk).transpose(2, 0, 1, 3)
        os_ = o.reshape(B, nc, q_chunk, H, D).transpose(1, 0, 2, 3, 4)

        def chunk_step(_, xs):
            qc, qpc, mc, lc, oc = xs
            m_blk, o_blk, l_blk = _block_attn(
                qc, k32, v32, scale, qpc, kpos, causal
            )
            mc, lc, oc = _merge_partials(mc, lc, oc, m_blk, l_blk, o_blk)
            return None, (mc, lc, oc)

        _, (ms, ls, os_) = lax.scan(
            jax.checkpoint(chunk_step), None, (qs, qps, ms, ls, os_)
        )
        m = ms.transpose(1, 2, 0, 3).reshape(B, H, T)
        l = ls.transpose(1, 2, 0, 3).reshape(B, H, T)
        o = os_.transpose(1, 0, 2, 3, 4).reshape(B, T, H, D)
        return m, l, o

    def body(carry, hop):
        k_cur, v_cur, m, l, o = carry
        src = (me - hop) % n  # whose block we currently hold
        kpos = src * T + jnp.arange(T)
        m, l, o = hop_attn(k_cur, v_cur, m, l, o, kpos)
        k_nxt = lax.ppermute(k_cur, axis_name, perm=shift)
        v_nxt = lax.ppermute(v_cur, axis_name, perm=shift)
        return (k_nxt, v_nxt, m, l, o), None

    # Initial accumulators must carry the same varying-over-axis type as
    # their per-hop updates (shard_map VMA typing) — derive them from q so
    # they inherit q's full axis-varying set (works on multi-axis meshes,
    # e.g. peers × sp).
    zeros_bht = (q32 * 0.0).sum(-1).transpose(0, 2, 1)  # [B, H, T]
    m0 = zeros_bht - jnp.inf
    l0 = zeros_bht
    o0 = q32 * 0.0
    # Remat the hop: the backward pass re-runs each hop's score math from
    # the (small) K/V carry instead of keeping sp-many score panels alive.
    (k_f, v_f, m, l, o), _ = lax.scan(
        jax.checkpoint(body), (k, v, m0, l0, o0), jnp.arange(n)
    )
    l = jnp.maximum(l, 1e-20)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


@functools.partial(
    jax.jit, static_argnames=("axis_name", "causal", "mesh", "q_chunk", "impl")
)
def _jit_ring(q, k, v, mesh, axis_name, causal, q_chunk, impl):
    from dpwa_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    body = functools.partial(
        ring_attention_local, axis_name=axis_name, causal=causal,
        q_chunk=q_chunk, impl=impl,
    )
    spec = P(None, axis_name, None, None)
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh,
    axis_name: str = "sp",
    causal: bool = True,
    q_chunk: Optional[int] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Global-view convenience: q/k/v ``[B, T, H, D]`` sharded (or shardable)
    along T over ``mesh``'s ``axis_name``; returns the same layout."""
    return _jit_ring(q, k, v, mesh, axis_name, causal, q_chunk, impl)


def full_attention_reference(q, k, v, causal=True):
    """O(T²) single-device reference used by the parity tests."""
    B, T, H, D = q.shape
    s = jnp.einsum("bthd,bshd->bhts", q, k) / jnp.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), -1)
    return jnp.einsum("bhts,bshd->bthd", p, v).astype(q.dtype)
