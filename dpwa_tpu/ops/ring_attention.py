"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support (first-class, per the rebuild mandate; the reference
itself never touches model internals — SURVEY.md §5 "Long-context").  The
sequence is sharded into contiguous blocks over a mesh axis ``sp`` —
orthogonal to the gossip ``peers`` axis, so a 2-D mesh ``(peers, sp)`` runs
gossip-DP across replicas while each replica's long sequences span its
``sp`` sub-mesh.

Algorithm (Liu et al. 2023 ring attention; same math as blockwise/flash):
each device holds Q/K/V for its block; K/V blocks rotate around the ring
with ``lax.ppermute`` while a numerically-stable online softmax accumulates
(running max ``m``, denominator ``l``, weighted sum ``o``).  After
``sp``-many hops every query has attended to every key, with communication
overlapped block-by-block and O(T_local²) peak memory.  Exact — not an
approximation; verified against full attention in tests."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attn(q, k, v, scale, qpos, kpos, causal):
    """One Q-block × K-block partial attention. Returns (scores_max, exp
    scores @ v, exp scores row-sums).

    Grouped-query attention: ``k``/``v`` may carry fewer heads than ``q``
    (H % KV == 0); they are expanded HERE, per block, so the ring carries
    (and each hop ppermutes) only the small grouped K/V — GQA's whole
    point on a long-context fabric."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        mask = kpos[None, None, None, :] <= qpos[None, None, :, None]
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,T]
    # Guard fully-masked rows (no valid keys in this block yet).
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    o = jnp.einsum("bhts,bshd->bthd", p, v)
    l = jnp.sum(p, axis=-1)  # [B,H,T]
    return m, o, l


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Call INSIDE shard_map over ``axis_name``.

    Args:
      q, k, v: this device's sequence block, ``[B, T_local, H, D]``;
        device i holds global positions ``[i*T_local, (i+1)*T_local)``.
    Returns the local block of the attention output, ``[B, T_local, H, D]``.
    """
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = 1.0 / jnp.sqrt(D).astype(jnp.float32)
    q32 = q.astype(jnp.float32)
    qpos = me * T + jnp.arange(T)

    shift = [(j, (j + 1) % n) for j in range(n)]  # rotate kv around the ring

    def body(carry, hop):
        k_cur, v_cur, m, l, o = carry
        src = (me - hop) % n  # whose block we currently hold
        kpos = src * T + jnp.arange(T)
        m_blk, o_blk, l_blk = _block_attn(
            q32, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            scale, qpos, kpos, causal,
        )
        m_new = jnp.maximum(m, m_blk)
        # Rescale both accumulators to the new max.
        c_old = jnp.exp(m - m_new)
        c_blk = jnp.exp(m_blk - m_new)
        c_old = jnp.where(jnp.isfinite(c_old), c_old, 0.0)
        c_blk = jnp.where(jnp.isfinite(c_blk), c_blk, 0.0)
        l_new = l * c_old + l_blk * c_blk
        o_new = (
            o * c_old.transpose(0, 2, 1)[..., None]
            + o_blk * c_blk.transpose(0, 2, 1)[..., None]
        )
        k_nxt = lax.ppermute(k_cur, axis_name, perm=shift)
        v_nxt = lax.ppermute(v_cur, axis_name, perm=shift)
        return (k_nxt, v_nxt, m_new, l_new, o_new), None

    # Initial accumulators must carry the same varying-over-axis type as
    # their per-hop updates (shard_map VMA typing) — derive them from q so
    # they inherit q's full axis-varying set (works on multi-axis meshes,
    # e.g. peers × sp).
    zeros_bht = (q32 * 0.0).sum(-1).transpose(0, 2, 1)  # [B, H, T]
    m0 = zeros_bht - jnp.inf
    l0 = zeros_bht
    o0 = q32 * 0.0
    (k_f, v_f, m, l, o), _ = lax.scan(
        body, (k, v, m0, l0, o0), jnp.arange(n)
    )
    l = jnp.maximum(l, 1e-20)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("axis_name", "causal", "mesh"))
def _jit_ring(q, k, v, mesh, axis_name, causal):
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    body = functools.partial(
        ring_attention_local, axis_name=axis_name, causal=causal
    )
    spec = P(None, axis_name, None, None)
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh,
    axis_name: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Global-view convenience: q/k/v ``[B, T, H, D]`` sharded (or shardable)
    along T over ``mesh``'s ``axis_name``; returns the same layout."""
    return _jit_ring(q, k, v, mesh, axis_name, causal)


def full_attention_reference(q, k, v, causal=True):
    """O(T²) single-device reference used by the parity tests."""
    B, T, H, D = q.shape
    s = jnp.einsum("bthd,bshd->bhts", q, k) / jnp.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), -1)
    return jnp.einsum("bhts,bshd->bthd", p, v).astype(q.dtype)
