"""Pallas flash kernels inside every ring-attention hop.

VERDICT r3 weak #2: :mod:`dpwa_tpu.ops.ring_attention`'s per-hop compute is
q-chunked jnp einsum — score panels hit HBM — while only the single-device
path used the Pallas flash kernel.  This module puts the flash kernel in
the hop itself: per hop, each device runs the library TPU flash kernel
(``jax.experimental.pallas.ops.tpu.flash_attention`` — a dependency, not
copied code) over (its Q block, the K/V block currently held), and hop
partials are combined by logsumexp weights.  Scores live in VMEM tiles,
never HBM, so the sp path's per-hop throughput matches the single-device
flash kernel's.

Three standard ring-causality cases replace position masks entirely
(device ``me`` holding block ``src`` at some hop):

- ``src == me`` — the diagonal block: the kernel's own ``causal=True``.
- ``src <  me`` — a fully-visible past block: ``causal=False``.
- ``src >  me`` — a fully-masked future block: skipped (``lse = -inf``),
  no kernel launch (``lax.cond``).

Backward pass — the ring-attention trick the library kernels make exact:
their bwd kernels compute ``p = exp(s·scale − m) / l``; feeding
``m = global LSE`` and ``l = 1`` makes ``p`` the GLOBAL softmax restricted
to the held block, so per-hop calls of the library's ``dq``/``dkv``
kernels with global ``(LSE, out, dout, di)`` residuals produce exact
global gradients: ``dq`` accumulates locally, ``dk/dv`` accumulate on the
rotating block and arrive home after ``sp`` hops.  Verified against
full-attention autodiff to float epsilon (tests/test_flash_ring.py).

Every pallas call has a jnp twin with the identical (o, lse) / (dq, dk,
dv) contract, used off-TPU and by the CPU parity tests — so the ring +
merge + custom-vjp machinery is fully tested on the emulated mesh, and
the TPU path differs only by which (already TPU-proven) kernel computes
each hop.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from dpwa_tpu.utils.compat import axis_size

_NEG_INF = -1e30  # finite stand-in: -inf lse would NaN the merge weights


def _flash_mod():
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    return fa


def flash_ring_supported(q_shape) -> bool:
    """Shape eligibility for the pallas hop kernels ([B, T, H, D] layout):
    the kernels tile the sequence in 128-row blocks and want a
    lane-aligned head dim.  K/V shapes impose nothing extra: grouped
    heads are expanded before the kernel and T_kv == T_q on every hop."""
    B, T, H, D = q_shape
    return T % 128 == 0 and D % 128 == 0 and T > 0


# ---------------------------------------------------------------------------
# Per-hop forward: (q, k, v, causal) -> (o_normalized, lse), [B, H, T, D].
# ---------------------------------------------------------------------------


def _hop_fwd_pallas(q, k, v, causal: bool, scale: float):
    fa = _flash_mod()
    T = q.shape[2]
    blk = min(128, T)
    o, l, m = fa._flash_attention_impl(
        q, k, v, None, None,
        True,  # save_residuals
        causal, scale,
        1, blk, blk, blk,  # block_b, block_q, block_k_major, block_k
        False,
    )
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return o.astype(jnp.float32), lse.astype(jnp.float32)


# Above this many query rows, the jnp twins process q in chunks so the
# score panel peaks at [B, H, chunk, T_k] instead of [B, H, T_q, T_k] —
# the same memory profile as ring_attention.py's q-chunked einsum hop.
# Matters off-TPU and for flash-ineligible shapes at long T, where the
# twins ARE the execution path, not just the test harness.
_JNP_Q_CHUNK = 512


def _hop_fwd_jnp_panel(q, k, v, causal: bool, scale: float, row0: int):
    """One q-panel of the twin forward; ``row0`` is the panel's global
    row offset within the hop (causality compares k-column <= row)."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
    ) * scale
    if causal:
        rows = row0 + jnp.arange(q.shape[2])
        mask = jnp.arange(k.shape[2])[None, :] <= rows[:, None]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    o = o / jnp.maximum(l, 1e-30)[..., None]
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return o, lse


def _hop_fwd_jnp(q, k, v, causal: bool, scale: float):
    """jnp twin: same contract, same residual conventions as the kernel.

    (No remat here on purpose: the twins only run inside the ring
    custom-vjp's hand-written primal/backward, which autodiff never
    traces through, so checkpoint annotations would be dead weight.)"""
    B, H, T, D = q.shape
    if T <= _JNP_Q_CHUNK:
        return _hop_fwd_jnp_panel(q, k, v, causal, scale, 0)
    nc, rem = divmod(T, _JNP_Q_CHUNK)
    Tp = nc * _JNP_Q_CHUNK
    qs = q[:, :, :Tp].reshape(
        B, H, nc, _JNP_Q_CHUNK, D
    ).transpose(2, 0, 1, 3, 4)

    def chunk(_, xs):
        qc, i = xs
        o, lse = _hop_fwd_jnp_panel(
            qc, k, v, causal, scale, i * _JNP_Q_CHUNK
        )
        return None, (o, lse)

    _, (o, lse) = lax.scan(chunk, None, (qs, jnp.arange(nc)))
    o = o.transpose(1, 2, 0, 3, 4).reshape(B, H, Tp, D)
    lse = lse.transpose(1, 2, 0, 3).reshape(B, H, Tp)
    if rem:
        # Non-divisible tail: one final sub-chunk panel keeps the memory
        # bound for every T, not just multiples of the chunk.
        o_r, lse_r = _hop_fwd_jnp_panel(
            q[:, :, Tp:], k, v, causal, scale, Tp
        )
        o = jnp.concatenate([o, o_r], axis=2)
        lse = jnp.concatenate([lse, lse_r], axis=2)
    return o, lse


# ---------------------------------------------------------------------------
# Per-hop backward with GLOBAL residuals -> exact global (dq, dk, dv).
# ---------------------------------------------------------------------------


def _hop_bwd_pallas(q, k, v, lse, do, di, causal: bool, scale: float):
    fa = _flash_mod()
    T = q.shape[2]
    blk = min(128, T)
    # l = 1, m = global LSE  =>  the kernels' p = exp(s·scale − LSE) is the
    # global softmax restricted to this block.
    ones = jnp.ones_like(lse)
    dk, dv = fa._flash_attention_bwd_dkv(
        q, k, v, None, None, ones, lse, do, di,
        block_q_major=blk, block_k_major=blk, block_k=blk, block_q=blk,
        sm_scale=scale, causal=causal,
        mask_value=fa.DEFAULT_MASK_VALUE, debug=False,
    )
    dq, _ = fa._flash_attention_bwd_dq(
        q, k, v, None, None, ones, lse, do, di,
        block_q_major=blk, block_k_major=blk, block_k=blk,
        sm_scale=scale, causal=causal,
        mask_value=fa.DEFAULT_MASK_VALUE, debug=False,
    )
    return (
        dq.astype(jnp.float32),
        dk.astype(jnp.float32),
        dv.astype(jnp.float32),
    )


def _hop_bwd_jnp_panel(q, k, v, lse, do, di, causal, scale, row0):
    q32, k32, v32 = (t.astype(jnp.float32) for t in (q, k, v))
    do32 = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
    if causal:
        rows = row0 + jnp.arange(q.shape[2])
        mask = jnp.arange(k.shape[2])[None, :] <= rows[:, None]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])  # global softmax, this block's columns
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, do32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do32, v32)
    ds = (dp - di[..., None]) * p * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k32)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q32)
    return dq, dk, dv


def _hop_bwd_jnp(q, k, v, lse, do, di, causal: bool, scale: float):
    B, H, T, D = q.shape
    if T <= _JNP_Q_CHUNK:
        return _hop_bwd_jnp_panel(q, k, v, lse, do, di, causal, scale, 0)
    nc, rem = divmod(T, _JNP_Q_CHUNK)
    Tp = nc * _JNP_Q_CHUNK

    def rows(t):  # [B, H, Tp, ...] -> per-chunk leading axis
        return t[:, :, :Tp].reshape(
            B, H, nc, _JNP_Q_CHUNK, *t.shape[3:]
        ).transpose(2, 0, 1, 3, *range(4, t.ndim + 1))

    def chunk(carry, xs):
        dk_acc, dv_acc = carry
        qc, lsec, doc, dic, i = xs
        dq_c, dk_c, dv_c = _hop_bwd_jnp_panel(
            qc, k, v, lsec, doc, dic, causal, scale, i * _JNP_Q_CHUNK
        )
        return (dk_acc + dk_c, dv_acc + dv_c), dq_c

    (dk, dv), dq = lax.scan(
        chunk,
        (jnp.zeros_like(k, jnp.float32), jnp.zeros_like(v, jnp.float32)),
        (rows(q), rows(lse), rows(do), rows(di), jnp.arange(nc)),
    )
    dq = dq.transpose(1, 2, 0, 3, 4).reshape(B, H, Tp, D)
    if rem:
        dq_r, dk_r, dv_r = _hop_bwd_jnp_panel(
            q[:, :, Tp:], k, v, lse[:, :, Tp:], do[:, :, Tp:],
            di[:, :, Tp:], causal, scale, Tp,
        )
        dq = jnp.concatenate([dq, dq_r], axis=2)
        dk = dk + dk_r
        dv = dv + dv_r
    return dq, dk, dv


def _resolve_impl(impl: Optional[str], q_shape) -> str:
    if impl in ("pallas", "jnp"):
        return impl
    if jax.default_backend() == "tpu" and flash_ring_supported(q_shape):
        return "pallas"
    return "jnp"


# ---------------------------------------------------------------------------
# The ring, as one custom-vjp primitive per device (call inside shard_map).
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    causal: bool = True,
    impl: Optional[str] = None,
) -> jnp.ndarray:
    """Flash-kernel ring attention; call INSIDE shard_map over ``axis_name``.

    Same contract as
    :func:`dpwa_tpu.ops.ring_attention.ring_attention_local`: q/k/v are
    this device's sequence block ``[B, T_local, H, D]`` (grouped K/V heads
    allowed, expanded per hop so the ring still carries only the small
    grouped K/V), device ``i`` holding global positions
    ``[i·T_local, (i+1)·T_local)``; returns the local output block.

    ``impl``: "pallas" (TPU flash kernels), "jnp" (twin math, any
    backend), or None = auto (pallas on TPU when
    :func:`flash_ring_supported`)."""
    out, _ = _ring_fwd_parts(q, k, v, axis_name, causal, impl)
    return out


def _expand_kv(t, H):
    KV = t.shape[1]
    if KV == H:
        return t
    return jnp.repeat(t, H // KV, axis=1)


def _ring_fwd_parts(q, k, v, axis_name, causal, impl):
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = float(1.0 / (D ** 0.5))
    which = _resolve_impl(impl, q.shape)
    hop_fwd = _hop_fwd_pallas if which == "pallas" else _hop_fwd_jnp

    # Kernel layout [B, H, T, D]; the ring carries k/v GROUPED.
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    shift = [(j, (j + 1) % n) for j in range(n)]

    # Accumulators derive from q so they inherit its axis-varying type
    # under shard_map (multi-axis meshes, e.g. peers × sp).
    out0 = (qh * 0.0).astype(jnp.float32)
    lse0 = out0.sum(-1) + _NEG_INF  # [B, H, T]

    def body(carry, hop):
        k_cur, v_cur, out_acc, lse_acc = carry
        src = (me - hop) % n

        def run(diag: bool):
            def f(_):
                o, lse = hop_fwd(
                    qh, _expand_kv(k_cur, H), _expand_kv(v_cur, H),
                    diag and causal, scale,
                )
                return o, lse

            return f

        def skip(_):
            return out0, lse0

        if causal:
            o_i, lse_i = lax.cond(
                src > me,
                skip,
                lambda _: lax.cond(src == me, run(True), run(False), _),
                None,
            )
        else:
            o_i, lse_i = run(False)(None)

        # logsumexp-weighted online merge of normalized hop outputs.
        lse_new = jnp.logaddexp(lse_acc, lse_i)
        w_old = jnp.exp(jnp.minimum(lse_acc - lse_new, 0.0))
        w_new = jnp.exp(jnp.minimum(lse_i - lse_new, 0.0))
        out_acc = out_acc * w_old[..., None] + o_i * w_new[..., None]
        k_nxt = lax.ppermute(k_cur, axis_name, perm=shift)
        v_nxt = lax.ppermute(v_cur, axis_name, perm=shift)
        return (k_nxt, v_nxt, out_acc, lse_new), None

    (k_f, v_f, out, lse), _ = lax.scan(
        body, (kh, vh, out0, lse0), jnp.arange(n)
    )
    return out.transpose(0, 2, 1, 3).astype(q.dtype), (out, lse)


def _ring_flash_fwd(q, k, v, axis_name, causal, impl):
    result, (out32, lse) = _ring_fwd_parts(q, k, v, axis_name, causal, impl)
    return result, (q, k, v, out32, lse)


def _ring_flash_bwd(axis_name, causal, impl, res, g):
    q, k, v, out32, lse = res
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    KV = k.shape[2]
    rep = H // KV
    scale = float(1.0 / (D ** 0.5))
    which = _resolve_impl(impl, q.shape)
    hop_bwd = _hop_bwd_pallas if which == "pallas" else _hop_bwd_jnp

    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    do = g.transpose(0, 2, 1, 3).astype(jnp.float32)
    di = jnp.sum(out32 * do, axis=-1)  # [B, H, T] — global rowsum(out·dout)
    shift = [(j, (j + 1) % n) for j in range(n)]

    dq0 = (qh * 0.0).astype(jnp.float32)
    dkv0 = (kh * 0.0).astype(jnp.float32)

    def body(carry, hop):
        k_cur, v_cur, dk_cur, dv_cur, dq_acc = carry
        src = (me - hop) % n

        def run(diag: bool):
            def f(_):
                dq_i, dk_i, dv_i = hop_bwd(
                    qh, _expand_kv(k_cur, H), _expand_kv(v_cur, H),
                    lse, do, di, diag and causal, scale,
                )
                if rep > 1:  # fold expanded-head grads back to groups
                    dk_i = dk_i.reshape(B, KV, rep, T, D).sum(2)
                    dv_i = dv_i.reshape(B, KV, rep, T, D).sum(2)
                return dq_i, dk_i, dv_i

            return f

        def skip(_):
            return dq0, dkv0, dkv0

        if causal:
            dq_i, dk_i, dv_i = lax.cond(
                src > me,
                skip,
                lambda _: lax.cond(src == me, run(True), run(False), _),
                None,
            )
        else:
            dq_i, dk_i, dv_i = run(False)(None)

        dq_acc = dq_acc + dq_i
        # dk/dv accumulate ON the rotating block: after n hops each block's
        # gradient has collected every device's contribution and is home.
        k_nxt = lax.ppermute(k_cur, axis_name, perm=shift)
        v_nxt = lax.ppermute(v_cur, axis_name, perm=shift)
        dk_nxt = lax.ppermute(dk_cur + dk_i, axis_name, perm=shift)
        dv_nxt = lax.ppermute(dv_cur + dv_i, axis_name, perm=shift)
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, dq_acc), None

    (k_f, v_f, dk, dv, dq), _ = lax.scan(
        body, (kh, vh, dkv0, dkv0, dq0), jnp.arange(n)
    )
    return (
        dq.transpose(0, 2, 1, 3).astype(q.dtype),
        dk.transpose(0, 2, 1, 3).astype(k.dtype),
        dv.transpose(0, 2, 1, 3).astype(v.dtype),
    )


ring_flash_attention_local.defvjp(_ring_flash_fwd, _ring_flash_bwd)
