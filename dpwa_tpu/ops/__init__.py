from dpwa_tpu.ops.merge import (  # noqa: F401
    involution_pairs,
    pairwise_merge,
    pallas_pair_merge,
    pallas_pairwise_merge,
)
