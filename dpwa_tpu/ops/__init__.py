from dpwa_tpu.ops.merge import pairwise_merge, pallas_pairwise_merge  # noqa: F401
