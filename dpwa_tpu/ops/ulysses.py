"""Ulysses-style all-to-all sequence parallelism (head-sharded attention).

The second of the two standard long-context strategies (the build mandate
names "ring attention or all-to-all sequence/context parallelism"; the
ring lives in :mod:`dpwa_tpu.ops.ring_attention` / ``flash_ring`` /
``zigzag_ring``).  Instead of rotating K/V blocks, DeepSpeed-Ulysses-style
SP re-shards around attention itself:

1. the model runs sequence-sharded (each device: ``[B, T_local, H, D]``);
2. ``lax.all_to_all`` re-shards q/k/v to HEAD-sharded with the FULL
   sequence per device (``[B, T_global, H/sp, D]``);
3. each device runs ordinary single-device causal attention over its
   heads — on TPU the same Pallas flash kernel as the single-device model
   path, O(T) memory via VMEM score tiles;
4. a second ``all_to_all`` returns to sequence-sharded layout.

Trade-offs vs the ring: two all-to-alls per attention instead of n
ppermutes (cheaper on all-to-all-friendly fabrics, and attention itself
is then embarrassingly parallel over heads with NO causality cases), but
per-device activations grow to O(T_global · H/sp) and the head count
bounds sp (``H % sp == 0``).  Everything is built from differentiable
collectives + library attention, so autodiff needs no custom VJP —
gradient parity is tested, not hand-derived.

GQA: grouped K/V all-to-all directly when ``KV % sp == 0`` (each device
gets KV/sp groups — the wire stays grouped); otherwise K/V heads are
expanded to H before the exchange.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from dpwa_tpu.utils.compat import axis_size


def ulysses_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    causal: bool = True,
    impl: str = "auto",
) -> jnp.ndarray:
    """Call INSIDE shard_map over ``axis_name``.

    Same contract as
    :func:`dpwa_tpu.ops.ring_attention.ring_attention_local`: q/k/v are
    this device's CONTIGUOUS sequence block ``[B, T_local, H, D]``
    (grouped K/V heads allowed), device i holding global positions
    ``[i·T_local, (i+1)·T_local)``; returns the local output block.

    ``impl``: "auto" uses the Pallas flash kernel for the per-device
    attention on TPU when shapes allow; "dense"/"xla" forces the einsum
    reference; "flash" forces the kernel (TPU only).
    """
    n = axis_size(axis_name)
    B, T, H, D = q.shape
    KV = k.shape[2]
    if H % n:
        raise ValueError(
            f"ulysses needs n_heads {H} divisible by sp={n} "
            "(attention is head-sharded after the all-to-all)"
        )
    if KV % n:
        # Too few KV groups to shard: expand to full heads first (GQA's
        # wire saving is lost, correctness is not).
        rep = H // KV
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
        KV = H

    # Sequence-sharded -> head-sharded with the full sequence:
    # split the heads axis n ways, concatenate received blocks along T.
    def seq_to_heads(t):
        return lax.all_to_all(
            t, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh = seq_to_heads(q)  # [B, T_global, H/n, D]
    kh = seq_to_heads(k)  # [B, T_global, KV/n, D]
    vh = seq_to_heads(v)

    out = single_device_attention(qh, kh, vh, causal=causal, impl=impl)

    # Head-sharded -> sequence-sharded (the inverse exchange).
    return lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def single_device_attention(q, k, v, *, causal: bool, impl: str = "auto"):
    """THE single-device attention of the framework, shared by the Llama
    model's non-sp path and the a2a strategy's per-device compute:
    [B, T, h, D] layout, GQA expanded here if still grouped.  ``impl``:
    "flash" forces the Pallas kernel, "auto" uses it on TPU when shapes
    fit its tiling (T and head_dim multiples of 128), anything else runs
    the masked-softmax einsum with f32 accumulation."""
    B, T, h, D = q.shape
    if k.shape[2] != h:
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    use_flash = impl == "flash" or (
        impl == "auto"
        and jax.default_backend() == "tpu"
        and D % 128 == 0
        and T % 128 == 0
    )
    if use_flash:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention,
        )

        out = flash_attention(
            q.transpose(0, 2, 1, 3),
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
            causal=causal,
            sm_scale=float(1.0 / (D ** 0.5)),
        )
        return out.transpose(0, 2, 1, 3)
    s = jnp.einsum(
        "bthd,bshd->bhts",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
    ) / jnp.sqrt(D).astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum(
        "bhts,bshd->bthd", p, v.astype(jnp.float32)
    ).astype(q.dtype)
