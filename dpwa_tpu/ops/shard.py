"""Sharded gossip payloads: ship 1/k of the replica per exchange.

``shard: {k: >1}`` partitions the flattened replica into ``k``
contiguous shards and makes each publish carry exactly ONE of them —
the shard whose index :func:`dpwa_tpu.parallel.schedules.shard_draw`
assigns to the publish clock.  The draw is a pure function of
``(seed, step, k)``, so both sides of a pair land on the same shard
each round with no negotiation, and its per-epoch permutation visits
every shard exactly once per ``k`` rounds — after ``k`` rounds the
whole vector has crossed the wire once, for ``k×`` fewer bytes per
round.

On the wire this is payload code 6: a
:data:`~dpwa_tpu.parallel.protocol_constants.SHARD_HDR_FMT` preamble
(``shard_idx | k | d | inner_code``) followed by the slice in any
existing flat dtype or codec — top-k selects *within* the shard, the
int8 scale tables restart per shard (chunking is per-payload), so the
codecs compose multiplicatively with the ``k×`` shard saving.

Decode returns a :class:`ShardPayload`, not a vector: like the top-k
codec, only the receiver holds the replica the slice splices into, so
densification happens in the transport against the receiver's own
published view — and the merge touches ONLY the ``[lo, hi)`` slice,
leaving the other ``k−1`` slices bit-identical (an f32 lerp of a
coordinate with itself is NOT exact, so "merge the densified vector"
would silently perturb the unshipped coordinates).

Everything here is numpy + stdlib struct: the codec sits on the
per-fetch hot path and must be importable without a JAX backend.
"""

from __future__ import annotations

from typing import Tuple, Union

import numpy as np

from dpwa_tpu.ops.quantize import (
    TopkPayload,
    _le_view,
    decode_int8_payload,
    decode_topk_payload,
)
from dpwa_tpu.parallel import protocol_constants as _pc

try:  # bf16 inner slices — ml_dtypes ships with jax
    import ml_dtypes
except ImportError:  # pragma: no cover - ml_dtypes is a jax dependency
    ml_dtypes = None

_HDR = _pc.SHARD_HDR

# Inner encodings a shard body may carry.  Deliberately closed: f64/u16
# never ship from _publish, and a nested shard (code 6 inside code 6)
# would make frame size unbounded by recursion — all are rejected as
# malformed.
_INNER_CODES = (
    _pc.PAYLOAD_F32,
    _pc.PAYLOAD_BF16,
    _pc.PAYLOAD_INT8_CHUNKED,
    _pc.PAYLOAD_TOPK_DELTA,
)


def shard_bounds(d: int, k: int, idx: int) -> Tuple[int, int]:
    """``[lo, hi)`` of contiguous shard ``idx`` in a k-way partition.

    The first ``d % k`` shards carry one extra element, so sizes differ
    by at most one and every coordinate belongs to exactly one shard.
    Pure arithmetic shared by encode, decode, trust, and the merge —
    the partition must be impossible to fork between planes."""
    d, k, idx = int(d), int(k), int(idx)
    if k < 1:
        raise ValueError(f"shard count k must be >= 1, got {k}")
    if not 0 <= idx < k:
        raise ValueError(f"shard_idx {idx} out of range for k={k}")
    base, rem = divmod(d, k)
    lo = idx * base + min(idx, rem)
    return lo, lo + base + (1 if idx < rem else 0)


class ShardPayload:
    """A decoded shard frame: one contiguous slice of a d-element
    replica.  ``inner`` is the already-decoded slice content — an f32
    array for dense inner encodings, or a :class:`TopkPayload` over the
    slice for top-k-within-shard.  ``nbytes`` is the on-wire payload
    size (preamble included)."""

    __slots__ = ("d", "k", "shard_idx", "inner_code", "inner", "nbytes")

    def __init__(
        self,
        d: int,
        k: int,
        shard_idx: int,
        inner_code: int,
        inner: Union[np.ndarray, TopkPayload],
        nbytes: int = 0,
    ):
        self.d = int(d)
        self.k = int(k)
        self.shard_idx = int(shard_idx)
        self.inner_code = int(inner_code)
        self.inner = inner
        self.nbytes = int(nbytes)

    @property
    def bounds(self) -> Tuple[int, int]:
        return shard_bounds(self.d, self.k, self.shard_idx)

    def slice_estimate(self, local_slice: np.ndarray) -> np.ndarray:
        """The sender-slice estimate as f32: dense inners decode to it
        directly; a top-k inner splices into the receiver's OWN slice
        (same absolute-value contract as the full-vector codec)."""
        if isinstance(self.inner, TopkPayload):
            return self.inner.densify(local_slice)
        return self.inner

    def densify(self, local: np.ndarray) -> np.ndarray:
        """Full-vector sender estimate against the receiver's replica:
        ``est = local.copy(); est[lo:hi] = slice_estimate``.  For trust
        and guard plumbing only — the merge slices, never this."""
        local = np.ascontiguousarray(local, dtype=np.float32).reshape(-1)
        if local.shape[0] != self.d:
            raise ValueError(
                f"shard payload is for d={self.d} but local replica has "
                f"{local.shape[0]} elements"
            )
        lo, hi = self.bounds
        out = local.copy()
        out[lo:hi] = self.slice_estimate(local[lo:hi])
        return out


def encode_shard_payload(
    inner_payload: np.ndarray, d: int, k: int, shard_idx: int,
    inner_code: int,
) -> np.ndarray:
    """uint8 inner payload (the encoded SLICE) -> code-6 wire body."""
    if inner_code not in _INNER_CODES:
        raise ValueError(f"shard inner_code {inner_code} not shippable")
    lo, hi = shard_bounds(d, k, shard_idx)  # validates k / shard_idx
    del lo, hi
    body = np.ascontiguousarray(inner_payload, dtype=np.uint8).reshape(-1)
    out = np.empty(_HDR.size + body.size, np.uint8)
    _HDR.pack_into(
        out, 0, int(shard_idx), int(k), int(d), int(inner_code)
    )
    out[_HDR.size:] = body
    return out


def decode_shard_payload(buf: np.ndarray) -> ShardPayload:
    """uint8 payload -> :class:`ShardPayload`; raises ValueError on ANY
    malformed input — truncated preamble, k of zero, out-of-range
    shard_idx, a slice length that contradicts ``(d, k, shard_idx)``,
    an unknown/nested inner code, or an inner body that fails its own
    codec's validation — so the transport classifies the frame CORRUPT
    instead of crashing.  ``d`` against the local replica is checked by
    the transport (only it knows the local length)."""
    raw = np.ascontiguousarray(buf, dtype=np.uint8)
    if raw.size < _HDR.size:
        raise ValueError("shard wire payload shorter than its preamble")
    shard_idx, k, d, inner_code = _HDR.unpack_from(raw, 0)
    if k < 1:
        raise ValueError(f"shard wire payload with k={k}")
    if shard_idx >= k:
        raise ValueError(
            f"shard wire payload shard_idx={shard_idx} out of range for "
            f"k={k}"
        )
    if d < 1 or k > d:
        raise ValueError(f"shard wire payload claims k={k} > d={d}")
    lo, hi = shard_bounds(d, k, shard_idx)
    m = hi - lo
    body = raw[_HDR.size:]
    if inner_code == _pc.PAYLOAD_F32:
        if body.size != 4 * m:
            raise ValueError(
                f"shard f32 body is {body.size} bytes; {4 * m} expected "
                f"for slice length {m}"
            )
        # A VIEW into the receive buffer (lease-detach contract,
        # docs/transport.md) — the merge reads it once and never mutates.
        inner: Union[np.ndarray, TopkPayload] = _le_view(body, "<f4")
    elif inner_code == _pc.PAYLOAD_BF16:
        if ml_dtypes is None:  # pragma: no cover - jax dependency
            raise ValueError("bf16 shard payload requires ml_dtypes")
        if body.size != 2 * m:
            raise ValueError(
                f"shard bf16 body is {body.size} bytes; {2 * m} expected "
                f"for slice length {m}"
            )
        # The astype is the required bf16 -> f32 upcast (the one copy a
        # bf16 frame pays); the view itself costs nothing.
        inner = (
            body.view(np.dtype(ml_dtypes.bfloat16)).astype(np.float32)
        )
    elif inner_code == _pc.PAYLOAD_INT8_CHUNKED:
        inner = decode_int8_payload(body)
        if inner.shape[0] != m:
            raise ValueError(
                f"shard int8 body decodes {inner.shape[0]} elements; "
                f"{m} expected for slice length {m}"
            )
    elif inner_code == _pc.PAYLOAD_TOPK_DELTA:
        inner = decode_topk_payload(body)
        if inner.n != m:
            raise ValueError(
                f"shard top-k body is for n={inner.n}; slice length is {m}"
            )
    else:
        raise ValueError(
            f"shard wire payload with inner_code={inner_code}"
        )
    return ShardPayload(d, k, shard_idx, inner_code, inner, nbytes=raw.size)
