"""int8 stochastic-rounding wire compression for gossip exchanges.

``protocol.wire_dtype: int8`` compresses the SHIPPED replica to one byte
per element plus one f32 scale per :data:`CHUNK` elements — 3.9x fewer
wire bytes than f32 (the bf16 wire halves them; this quarters them), with
the local replica and all merge arithmetic staying f32.  The reference
has no compression at all (its wire is pickled f64/f32 numpy — SURVEY.md
§2 "TCP transport" row; mount empty); bf16 and int8 wires are rebuild
extensions motivated by the DCN/TCP fabric being the gossip bottleneck
(BASELINE.md: 0.15–0.3 GB/s TCP vs 645.9 GB/s on-chip).

Scheme: per-chunk absmax scaling, ``scale = max|chunk| / 127``, and
**stochastic rounding** ``q = floor(v/scale + u)``, ``u ~ U[0,1)``.
Stochastic rounding is the load-bearing choice: it makes the quantizer
unbiased (``E[q·scale] = v`` exactly), so repeated gossip averaging sees
zero-mean noise instead of a systematic pull toward the int8 grid —
deterministic rounding at α=0.5 freezes any coordinate pair whose gap is
under one grid step, a real convergence failure mode at consensus time
when replicas are already close.

Two implementations with one contract:

- the jittable JAX path (:func:`fake_quant_wire`) used by the SPMD
  transports to emulate the wire in-graph — keyed on
  ``(seed, step, sender)`` so the ICI and stacked transports produce
  BIT-IDENTICAL merges (same guarantee the bf16 wire has);
- the numpy path (:func:`quantize_np` / :func:`dequantize_np`) used by
  the TCP transport's publish/fetch codec — keyed on
  ``(seed, clock, sender)`` via ``numpy.random.Philox``.  The two RNGs
  differ, so TCP merges match the SPMD ones in distribution, not bits
  (documented non-goal; the bf16 wire's determinism comes free from
  rounding, stochastic rounding priced it in).
"""

from __future__ import annotations

import math
from typing import Tuple

import numpy as np

CHUNK = 256  # f32 scale per 256 int8 elements: 1.6 % metadata overhead

# Domain-separation constant so wire-quantization draws never collide
# with the participation/fault streams (schedules.participation_draw /
# fault_draw fold different data but share the schedule seed).
_WIRE_SALT = 0x51A7

# Separate salt for the top-k selection's tie-break stream: selection and
# value quantization run at the same (seed, clock, sender) and must not
# share a dither sequence.
_TOPK_SALT = 0x70CC


def _n_chunks(n: int) -> int:
    return max(1, math.ceil(n / CHUNK))


# --------------------------------------------------------------------------
# JAX path (SPMD transports; jit/shard_map-safe, static shapes)
# --------------------------------------------------------------------------


def wire_key(seed: int, step, sender, leaf: int = 0):
    """Per-(step, sender, leaf) threefry key for the shipped-copy
    quantization — the leaf index keeps same-shaped pytree leaves from
    sharing rounding noise."""
    import jax

    key = jax.random.key(seed ^ _WIRE_SALT)
    key = jax.random.fold_in(jax.random.fold_in(key, step), sender)
    return jax.random.fold_in(key, leaf)


def quantize(v, key) -> Tuple["jax.Array", "jax.Array"]:  # noqa: F821
    """f32 array (any shape) -> (int8[K, CHUNK], f32 scales[K])."""
    import jax
    import jax.numpy as jnp

    flat = v.reshape(-1)
    n = flat.shape[0]
    k = _n_chunks(n)
    padded = jnp.pad(flat, (0, k * CHUNK - n))
    chunks = padded.reshape(k, CHUNK)
    scale = jnp.max(jnp.abs(chunks), axis=1) / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    r = chunks / safe[:, None]
    u = jax.random.uniform(key, chunks.shape, dtype=chunks.dtype)
    q = jnp.clip(jnp.floor(r + u), -127, 127).astype(jnp.int8)
    q = jnp.where(scale[:, None] > 0, q, jnp.int8(0))
    return q, scale.astype(jnp.float32)


def dequantize(q, scale, shape):
    """(int8[K, CHUNK], f32[K]) -> f32 array of ``shape``."""
    import jax.numpy as jnp

    flat = (q.astype(jnp.float32) * scale[:, None]).reshape(-1)
    n = math.prod(shape) if shape else 1
    return flat[:n].reshape(shape)


def fake_quant_wire(v, seed: int, step, sender, leaf: int = 0):
    """Quantize-dequantize ``v`` exactly as the wire would — the in-graph
    emulation the SPMD transports apply to the SHIPPED copy (f32 leaves
    only; callers gate on dtype)."""
    q, scale = quantize(v, wire_key(seed, step, sender, leaf))
    return dequantize(q, scale, v.shape)


def fake_quant_tree(params, seed: int, step, sender):
    """Apply :func:`fake_quant_wire` to every f32 leaf of a pytree, with
    the leaf's flatten-order index folded into its key.  Both SPMD
    transports build their shipped copy through THIS function, so their
    per-leaf keys — and therefore their merges — are bit-identical."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(params)
    out = [
        fake_quant_wire(v, seed, step, sender, leaf=i)
        if v.dtype == jnp.float32
        else v
        for i, v in enumerate(leaves)
    ]
    return jax.tree.unflatten(treedef, out)


# --------------------------------------------------------------------------
# numpy path (TCP transport codec; free-running host processes)
# --------------------------------------------------------------------------


def _np_key_words(seed: int, clock: float, sender: int) -> Tuple[int, int]:
    """One logical 128-bit key for both host codecs: (seed, sender) in
    one u64 word, the publish clock in the other.

    The clock word is the full IEEE-754 bit pattern, not ``int(clock)``:
    free-running publishers stamp fractional clocks, and truncation would
    hand e.g. clock 1.0 and 1.5 the same dither stream, breaking the
    documented per-(seed, clock, sender) uniqueness.  (Decode never
    derives the key — scales ride the payload — so only stream
    distinctness is at stake.)"""
    k0 = ((seed ^ _WIRE_SALT) & 0xFFFFFFFF) | ((sender & 0xFFFFFFFF) << 32)
    k1 = int(np.float64(clock).view(np.uint64))
    return k0, k1


def _np_rng(seed: int, clock: float, sender: int) -> np.random.Generator:
    return np.random.Generator(
        np.random.Philox(key=list(_np_key_words(seed, clock, sender)))
    )


def quantize_np(
    vec: np.ndarray, seed: int, clock: float, sender: int,
    impl: str = "auto",
) -> Tuple[np.ndarray, np.ndarray]:
    """f32[n] -> (int8[n], f32 scales[K]) with stochastic rounding.

    ``impl="auto"`` uses the native single-pass kernel
    (``native.quantize_sr``, splitmix64 dither) when the library is
    available — the codec is memory-bandwidth work, and numpy's
    ``Generator.random`` alone costs more than the int8 byte saving on
    a cheap fabric — with this numpy/Philox path as the fallback.  The
    two dither streams differ, so ``impl="numpy"`` pins this path where
    a test needs it; both satisfy the same contract (unbiased, error
    < 1 grid step, deterministic per (seed, clock, sender))."""
    flat = np.ascontiguousarray(vec, dtype=np.float32).reshape(-1)
    if impl == "auto":
        from dpwa_tpu import native

        out = native.quantize_sr(
            flat, CHUNK, *_np_key_words(seed, clock, sender)
        )
        if out is not None:
            return out
    n = flat.shape[0]
    k = _n_chunks(n)
    padded = np.zeros(k * CHUNK, np.float32)
    padded[:n] = flat
    chunks = padded.reshape(k, CHUNK)
    scale = (np.max(np.abs(chunks), axis=1) / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0).astype(np.float32)
    u = _np_rng(seed, clock, sender).random(
        chunks.shape, dtype=np.float32
    )
    q = np.clip(np.floor(chunks / safe[:, None] + u), -127, 127).astype(
        np.int8
    )
    q[scale == 0, :] = 0
    return q.reshape(-1)[:n].copy(), scale


def dequantize_np(
    q: np.ndarray, scale: np.ndarray, impl: str = "auto"
) -> np.ndarray:
    """(int8[n], f32[K]) -> f32[n] (native one-pass decode when
    available; the two impls are bit-identical here — no RNG)."""
    if q.shape[0] > 0 and scale.shape[0] != _n_chunks(q.shape[0]):
        # Checked HERE for both impls: the native kernel would read past
        # a short scales buffer, and numpy's broadcasting would silently
        # smear one scale across every chunk.
        raise ValueError(
            f"scales has {scale.shape[0]} entries; "
            f"{_n_chunks(q.shape[0])} expected for n={q.shape[0]}"
        )
    if impl == "auto":
        from dpwa_tpu import native

        out = native.dequantize(
            np.ascontiguousarray(q),
            np.ascontiguousarray(scale, dtype=np.float32),
            CHUNK,
        )
        if out is not None:
            return out
    n = q.shape[0]
    k = _n_chunks(n)
    padded = np.zeros(k * CHUNK, np.int8)
    padded[:n] = q
    out = padded.reshape(k, CHUNK).astype(np.float32) * scale[:, None]
    return out.reshape(-1)[:n].copy()


# TCP wire payload for dtype code 4 (int8-chunked):
#   u64 n_elems | f32 scales[ceil(n/CHUNK)] | int8 q[n]
_LEN = np.dtype("<u8")


def _le_view(raw: np.ndarray, dtype: str) -> np.ndarray:
    """Reinterpret a contiguous uint8 slice as little-endian ``dtype``
    WITHOUT copying (numpy views are fine at unaligned offsets).  On a
    little-endian host ``np.dtype("<f4")`` IS the native dtype, so the
    view is the final array; only a big-endian host pays a byte-swapping
    ``astype`` — the zero-copy decode contract is LE-host-only, which is
    every deployment target (tests/test_zerocopy.py pins the LE case)."""
    out = raw.view(dtype)
    if out.dtype.byteorder == "<" and out.dtype.itemsize > 1:
        out = out.astype(out.dtype.newbyteorder("="))  # pragma: no cover
    return out


def encode_int8_payload(
    vec: np.ndarray, seed: int, clock: float, sender: int
) -> np.ndarray:
    q, scale = quantize_np(vec, seed, clock, sender)
    n = q.shape[0]
    kb = 4 * scale.shape[0]
    buf = np.empty(8 + kb + n, np.uint8)
    buf[:8].view("<u8")[0] = n
    buf[8:8 + kb].view("<f4")[:] = scale
    buf[8 + kb:] = q.view(np.uint8)
    return buf


def decode_int8_payload(buf: np.ndarray) -> np.ndarray:
    """uint8 payload -> f32[n]; raises ValueError on malformed payloads
    (callers treat that as a skipped fetch).

    Zero-copy discipline: the length/scale fields are read as views
    straight out of ``buf`` (which may alias a receive-ring buffer); the
    only payload-sized allocation is the dequantized f32 output itself.
    """
    raw = np.ascontiguousarray(buf, dtype=np.uint8)
    if raw.size < 8:
        raise ValueError("int8 wire payload shorter than its length field")
    n = int(raw[:8].view("<u8")[0])
    k = _n_chunks(n)
    if raw.size != 8 + 4 * k + n:
        raise ValueError(
            f"int8 wire payload size {raw.size} != {8 + 4 * k + n} "
            f"expected for n={n}"
        )
    scale = _le_view(raw[8:8 + 4 * k], "<f4")
    q = raw[8 + 4 * k:].view(np.int8)
    return dequantize_np(q, scale)


def int8_payload_views(buf: np.ndarray):
    """``(n, scales_view, q_view)`` of an int8 wire body WITHOUT
    dequantizing — the raw operands of the device engine's fused
    dequant-lerp kernel (dpwa_tpu/device/kernels.py), validated exactly
    like :func:`decode_int8_payload` but with the dense f32 output never
    materialized: both returned arrays are views into ``buf``."""
    raw = np.ascontiguousarray(buf, dtype=np.uint8)
    if raw.size < 8:
        raise ValueError("int8 wire payload shorter than its length field")
    n = int(raw[:8].view("<u8")[0])
    k = _n_chunks(n)
    if raw.size != 8 + 4 * k + n:
        raise ValueError(
            f"int8 wire payload size {raw.size} != {8 + 4 * k + n} "
            f"expected for n={n}"
        )
    return n, _le_view(raw[8:8 + 4 * k], "<f4"), raw[8 + 4 * k:].view(np.int8)


# --------------------------------------------------------------------------
# Top-k delta codec (TCP wire payload code 5)
# --------------------------------------------------------------------------
#
# Ships only the k largest-magnitude *changed* coordinates since the last
# publish, against an error-feedback accumulator, so a coordinate whose
# delta missed the cut this round keeps its full residual score and wins a
# later round — nothing is ever silently dropped (Stich et al.-style
# memory/error feedback, adapted to gossip's averaging merge).
#
# Payload layout (code 5):
#   u64 n | u32 k | u8 value_code | u32 idx[k] (strictly increasing) | values
# where value_code 0 ships f32 values (4k bytes) and value_code 1 ships the
# int8-chunked block f32 scales[ceil(k/CHUNK)] + int8 q[k].
#
# The shipped values are ABSOLUTE coordinates ``vec[idx]``, not deltas: the
# receiver rebuilds its estimate of the sender by overwriting its OWN
# replica at idx (``est = local.copy(); est[idx] = values``) and merges
# that densified estimate exactly like a dense payload.  Absolute values
# make the codec stateless on the receive side (no mirror to keep in sync
# across skipped fetches, restarts, or partner remaps) and make honest
# payloads look like the local replica to the trust plane (cosine ≈ +1 on
# the selected coordinates), so the PR 4 hard bounds screen sparse frames
# with no new thresholds.

TOPK_VALUE_F32 = 0
TOPK_VALUE_INT8 = 1


def topk_nbytes(n: int, k: int, value_dtype: str = "int8") -> int:
    """Exact on-wire payload bytes for a top-k frame (header + indices +
    value block) — used by ``_wire_nbytes`` / ``tree_wire_bytes`` so
    logged GB/s reflects the compressed traffic."""
    k = max(1, min(int(k), int(n))) if n else 0
    vals = 4 * k if value_dtype == "f32" else 4 * _n_chunks(k) + k
    return 13 + 4 * k + vals


def topk_k(n: int, fraction: float) -> int:
    """k for a given vector length and ``protocol.topk_fraction`` —
    clamped to [1, n] so degenerate fractions still make progress."""
    return max(1, min(int(n), int(round(float(fraction) * int(n)))))


def topk_select(
    delta: np.ndarray, k: int, seed: int, clock: float, sender: int
) -> np.ndarray:
    """Indices (sorted ascending) of the k largest-|delta| coordinates.

    Ties at the selection boundary are broken by a Philox draw keyed on
    (seed, clock, sender) — the host-path counterpart of the threefry
    keying the JAX codec uses, same convention as :func:`quantize_np` —
    then by index, so reruns are bit-identical and peers with identical
    deltas still make independent, unbiased boundary choices."""
    n = delta.shape[0]
    k = max(1, min(int(k), n))
    if k == n:
        return np.arange(n, dtype=np.uint32)
    score = np.abs(delta)
    part = np.argpartition(score, n - k)
    thresh = score[part[n - k]]
    above = np.nonzero(score > thresh)[0]
    need = k - above.shape[0]
    if need <= 0:
        # More strictly-above entries than k can't happen (partition
        # invariant), but guard the == 0 edge exactly.
        idx = above[:k]
    else:
        at = np.nonzero(score == thresh)[0]
        tie = np.random.Generator(
            np.random.Philox(
                key=list(_np_key_words(seed ^ _TOPK_SALT, clock, sender))
            )
        ).random(at.shape[0])
        order = np.lexsort((at, tie))
        idx = np.concatenate([above, at[order[:need]]])
    return np.sort(idx).astype(np.uint32)


class TopkPayload:
    """A decoded sparse frame: ``n`` total coordinates, sorted ``indices``
    (u32[k]) and f32 ``values`` — absolute sender coordinates, already
    dequantized when the value block was int8.  ``value_dtype`` records
    which block arrived (for per-codec accounting/baselines) and
    ``nbytes`` the on-wire payload size."""

    __slots__ = ("n", "indices", "values", "value_dtype", "nbytes")

    def __init__(self, n, indices, values, value_dtype="f32", nbytes=0):
        self.n = int(n)
        self.indices = np.ascontiguousarray(indices, dtype=np.uint32)
        self.values = np.ascontiguousarray(values, dtype=np.float32)
        self.value_dtype = value_dtype
        self.nbytes = int(nbytes)

    @property
    def k(self) -> int:
        return self.indices.shape[0]

    def densify(self, local: np.ndarray) -> np.ndarray:
        """Rebuild the sender estimate against the receiver's own
        replica: ``est = local.copy(); est[indices] = values``."""
        local = np.ascontiguousarray(local, dtype=np.float32).reshape(-1)
        if local.shape[0] != self.n:
            raise ValueError(
                f"top-k payload is for n={self.n} but local replica has "
                f"{local.shape[0]} elements"
            )
        out = local.copy()
        out[self.indices] = self.values
        return out


class TopkEncoder:
    """Sender-side error-feedback state for the top-k wire.

    ``base`` is this sender's record of what the ring has been told about
    each coordinate.  Each publish scores coordinates by
    ``|vec - base|`` (the residual: real movement PLUS anything previous
    rounds dropped or rounded away), ships the top-k as absolute values,
    and overwrites ``base`` only at the shipped indices with the values
    as they decode on the wire — so quantization error also stays in the
    score and un-shipped coordinates accumulate until they win."""

    def __init__(self, fraction: float, value_dtype: str = "int8"):
        self.fraction = float(fraction)
        self.value_dtype = value_dtype
        self.base: np.ndarray | None = None

    def reset(self) -> None:
        self.base = None

    def retune(self, fraction: float) -> None:
        """Swap the shipped fraction AND drop the error-feedback base.

        ``base`` records what the ring was told under the OLD rung; a
        codec change invalidates that record (the receivers that decode
        the next frame may have merged dense/bf16 frames meanwhile, and
        a stale residual would re-ship coordinates the new rung already
        covers — the "stale topk memory" failure the tune plane's
        reset-on-rung-change rule exists to prevent).  The next encode
        rebuilds ``base`` from zeros, exactly like a fresh encoder."""
        self.fraction = float(fraction)
        self.reset()

    def encode(
        self, vec: np.ndarray, seed: int, clock: float, sender: int
    ) -> np.ndarray:
        """f32[n] -> uint8 payload (code 5 body)."""
        flat = np.ascontiguousarray(vec, dtype=np.float32).reshape(-1)
        n = flat.shape[0]
        if self.base is None or self.base.shape[0] != n:
            self.base = np.zeros(n, np.float32)
        k = topk_k(n, self.fraction)
        idx = topk_select(flat - self.base, k, seed, clock, sender)
        vals = flat[idx]
        if self.value_dtype == "int8":
            q, scale = quantize_np(vals, seed, clock, sender)
            shipped = dequantize_np(q, scale)
            code = TOPK_VALUE_INT8
            sb = 4 * scale.shape[0]
            vb = sb + k
        else:
            q = scale = None
            sb = 0
            shipped = vals
            code = TOPK_VALUE_F32
            vb = 4 * k
        self.base[idx] = shipped
        # One preallocated buffer, header and blocks written through
        # views — no per-section tobytes round-trips, no concatenate.
        buf = np.empty(13 + 4 * k + vb, np.uint8)
        buf[:8].view("<u8")[0] = n
        buf[8:12].view("<u4")[0] = k
        buf[12] = code
        buf[13:13 + 4 * k].view("<u4")[:] = idx
        vstart = 13 + 4 * k
        if code == TOPK_VALUE_INT8:
            buf[vstart:vstart + sb].view("<f4")[:] = scale
            buf[vstart + sb:] = q.view(np.uint8)
        else:
            buf[vstart:].view("<f4")[:] = vals
        return buf


def decode_topk_payload(buf: np.ndarray) -> TopkPayload:
    """uint8 payload -> :class:`TopkPayload`; raises ValueError on ANY
    malformed input — truncated index list, k > n, out-of-range /
    unsorted / duplicate indices, or a value block whose length lies —
    so the transport classifies the frame CORRUPT instead of crashing."""
    raw = np.ascontiguousarray(buf, dtype=np.uint8)
    if raw.size < 13:
        raise ValueError("top-k wire payload shorter than its header")
    n = int(raw[:8].view("<u8")[0])
    k = int(raw[8:12].view("<u4")[0])
    code = int(raw[12])
    if n < 1 or k < 1:
        raise ValueError(f"top-k wire payload with n={n}, k={k}")
    if k > n:
        raise ValueError(f"top-k wire payload claims k={k} > n={n}")
    if code not in (TOPK_VALUE_F32, TOPK_VALUE_INT8):
        raise ValueError(f"top-k wire payload with value_code={code}")
    vals_nbytes = 4 * k if code == TOPK_VALUE_F32 else 4 * _n_chunks(k) + k
    expect = 13 + 4 * k + vals_nbytes
    if raw.size != expect:
        raise ValueError(
            f"top-k wire payload size {raw.size} != {expect} expected "
            f"for n={n}, k={k}, value_code={code}"
        )
    idx = _le_view(raw[13:13 + 4 * k], "<u4")
    if int(idx[-1]) >= n:
        raise ValueError(
            f"top-k wire payload index {int(idx[-1])} out of range for "
            f"n={n}"
        )
    if k > 1 and not np.all(idx[1:] > idx[:-1]):
        raise ValueError(
            "top-k wire payload indices not strictly increasing"
        )
    body = raw[13 + 4 * k:]
    if code == TOPK_VALUE_F32:
        # Values stay a VIEW into the receive buffer — the ownership
        # contract (docs/transport.md) is that the buffer's lease was
        # detached before these views escape.
        vals = _le_view(body, "<f4")
        vdtype = "f32"
    else:
        kc = _n_chunks(k)
        scale = _le_view(body[:4 * kc], "<f4")
        vals = dequantize_np(body[4 * kc:].view(np.int8), scale)
        vdtype = "int8"
    return TopkPayload(n, idx, vals, value_dtype=vdtype, nbytes=raw.size)
