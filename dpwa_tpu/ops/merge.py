"""Fused pairwise-average merge ops.

``x_i ← (1−α_i)·x_i + α_i·x_{partner(i)}`` over a stacked peer axis — the
single-chip ("virtual peers") form of the gossip exchange, used by the
bandwidth benchmark and by single-device fallbacks.  (Across real devices
the exchange is ``ppermute`` inside :mod:`dpwa_tpu.parallel.ici`; this op is
its stacked-axis twin.)

Three implementations:

- :func:`xla_pairwise_merge` — ``x[partner]`` gather fused with the axpy by
  XLA.  Portable.
- :func:`pallas_pairwise_merge` — TPU Pallas kernel that streams row tiles
  HBM→VMEM with the partner row resolved by scalar prefetch, so the merge
  is one pipelined pass.  The partner index arrives as data (scalar-prefetch
  operand), NOT as a compile-time constant — one compiled kernel serves
  every pairing in a schedule pool.  3 HBM ops per row (read self, read
  partner, write self).
- :func:`pallas_pair_merge` — the bandwidth-optimal form.  One program per
  *pair* of the involution loads both member rows once, computes both
  merged outputs, and writes them back **in place** (the input buffer is
  donated and aliased to the output).  2 HBM ops per row — the theoretical
  minimum, since every row must be read and written — vs 3 for the kernels
  above.  Manual double-buffered DMA (HBM↔VMEM) keeps the copy engines
  saturated; measured at the chip's streaming roofline on v5e
  (~2.3× :func:`pallas_pairwise_merge` at 100 MB vectors).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def xla_pairwise_merge(
    x: jnp.ndarray, partner: jnp.ndarray, alpha: jnp.ndarray
) -> jnp.ndarray:
    """Reference XLA formulation: fused gather + axpy.

    Args:
      x: [n, d] stacked peer vectors.
      partner: int32[n] involution (partner[partner[i]] == i).
      alpha: float32[n] per-peer merge coefficient.
    """
    a = alpha[:, None].astype(x.dtype)
    return (1 - a) * x + a * x[partner]


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def pallas_pairwise_merge(
    x: jnp.ndarray,
    partner: jnp.ndarray,
    alpha: jnp.ndarray,
    *,
    tile: int = 512 * 1024,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas TPU kernel: one pipelined HBM pass over the stacked peers.

    Grid is (n, d/tile); each program loads its own row tile and its
    partner's row tile (row index resolved from the scalar-prefetched
    pairing — dynamic data, no recompile per pairing) and writes the fused
    merge.  ``tile`` floats per block × 3 buffers × double buffering stays
    well inside the ~16 MB of VMEM.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = x.shape
    # TPU blocks want trailing dims (8k, 128): view each peer row as a
    # [rows, 128] tile grid and stream R-row blocks of it.
    lanes = 128
    sublanes = 8
    if d % (lanes * sublanes) != 0:
        return xla_pairwise_merge(x, partner, alpha)
    rows = d // lanes
    r_block = max(sublanes, min(rows, tile // lanes // sublanes * sublanes))
    while rows % r_block != 0:
        r_block -= sublanes
    x3 = x.reshape(n, rows, lanes)

    def kernel(partner_ref, alpha_ref, x_self, x_part, out_ref):
        i = pl.program_id(0)
        a = alpha_ref[i].astype(x_self.dtype)
        out_ref[...] = (1 - a) * x_self[...] + a * x_part[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n, rows // r_block),
        in_specs=[
            pl.BlockSpec((1, r_block, lanes), lambda i, t, part, alph: (i, t, 0)),
            pl.BlockSpec(
                (1, r_block, lanes), lambda i, t, part, alph: (part[i], t, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, r_block, lanes), lambda i, t, part, alph: (i, t, 0)
        ),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, rows, lanes), x.dtype),
        interpret=interpret,
    )(partner.astype(jnp.int32), alpha.astype(jnp.float32), x3, x3)
    return out.reshape(n, d)


def involution_pairs(
    partner, *, pad_to: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Host helper: (left, right) pair row-lists from an involution.

    Fixed points (``partner[i] == i`` — peers sitting this round out) are
    dropped: with the in-place :func:`pallas_pair_merge` an unlisted row is
    simply left untouched, which is exactly the α=0 self-merge semantics.
    ``pad_to`` pads the lists to a fixed length by repeating fixed-point
    rows as no-op self-pairs, so every entry of a schedule pool can share
    one compiled kernel shape; padding is only ever needed when fixed
    points exist, so a pad row is always available.
    """
    p = np.asarray(partner)
    (n,) = p.shape
    if not np.array_equal(p[p], np.arange(n)):
        raise ValueError("partner is not an involution")
    left = np.flatnonzero(np.arange(n) < p)
    right = p[left]
    if pad_to is not None:
        if len(left) > pad_to:
            raise ValueError(f"{len(left)} pairs cannot pad to {pad_to}")
        deficit = pad_to - len(left)
        if deficit:
            fixed = np.flatnonzero(p == np.arange(n))
            if fixed.size == 0:
                raise ValueError(
                    "cannot pad a perfect matching: no fixed-point row is "
                    "available for no-op self-pairs"
                )
            pad = np.resize(fixed, deficit)
            left = np.concatenate([left, pad])
            right = np.concatenate([right, pad])
    return left.astype(np.int32), right.astype(np.int32)


def pallas_pair_merge(
    x: jnp.ndarray,
    left: jnp.ndarray,
    right: jnp.ndarray,
    alpha: jnp.ndarray,
    *,
    r_block: int = 1024,
    n_buf: int = 2,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Bandwidth-optimal in-place pairwise merge over explicit pair lists.

    For pair k with rows ``L = left[k]``, ``R = right[k]``::

        x[L] ← (1−α[L])·x[L] + α[L]·x[R]
        x[R] ← (1−α[R])·x[R] + α[R]·x[L]

    both computed from the pre-merge values.  ``x`` is DONATED and updated
    in place (the caller's reference is invalidated — use the return
    value).  Rows in neither list are left untouched.  Pair lists must
    name disjoint rows, except that a fixed-point row may repeat as a
    no-op ``L == R`` pad (see :func:`involution_pairs`).

    The kernel keeps ``x`` in HBM (`pl.ANY` + input/output aliasing) and
    hand-pipelines DMA: while pair-chunk ``c`` is being merged in VMEM,
    chunk ``c+1``'s two row tiles are already streaming in and chunk
    ``c−n_buf``'s outputs are streaming out.  Total traffic is one read
    and one write per element — the floor for any merge — and measures at
    the same GB/s as a pure copy kernel on v5e.

    ``left``/``right``/``alpha`` arrive as scalar-prefetch data, so one
    compiled kernel serves every pairing of a schedule pool.

    Accepts ``x`` as ``[n, d]`` or, for the zero-copy fast path, already
    tiled as ``[n, d//128, 128]`` (same ravel order); output shape matches
    input.  With 2D input the internal reshape materializes one extra HBM
    copy — keep the buffer 3D across a hot loop.
    """
    if n_buf < 2:
        # The pipeline prefetches chunk c+1 into slot (c+1) % n_buf while
        # chunk c's tiles in the same slot are still in flight; with a
        # single slot that is a data race, not a slower schedule.
        raise ValueError("n_buf must be >= 2 (double buffering)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _pair_merge_impl(
        x, left, right, alpha, r_block=r_block, n_buf=n_buf,
        interpret=interpret,
    )


@functools.partial(
    jax.jit,
    static_argnames=("r_block", "n_buf", "interpret"),
    donate_argnums=(0,),
)
def _pair_merge_impl(
    x: jnp.ndarray,
    left: jnp.ndarray,
    right: jnp.ndarray,
    alpha: jnp.ndarray,
    *,
    r_block: int,
    n_buf: int,
    interpret: bool,
) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lanes, sublanes = 128, 8
    n_pairs = left.shape[0]
    # A (n, rows, 128) input skips the flattening reshape entirely: the
    # donated buffer aliases straight into the kernel with zero extra
    # copies.  A 2D (n, d) input works too, but XLA materializes the
    # internal reshape as a copy, which costs one extra HBM pass — hot
    # loops should carry the 3D layout (ravel order is identical).
    was_2d = x.ndim == 2
    n = x.shape[0]
    d = int(np.prod(x.shape[1:]))
    tiled_ok = (
        n_pairs > 0
        and d % (lanes * sublanes) == 0
        and (was_2d or (x.ndim == 3 and x.shape[2] == lanes))
    )
    # Pad self-pairs (L == R) must be exact no-ops ON EVERY PATH.
    # (1−a)·x + a·x is NOT bitwise x in floating point for a ∉ {0, 1}, so
    # force a = 0 there: 1.0·x + 0.0·x IS exact, keeping sat-out rows
    # bit-identical (the α=0 self-merge semantics the transports
    # guarantee).  Hoisted above the fallback branch so the tiled kernel
    # and the scatter fallback agree.
    noop = left == right
    a_left = jnp.where(noop, 0.0, alpha[left]).astype(jnp.float32)
    a_right = jnp.where(noop, 0.0, alpha[right]).astype(jnp.float32)

    if not tiled_ok:
        # Shapes the tiled kernel can't take: scatter-form XLA fallback.
        # Repeated pad rows put duplicate indices into `.at[].set`; with
        # the forced a = 0 every duplicate writes the identical pre-merge
        # value, so the unspecified winner is harmless.
        if n_pairs == 0:
            return x
        bshape = (-1,) + (1,) * (x.ndim - 1)
        a_l = a_left.reshape(bshape).astype(x.dtype)
        a_r = a_right.reshape(bshape).astype(x.dtype)
        x_l, x_r = x[left], x[right]
        x = x.at[left].set((1 - a_l) * x_l + a_l * x_r)
        return x.at[right].set((1 - a_r) * x_r + a_r * x_l)

    rows = d // lanes
    r_block = max(sublanes, min(r_block, rows))
    while rows % r_block != 0:
        r_block -= sublanes
    x3 = x.reshape(n, rows, lanes) if was_2d else x
    tiles = rows // r_block
    total = n_pairs * tiles

    def kernel(l_ref, r_ref, a_ref, x_hbm, o_hbm, ibuf, obuf, isem, osem):
        def in_dma(c, slot):
            k, t = c // tiles, c % tiles
            sl = pl.ds(t * r_block, r_block)
            return (
                pltpu.make_async_copy(
                    x_hbm.at[l_ref[k], sl, :], ibuf.at[slot, 0],
                    isem.at[slot, 0]),
                pltpu.make_async_copy(
                    x_hbm.at[r_ref[k], sl, :], ibuf.at[slot, 1],
                    isem.at[slot, 1]),
            )

        def out_dma(c, slot):
            k, t = c // tiles, c % tiles
            sl = pl.ds(t * r_block, r_block)
            return (
                pltpu.make_async_copy(
                    obuf.at[slot, 0], o_hbm.at[l_ref[k], sl, :],
                    osem.at[slot, 0]),
                pltpu.make_async_copy(
                    obuf.at[slot, 1], o_hbm.at[r_ref[k], sl, :],
                    osem.at[slot, 1]),
            )

        for dma in in_dma(0, 0):
            dma.start()

        def body(c, _):
            slot = c % n_buf

            @pl.when(c + 1 < total)
            def _():
                for dma in in_dma(c + 1, (c + 1) % n_buf):
                    dma.start()

            for dma in in_dma(c, slot):
                dma.wait()

            # The out buffers of this slot were last used n_buf chunks ago;
            # their write-back must have landed before we overwrite them.
            @pl.when(c >= n_buf)
            def _():
                for dma in out_dma(c - n_buf, slot):
                    dma.wait()

            k = c // tiles
            a_l = a_ref[2 * k]
            a_r = a_ref[2 * k + 1]
            x_l = ibuf[slot, 0].astype(jnp.float32)
            x_r = ibuf[slot, 1].astype(jnp.float32)
            dt = x_hbm.dtype
            obuf[slot, 0] = ((1.0 - a_l) * x_l + a_l * x_r).astype(dt)
            obuf[slot, 1] = ((1.0 - a_r) * x_r + a_r * x_l).astype(dt)
            for dma in out_dma(c, slot):
                dma.start()
            return 0

        jax.lax.fori_loop(0, total, body, 0)
        for c in range(max(0, total - n_buf), total):
            for dma in out_dma(c, c % n_buf):
                dma.wait()

    # Interleave the (already pad-masked) per-pair alphas for the kernel.
    a_pairs = jnp.stack([a_left, a_right], axis=1).reshape(-1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((n_buf, 2, r_block, lanes), x.dtype),
            pltpu.VMEM((n_buf, 2, r_block, lanes), x.dtype),
            pltpu.SemaphoreType.DMA((n_buf, 2)),
            pltpu.SemaphoreType.DMA((n_buf, 2)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(x3.shape, x3.dtype),
        input_output_aliases={3: 0},  # x (input 3 after the scalars) ↔ out
        interpret=interpret,
    )(left.astype(jnp.int32), right.astype(jnp.int32), a_pairs, x3)
    return out.reshape(n, d) if was_2d else out


def pairwise_merge(
    x: jnp.ndarray,
    partner: jnp.ndarray,
    alpha: jnp.ndarray,
    *,
    prefer_pallas: bool | None = None,
) -> jnp.ndarray:
    """Merge with the best available backend (Pallas on TPU, XLA elsewhere).

    Functional (non-donating) API keyed by the involution ``partner``.  The
    in-place bandwidth-optimal path is :func:`pallas_pair_merge`; callers
    that hold their payload as one flat resident ``[n, d/128, 128]`` buffer
    (the bandwidth bench, flat-vector adapters) should call it directly.

    The stacked TRAIN step deliberately does not: measured on a v5e chip
    (experiments/stacked_exchange_profile.py, committed in
    artifacts/stacked_exchange_profile.json), the XLA gather merge is 9 %
    of a ResNet-50-scale step, and the kernel's 3→2 HBM-pass saving (a
    2.45× faster exchange in isolation) caps the end-to-end gain at ~5 %
    — less than the cost of carrying the params pytree as a flat buffer
    (ravel/unravel passes) or of the per-leaf retiling reshapes that
    leaf-wise grafting would add.
    """
    if prefer_pallas is None:
        prefer_pallas = jax.default_backend() == "tpu"
    if prefer_pallas:
        return pallas_pairwise_merge(x, partner, alpha)
    return xla_pairwise_merge(x, partner, alpha)
