"""Fused pairwise-average merge ops.

``x_i ← (1−α_i)·x_i + α_i·x_{partner(i)}`` over a stacked peer axis — the
single-chip ("virtual peers") form of the gossip exchange, used by the
bandwidth benchmark and by single-device fallbacks.  (Across real devices
the exchange is ``ppermute`` inside :mod:`dpwa_tpu.parallel.ici`; this op is
its stacked-axis twin.)

Two implementations:

- :func:`xla_pairwise_merge` — ``x[partner]`` gather fused with the axpy by
  XLA.  Portable, decent (~157 GB/s/chip on v5e at 100 MB vectors).
- :func:`pallas_pairwise_merge` — TPU Pallas kernel that streams row tiles
  HBM→VMEM with the partner row resolved by scalar prefetch, so the merge
  is one pipelined pass.  The partner index arrives as data (scalar-prefetch
  operand), NOT as a compile-time constant — one compiled kernel serves
  every pairing in a schedule pool.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def xla_pairwise_merge(
    x: jnp.ndarray, partner: jnp.ndarray, alpha: jnp.ndarray
) -> jnp.ndarray:
    """Reference XLA formulation: fused gather + axpy.

    Args:
      x: [n, d] stacked peer vectors.
      partner: int32[n] involution (partner[partner[i]] == i).
      alpha: float32[n] per-peer merge coefficient.
    """
    a = alpha[:, None].astype(x.dtype)
    return (1 - a) * x + a * x[partner]


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def pallas_pairwise_merge(
    x: jnp.ndarray,
    partner: jnp.ndarray,
    alpha: jnp.ndarray,
    *,
    tile: int = 512 * 1024,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas TPU kernel: one pipelined HBM pass over the stacked peers.

    Grid is (n, d/tile); each program loads its own row tile and its
    partner's row tile (row index resolved from the scalar-prefetched
    pairing — dynamic data, no recompile per pairing) and writes the fused
    merge.  ``tile`` floats per block × 3 buffers × double buffering stays
    well inside the ~16 MB of VMEM.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = x.shape
    # TPU blocks want trailing dims (8k, 128): view each peer row as a
    # [rows, 128] tile grid and stream R-row blocks of it.
    lanes = 128
    sublanes = 8
    if d % (lanes * sublanes) != 0:
        return xla_pairwise_merge(x, partner, alpha)
    rows = d // lanes
    r_block = max(sublanes, min(rows, tile // lanes // sublanes * sublanes))
    while rows % r_block != 0:
        r_block -= sublanes
    x3 = x.reshape(n, rows, lanes)

    def kernel(partner_ref, alpha_ref, x_self, x_part, out_ref):
        i = pl.program_id(0)
        a = alpha_ref[i].astype(x_self.dtype)
        out_ref[...] = (1 - a) * x_self[...] + a * x_part[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n, rows // r_block),
        in_specs=[
            pl.BlockSpec((1, r_block, lanes), lambda i, t, part, alph: (i, t, 0)),
            pl.BlockSpec(
                (1, r_block, lanes), lambda i, t, part, alph: (part[i], t, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, r_block, lanes), lambda i, t, part, alph: (i, t, 0)
        ),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, rows, lanes), x.dtype),
        interpret=interpret,
    )(partner.astype(jnp.int32), alpha.astype(jnp.float32), x3, x3)
    return out.reshape(n, d)


def pairwise_merge(
    x: jnp.ndarray,
    partner: jnp.ndarray,
    alpha: jnp.ndarray,
    *,
    prefer_pallas: bool | None = None,
) -> jnp.ndarray:
    """Merge with the best available backend (Pallas on TPU, XLA elsewhere)."""
    if prefer_pallas is None:
        prefer_pallas = jax.default_backend() == "tpu"
    if prefer_pallas:
        return pallas_pairwise_merge(x, partner, alpha)
    return xla_pairwise_merge(x, partner, alpha)
